"""High-throughput decode engine: paged KV + continuous batching.

The lockstep decoder (``models.lm.generate``) is a fixed-batch program:
every sequence enters together, decodes in step, and the batch ends
when the longest member does — between requests the chip idles and
short sequences pad out long ones. This engine is the Orca-style
answer, hand-rolled in the repo's idiom (explicit state, no framework
wrappers):

- **Paged KV** (``decode/paged.py``): one static-shape block pool for
  every sequence; a finished sequence frees its blocks with a host-side
  table edit — no recompile, no pool reshape.
- **Continuous batching**: a host scheduler admits queued prompts into
  freed slots *between* compiled steps. The compiled surface is a small
  static set — one decode program per power-of-two slot bucket, one
  prefill program per power-of-two chunk bucket — so steady-state steps
  are dispatch-only and the compile count is bounded by the bucket
  count (the ``--log_every`` chunk discipline, recompile-guard-tested).
- **Chunked prefill**: long prompts enter in bounded chunks
  (``models.attention.chunk_attn`` over the gathered cache), so a new
  long prompt costs one chunk per engine step instead of stalling every
  running decode behind a full-prompt pass.
- **Fused sampling** (``decode/sampling.py``): temperature / top-k /
  top-p picked inside the compiled step, keyed on
  ``(engine seed, sequence uid, position)`` — continuous-batching
  output is token-identical to decoding each sequence alone.

Strategies: ``mesh=None`` runs single-device (the ``lm`` family);
passing a model-axis mesh runs the Megatron decode layout
(``parallel.lm``): head-sharded KV pool (each shard caches its own
``H/n`` heads), vocab-parallel tied head, and an in-graph logits
gather feeding the same fused pick on every shard.

Determinism contract: a sequence's output depends only on
``(params, engine seed, uid, prompt, sampling config)`` — never on slot
assignment, admission order, chunk interleaving, or pool layout
(tests/test_decode_engine.py pins paged==contiguous bit-for-bit at f32
and continuous==sequential token-for-token).

Reliability layer (round 10, DESIGN.md section 16 — the serving
counterpart of the self-healing training ladder):

- **In-graph logits guardrail**: every compiled step returns a per-row
  all-finite flag over the full-vocab logits
  (``runtime.guardrails.rows_finite``) next to the picks; a non-finite
  sequence is **quarantined** at that step — slot and blocks freed
  (blocks scrubbed: NaN stale bytes are the one thing the masks can't
  neutralize), uid reported FAILED with a reason, every other sequence
  untouched. Because the sampling keys and the per-slot gathers never
  reference the slot, survivors are bit-identical to a run that never
  admitted the poisoned request.
- **Per-request retry**: a quarantined request with budget left
  (``ServePolicy.max_retries``) re-enters the queue and is replayed —
  prompt re-prefilled, already-emitted tokens teacher-forced through
  the decode path so the KV write history (and hence the int8
  quantization history) is bit-identical to the uninterrupted run's.
  The same replay mechanism serves **preemption** (pool-pressure
  eviction of the youngest sequence back to WAITING) and the
  supervisor's **snapshot-resume** (``decode/supervise.py``).
- **Admission control**: bounded waiting queue (``queue_limit``,
  reject-on-full with ``AdmissionError``), per-request TTL
  (``deadline_steps``), and lifecycle telemetry — one schema-v4
  ``request`` record per transition (admitted / preempted / retried /
  quarantined / completed / rejected / expired).

Raw-latency layer (round 12, DESIGN.md section 18 — two compounding
attacks on per-token cost):

- **Speculative decoding** (``EngineConfig(speculate=k)``): an n-gram
  prompt-copy drafter (``decode/draft.py`` — no second model, state a
  pure function of ``prompt + out``) proposes up to ``k`` tokens per
  slot; ONE compiled verify dispatch chains ``k+1`` single-token
  sub-steps (the decode body unrolled) and accepts the matched greedy
  prefix, so a step emits ``1 + accepted`` tokens per sequence at one
  dispatch's host/scheduler cost. Verification is greedy and the KV
  write of a drafted row is MASKED by its own acceptance (a rejected
  row's scatter is redirected to the scratch block — the existing pad
  idiom), so the pool's write history contains exactly the rows the
  non-speculative engine would have written: token identity holds
  BIT-FOR-BIT at every kv_dtype, int8 requant history included, and
  rollback of a rejected tail is literally nothing (the rows never
  landed). Replay teacher-forces recorded tokens as drafts (all
  accepted on a healthy replay), so quarantine/preempt/crash-resume
  re-draft identically; teacher-forced tokens stay OUT of the
  ``drafted_tokens``/``accepted_tokens`` telemetry pair, which scores
  the live n-gram drafter only.
- **Fused paged-attention kernel** (``EngineConfig(kernel="fused")``):
  the decode/verify cache read runs the Pallas block-table walk
  (``ops/pallas_paged_attention.py``) instead of the gather →
  ``decode_attn`` two-pass — pool bytes cross the bus once, at the
  storage dtype, int8 dequant folded in. The gather path stays the
  differential oracle (bit-identical at f32 under jit).

Shared-prefix layer (round 13, DESIGN.md section 19 — the capacity
multiplier: most requests share a long system prompt, so N admissions
should pay ~1 prefill and ~1 copy of the shared KV, not N):

- **Radix prefix cache** (``decode/prefix.py``,
  ``EngineConfig(prefix_cache=True)``, the default): every fully
  prefilled FULL block of a prompt is content-keyed into a host-side
  radix tree (the edge is the block's token tuple); admission walks the
  tree and maps every hit block straight into the new slot's table —
  refcounted, zero recompiles (tables are data). A hit block's bytes
  are bit-identical to what the sequence's own prefill would have
  written (full-block content is a pure function of the token prefix
  and the engine config — chunk boundaries inside full blocks are
  position-determined, so even the int8 requant history matches), and
  the walk always leaves >= 1 prompt token to prefill so the first
  pick comes from the same prefill program the unshared engine ran:
  prefix-cached output == unshared output token for token at every
  kv_dtype.
- **Copy-on-write**: a shared block is read-only. Structurally no
  scheduler write ever aims at one (hits cover only fully-prefilled
  prompt blocks; every write — decode, chunked prefill, spec-decode
  verify, whose rejected rows redirect to scratch — lands at or past
  the prefill frontier), and ``_cow_private`` ENFORCES it: any write
  window that would touch a shared block first takes a bit-identical
  private copy (``paged.copy_block``), leaving every sharer's bytes
  untouched. ``cow_copies`` counts triggers (0 in steady state — the
  invariant, pinned by tests).
- **Reliability composition**: quarantine and preemption DECREF shared
  blocks instead of scrubbing while sharers remain (a poisoned sharer
  must not zero an innocent survivor's prefix); the last distrusted
  release scrubs-and-detaches. Chaos-corrupted blocks are poisoned in
  the tree immediately (no new sharer inherits the NaN). refs-0 cached
  blocks are reclaimed LRU under pool pressure, so retention never
  shrinks usable capacity. Snapshot v4 persists the tree + refcounts;
  resume rebuilds the share graph through replay (the first replayed
  sharer re-prefills and re-inserts, later ones hit).

Live weight hot-swap layer (round 17, DESIGN.md section 23 — the
fleet's rolling deploy rides it):

- **Double-buffered weights**: ``weights: {version -> params}`` with
  ``serving_version`` naming what new admissions take
  (``load_weights`` / ``set_serving_version``). Weights are traced
  OPERANDS of every compiled program, so a swap costs one device_put
  and zero recompiles; old versions stay resident while any live
  sequence pins them (unpinned non-serving versions retire).
- **Per-request version pin** (``_Seq.weights_version``): set ONCE at
  first admission, carried through replay/preemption/quarantine,
  snapshot v6, and handoff doc v4 — an in-flight sequence finishes on
  the version it STARTED on, wherever it lands. Dispatches group
  ready slots by pin (one dispatch per resident version); the
  sampling keys and per-slot gathers never reference batch
  composition, so the mixed-version batch is token-identical to each
  pin's single-version oracle. The radix prefix cache is
  version-partitioned (one root per version): block bytes are a
  function of the weights, so a v0 block is never a v1 hit.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.attention import chunk_attn, rope
from ..models.lm import LMParams, decode_attn
from ..ops.norm import layernorm
from ..runtime.guardrails import rows_finite
from ..runtime.policy import QosPolicy
from ..runtime.telemetry import FLIGHT_FILENAME
from ..runtime.tracing import SpanTracer
from ..runtime.workload import tenant_key
from ..runtime.weights import (BOOT_VERSION, architecture_diff,
                               model_fingerprint, same_architecture)
from ..runtime import wire
from .draft import draft_tokens
from .paged import (PagedKV, SCRATCH_BLOCK, copy_block, copy_block_rows,
                    corrupt_block as
                    _pool_corrupt_block, extract_blocks,
                    fused_decode_attn, gather_layer, implant_block,
                    init_pool, kv_bytes_per_token, pool_bytes,
                    scrub_blocks, write_chunk, write_rows)
from .prefix import PrefixCache
from .spill import SpillTier
from .sampling import check_sampling, check_speculation, make_pick

# poison operand values for the compiled steps (chaos nan_logits
# injection rides a runtime operand, so arming a fault never recompiles)
POISON_NONE = -1
POISON_ALL = -2

# the request-record event vocabulary (telemetry schema v4 ``request``
# kind; runtime/telemetry.py REQUEST_REQUIRED pins the KEY set, this
# names the transitions; "handoff" is the round-14 addition — a
# sequence leaving this engine via the single-sequence KV handoff,
# decode/fleet.py)
REQUEST_EVENTS = ("admitted", "preempted", "retried", "quarantined",
                  "completed", "rejected", "expired", "handoff")

# the single-sequence KV handoff wire format (export_sequence /
# import_sequence): one uid's written blocks + int8 scales + position +
# scheduler state, restored into a FOREIGN pool under that pool's block
# numbering. v1 (round 14, DESIGN.md section 20). v2 (round 15): the
# document carries ``t_first`` — the sequence's first-token timestamp —
# so a migrated request's completed record still reports its true
# ``ttft_s`` (schema v9, DESIGN.md section 21). v3 (round 16): the
# document is a WIRE contract, not just an in-process dict — every
# non-array value is JSON-safe (plain ints/floats/strings/lists/dicts/
# None), every array a numpy array AT THE STORAGE DTYPE — so it
# round-trips the versioned npz wire format (``runtime/wire.py``:
# per-array CRC-32, atomic publish) bit-identically across a process
# boundary; a mismatched version is rejected BEFORE any engine state is
# touched, like every other import_sequence check (DESIGN.md
# section 22). v4 (round 17): the document carries the sequence's
# ``weights_version`` pin and the fingerprint OF THAT VERSION — a
# migrated request decodes on its pinned weights even on a target
# already serving newer ones, so the importing engine must HOLD the
# pinned version (the rolling deploy's double-buffer guarantees it)
# and its fingerprint must match (DESIGN.md section 23). v5 (round
# 18): the document carries the sequence's ``trace_id`` — the causal
# identity minted once at admission (schema v12) — so a migrated
# request's records on the TARGET engine stitch into the same
# cross-process trace waterfall (DESIGN.md section 24). v6 (round
# 19): the document carries the sequence's ``tenant`` tag (schema
# v13) — a migrated request's per-tenant attribution survives the
# move, so the workload plane's noisy-tenant numbers stay honest
# through kills and deploys (DESIGN.md section 25).
# v7 (round 22): the config schema grew the spill-tier capacity keys
# (``spill_blocks`` / ``spill_restore_per_step`` / ``spill_low_water``)
# — engine-local capacity knobs, pool-size class, so two engines may
# disagree on them and still exchange sequences.
HANDOFF_VERSION = 7

# EngineConfig keys two engines may legitimately disagree on and still
# exchange sequences: pool SIZE is an engine-local capacity choice —
# device pool shape AND the host spill tier behind it (a spilled block
# restores bit-identically, so tier sizing never touches numerics).
# Every other key participates in the token-identity proof (sampling
# keys, chunk grouping — hence int8 requant history — kernel and
# speculation paths) and must match exactly; ``prefix_partial`` is
# deliberately NOT here — at int8 a sub-block share carries the
# donor's frozen scale, so the flag is a numerics key.
_HANDOFF_POOL_KEYS = ("n_blocks", "max_slots", "max_blocks_per_seq",
                      "spill_blocks", "spill_restore_per_step",
                      "spill_low_water")

# flight recorder: bounded ring of per-step scheduler digests, dumped
# atomically on quarantine / watchdog latch / chaos kill — the "what
# was the engine doing in the steps before the fault" record a
# post-mortem needs when the process (or the pool) is already gone.
# 256 steps of digests is a few hundred KB at worst; the ring bounds a
# long-lived engine by construction. The dump filename lives in
# runtime/telemetry.py (FLIGHT_FILENAME, re-exported here) so
# report --postmortem can discover the file without importing this
# (jax-heavy) module.
FLIGHT_RECORDER_STEPS = 256


class AdmissionError(RuntimeError):
    """A request was shed at submit time (bounded queue full, or a
    predicted deadline miss under a QoS policy) — the serving 503,
    distinct from the ValueError family (malformed requests) so
    callers can tell load shedding from bad input. ``reason`` names
    the shed cause (``queue_full`` / ``predicted_deadline_miss``) so
    the fleet router's shed records attribute it instead of guessing."""

    def __init__(self, msg: str, reason: str = "queue_full"):
        super().__init__(msg)
        self.reason = reason


def blocks_needed(prompt_len: int, max_new: int, block_size: int) -> int:
    """Full block reservation for one request: the final generated
    token is returned, never cached, so ``prompt_len + max_new - 1``
    positions round up to blocks. THE one definition — the engine's
    admission math and the fleet transports' remote capacity probes
    (``decode/worker.py``) must never disagree on this count."""
    return -(-(prompt_len + max_new - 1) // block_size)


def _buckets(limit: int) -> tuple[int, ...]:
    """Power-of-two sizes up to ``limit`` (``limit`` itself appended
    when it isn't one) — the static shape set for slots and chunks."""
    out = []
    b = 1
    while b < limit:
        out.append(b)
        b *= 2
    out.append(limit)
    return tuple(out)


def _bucket_for(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


@dataclass(frozen=True)
class EngineConfig:
    """Static decode-engine configuration (one compiled program set per
    config). ``block_size`` must be a power of two so power-of-two
    prefill chunks never straddle a block boundary (``paged.write_chunk``).
    ``n_blocks`` includes the reserved scratch block. ``temperature=0``
    is greedy; ``top_k=0`` / ``top_p=0`` disable those truncations.

    ``speculate`` is the per-step draft budget (0 = off): each decode
    dispatch becomes a ``speculate+1``-token verify program emitting
    the accepted greedy prefix (requires ``temperature == 0``;
    ``decode/draft.py``). ``kernel`` selects the cache-read path for
    decode/verify steps: ``"gather"`` (two-pass oracle:
    ``gather_paged_kv`` then ``decode_attn``) or ``"fused"`` (the
    Pallas block-table walk, single-device only — prefill keeps its
    chunked gather attention either way). ``prefix_cache`` enables the
    shared-prefix radix cache (``decode/prefix.py``) — host-side only,
    so the flag never changes a compiled program; it lives in the
    config because snapshot-resume must restore onto the same sharing
    policy.

    The KV memory hierarchy (round 22, DESIGN.md section 29):
    ``spill_blocks`` sizes the host-RAM spill tier in blocks
    (``decode/spill.py``; 0 = off, requires the prefix cache) —
    pool-pressure demotion moves refs-0 cached blocks there instead of
    discarding them, and a radix hit on a spilled edge restores the
    bytes through the implant program instead of re-prefilling.
    ``spill_restore_per_step`` budgets restores per engine step (the
    chunked-prefill stance: promotion must never stall running
    decodes — an over-budget admission keeps its partial restores and
    finishes next step). ``spill_low_water`` demotes proactively
    whenever the free list dips below it (0 = demand-only).
    ``prefix_partial`` enables SUB-BLOCK sharing: a partial-block
    radix hit row-copies the shared prefix rows into a private block
    (``paged.copy_block_rows``) and prefills past them. Exact at
    f32/bf16 (rows are per-row pure); at int8 the borrowed rows carry
    the donor's FROZEN per-block scale — deterministic, but not
    bit-equal to an unshared run — which is why the flag is off by
    default and a numerics key for handoff."""
    block_size: int = 16
    n_blocks: int = 65
    max_slots: int = 4
    max_blocks_per_seq: int = 8
    prefill_chunk: int = 16
    kv_dtype: str = "f32"
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    use_rope: bool = False
    speculate: int = 0
    kernel: str = "gather"
    prefix_cache: bool = True
    spill_blocks: int = 0
    spill_restore_per_step: int = 2
    spill_low_water: int = 0
    prefix_partial: bool = False

    @property
    def capacity(self) -> int:
        """Max cached positions per sequence."""
        return self.max_blocks_per_seq * self.block_size


@dataclass(frozen=True)
class ServePolicy:
    """Host-side scheduling/reliability knobs — unlike ``EngineConfig``
    these never touch a compiled program, so any policy mix shares the
    same program set. All zeros (the default) reproduce the round-9
    engine exactly.

    - ``queue_limit``: bounded waiting queue; a submit past it raises
      ``AdmissionError`` (reject-on-full, the serving 503). 0 = off.
    - ``deadline_steps``: per-request TTL in engine steps from submit;
      an unfinished request past it is failed with reason
      ``deadline`` (waiting OR running — queue time counts). 0 = off.
    - ``max_retries``: per-request budget for re-queuing a QUARANTINED
      request (replayed from its prompt + already-emitted tokens);
      budget exhausted -> reported FAILED. 0 = fail on first fault.
    - ``preempt_after_steps``: pool-pressure preemption — when the
      head-of-line request has a free slot but not its block
      reservation for this many consecutive steps, the YOUNGEST running
      sequence is evicted back to WAITING (resumed later, token-
      identically, via replay). Two guards bound the churn: the wait
      threshold is hysteresis (each eviction is preceded by that many
      steps of decode), and the LAST running sequence is never evicted
      — so the oldest resident always makes live progress and every
      request eventually completes. 0 = off (strict reserve-on-admit
      FCFS)."""

    queue_limit: int = 0
    deadline_steps: int = 0
    max_retries: int = 0
    preempt_after_steps: int = 0

    def __post_init__(self):
        for name in ("queue_limit", "deadline_steps", "max_retries",
                     "preempt_after_steps"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0, got "
                                 f"{getattr(self, name)}")


@dataclasses.dataclass
class _Seq:
    """Host-side per-sequence record (the scheduler's unit of state).

    ``emitted`` counts the ``out`` tokens already fed through the decode
    path since the last (re)admission. ``emitted < len(out)`` is the
    REPLAY state (after a retry / preemption / snapshot-resume): the
    prompt re-prefills, then each recorded token is teacher-forced
    through the decode step — the picks are discarded but the KV write
    history is bit-identical to the uninterrupted run's, which is what
    makes resume token-identical at every kv_dtype (int8 included: the
    quantization history is the write history)."""
    uid: int
    prompt: list[int]
    max_new: int
    out: list[int] = field(default_factory=list)
    prefilled: int = 0
    blocks: list[int] = field(default_factory=list)
    # nodes[i] is the PrefixNode backing blocks[i] when that leading
    # block is shared through the radix cache (a prefix-hit at
    # admission, or this sequence's own full prompt block transferred
    # into the tree at prefill completion); None = private. The shared
    # region is always a leading run of fully-prefilled prompt blocks,
    # which is why no write ever aims at it (writes land at or past
    # the prefill frontier).
    nodes: list = field(default_factory=list)
    emitted: int = 0
    retries: int = 0
    submit_step: int = 0
    admit_index: int = -1
    t_submit: float = field(default_factory=time.time)
    # the weights-version pin (round 17): None until the sequence
    # STARTS (first admission pins the engine's serving version); a
    # pinned sequence finishes on that version through every replay,
    # preemption, migration, and crash-resume — the hot-swap identity
    # contract (DESIGN.md section 23)
    weights_version: int | None = None
    # the causal identity (round 18, schema v12): minted ONCE at
    # submit (by the fleet router, or by the engine itself when no
    # router fronts it) and carried verbatim through replay,
    # preemption, quarantine, migration (handoff doc v5), crash-resume
    # (snapshot v7), and version pins — the stitch key every
    # request/span/router record for this sequence pins
    trace_id: str | None = None
    # the tenant tag (round 19, schema v13): set at submit (None
    # single-tenant) and carried exactly like trace_id — through
    # replay, preemption, migration (handoff doc v6), and crash-resume
    # (snapshot v8) — the per-tenant accounting key the workload
    # plane's report slices pin
    tenant: str | None = None

    @property
    def prompt_done(self) -> bool:
        return self.prefilled >= len(self.prompt)

    @property
    def replaying(self) -> bool:
        return self.emitted < len(self.out)

    @property
    def finished(self) -> bool:
        return len(self.out) >= self.max_new and not self.replaying


class DecodeEngine:
    """The serving loop. ``submit()`` queues prompts; ``step()`` runs one
    scheduler iteration (admit -> at most one prefill chunk -> one decode
    dispatch over every ready slot); ``run()`` drains everything and
    returns ``{uid: full token list}``. See the module docstring for the
    design; DESIGN.md section 15 for the state machine."""

    def __init__(self, params: LMParams, n_heads: int,
                 config: EngineConfig | None = None, mesh=None,
                 policy: ServePolicy | None = None, metrics=None,
                 qos: QosPolicy | None = None):
        cfg = config or EngineConfig()
        if cfg.block_size & (cfg.block_size - 1):
            raise ValueError(f"block_size must be a power of two, got "
                             f"{cfg.block_size}")
        if cfg.max_slots < 1 or cfg.max_blocks_per_seq < 1:
            raise ValueError("max_slots and max_blocks_per_seq must be "
                             ">= 1")
        if cfg.prefill_chunk < 1 or (cfg.prefill_chunk
                                     & (cfg.prefill_chunk - 1)):
            raise ValueError(
                f"prefill_chunk must be a power of two >= 1, got "
                f"{cfg.prefill_chunk} (power-of-two chunks are what "
                "keeps a chunk inside one block — paged.write_chunk)")
        if cfg.spill_blocks < 0:
            raise ValueError(f"spill_blocks must be >= 0, got "
                             f"{cfg.spill_blocks}")
        if cfg.spill_restore_per_step < 1:
            raise ValueError(
                f"spill_restore_per_step must be >= 1, got "
                f"{cfg.spill_restore_per_step} (a zero budget would "
                "starve every admission whose prefix spilled)")
        if cfg.spill_low_water < 0:
            raise ValueError(f"spill_low_water must be >= 0, got "
                             f"{cfg.spill_low_water}")
        if (cfg.spill_blocks > 0 or cfg.prefix_partial) \
                and not cfg.prefix_cache:
            raise ValueError(
                "spill_blocks / prefix_partial extend the radix prefix "
                "cache; they require prefix_cache=True")
        check_sampling(cfg.temperature, cfg.top_k, cfg.top_p, params.vocab)
        check_speculation(cfg.speculate, cfg.temperature)
        if cfg.kernel not in ("gather", "fused"):
            raise ValueError(f"kernel must be 'gather' or 'fused', got "
                             f"{cfg.kernel!r}")
        if cfg.kernel == "fused":
            if mesh is not None:
                raise ValueError(
                    "kernel='fused' is single-device (the head-sharded "
                    "TP pool runs the gather path); pass mesh=None or "
                    "kernel='gather'")
            from ..ops.pallas_paged_attention import interpret_supported
            if jax.default_backend() != "tpu" and not \
                    interpret_supported():
                raise ValueError(
                    "kernel='fused' needs the scalar-prefetch pallas "
                    "surface for its off-chip interpret mode; this jax "
                    "lacks it — use kernel='gather'")
        self.params = params
        self.n_heads = n_heads
        self.cfg = cfg
        self.mesh = mesh
        self.dh = params.d_model // n_heads
        self.kv_heads = params.blocks.wk.shape[1] // self.dh
        if mesh is not None:
            from ..parallel.lm import tp_shard_params
            from ..parallel.mesh import MODEL_AXIS, require_axes
            from ..parallel.transformer import _validate_tp
            require_axes(mesh, MODEL_AXIS)
            n = mesh.shape[MODEL_AXIS]
            _validate_tp(params.blocks, n_heads, n)
            if params.vocab % n:
                raise ValueError(f"vocab={params.vocab} not divisible by "
                                 f"model-axis size {n}")
            self.params = tp_shard_params(params, mesh)
        # -- live weight hot-swap (round 17, DESIGN.md section 23) --
        # double-buffered weights: version id -> params. The BOOT
        # weights are version 0; a deploy loads a checkpoint step as a
        # new version (``load_weights``) while the old one stays
        # resident, so in-flight sequences finish on the version they
        # started on (their ``_Seq.weights_version`` pin) while new
        # admissions take ``serving_version``. Every compiled program
        # takes params as a traced operand, so a swap never recompiles.
        self.weights: dict[int, LMParams] = {BOOT_VERSION: self.params}
        self.serving_version = BOOT_VERSION
        # the architecture anchor for load_weights: held VERSIONS come
        # and go (retirement), but the engine's shape never does — a
        # check against weights[BOOT_VERSION] would break the third
        # deploy, once retirement has dropped the boot buffers
        self._arch_fingerprint = model_fingerprint(self.params,
                                                   n_heads)
        # uid -> pin (None until first admission) — the request-record
        # attribution (telemetry v11: every request record carries
        # ``weights_version``); kept like prompt_lens, per uid
        self._pins: dict[int, int | None] = {}
        # -- fleet trace spine (round 18, DESIGN.md section 24) --
        # uid -> trace_id: the causal identity every request/span
        # record for the uid pins (schema v12). The engine mints one
        # at submit when the caller (a fleet router) didn't — the
        # nonce makes ids unique across engines/processes, the uid
        # suffix makes them unique within a run. Host metadata only:
        # no compiled program ever sees a trace id (the zero-new-
        # compiles overhead contract).
        self._trace_nonce = os.urandom(4).hex()
        self._traces: dict[int, str] = {}
        # uid -> tenant tag (round 19, schema v13): the per-tenant
        # attribution key every request/span record for the uid pins
        # (None single-tenant) — host metadata only, like _traces
        self._tenants: dict[int, str | None] = {}
        self.pool = self._init_pool()
        s, mb = cfg.max_slots, cfg.max_blocks_per_seq
        self.tables = np.full((s, mb), SCRATCH_BLOCK, np.int32)
        self.lengths = np.zeros((s,), np.int32)
        self.next_token = np.zeros((s,), np.int32)
        self.uids = np.zeros((s,), np.int32)
        self.slots: list[_Seq | None] = [None] * s
        self.waiting: collections.deque[_Seq] = collections.deque()
        self.finished: dict[int, list[int]] = {}
        self.failed: dict[int, dict] = {}     # uid -> {reason, retries}
        self.prompt_lens: dict[int, int] = {}  # uid -> len(prompt)
        self.free_blocks = list(range(1, cfg.n_blocks))
        self.slot_buckets = _buckets(cfg.max_slots)
        self.chunk_buckets = _buckets(cfg.prefill_chunk)
        self._programs: dict = {}
        self.compile_count = 0       # program builds (recompile guard)
        self.dispatch_count = 0
        self.steps = 0
        self.step_base = 0        # snapshot-resume offset (global step)
        self.tokens_generated = 0
        self._occ_sum = 0.0
        self._next_uid = 0
        self.policy = policy or ServePolicy()
        # -- tenant QoS (round 20, DESIGN.md section 26) --
        # None = the historical strict-FCFS engine exactly. All QoS
        # state is host-side scheduling metadata (like _head_blocked):
        # it never enters a compiled program or a sampling key, so a
        # policy change reorders ADMISSIONS, never a request's tokens.
        self.qos = qos
        # tenant_key -> tokens served (live + replayed emissions): the
        # WFQ virtual clock's numerator. Deterministic by construction
        # (token counts, never wall time), so the admission order a
        # policy produces replays identically with the tokens.
        self._tenant_served: dict[str, int] = {}
        # uids whose budget deferral was already recorded (one qos
        # record per uid per wait, not one per scheduler iteration)
        self._budget_deferred: set[int] = set()
        self.metrics = metrics           # TelemetryWriter (or None)
        # host-side audit ring (the durable trail is the telemetry
        # stream; this is for in-process inspection, bounded so a
        # long-lived engine can't grow it without limit)
        self.request_events: collections.deque[dict] = \
            collections.deque(maxlen=4096)
        self._corrupted: set[int] = set()   # chaos-poisoned block ids
        self.quarantined = 0
        self.retried = 0
        self.preempted = 0
        self.rejected = 0
        self.expired = 0
        self._admit_counter = 0     # admission order (preempt youngest)
        self._head_blocked = 0      # head-of-line pool-starved streak
        self._head_blocked_uid: int | None = None  # whose streak it is
        self._poison_uid = POISON_NONE   # armed for the NEXT step only
        # -- serving observability (round 11, DESIGN.md section 17) --
        # per-request lifecycle spans; the writer is looked up lazily
        # because run(metrics=...) re-binds it after construction
        # (trace_fn: every span record pins the uid's trace_id)
        self.tracer = SpanTracer(lambda: self.metrics,
                                 trace_fn=self._traces.get,
                                 tenant_fn=self._tenants.get)
        # KV-pool churn (cumulative; snapshot-persisted so they stay
        # monotonic across crash-resume) + free-block watermark window
        # (min/max since the last decode record)
        self.block_allocs = 0
        self.block_frees = 0
        self.block_scrubs = 0
        # speculative-decoding counters (cumulative; snapshot-persisted
        # like the churn trio): drafted = tokens proposed to verify
        # steps, accepted = drafted tokens the greedy verify kept (the
        # per-step bonus token is counted in tokens_generated, not here
        # — accept_rate = accepted / drafted is the drafter's score)
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        # -- shared-prefix KV reuse (round 13, DESIGN.md section 19) --
        # the radix tree over full prompt blocks; None = sharing off
        # (every block private, the round-9..12 engine exactly)
        # -- KV memory hierarchy (round 22, DESIGN.md section 29) --
        # the host-RAM spill tier behind the device pool; None = the
        # round-13 single-tier cache exactly (demotion discards)
        self.spill = (SpillTier(cfg.spill_blocks)
                      if cfg.prefix_cache and cfg.spill_blocks > 0
                      else None)
        self.prefix = (PrefixCache(cfg.block_size, spill=self.spill)
                       if cfg.prefix_cache else None)
        # cumulative, snapshot-persisted (monotonic across crash-resume
        # like the churn trio): hit blocks mapped at admission, prompt
        # tokens those hits skipped, copy-on-write triggers (0 in
        # steady state — the write-barrier invariant), and candidate
        # full blocks walked (the hit-rate denominator)
        self.prefix_hit_blocks = 0
        self.prefill_tokens_saved = 0
        self.cow_copies = 0
        self.prefix_lookup_blocks = 0
        # spill-tier counters (schema v17, cumulative and snapshot-
        # persisted like the churn trio — the TIER dies with the
        # process, these survive it): blocks demoted to host RAM,
        # wire bytes they serialized to, blocks promoted back through
        # the implant program, prompt tokens those promotions kept off
        # the prefill path, host wall-clock the promotions cost (the
        # stall budget's measured term), and sub-block partial hits
        self.spilled_blocks = 0
        self.spill_bytes = 0
        self.restores = 0
        self.restore_tokens_saved = 0
        self.restore_stall_s = 0.0
        self.partial_hits = 0
        # per-step promotion budget state (reset in step())
        self._restores_left = cfg.spill_restore_per_step
        self._step_restores = 0
        # prefill program dispatches (the shared-prefix win is provable
        # as a dispatch count: N sharers run ~1 prefill pass over the
        # shared prefix, not N); snapshot-persisted
        self.prefill_dispatches = 0
        # tokens emitted inside the CURRENT span per uid (decode/replay
        # segments emit many tokens per step under speculation; the
        # span record carries the count so a waterfall shows work, not
        # just wall clock)
        self._span_tokens: dict[int, int] = {}
        free0 = len(self.free_blocks)
        self._free_lo = self._free_hi = free0
        # flight recorder: per-step digests + the current step's
        # request events / dispatch evidence feeding the next digest
        self.flight: collections.deque[dict] = collections.deque(
            maxlen=FLIGHT_RECORDER_STEPS)
        self.flight_dir: str | None = None  # default: the metrics dir
        self._step_events: list[str] = []
        self._step_finite: list[bool] | None = None
        self._step_prefill_uid: int | None = None
        self._step_decode_uids: list[int] = []
        self._dump_reason: str | None = None

    # -- pool ----------------------------------------------------------

    def _init_pool(self) -> PagedKV:
        cfg = self.cfg
        pool = init_pool(self.params.n_layers, cfg.n_blocks,
                         self.kv_heads, cfg.block_size, self.dh,
                         cfg.kv_dtype)
        if self.mesh is None:
            return pool
        from ..parallel.mesh import MODEL_AXIS
        # head-sharded pool: each model shard caches its own KV heads
        arr = P(None, None, MODEL_AXIS, None, None)
        sc = None if pool.k_scale is None else P(None, None, MODEL_AXIS)
        return PagedKV(*(None if x is None
                         else jax.device_put(x, NamedSharding(self.mesh,
                                                              spec))
                         for x, spec in zip(pool, (arr, arr, sc, sc))))

    def _pool_specs(self) -> PagedKV:
        from ..parallel.mesh import MODEL_AXIS
        arr = P(None, None, MODEL_AXIS, None, None)
        sc = None if self.pool.k_scale is None else P(None, None,
                                                      MODEL_AXIS)
        return PagedKV(arr, arr, sc, sc)

    # -- compiled programs (one per (kind, bucket); bounded) -----------

    def _program(self, kind: str, bucket: int):
        key = (kind, bucket)
        fn = self._programs.get(key)
        if fn is None:
            self.compile_count += 1
            builder = {"decode": self._build_decode,
                       "prefill": self._build_prefill,
                       "verify": self._build_verify,
                       "cow": self._build_cow,
                       "cow_rows": self._build_cow_rows,
                       "implant": self._build_implant}[kind]
            fn = builder(bucket)
            self._programs[key] = fn
        self.dispatch_count += 1
        return fn

    def warm(self) -> int:
        """Prebuild the engine's full program set — every decode (and
        verify, when speculating) slot bucket, every prefill chunk
        bucket, and the implant program — so a freshly spawned engine
        pays its compiles BEFORE it takes traffic (the autoscaler's
        warm-before-traffic contract; also the worker protocol's
        ``warm`` op). Idempotent; returns ``compile_count``."""
        for b in self.slot_buckets:
            self._program("decode", b)
            if self.cfg.speculate:
                self._program("verify", b)
        for c in self.chunk_buckets:
            self._program("prefill", c)
        self._program("implant", 0)
        return self.compile_count

    def _attn_qkv(self, p: LMParams, l: int, a, positions):
        """Shared q/k/v projection + rotary for one layer: ``a [N, d]``
        -> ``q [N, h_loc, dh], k/v [N, kv_loc, dh]`` (local head counts
        read off the — possibly sharded — weight shapes, the
        ``cached_attn_step`` convention)."""
        blk = p.blocks
        dh = self.dh
        h_loc = blk.wq.shape[1] // dh
        kv_loc = blk.wk.shape[1] // dh
        q = (a @ blk.wq[l].T).reshape(-1, h_loc, dh)
        k = (a @ blk.wk[l].T).reshape(-1, kv_loc, dh)
        v = (a @ blk.wv[l].T).reshape(-1, kv_loc, dh)
        if self.cfg.use_rope:
            rot = jax.vmap(lambda x, pos: rope(x[:, None, :],
                                               pos[None])[:, 0, :])
            q = rot(q, positions)
            k = rot(k, positions)
        return q, k, v

    def _embed(self, p: LMParams, tokens, positions):
        if self.mesh is not None:
            from ..parallel.lm import vp_embed
            return vp_embed(p.wte, tokens) + p.wpe[positions]
        return p.wte[tokens] + p.wpe[positions]

    def _trunk(self, p: LMParams, pool: PagedKV, x, positions,
               write_attn):
        """The shared per-layer forward both compiled programs run —
        ONE definition, so prefill and decode numerics can never drift:
        LN, q/k/v, then the caller's ``write_attn(l, pool, q, k, v) ->
        (pool, y [N, h_loc, dh])`` (the only step where the two programs
        differ: batched single-token writes + per-slot gathers vs one
        slot's chunk write + chunk attention), output projection, FFN
        — with the Megatron psums when a mesh is set."""
        tp = self.mesh is not None
        if tp:
            from ..parallel.collectives import all_reduce
            from ..parallel.mesh import MODEL_AXIS
        blk = p.blocks
        n = x.shape[0]
        for l in range(p.n_layers):
            a = layernorm(blk.ln1[l], x)
            q, k, v = self._attn_qkv(p, l, a, positions)
            pool, y = write_attn(l, pool, q, k, v)
            y = y.reshape(n, -1) @ blk.wo[l].T
            x = x + (all_reduce(y, MODEL_AXIS) if tp else y)
            h = layernorm(blk.ln2[l], x)
            f = jnp.maximum(h @ blk.w1[l].T, 0.0) @ blk.w2[l].T
            x = x + (all_reduce(f, MODEL_AXIS) if tp else f)
        return pool, x

    def _logits(self, p: LMParams, h):
        """Tied head; under TP each shard scores its V/n vocab rows and
        the in-graph gather re-assembles the full row so the fused pick
        (keys fold uid/position, never the shard) draws identically
        everywhere — the output is replicated."""
        logits = h @ p.wte.T
        if self.mesh is not None:
            from ..parallel.collectives import all_gather
            from ..parallel.mesh import MODEL_AXIS
            logits = all_gather(logits, MODEL_AXIS, dim=1)
        return logits

    def _wrap(self, run, n_aux: int = 5, n_out: int = 3):
        """The (possibly shard_mapped) callable a compiled program is
        built from — split from ``_jit`` so the static attribution path
        (``decode_static_report``) can lower the SAME program without a
        second donation annotation. ``n_aux`` counts the replicated
        host operands after ``(params, pool)`` and ``n_out`` the
        returned arrays (the verify program carries two extra operands
        — drafts, draft lengths — and one extra output — the accepted
        counts — over decode/prefill's 5/3)."""
        if self.mesh is None:
            return run
        from ..parallel.lm import tp_decode_specs
        return jax.shard_map(
            run, mesh=self.mesh,
            in_specs=(tp_decode_specs(), self._pool_specs())
            + (P(),) * n_aux,
            out_specs=(self._pool_specs(),) + (P(),) * (n_out - 1),
            check_vma=False)

    def _jit(self, run, n_aux: int = 5, n_out: int = 3):
        """jit (or shard_map+jit under TP) with the pool donated: the
        engine replaces ``self.pool`` with the returned pool after every
        dispatch, so XLA may update the blocks in place instead of
        copying the whole pool per step — without donation each decode
        step would pay a full-pool allocate+copy, swamping the
        kv_bytes roofline term this engine exists to shrink."""
        return jax.jit(self._wrap(run, n_aux, n_out), donate_argnums=(1,))

    def _cached_attn(self, pool: PagedKV, l: int, q, tables, n_attend):
        """One single-query attention over the block-table cache — the
        ``kernel=`` knob. ``gather``: materialize each slot's
        contiguous view (``gather_layer``, the "gather" scope) and run
        ``decode_attn`` — the differential oracle. ``fused``: the
        Pallas block-table walk (``ops/pallas_paged_attention.py``),
        dequant folded in, no gathered layout in HBM — bit-identical
        to the oracle at f32 under jit. ``n_attend [b]`` is the
        per-slot attendable-position count (always >= 1)."""
        if self.cfg.kernel == "fused":
            with jax.named_scope("attn"):
                return fused_decode_attn(pool, l, q, tables, n_attend)
        ck, cv = jax.vmap(
            lambda t, _l=l, _pool=pool: gather_layer(_pool, _l, t)
        )(tables)                           # [b, Hkv_loc, T_cap, dh]
        with jax.named_scope("attn"):
            return decode_attn(q, ck, cv, n_attend)

    def _decode_fn(self, b: int):
        """The raw (un-jitted) decode-step body for a ``b``-slot bucket:
        write each slot's input token at its own position, attend over
        its gathered blocks, pick the next token in-graph — and return
        each row's all-finite logits flag (the serving guardrail: a
        poisoned sequence is detected the step it happens, on the same
        readback as the picks). ``poison`` is the chaos nan_logits
        operand: a uid (or POISON_ALL) whose row's logits are NaN'd
        in-graph; POISON_NONE leaves every row bit-identical (a false
        ``where`` selects the original value).

        Cost-attribution scopes (utils/trace_analysis ``SCOPES``): the
        body runs under ``decode/``, with ``gather``/``requant`` tagged
        inside the paged pool ops, ``attn`` on the score+AV math,
        ``head`` on the final LN + tied head (+ TP logits gather), and
        ``sample`` on the fused pick — so a hardware trace (or an HLO
        dump) splits one decode step's time by the roofline's own
        terms. Scopes are metadata only: the compiled program set is
        unchanged (the recompile guard pins it)."""
        cfg = self.cfg
        pick = make_pick(cfg.temperature, cfg.top_k, cfg.top_p,
                         self.params.vocab, cfg.seed)

        @jax.named_scope("decode")
        def run(p: LMParams, pool: PagedKV, tables, lengths, tokens,
                uids, poison):
            x = self._embed(p, tokens, lengths)             # [b, d]
            slot_phys = lengths // cfg.block_size
            off = lengths % cfg.block_size

            def write_attn(l, pool, q, k, v):
                phys = tables[jnp.arange(b), slot_phys]
                pool = write_rows(pool, l, phys, off, k, v, cfg.kv_dtype)
                return pool, self._cached_attn(pool, l, q, tables,
                                               lengths + 1)

            pool, x = self._trunk(p, pool, x, lengths, write_attn)
            with jax.named_scope("head"):
                logits = self._logits(p, layernorm(p.ln_f, x))
            bad = jnp.logical_or(uids == poison, poison == POISON_ALL)
            logits = jnp.where(bad[:, None],
                               jnp.asarray(jnp.nan, logits.dtype), logits)
            with jax.named_scope("sample"):
                picks = pick(logits, uids, lengths + 1)
            return pool, picks, rows_finite(logits)

        return run

    def _build_decode(self, b: int):
        return self._jit(self._decode_fn(b))

    def _verify_fn(self, b: int):
        """The speculative verify body for a ``b``-slot bucket:
        ``speculate + 1`` single-token decode sub-steps UNROLLED into
        one program — sub-step 0 feeds each slot's pending token, every
        later sub-step feeds the next drafted token, and the in-graph
        acceptance chain ``alive_i = alive_{i-1} and draft_i == pick_{i-1}``
        masks each drafted row's KV WRITE by its own acceptance (a dead
        row's scatter is redirected to the scratch block, the pad
        idiom). The sub-steps are sequential on purpose: each one reads
        the cache state its predecessor wrote — the same bytes the
        non-speculative engine would have read at that position — which
        is what makes speculative output bit-identical at every
        kv_dtype (int8's cross-row requant coupling rules out a
        position-parallel verify; the win here is one dispatch + one
        scheduler pass per ``1 + accepted`` tokens, and the rejected
        tail needs no rollback because it never landed).

        Returns ``(pool, picks [b, k+1], accepted [b], finite
        [b, k+1])``; the host emits ``picks[:, :accepted+1]`` and
        advances lengths by the same count."""
        cfg = self.cfg
        k = cfg.speculate
        pick = make_pick(cfg.temperature, cfg.top_k, cfg.top_p,
                         self.params.vocab, cfg.seed)

        @jax.named_scope("decode")
        def run(p: LMParams, pool: PagedKV, tables, lengths, tokens,
                uids, drafts, dlens, poison):
            rows = jnp.arange(b)
            alive = jnp.ones((b,), bool)
            acc = jnp.zeros((b,), jnp.int32)
            cur = tokens
            picks_all, finite_all = [], []
            for i in range(k + 1):
                pos = lengths + i
                x = self._embed(p, cur, pos)                 # [b, d]
                slot_phys = pos // cfg.block_size
                off = pos % cfg.block_size

                def write_attn(l, pool, q, kk, vv, _off=off,
                               _sp=slot_phys, _keep=alive, _i=i):
                    phys = tables[rows, _sp]
                    phys = jnp.where(_keep, phys, SCRATCH_BLOCK)
                    pool = write_rows(pool, l, phys, _off, kk, vv,
                                      cfg.kv_dtype)
                    return pool, self._cached_attn(pool, l, q, tables,
                                                   lengths + _i + 1)

                pool, x = self._trunk(p, pool, x, pos, write_attn)
                with jax.named_scope("head"):
                    logits = self._logits(p, layernorm(p.ln_f, x))
                bad = jnp.logical_or(uids == poison,
                                     poison == POISON_ALL)
                logits = jnp.where(bad[:, None],
                                   jnp.asarray(jnp.nan, logits.dtype),
                                   logits)
                with jax.named_scope("sample"):
                    pk = pick(logits, uids, pos + 1)
                picks_all.append(pk)
                finite_all.append(rows_finite(logits))
                if i < k:
                    d = drafts[:, i]
                    alive = jnp.logical_and(
                        alive, jnp.logical_and(i < dlens, d == pk))
                    acc = acc + alive.astype(jnp.int32)
                    cur = d
            return (pool, jnp.stack(picks_all, 1), acc,
                    jnp.stack(finite_all, 1))

        return run

    def _build_verify(self, b: int):
        return self._jit(self._verify_fn(b), n_aux=7, n_out=4)

    def _prefill_fn(self, c: int):
        """The raw prefill-chunk body for one slot: ``c`` prompt tokens
        enter the cache through the block table; the chunk's own causal
        attention runs against the gathered view
        (``models.attention.chunk_attn``). Returns the in-graph pick
        from the final row — used by the host only when the chunk
        completes the prompt. Same attribution scopes as the decode
        body, under ``prefill/``."""
        cfg = self.cfg
        pick = make_pick(cfg.temperature, cfg.top_k, cfg.top_p,
                         self.params.vocab, cfg.seed)

        @jax.named_scope("prefill")
        def run(p: LMParams, pool: PagedKV, table, pos0, tokens, uid,
                poison):
            positions = pos0 + jnp.arange(c)
            x = self._embed(p, tokens, positions)           # [c, d]

            def write_attn(l, pool, q, k, v):
                pool = write_chunk(pool, l, table, pos0, k, v,
                                   cfg.kv_dtype)
                ck, cv = gather_layer(pool, l, table)
                with jax.named_scope("attn"):
                    y = chunk_attn(q.transpose(1, 0, 2), ck, cv, pos0)
                return pool, y.transpose(1, 0, 2)

            pool, x = self._trunk(p, pool, x, positions, write_attn)
            with jax.named_scope("head"):
                h = layernorm(p.ln_f, x[-1:])               # last row
                logits = self._logits(p, h)
            bad = jnp.logical_or(uid == poison, poison == POISON_ALL)
            logits = jnp.where(bad,
                               jnp.asarray(jnp.nan, logits.dtype), logits)
            with jax.named_scope("sample"):
                nxt = pick(logits, uid[None], (pos0 + c)[None])
            return pool, nxt[0], rows_finite(logits)[0]

        return run

    def _build_prefill(self, c: int):
        return self._jit(self._prefill_fn(c))

    def _build_cow(self, _bucket: int):
        """The copy-on-write block copy (``paged.copy_block``) as one
        compiled program for every (src, dst) pair — block ids are
        traced operands, so privatizing never recompiles. Donated like
        the step programs (the copy must not pay a whole-pool
        allocate). Built lazily and only when a CoW actually fires,
        which steady state never does — the recompile-guard tests keep
        holding with the barrier armed."""
        return jax.jit(copy_block, donate_argnums=(0,))

    def _build_cow_rows(self, _bucket: int):
        """The sub-block share copy (``paged.copy_block_rows``) as one
        compiled program for every (src, dst, rows) triple — all three
        are traced operands, so a partial hit never recompiles. Donated
        like the step programs; built lazily on the first partial hit
        (``prefix_partial`` off keeps the program set byte-identical to
        the round-13 engine's)."""
        return jax.jit(copy_block_rows, donate_argnums=(0,))

    def _build_implant(self, _bucket: int):
        """The KV-handoff import copy (``paged.implant_block``) as one
        compiled program for every destination block — the block id is
        a traced operand, so importing a sequence never recompiles past
        the first handoff. Donated like the step programs. Built lazily
        on the first import (the "first migration wave" — the
        zero-new-compiles-after contract starts there)."""
        return jax.jit(implant_block, donate_argnums=(0,))

    # -- model identity (snapshots + KV handoff pin it) ----------------

    def model_meta(self, version: int | None = None) -> dict:
        """Model identity the snapshot AND the KV handoff pin: resume
        replays recorded tokens through the pinned version's weights,
        and an imported sequence's KV was written by the SOURCE's
        weights for that version — either under different weights
        silently breaks the token-identical contract. THE fingerprint
        definition lives in ``runtime/weights.py``
        (``model_fingerprint`` — shapes + the coarse embedding-row
        sum); this is a re-binding per held version. Default: the
        current serving version."""
        ver = self.serving_version if version is None else int(version)
        return model_fingerprint(self._params_for(ver), self.n_heads)

    # -- live weight hot-swap (round 17, DESIGN.md section 23) ---------

    def _params_for(self, version: int) -> LMParams:
        try:
            return self.weights[int(version)]
        except KeyError:
            raise RuntimeError(
                f"engine does not hold weights version {version} "
                f"(held: {sorted(self.weights)}) — a pinned sequence "
                "can only run where its version is resident") from None

    def pinned_versions(self) -> set[int]:
        """Versions some live (resident or waiting) sequence is pinned
        to — what ``load_weights``'s double-buffer retirement must
        keep."""
        pins = {s.weights_version for s in self.slots
                if s is not None and s.weights_version is not None}
        pins |= {s.weights_version for s in self.waiting
                 if s.weights_version is not None}
        return pins

    def load_weights(self, version: int, params: LMParams) -> dict:
        """Install ``params`` as weights version ``version`` —
        double-buffered: the previous versions stay resident while any
        live sequence pins them (an in-flight request must finish on
        its version), and unpinned non-serving versions retire to keep
        the buffer at ~2. The params arrive as device arrays (the
        ledger's restore already performed the one fresh-ownership
        device_put) and every compiled program takes them as a traced
        operand, so this call costs zero recompiles. Architecture must
        match the boot weights exactly — the pool layout and program
        set are shape functions. Idempotent for an already-held
        version with the same fingerprint."""
        if self.mesh is not None:
            raise ValueError(
                "load_weights is single-device (the fleet's rolling "
                "deploy runs single-device replicas; TP engines "
                "redeploy by restart)")
        version = int(version)
        new_fp = model_fingerprint(params, self.n_heads)
        if version in self.weights:
            held = self.model_meta(version)
            if held != new_fp:
                raise ValueError(
                    f"weights version {version} already held with a "
                    f"different fingerprint ({held} != {new_fp}) — "
                    "version ids are immutable once loaded")
            return new_fp
        if not same_architecture(self._arch_fingerprint, new_fp):
            raise ValueError(
                "weights architecture != engine architecture: "
                f"{architecture_diff(self._arch_fingerprint, new_fp)} "
                "— hot-swap requires the identical model shape (the "
                "KV pool and compiled programs are shape functions)")
        # double-buffer retirement: non-serving versions no live
        # sequence pins free their buffers now (their refs-0 cached
        # prefix blocks decay through the ordinary LRU)
        keep = self.pinned_versions() | {self.serving_version, version}
        for old in [v for v in self.weights if v not in keep]:
            if self.weights[old] is self.params:
                # the construction-time alias (static shape/vocab
                # reads, the ledger-restore template, the static cost
                # report) would otherwise pin the retired buffers for
                # the process lifetime — rebind it to the incoming
                # version; every such read is architecture-only, so
                # any held version serves it identically
                self.params = params
            del self.weights[old]
        self.weights[version] = params
        return new_fp

    def set_serving_version(self, version: int) -> None:
        """New admissions pin ``version`` from now on; sequences
        already pinned elsewhere are untouched (they keep decoding on
        their own resident version — the mixed-version engine the
        version-grouped dispatch below serves)."""
        version = int(version)
        if version not in self.weights:
            raise ValueError(
                f"cannot serve weights version {version}: not loaded "
                f"(held: {sorted(self.weights)}) — load_weights first")
        self.serving_version = version

    # -- single-sequence KV handoff (DESIGN.md section 20) -------------

    def export_sequence(self, uid: int, keep: bool = False) -> dict:
        """Export one RESIDENT fully-prefilled sequence as a handoff
        document: scheduler state (prompt, emitted tokens, position,
        pending next token) plus the WRITTEN blocks' bytes and int8
        scales at the storage dtype — everything a foreign engine needs
        to continue the sequence token-identically without replay. The
        sequence leaves this engine on the way out: shared prefix
        blocks DECREF (an innocent sharer's prefix is untouched — the
        quarantine stance, without the distrust), private blocks return
        to the free list clean. Generalizes the PR 5 snapshot from
        whole-engine metadata to one sequence WITH its KV content.

        ``keep=True`` is the SHIP half of an async live migration
        (round 22): the document is built at the current position but
        the sequence STAYS resident and keeps decoding while the
        snapshot ships — ``finish_export`` later evicts it and returns
        the delta tokens emitted during the ship window, which the
        target teacher-forces after importing the shipped document
        (the replay contract: forced tokens rebuild KV bit-identically,
        so the splice of shipped blocks + caught-up delta is the same
        KV the sync path would have shipped). No handoff event is
        emitted and no span closes until the commit — the sequence has
        not left yet."""
        if self.mesh is not None:
            raise ValueError(
                "KV handoff is single-device (the fleet runs "
                "single-device replicas; TP engines keep the "
                "whole-engine snapshot path)")
        slot = next((i for i, s in enumerate(self.slots)
                     if s is not None and s.uid == uid), None)
        if slot is None:
            raise ValueError(f"uid {uid} is not resident on this engine "
                             "(waiting/finished requests migrate by "
                             "replay, not handoff)")
        seq = self.slots[slot]
        if not seq.prompt_done:
            raise ValueError(
                f"uid {uid} is mid-prefill ({seq.prefilled}/"
                f"{len(seq.prompt)} tokens): handoff exports fully-"
                "prefilled sequences; an unprefilled request migrates "
                "by replay")
        pos = int(self.lengths[slot])
        nb_written = -(-pos // self.cfg.block_size)
        phys = [int(b) for b in seq.blocks[:nb_written]]
        bad = [b for b in phys if b in self._corrupted]
        if bad:
            raise ValueError(
                f"uid {uid} holds chaos-corrupted block(s) {bad}: a "
                "poisoned sequence must quarantine, not migrate the "
                "poison to an innocent engine")
        doc = {
            "handoff_version": HANDOFF_VERSION,
            # the pin travels (v4): the sequence's KV was written by
            # THIS version's weights, and the target must finish it
            # there — the fingerprint is the pinned version's
            "weights_version": int(seq.weights_version),
            "model": self.model_meta(seq.weights_version),
            "config": dataclasses.asdict(self.cfg),
            "uid": int(seq.uid),
            # the causal identity travels (v5): the target's records
            # stitch into the same trace waterfall
            "trace_id": seq.trace_id,
            # the tenant tag travels (v6): per-tenant attribution
            # survives the move
            "tenant": seq.tenant,
            "prompt": list(seq.prompt),
            "out": list(seq.out),
            "max_new": int(seq.max_new),
            "emitted": int(seq.emitted),
            "retries": int(seq.retries),
            "t_submit": float(seq.t_submit),
            "position": pos,
            "next_token": int(self.next_token[slot]),
            # the first-token mark travels with the sequence (handoff
            # v2) so the importing engine's completed record reports
            # the TRUE ttft_s, not a restarted clock
            "t_first": self.tracer.pop_first_token(seq.uid),
            "blocks_written": nb_written,
            "source_blocks": phys,     # the renumbering certificate
            **extract_blocks(self.pool, phys),
        }
        if keep:
            # the ship half: the doc captured t_first by POPPING the
            # mark — restore it, the sequence is still live here and
            # may yet complete locally (an aborted migration must
            # still report the true ttft_s)
            if doc["t_first"] is not None:
                self.tracer.mark_first_token(seq.uid, doc["t_first"])
            return doc
        self._event("handoff", seq.uid, reason="exported",
                    n_out=len(seq.out), position=pos)
        self.tracer.close(seq.uid, self.global_step, reason="handoff",
                          tokens=self._span_tokens.pop(seq.uid, 0))
        self._evict(slot)
        return doc

    def finish_export(self, uid: int) -> dict:
        """Commit half of an async live migration: the snapshot from
        ``export_sequence(uid, keep=True)`` has shipped, so take the
        sequence OFF this engine now and return the delta —
        ``{"status": "resident", "out": [...], "position": P}`` with
        the FULL token list as of the commit (the shipped document's
        ``out`` is a strict prefix; the difference is what the target
        teacher-forces to catch up). If the request finished, failed,
        or was preempted back to WAITING during the ship window, the
        migration aborts instead: the terminal/requeued state is
        reported (``finished`` / ``failed`` / ``waiting`` / ``gone``)
        and NOTHING is evicted — the request never left this engine,
        and the target discards its staged copy."""
        slot = next((i for i, s in enumerate(self.slots)
                     if s is not None and s.uid == uid), None)
        if slot is None:
            if uid in self.finished:
                return {"status": "finished"}
            if uid in self.failed:
                return {"status": "failed"}
            if any(s.uid == uid for s in self.waiting):
                return {"status": "waiting"}
            return {"status": "gone"}
        seq = self.slots[slot]
        out = [int(t) for t in seq.out]
        pos = int(self.lengths[slot])
        self._event("handoff", seq.uid, reason="exported",
                    n_out=len(out), position=pos)
        self.tracer.close(seq.uid, self.global_step, reason="handoff",
                          tokens=self._span_tokens.pop(seq.uid, 0))
        self.tracer.pop_first_token(seq.uid)   # travels with the doc
        self._evict(slot)
        return {"status": "resident", "out": out, "position": pos}

    def import_sequence(self, doc: dict) -> int:
        """Restore an ``export_sequence`` document into THIS engine's
        pool under THIS pool's block numbering: allocate the full block
        reservation, implant the written blocks' bytes (+ int8 scales,
        bit-exactly — the content is copied at the storage dtype, never
        round-tripped through f32), install the sequence into a free
        slot at its exported position, and transfer its full prompt
        blocks into the local radix tree so the NEXT local sharer hits
        them (cross-engine prefix reuse). Decode continues on the very
        next step — no replay, no prefill dispatch. Model fingerprint
        and the numerics-relevant config keys must match the source's
        (pool-size keys may differ; that is the point of renumbering)."""
        if self.mesh is not None:
            raise ValueError(
                "KV handoff is single-device (the fleet runs "
                "single-device replicas; TP engines keep the "
                "whole-engine snapshot path)")
        if doc.get("handoff_version") != HANDOFF_VERSION:
            raise ValueError(f"handoff version "
                             f"{doc.get('handoff_version')!r} != "
                             f"{HANDOFF_VERSION}")
        ver = int(doc["weights_version"])
        if ver not in self.weights:
            raise ValueError(
                f"engine does not hold weights version {ver} (held: "
                f"{sorted(self.weights)}) — the imported sequence is "
                "pinned there and would decode on the wrong weights")
        model = self.model_meta(ver)
        if doc["model"] != model:
            diff = {k: (doc["model"].get(k), model.get(k))
                    for k in set(model) | set(doc["model"])
                    if doc["model"].get(k) != model.get(k)}
            raise ValueError(
                f"model != handoff model: {diff} — the imported KV was "
                "written by the source's weights, so the identical "
                "model (same shape AND same init) is required for the "
                "token-identical contract")
        cfg = dataclasses.asdict(self.cfg)
        diff = {k: (doc["config"].get(k), cfg[k]) for k in cfg
                if k not in _HANDOFF_POOL_KEYS
                and doc["config"].get(k) != cfg[k]}
        if diff:
            raise ValueError(
                f"engine config != handoff config: {diff} (pool-size "
                f"keys {_HANDOFF_POOL_KEYS} may differ; every numerics "
                "key must match for token identity)")
        uid = int(doc["uid"])
        prompt = [int(t) for t in doc["prompt"]]
        max_new = int(doc["max_new"])
        if uid in self.finished or uid in self.failed \
                or any(s is not None and s.uid == uid for s in self.slots) \
                or any(s.uid == uid for s in self.waiting):
            raise ValueError(f"uid {uid} already in use")
        need = self._blocks_needed(len(prompt), max_new)
        if need > self.cfg.max_blocks_per_seq:
            raise ValueError(
                f"handoff needs {need} blocks, exceeding this engine's "
                f"max_blocks_per_seq {self.cfg.max_blocks_per_seq}")
        if len(prompt) + max_new - 1 > self.params.max_seq_len:
            raise ValueError("handoff exceeds max_seq_len")
        slot = next((i for i, s in enumerate(self.slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError("no free slot for handoff import (the "
                               "router checks capacity before "
                               "dispatching a handoff)")
        if need > len(self.free_blocks) and self.prefix is not None:
            self._reclaim_cached(need - len(self.free_blocks))
        if need > len(self.free_blocks):
            raise RuntimeError(
                f"handoff needs {need} blocks, {len(self.free_blocks)} "
                "free (the router checks capacity before dispatching)")
        blocks = [self.free_blocks.pop(0) for _ in range(need)]
        nb = int(doc["blocks_written"])
        fn_args = []
        for i in range(nb):
            args = [jnp.asarray(doc["k"][:, i]),
                    jnp.asarray(doc["v"][:, i])]
            if doc["k_scale"] is not None:
                args += [jnp.asarray(doc["k_scale"][:, i]),
                         jnp.asarray(doc["v_scale"][:, i])]
            fn_args.append(args)
        for i, args in enumerate(fn_args):
            fn = self._program("implant", 0)
            self.pool = fn(self.pool, jnp.int32(blocks[i]), *args)
        seq = _Seq(uid=uid, prompt=prompt, max_new=max_new,
                   out=[int(t) for t in doc["out"]],
                   retries=int(doc["retries"]),
                   submit_step=self.global_step,
                   weights_version=ver,
                   trace_id=(doc.get("trace_id")
                             or f"{self._trace_nonce}-{uid}"),
                   tenant=doc.get("tenant"))
        self._pins[uid] = ver
        self._traces[uid] = seq.trace_id
        self._tenants[uid] = seq.tenant
        seq.emitted = int(doc["emitted"])
        seq.t_submit = float(doc["t_submit"])
        seq.prefilled = len(prompt)
        seq.blocks = blocks
        self.prompt_lens[uid] = len(prompt)
        row = np.full((self.cfg.max_blocks_per_seq,), SCRATCH_BLOCK,
                      np.int32)
        row[:need] = blocks
        self.tables[slot] = row
        self.lengths[slot] = int(doc["position"])
        self.next_token[slot] = int(doc["next_token"])
        self.uids[slot] = uid
        self.slots[slot] = seq
        seq.admit_index = self._admit_counter
        self._admit_counter += 1
        self.block_allocs += need
        self._next_uid = max(self._next_uid, uid) + 1
        self._event("admitted", uid, reason="handoff",
                    position=int(doc["position"]), replay=0)
        # the span clock restarts at import (the resume stance: the
        # in-transit gap is visibly unaccounted rather than invented —
        # report --slo attributes it to `migration` via the router's
        # handoff record), but the first-token mark RIDES the document:
        # the first token really happened then, on the source
        if doc.get("t_first") is not None:
            self.tracer.mark_first_token(uid, float(doc["t_first"]))
        self.tracer.open(uid, "replay" if seq.replaying else "decode",
                         self.global_step)
        # cross-engine prefix reuse: the imported full prompt blocks
        # enter THIS engine's radix tree (late dedup applies — a local
        # twin already cached wins and the duplicate frees)
        self._cache_full_blocks(slot)
        return uid

    def release_request(self, uid: int) -> dict:
        """Take one live request OFF this engine (waiting or resident,
        prefilled or not) and return its replay entry — the rolling
        deploy's drain primitive for everything the KV handoff can't
        carry (mid-prefill or still-queued requests migrate by replay;
        fully-prefilled residents go through ``export_sequence``
        instead, which ships the KV). The entry is exactly what a
        peer's ``resume_request`` takes: replay re-prefills and
        teacher-forces on the PINNED version, so the moved request's
        remaining tokens stay bit-identical to its unmoved oracle."""
        uid = int(uid)
        seq = None
        for i, s in enumerate(self.waiting):
            if s.uid == uid:
                seq = s
                del self.waiting[i]
                break
        if seq is None:
            slot = next((i for i, s in enumerate(self.slots)
                         if s is not None and s.uid == uid), None)
            if slot is None:
                raise ValueError(f"uid {uid} is not live on this "
                                 "engine (finished/failed requests "
                                 "have nothing to drain)")
            seq = self._evict(slot)
        self._event("handoff", uid, reason="drained",
                    n_out=len(seq.out))
        self.tracer.close(uid, self.global_step, reason="drained",
                          tokens=self._span_tokens.pop(uid, 0))
        return {"uid": uid, "prompt": list(seq.prompt),
                "out": list(seq.out), "max_new": int(seq.max_new),
                "retries": int(seq.retries),
                "t_submit": float(seq.t_submit),
                "t_first": self.tracer.pop_first_token(uid),
                "weights_version": seq.weights_version,
                "trace_id": seq.trace_id,
                "tenant": seq.tenant}

    # -- scheduler -----------------------------------------------------

    def submit(self, prompt, max_new: int, uid: int | None = None,
               trace: str | None = None,
               tenant: str | None = None) -> int:
        """Queue one request. ``prompt`` is a list of token ids; the
        capacity checks run here so an impossible request fails at
        submit time, never mid-serve. ``trace`` is the caller-minted
        trace id (the fleet router mints at fleet admission); None
        mints one here — either way the id sticks to the uid for the
        request's whole cross-engine life (schema v12). ``tenant`` is
        the request's tenant tag (schema v13; None single-tenant),
        carried exactly like the trace id."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if any(not 0 <= t < self.params.vocab for t in prompt):
            raise ValueError("prompt token out of vocab range")
        # the final generated token is returned, never cached or embedded
        # (_blocks_needed counts the same way), so a request may exactly
        # fill its block reservation
        cached = len(prompt) + max_new - 1
        if cached > self.cfg.capacity:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} needs "
                f"{cached} cached positions, exceeding the per-sequence "
                f"cache capacity {self.cfg.capacity} "
                "(max_blocks_per_seq * block_size)")
        if cached > self.params.max_seq_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} needs "
                f"{cached} cached positions, exceeding max_seq_len "
                f"{self.params.max_seq_len}")
        if self._blocks_needed(len(prompt), max_new) > self.cfg.n_blocks - 1:
            raise ValueError("request needs more blocks than the pool "
                             f"holds ({self.cfg.n_blocks - 1} usable)")
        auto_uid = uid is None
        if auto_uid:
            uid = self._next_uid
        elif uid < 0:
            # negative uids collide with the poison operand sentinels
            # (POISON_NONE/POISON_ALL): uid -1 would match the idle
            # poison comparison and be NaN'd every step
            raise ValueError(f"uid must be >= 0, got {uid}")
        elif (uid in self.finished or uid in self.failed
              or any(s is not None and s.uid == uid for s in self.slots)
              or any(s.uid == uid for s in self.waiting)):
            # a duplicate uid would sample in lockstep with its twin
            # (the key folds the uid) and overwrite its finished entry
            raise ValueError(f"uid {uid} already in use")
        if (self.policy.queue_limit
                and len(self.waiting) >= self.policy.queue_limit):
            # reject-on-full: shed load at the door instead of growing
            # an unbounded queue every waiter times out in. An
            # auto-assigned uid is NOT consumed (_next_uid only
            # advances on acceptance) — so its rejected record carries
            # uid -1, not a number a LATER accepted request will reuse
            # (aliasing two requests in the per-uid audit trail)
            self.rejected += 1
            self._event("rejected", -1 if auto_uid else uid,
                        reason="queue_full",
                        queue_len=len(self.waiting))
            raise AdmissionError(
                f"waiting queue full ({len(self.waiting)} >= "
                f"queue_limit {self.policy.queue_limit}); request "
                f"uid {uid} shed")
        if (self.qos is not None and self.qos.predictive_shed
                and self.policy.deadline_steps > 0):
            # admission throttling by predicted deadline miss: when
            # even an OPTIMISTIC queue-position ETA (every engine step
            # serves max_slots requests' tokens in parallel, nobody
            # else's prefill costs anything) already blows the
            # deadline, admitting the request would only burn pool
            # blocks on work _expire_deadlines is certain to fail —
            # shed it at the door with the ETA on the record instead
            eta = self._eta_steps(len(prompt), max_new)
            if eta >= self.policy.deadline_steps:
                self.rejected += 1
                self._event("rejected", -1 if auto_uid else uid,
                            reason="predicted_deadline_miss",
                            eta_steps=eta,
                            deadline_steps=self.policy.deadline_steps,
                            queue_len=len(self.waiting))
                self._qos_event("predicted_miss_shed", tenant,
                                uid=-1 if auto_uid else uid,
                                eta_steps=eta,
                                deadline_steps=self.policy
                                .deadline_steps)
                raise AdmissionError(
                    f"predicted deadline miss (eta {eta} >= "
                    f"deadline_steps {self.policy.deadline_steps} "
                    f"steps); request uid {uid} shed",
                    reason="predicted_deadline_miss")
        self._next_uid = max(self._next_uid, uid) + 1
        self.prompt_lens[uid] = len(prompt)
        self._pins.setdefault(uid, None)    # pinned at first admission
        seq = _Seq(uid=uid, prompt=prompt, max_new=max_new,
                   submit_step=self.global_step,
                   trace_id=(trace if trace is not None
                             else f"{self._trace_nonce}-{uid}"),
                   tenant=tenant)
        self._traces[uid] = seq.trace_id
        self._tenants[uid] = tenant
        self.waiting.append(seq)
        # the queued span opens at t_submit — the same clock latency_s
        # measures from, so the waterfall's span sum reconciles with it
        self.tracer.open(uid, "queued", self.global_step, t=seq.t_submit)
        return uid

    def resume_request(self, uid: int, prompt, max_new: int, out=(),
                       retries: int = 0, t_submit=None,
                       submit_step=None, t_first=None,
                       weights_version=None,
                       trace: str | None = None,
                       tenant: str | None = None) -> int:
        """Re-enter a request from an engine snapshot
        (``decode/supervise.py``): queued for replay-resume — prompt
        re-prefilled, recorded ``out`` tokens teacher-forced, then live
        generation continues token-identically (the sampling keys fold
        ``(seed, uid, position)``, never the slot or the crash).
        Bypasses ``queue_limit`` (the request was admitted once — a
        crash must not shed it). ``weights_version`` carries the pin
        across the resume: a pinned request replays and finishes on
        the version it started on (the engine must hold it by
        admission time); None re-pins at admission — the request never
        started."""
        prompt = [int(t) for t in prompt]
        out = [int(t) for t in out]
        if uid < 0:
            raise ValueError(f"uid must be >= 0, got {uid}")
        if uid in self.finished or uid in self.failed \
                or any(s is not None and s.uid == uid for s in self.slots) \
                or any(s.uid == uid for s in self.waiting):
            raise ValueError(f"uid {uid} already in use")
        seq = _Seq(uid=int(uid), prompt=prompt, max_new=int(max_new),
                   out=out, retries=int(retries),
                   submit_step=(self.global_step if submit_step is None
                                else int(submit_step)),
                   weights_version=(None if weights_version is None
                                    else int(weights_version)),
                   # trace carries the causal identity across the
                   # resume (snapshot v7 / the caller's book persisted
                   # it); None mints fresh — a pre-v12 entry had none
                   trace_id=(trace if trace is not None
                             else f"{self._trace_nonce}-{int(uid)}"),
                   # the tenant rides the resume exactly like the
                   # trace id (snapshot v8 / handoff v6 persisted it)
                   tenant=tenant)
        self._pins[int(uid)] = seq.weights_version
        self._traces[int(uid)] = seq.trace_id
        self._tenants[int(uid)] = tenant
        if t_submit is not None:
            seq.t_submit = float(t_submit)
        if t_first is not None:
            # the snapshot persisted the first-token mark (v5): the
            # first token really happened then, so the resumed
            # request's completed record keeps its true ttft_s (the
            # crash GAP still shows as unaccounted span time)
            self.tracer.mark_first_token(seq.uid, float(t_first))
        self._next_uid = max(self._next_uid, int(uid)) + 1
        self.prompt_lens[seq.uid] = len(prompt)
        self.waiting.append(seq)
        # a resumed request's span clock restarts NOW: the crash gap is
        # deliberately unaccounted (the waterfall flags the request
        # unreconciled instead of inventing a phase for dead time)
        self.tracer.open(seq.uid, "queued", self.global_step)
        return seq.uid

    def _blocks_needed(self, t0: int, max_new: int) -> int:
        return blocks_needed(t0, max_new, self.cfg.block_size)

    # -- request lifecycle (telemetry schema v4 `request` records) -----

    @property
    def global_step(self) -> int:
        """Engine steps across crash-resumes: ``step_base`` (the
        snapshot step a resumed engine continues from) + in-process
        steps — the index chaos schedules and request records use."""
        return self.step_base + self.steps

    def _event(self, event: str, uid: int, reason: str | None = None,
               **extra) -> None:
        # telemetry v11: every request record carries the uid's
        # weights-version pin (None before first admission / for the
        # anonymous rejected uid -1) — the per-version attribution the
        # mixed-version report reads; v12: and its trace_id (None only
        # for requests that never entered — the anonymous rejected -1)
        rec = {"step": self.global_step, "uid": int(uid),
               "event": event, "reason": reason,
               "weights_version": self._pins.get(int(uid)),
               "trace_id": self._traces.get(int(uid)),
               "tenant": self._tenants.get(int(uid)), **extra}
        self.request_events.append(rec)
        # the flight recorder's per-step decision line (compact: the
        # digest ring is bounded memory, the durable trail is the
        # telemetry stream)
        self._step_events.append(
            f"{event} uid {uid}" + (f" ({reason})" if reason else ""))
        if self.metrics is not None:
            self.metrics.request(rec)

    def _qos_event(self, event: str, tenant, **extra) -> None:
        """One tenant-QoS scheduling decision record (telemetry v14
        ``qos`` kind): the step clock + the numbers that justified the
        decision — all deterministic, so the decision stream replays
        identically with the tokens."""
        if self.metrics is not None:
            self.metrics.qos({"step": self.global_step, "event": event,
                              "tenant": tenant, **extra})
        self._step_events.append(f"qos {event}"
                                 + (f" tenant {tenant}" if tenant
                                    else ""))

    def arm_poison(self, uid: int) -> None:
        """Arm the chaos nan_logits operand for the NEXT engine step:
        ``uid``'s logits row (every row for ``POISON_ALL``) comes out
        NaN, in-graph, zero recompiles. Consumed by that step."""
        self._poison_uid = int(uid)

    def corrupt_block(self, block: int) -> None:
        """Chaos ``corrupt_block``: poison one physical pool block
        (``paged.corrupt_block`` — NaN values, or NaN scales under
        int8) host-side between steps. The id is tracked so ANY
        release of the block (not just quarantine — a preemption or
        deadline expiry can evict the owner before its next dispatch
        flags the NaN) scrubs it instead of handing the poison to an
        innocent successor. A block the radix cache holds is POISONED
        in the tree immediately: no new sharer may match it (the fault
        must not propagate into future admissions), while its bytes are
        left alone until the last live sharer releases it (the
        decref-not-scrub contract — current sharers' own dispatches
        flag the NaN through the logits guardrail)."""
        self.pool = _pool_corrupt_block(self.pool, block)
        self._corrupted.add(int(block))
        if self.prefix is not None:
            node = self.prefix.node_for_block(int(block))
            if node is not None:
                node.poisoned = True

    def corrupt_spill(self, spill_id: int) -> bool:
        """Chaos ``corrupt_spill``: flip one byte of a HOST-TIER entry
        (``SpillTier.corrupt``) — the host-RAM bit rot the wire CRC
        ladder exists to catch. The damage is latent until a radix hit
        tries to restore the entry: ``take``'s CRC check raises, the
        edge detaches, and the restoring request quarantines
        (``corrupt_spill`` reason) while every survivor — including
        sharers of the RESIDENT prefix above the damaged edge — is
        untouched. Returns False when the entry no longer exists
        (already restored or dropped: the fault found nothing, exactly
        like poisoning an already-freed block)."""
        if self.spill is None:
            return False
        return self.spill.corrupt(int(spill_id))

    # -- scheduler (continued) -----------------------------------------

    def _eta_steps(self, prompt_len: int, max_new: int) -> int:
        """OPTIMISTIC engine steps from now until a newly submitted
        request would finish: all queued + resident remaining tokens
        plus its own, served max_slots per step (the engine's best
        case), plus its own prefill chunks. Deliberately a lower
        bound — predictive shedding must only fire on CERTAIN misses
        (an optimistic ETA past the deadline is a proof, a pessimistic
        one a guess). Deterministic: token counts and the step clock
        only."""
        work = max_new
        for s in self.slots:
            if s is not None:
                work += max(s.max_new - len(s.out), 0)
        for s in self.waiting:
            work += s.max_new
        chunks = -(-prompt_len // self.cfg.prefill_chunk)
        return -(-work // self.cfg.max_slots) + chunks

    def _resident_tokens(self) -> dict[str, int]:
        """Per-tenant RESIDENT reserved tokens: the sum of admitted-
        but-unfinished ``max_new`` across slots — the token-budget
        gate's measure (reservations, not emissions: a budget caps how
        much of the pool's future work one tenant may hold)."""
        out: dict[str, int] = {}
        for s in self.slots:
            if s is not None:
                tk = tenant_key(s.tenant)
                out[tk] = out.get(tk, 0) + s.max_new
        return out

    def _next_waiting_index(self) -> tuple[int, float | None]:
        """The admission order's ONE decision point: ``(index into
        waiting of the next request to admit, its virtual time)``.
        FCFS (no qos policy, or discipline fcfs) returns ``(0, None)``
        — the historical strict head-of-line engine. WFQ picks among
        each tenant's FIFO head the tenant with the smallest virtual
        time (served_tokens / weight), ties broken by (submit_step,
        uid) — deterministic by construction. A tenant whose resident
        reservation would exceed ``token_budget`` is skipped (recorded
        once per uid) unless EVERY candidate is over budget — the
        budget shapes order, it never deadlocks the pool."""
        if (self.qos is None or self.qos.discipline == "fcfs"
                or len(self.waiting) <= 1):
            return 0, None
        heads: dict[str, tuple[int, _Seq]] = {}
        for i, s in enumerate(self.waiting):
            heads.setdefault(tenant_key(s.tenant), (i, s))
        budget = self.qos.token_budget
        if budget > 0 and len(heads) > 1:
            resident = self._resident_tokens()
            under = {}
            for tk, (i, s) in heads.items():
                if resident.get(tk, 0) + s.max_new <= budget:
                    under[tk] = (i, s)
                elif s.uid not in self._budget_deferred:
                    self._budget_deferred.add(s.uid)
                    self._qos_event("budget_deferred", s.tenant,
                                    uid=s.uid,
                                    resident_tokens=resident.get(tk, 0),
                                    token_budget=budget)
            if under:
                heads = under

        def vtime(tk: str) -> float:
            return (self._tenant_served.get(tk, 0)
                    / self.qos.weight_of(tk))

        tk = min(heads, key=lambda k: (vtime(k), heads[k][1].submit_step,
                                       heads[k][1].uid))
        i, _ = heads[tk]
        return i, round(vtime(tk), 6)

    def _admit(self) -> int:
        """FCFS admission: move waiting requests into free slots while
        both a slot and the request's full block reservation are
        available (reserve-on-admit keeps steady-state serving
        preemption-free). A head-of-line request that doesn't fit
        blocks the queue — strict FCFS keeps admission deterministic.
        With ``policy.preempt_after_steps > 0``, a head-of-line request
        that has been pool-starved (free slot, not enough free blocks)
        for that many consecutive steps evicts the YOUNGEST running
        sequence back to WAITING (replay resumes it token-identically
        later); the wait threshold is the anti-thrash hysteresis.

        With the prefix cache on, admission first walks the radix tree:
        every hit block is mapped into the table (locked, skipping its
        prefill) and only the MISSED blocks draw on the free list —
        refs-0 cached blocks are reclaimed LRU on demand, so retention
        never starves admission (the "effective sequences" capacity
        multiplier: N sharers of a k-block prefix reserve k + N * tail
        blocks, not N * (k + tail))."""
        admitted = 0
        bumped = False
        while self.waiting:
            # the ONE head-selection point: FCFS index 0, or the WFQ
            # virtual-time pick (DESIGN.md section 26) — either way
            # the chosen request is "the head" for everything below
            # (streaks, preemption, the blocked-queue break)
            head_i, head_vt = self._next_waiting_index()
            seq = self.waiting[head_i]
            need = self._blocks_needed(len(seq.prompt), seq.max_new)
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            # the version this admission would run under: an existing
            # pin (replay/migration — the sequence already started on
            # that version) or the current serving version (a fresh
            # start pins HERE, not at submit: "in-flight finishes on
            # the version it STARTED on, new admissions take the
            # latest" — a queued request that never prefilled takes
            # the post-deploy weights)
            ver = (seq.weights_version if seq.weights_version is not None
                   else self.serving_version)
            hits = ([] if self.prefix is None
                    else self.prefix.match(seq.prompt, ver))
            # split the matched path at its spilled suffix (the device-
            # leaf demotion rule guarantees the suffix shape): resident
            # hits map straight into the table, spilled hits must
            # RESTORE into fresh device blocks first — they draw on the
            # free list exactly like misses; what the hit saves is the
            # prefill, not the block
            n_res = 0
            while n_res < len(hits) and not hits[n_res].spilled:
                n_res += 1
            resident, spilled_sfx = hits[:n_res], hits[n_res:]
            avail = len(self.free_blocks)
            if self.prefix is not None:
                # refs-0 cached blocks are reclaimable — minus the hit
                # nodes themselves (about to be locked, not evicted)
                avail += (self.prefix.evictable_blocks()
                          - sum(1 for n in resident if n.refs == 0))
            if need - n_res > avail:
                pa = self.policy.preempt_after_steps
                if pa > 0:
                    if self._head_blocked_uid != seq.uid:
                        # the streak belongs to ONE head: a new head
                        # (old one admitted/expired/shed) must earn its
                        # own hysteresis, not inherit the old streak
                        self._head_blocked = 0
                        self._head_blocked_uid = seq.uid
                    if not bumped:      # one streak tick per step
                        self._head_blocked += 1
                        bumped = True
                    if (self._head_blocked >= pa
                            and self._preempt_youngest()):
                        continue        # blocks freed: re-check the head
                break
            self._head_blocked = 0
            self._head_blocked_uid = None
            if spilled_sfx:
                step = self.global_step
                todo = spilled_sfx[:max(0, self._restores_left)]
                # pin the resident prefix (and each node as it comes
                # back) so restore-pressure demotion can't reclaim the
                # matched path out from under its own admission
                self.prefix.lock(resident, step)
                locked = list(resident)
                corrupt = None
                try:
                    for node in todo:
                        self._restore_node(node)
                        self.prefix.lock([node], step)
                        locked.append(node)
                except wire.WireError:
                    corrupt = todo[len(locked) - n_res]
                finally:
                    for n in locked:
                        self.prefix.release(n, step)
                if corrupt is not None:
                    # CRC caught a damaged host-tier entry: the edge
                    # (with its now-unreachable spilled descendants)
                    # leaves the tree, and the request that would have
                    # trusted those bytes quarantines from the queue —
                    # survivors never read them
                    self.free_blocks.extend(
                        self.prefix.detach_subtree(corrupt))
                    self._quarantine_waiting(head_i, "corrupt_spill")
                    continue
                if len(todo) < len(spilled_sfx):
                    # promotion budget exhausted: keep what restored
                    # (resident, refs-0, warm — next step's budget
                    # continues from there) and defer the admission —
                    # a restore burst must never stall running decodes
                    break
            del self.waiting[head_i]
            self._budget_deferred.discard(seq.uid)
            if head_i != 0:
                # a non-head-of-line admit is the WFQ decision made
                # visible: record the virtual time that won it
                self._qos_event("wfq_pick", seq.tenant, uid=seq.uid,
                                virtual_time=head_vt)
            if seq.weights_version is None:
                seq.weights_version = ver   # the pin: set ONCE, here
            self._pins[seq.uid] = seq.weights_version
            slot = free_slots[0]
            need_priv = need - len(hits)
            if hits:
                # lock BEFORE any eviction so the matched path can't be
                # reclaimed out from under its own admission
                self.prefix.lock(hits, self.global_step)
                self.prefix_hit_blocks += len(hits)
                self.prefill_tokens_saved += (len(hits)
                                              * self.cfg.block_size)
            if self.prefix is not None:
                self.prefix_lookup_blocks += self.prefix.match_cap(
                    len(seq.prompt))
                if need_priv > len(self.free_blocks):
                    self._reclaim_cached(need_priv
                                         - len(self.free_blocks))
            seq.nodes = list(hits)
            seq.blocks = [n.block for n in hits] + [
                self.free_blocks.pop(0) for _ in range(need_priv)]
            # the hit region is already prefilled CONTENT — the prefill
            # clock starts past it (>= 1 token always remains, so the
            # first pick still comes from the prefill program)
            seq.prefilled = len(hits) * self.cfg.block_size
            if self.cfg.prefix_partial:
                # sub-block sharing: the longest resident edge sharing
                # a PARTIAL leading run of the remaining tokens donates
                # its first m rows into this sequence's first private
                # block (one compiled row-masked copy — scales freeze
                # at share time), and the prefill clock starts past
                # them. need_priv >= 1 always (the final-token block
                # is never a hit), so the destination exists.
                part = self.prefix.partial_match(seq.prompt, hits, ver)
                if part is not None:
                    donor, m = part
                    fn = self._program("cow_rows", 0)
                    self.pool = fn(self.pool, jnp.int32(donor.block),
                                   jnp.int32(seq.blocks[len(hits)]),
                                   jnp.int32(m))
                    donor.last_use = self.global_step  # LRU touch
                    seq.prefilled += m
                    self.partial_hits += 1
                    self.prefill_tokens_saved += m
            self.block_allocs += need
            row = np.full((self.cfg.max_blocks_per_seq,), SCRATCH_BLOCK,
                          np.int32)
            row[:need] = seq.blocks
            self.tables[slot] = row
            self.lengths[slot] = 0
            self.uids[slot] = seq.uid
            self.slots[slot] = seq
            seq.admit_index = self._admit_counter
            self._admit_counter += 1
            self._event("admitted", seq.uid,
                        wait_steps=self.global_step - seq.submit_step,
                        replay=len(seq.out),
                        prefix_hit_blocks=len(hits))
            # admission closes whatever gap span the request sat in
            # (queued / preempt_gap / quarantine) and starts prefill
            self.tracer.transition(seq.uid, "prefill", self.global_step)
            admitted += 1
        return admitted

    def _reclaim_cached(self, n: int) -> None:
        """Convert up to ``n`` refs-0 cached blocks back into free-list
        blocks (LRU, ``prefix.evict_lru``) — the pool-pressure valve
        that makes retention free: cached capacity is always
        reclaimable capacity. A reclaimed block the chaos layer
        corrupted is scrubbed on the way out (the ANY-release scrub
        contract: a poisoned refs-0 cached block has no owner whose
        eviction would otherwise scrub it). With the spill tier armed,
        reclamation DEMOTES instead of discarding — same LRU order,
        same freed device blocks, but the bytes move to host RAM and
        the edges stay matchable."""
        if self.spill is not None:
            self._demote(n)
            return
        got = self.prefix.evict_lru(n, self.global_step)
        bad = [b for b in got if b in self._corrupted]
        if bad:
            self.pool = scrub_blocks(self.pool, bad)
            self._corrupted.difference_update(bad)
            self.block_scrubs += len(bad)
        self.free_blocks.extend(got)

    def _demote(self, n: int) -> None:
        """Spill up to ``n`` refs-0 cached device-leaves to the host
        tier (``prefix.spill_victims`` — LRU, non-detaching): each
        victim's bytes leave the device through ``extract_blocks`` as
        ONE wire document (storage dtype + int8 scales, per-array
        CRC-32 — ``decode/spill.py``), the node flips to spilled, and
        the device block joins the free list. Poisoned / chaos-
        corrupted victims NEVER spill: the tier stores only bytes the
        purity argument certifies — those detach-and-scrub exactly as
        the single-tier engine did. A tier-capacity overflow drops the
        oldest-spilled entries; their now-unrestorable edges detach
        from the tree (FIFO by spill id IS LRU by spill time — a
        spilled node's clock cannot advance until restore)."""
        for node in self.prefix.spill_victims(n, self.global_step):
            b = node.block
            if node.poisoned or b in self._corrupted:
                sub = self.prefix.detach_subtree(node)
                bad = [x for x in sub if x in self._corrupted]
                if bad:
                    self.pool = scrub_blocks(self.pool, bad)
                    self._corrupted.difference_update(bad)
                    self.block_scrubs += len(bad)
                self.free_blocks.extend(sub)
                continue
            got = extract_blocks(self.pool, [b])
            doc = {"k": got["k"][:, 0], "v": got["v"][:, 0],
                   "k_scale": (None if got["k_scale"] is None
                               else got["k_scale"][:, 0]),
                   "v_scale": (None if got["v_scale"] is None
                               else got["v_scale"][:, 0])}
            before = self.spill.bytes_spilled
            sid, dropped = self.spill.put(node, doc)
            self.prefix.mark_spilled(node, sid)
            self.spilled_blocks += 1
            self.spill_bytes += self.spill.bytes_spilled - before
            self.free_blocks.append(b)
            for victim in dropped:
                if victim.parent is not None:    # still in the tree
                    self.free_blocks.extend(
                        self.prefix.detach_subtree(victim))

    def _restore_node(self, node) -> None:
        """Promote ONE spilled node back into a fresh device block: CRC-
        verify the tier entry (``SpillTier.take`` — raises
        ``wire.WireError`` on damage, the caller's quarantine path),
        implant the bytes through the same donated compiled program the
        KV handoff uses, and re-enter the node into every block-indexed
        view with a fresh LRU clock. The host wall-clock this costs is
        the ``restore_stall_s`` term the per-step budget bounds; each
        restored block is ``block_size`` prompt tokens that did NOT
        re-prefill."""
        t0 = time.perf_counter()
        # secure the destination BEFORE consuming the tier entry: a
        # corrupt entry (WireError below) must leave the free list
        # untouched for the survivors
        if not self.free_blocks:
            self._reclaim_cached(1)
        if not self.free_blocks:
            raise RuntimeError(
                "spill restore needs a free block and the pool has "
                "none (admission checked availability — this is a "
                "bookkeeping bug)")
        doc = self.spill.take(node.spill_id)
        dst = self.free_blocks.pop(0)
        args = [jnp.asarray(doc["k"]), jnp.asarray(doc["v"])]
        if doc["k_scale"] is not None:
            args += [jnp.asarray(doc["k_scale"]),
                     jnp.asarray(doc["v_scale"])]
        fn = self._program("implant", 0)
        self.pool = fn(self.pool, jnp.int32(dst), *args)
        self.prefix.mark_restored(node, dst, self.global_step)
        self.restores += 1
        self.restore_tokens_saved += self.cfg.block_size
        self.restore_stall_s += time.perf_counter() - t0
        self._step_restores += 1
        self._restores_left -= 1

    def _cache_full_blocks(self, slot: int) -> None:
        """Transfer a slot's newly fully-prefilled FULL prompt blocks
        into the radix tree (the insert side of the prefix cache; runs
        after every prefill chunk). Only blocks whose every row came
        from prompt tokens are cacheable — a partial block's remaining
        rows will be decode writes, making its content a function of
        the sampled continuation, not the prompt. The inserting
        sequence keeps using the block and holds one ref (its table
        entry). When ANOTHER sequence already cached this exact token
        path (two sharers prefilled concurrently — neither admission
        could see the other's blocks), the slot remaps onto the cached
        block and frees its freshly-written duplicate: the bytes are
        identical by the purity argument, so the remap is invisible to
        the sequence and the pool just got one block richer."""
        if self.prefix is None:
            return
        seq = self.slots[slot]
        bs = self.cfg.block_size
        full = min(seq.prefilled, len(seq.prompt)) // bs
        step = self.global_step
        while len(seq.nodes) < full:
            i = len(seq.nodes)
            # inserts land under the sequence's PINNED version root:
            # block bytes are a function of the weights, so a block
            # prefilled under v is only ever a hit for v-admissions
            node = self.prefix.insert(seq.prompt, i, seq.blocks[i],
                                      step, version=seq.weights_version)
            if node is None:
                # parent path evicted/poisoned mid-prefill: the block
                # simply stays private (correct, just unshared)
                seq.nodes.append(None)
                continue
            if node.block != seq.blocks[i]:
                # late dedup: remap onto the cached twin, free ours
                self.free_blocks.append(seq.blocks[i])
                self.block_frees += 1
                self.block_allocs += 1      # the new shared mapping
                seq.blocks[i] = node.block
                self.tables[slot][i] = node.block
            self.prefix.lock([node], step)
            seq.nodes.append(node)

    def _cow_private(self, slot: int, lo: int, hi: int) -> None:
        """The copy-on-write barrier: before a dispatch whose KV write
        window covers table indices ``lo..hi`` of ``slot``, any block
        in that window still backed by a radix-tree node is privatized
        — a bit-identical device copy (``paged.copy_block``) into a
        fresh block, table remapped, node ref released — so no write
        can ever land in a block another sequence (or the cache) still
        reads. Structurally the scheduler never aims a write at a
        shared block (hits and inserts cover only fully-prefilled
        prompt blocks; every write lands at or past the prefill
        frontier), so this is an ENFORCED invariant, not a hot path:
        ``cow_copies`` stays 0 in steady state and the tests pin both
        the zero and the barrier's correctness when triggered by
        hand."""
        seq = self.slots[slot]
        if self.prefix is None or not seq.nodes:
            return
        for li in range(lo, min(hi + 1, len(seq.nodes))):
            node = seq.nodes[li]
            if node is None:
                continue
            if not self.free_blocks:
                self._reclaim_cached(1)
            if not self.free_blocks:
                raise RuntimeError(
                    "copy-on-write of a shared block needs a free "
                    "block and the pool has none (refs-0 cache empty)")
            dst = self.free_blocks.pop(0)
            fn = self._program("cow", 0)
            self.pool = fn(self.pool, jnp.int32(node.block),
                           jnp.int32(dst))
            self.prefix.release(node, self.global_step)
            seq.nodes[li] = None
            seq.blocks[li] = dst
            self.tables[slot][li] = dst
            self.block_allocs += 1          # the private replacement
            self.block_frees += 1           # the released shared map
            self.cow_copies += 1

    def _evict(self, slot: int, drop_shared: bool = False) -> _Seq:
        """Take a sequence off its slot and return its blocks (shared
        tail of release/quarantine/preempt/expire).

        Private blocks go back to the free list — scrubbed when the
        chaos layer marked them corrupted (an eviction that precedes
        the owner's next dispatch would otherwise hand the NaN to
        whoever reserves the block next), or wholesale under
        ``drop_shared`` (the quarantine stance: a poisoned run's
        PRIVATE bytes are not trusted).

        Shared blocks DECREF instead of free: while sharers remain,
        the bytes — an innocent survivor's prefix — are untouched (the
        decref-not-scrub contract). A clean last release leaves the
        block CACHED (refs-0, LRU-evictable: the cross-request reuse).
        A distrusted last release (``drop_shared`` or chaos-corrupted)
        scrubs it and detaches it — with its now-unreachable cached
        descendants — back to the free list. Released deepest-first so
        refcounts stay monotone root-to-leaf throughout."""
        seq = self.slots[slot]
        step = self.global_step
        to_free: list[int] = []
        to_scrub: set[int] = set()
        for li in reversed(range(len(seq.blocks))):
            b = seq.blocks[li]
            node = seq.nodes[li] if li < len(seq.nodes) else None
            if node is not None:
                self.prefix.release(node, step)
                if node.refs == 0 and (drop_shared
                                       or b in self._corrupted):
                    sub = self.prefix.detach_subtree(node)
                    to_scrub.update(x for x in sub
                                    if x == b or x in self._corrupted)
                    to_free.extend(sub)
            else:
                if drop_shared or b in self._corrupted:
                    to_scrub.add(b)
                to_free.append(b)
        if to_scrub:
            self.pool = scrub_blocks(self.pool, sorted(to_scrub))
            self._corrupted.difference_update(to_scrub)
            self.block_scrubs += len(to_scrub)
        self.block_frees += len(seq.blocks)
        self.free_blocks.extend(to_free)
        seq.blocks = []
        seq.nodes = []
        self.tables[slot] = SCRATCH_BLOCK
        self.lengths[slot] = 0
        self.next_token[slot] = 0
        self.uids[slot] = 0
        self.slots[slot] = None
        return seq

    def _release(self, slot: int) -> None:
        seq = self.slots[slot]
        self.finished[seq.uid] = seq.prompt + seq.out
        # ONE completion timestamp feeds both the latency record and
        # the final span close — that identity is the reconciliation
        # the report waterfall asserts. ttft_s decomposes the latency
        # at the first-token mark (schema v9); null when the first
        # token predates a crash-resume that lost the mark.
        now = time.time()
        t_first = self.tracer.pop_first_token(seq.uid)
        self._event("completed", seq.uid,
                    latency_s=round(now - seq.t_submit, 4),
                    ttft_s=(None if t_first is None
                            else round(t_first - seq.t_submit, 4)),
                    n_new=len(seq.out), retries=seq.retries)
        self.tracer.close(seq.uid, self.global_step, t=now,
                          n_new=len(seq.out),
                          tokens=self._span_tokens.pop(seq.uid, 0))
        self._evict(slot)

    def _requeue(self, seq: _Seq) -> None:
        """Send a live sequence back to WAITING for replay-resume:
        prefill restarts from zero, recorded ``out`` tokens will be
        teacher-forced (``_Seq.emitted``). ``submit_step`` is
        deliberately NOT reset: the deadline TTL measures from the
        ORIGINAL submission, so preemption/retry churn cannot extend a
        request's life past its deadline."""
        seq.prefilled = 0
        seq.emitted = 0
        self.waiting.append(seq)

    def _preempt_youngest(self) -> bool:
        """Evict the most recently admitted running sequence back to
        WAITING (pool-pressure preemption). Never evicts the LAST
        running sequence: with >= 2 residents the oldest is never the
        victim and always makes live progress (termination guarantee);
        evicting a lone resident would hand out replay-only windows in
        which a long sequence never advances — the one true livelock
        shape, excluded by construction. Returns False when no eviction
        is allowed (the head then waits for a completion)."""
        victims = [(s.admit_index, i) for i, s in enumerate(self.slots)
                   if s is not None]
        if len(victims) < 2:
            return False
        _, slot = max(victims)
        seq = self._evict(slot)
        self.preempted += 1
        self._event("preempted", seq.uid, reason="pool_pressure",
                    n_out=len(seq.out))
        self.tracer.transition(seq.uid, "preempt_gap", self.global_step,
                               reason="pool_pressure",
                               tokens=self._span_tokens.pop(seq.uid, 0))
        self._requeue(seq)
        self._head_blocked = 0
        return True

    def _quarantine(self, slot: int, reason: str) -> None:
        """The guardrail's remedy: free exactly this sequence's slot and
        blocks — SCRUBBED, because a poisoned cache may hold NaN/Inf
        the masks cannot neutralize — and either retry (budget left:
        re-queue for replay-resume; the fault's garbage pick was never
        appended, so the retried request re-generates that token
        cleanly) or report the uid FAILED with the reason. Every other
        running sequence is untouched: per-slot gathers and
        (seed, uid, position) sampling keys make survivors bit-identical
        to a run that never admitted this request."""
        seq = self.slots[slot]
        # drop_shared: the poisoned run's PRIVATE blocks are scrubbed
        # wholesale (its bytes are not trusted), but blocks shared
        # through the radix cache only DECREF while sharers remain —
        # the bytes are an innocent survivor's prefix, pure functions
        # of the shared tokens, and zeroing them would corrupt the
        # survivor (the scrub-vs-decref contract; the last distrusted
        # release detaches and scrubs inside _evict)
        self._evict(slot, drop_shared=True)
        # scrub the shared scratch block too: every table pads with
        # SCRATCH_BLOCK, so a corrupted scratch poisons every gather
        # (0*nan==nan) — scrubbing it here is what turns "scratch
        # corrupted" into one quarantine wave + clean retries instead
        # of a permanent all-requests failure. Scratch is semantically
        # all-zeros (only pad writes land there, always masked), so
        # the scrub is always safe.
        self.pool = scrub_blocks(self.pool, [SCRATCH_BLOCK])
        self._corrupted.discard(SCRATCH_BLOCK)
        self.block_scrubs += 1
        self.quarantined += 1
        # dump the flight recorder at the END of this engine step (so
        # the digest covering the quarantine itself is in the ring)
        self._dump_reason = f"quarantine uid {seq.uid} ({reason})"
        self.tracer.transition(seq.uid, "quarantine", self.global_step,
                               reason=reason,
                               tokens=self._span_tokens.pop(seq.uid, 0))
        if seq.retries < self.policy.max_retries:
            seq.retries += 1
            self.retried += 1
            self._event("quarantined", seq.uid, reason=reason,
                        retrying=True)
            self._event("retried", seq.uid, reason=reason,
                        attempt=seq.retries,
                        max_retries=self.policy.max_retries)
            self._requeue(seq)
            return
        self._event("quarantined", seq.uid, reason=reason,
                    retrying=False, retries=seq.retries)
        self.tracer.close(seq.uid, self.global_step, reason=reason)
        self.tracer.pop_first_token(seq.uid)    # terminal: forget
        self.failed[seq.uid] = {"reason": reason, "retries": seq.retries,
                                "n_out": len(seq.out)}

    def _quarantine_waiting(self, head_i: int, reason: str) -> None:
        """Quarantine a request that faulted BEFORE taking a slot — the
        spill-restore failure mode: its radix hit named a host-tier
        entry whose CRC check failed (``corrupt_spill``), so the
        request that would have trusted those bytes is the one
        quarantined, at its waiting-queue position. No slot, no blocks,
        no pool bytes were touched; the corrupt edge is already
        detached, so a retry re-matches WITHOUT it and re-prefills the
        lost span cleanly. Same retry-or-fail ladder as the running
        quarantine, same record vocabulary — a report reader sees one
        quarantine story with two entry points."""
        seq = self.waiting[head_i]
        del self.waiting[head_i]
        self._budget_deferred.discard(seq.uid)
        self.quarantined += 1
        self._dump_reason = f"quarantine uid {seq.uid} ({reason})"
        self.tracer.transition(seq.uid, "quarantine", self.global_step,
                               reason=reason,
                               tokens=self._span_tokens.pop(seq.uid, 0))
        if seq.retries < self.policy.max_retries:
            seq.retries += 1
            self.retried += 1
            self._event("quarantined", seq.uid, reason=reason,
                        retrying=True)
            self._event("retried", seq.uid, reason=reason,
                        attempt=seq.retries,
                        max_retries=self.policy.max_retries)
            self._requeue(seq)
            return
        self._event("quarantined", seq.uid, reason=reason,
                    retrying=False, retries=seq.retries)
        self.tracer.close(seq.uid, self.global_step, reason=reason)
        self.tracer.pop_first_token(seq.uid)    # terminal: forget
        self.failed[seq.uid] = {"reason": reason, "retries": seq.retries,
                                "n_out": len(seq.out)}

    def _expire_deadlines(self) -> None:
        """Per-request TTL: fail any request (waiting or running) still
        unfinished ``deadline_steps`` engine steps after submission —
        graceful degradation under overload beats unbounded tail
        latency. Runs before admission so an expired waiter never
        takes a slot."""
        dl = self.policy.deadline_steps
        if dl <= 0:
            return

        def expire(seq: _Seq) -> None:
            # the one place the deadline record/entry shape is built —
            # waiting and running expiries cannot fork
            self.expired += 1
            self._event("expired", seq.uid, reason="deadline",
                        n_out=len(seq.out))
            self.tracer.close(seq.uid, self.global_step,
                              reason="deadline",
                              tokens=self._span_tokens.pop(seq.uid, 0))
            self.tracer.pop_first_token(seq.uid)    # terminal: forget
            self.failed[seq.uid] = {"reason": "deadline",
                                    "retries": seq.retries,
                                    "n_out": len(seq.out)}
            self._budget_deferred.discard(seq.uid)

        def overdue(seq: _Seq) -> bool:
            return self.global_step - seq.submit_step >= dl

        for slot, seq in enumerate(self.slots):
            if seq is not None and overdue(seq):
                self._evict(slot)
                expire(seq)
        if any(overdue(seq) for seq in self.waiting):
            keep = collections.deque()
            for seq in self.waiting:
                if overdue(seq):
                    expire(seq)
                else:
                    keep.append(seq)
            self.waiting = keep

    def _emit(self, slot: int, pick: int) -> None:
        """Fold one picked token into a slot: the live path appends the
        pick; the REPLAY path discards it and teacher-forces the
        recorded token instead (the picks match bit-for-bit on a
        healthy replay — forcing just removes the need to assume it)."""
        seq = self.slots[slot]
        was_replaying = seq.replaying
        if seq.replaying:
            tok = seq.out[seq.emitted]
        else:
            tok = pick
            seq.out.append(tok)
            self.tokens_generated += 1
        seq.emitted += 1
        # the WFQ virtual clock: every emission (live or teacher-
        # forced replay — a migrated request's service on THIS engine
        # counts as this engine's service) advances its tenant's
        # served-token count
        tk = tenant_key(seq.tenant)
        self._tenant_served[tk] = self._tenant_served.get(tk, 0) + 1
        self.next_token[slot] = tok
        # the emission belongs to the CURRENT span (replay or decode
        # segment) — speculation makes steps multi-token, so span
        # records carry the count, not just the wall clock
        self._span_tokens[seq.uid] = self._span_tokens.get(seq.uid,
                                                           0) + 1
        if seq.finished:
            self._release(slot)
        elif was_replaying and not seq.replaying:
            # caught up: the teacher-forcing window ends, live decode
            # begins (a new decode SEGMENT span)
            self.tracer.transition(seq.uid, "decode", self.global_step,
                                   replayed=len(seq.out),
                                   tokens=self._span_tokens.pop(
                                       seq.uid, 0))

    @staticmethod
    def _maybe_capture(fn, *args) -> None:
        """The PR 2 capture hook, shared with the training launcher:
        when ``parallel.launcher.CAPTURE_COMPILED`` is armed, append
        this dispatch's optimized HLO so the named-scope attribution
        contract is asserted against the REAL compiled serving program
        (tests), not a reconstruction. None (the default) costs one
        attribute read per dispatch.

        The capture compile bypasses the persistent XLA cache: a
        deserialized executable's ``as_text()`` drops op_name metadata
        — exactly the scope names being asserted — and unlike the
        shard_map'd training programs (which the cache can't serialize)
        the single-device engine programs DO round-trip through it, so
        a warm tier-1 cache would void the contract test."""
        from ..parallel import launcher
        if launcher.CAPTURE_COMPILED is None:
            return
        old = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            launcher.CAPTURE_COMPILED.append(
                fn.lower(*args).compile().as_text())
        finally:
            jax.config.update("jax_compilation_cache_dir", old)

    def _prefill_step(self, slot: int) -> None:
        seq = self.slots[slot]
        remaining = len(seq.prompt) - seq.prefilled
        # largest power-of-two bucket that fits the remaining prompt:
        # chunk starts stay multiples of the chunk size, so no chunk
        # ever straddles a block boundary (paged.write_chunk's contract)
        c = max(b for b in self.chunk_buckets if b <= remaining)
        bs = self.cfg.block_size
        if seq.prefilled % bs:
            # a sub-block partial hit started the clock mid-block: cap
            # the chunk at the largest power of two that stays inside
            # the current block (write_chunk's single-block contract);
            # once the clock reaches the boundary, normal chunking
            # resumes — same greedy power-of-two discipline, just
            # anchored to the block edge instead of offset zero
            gap = bs - seq.prefilled % bs
            c = max(b for b in self.chunk_buckets if b <= min(c, gap))
        self._cow_private(slot, seq.prefilled // bs,
                          (seq.prefilled + c - 1) // bs)
        self.prefill_dispatches += 1
        fn = self._program("prefill", c)
        chunk = np.asarray(seq.prompt[seq.prefilled:seq.prefilled + c],
                           np.int32)
        args = (self._params_for(seq.weights_version), self.pool,
                jnp.asarray(self.tables[slot]),
                jnp.int32(seq.prefilled), jnp.asarray(chunk),
                jnp.int32(seq.uid), jnp.int32(self._poison_uid))
        self._maybe_capture(fn, *args)
        pool, nxt, ok = fn(*args)
        self.pool = pool
        self._step_prefill_uid = seq.uid
        self._step_finite = [bool(ok)]
        if not bool(ok):
            self._quarantine(slot, "nonfinite_logits")
            return
        seq.prefilled += c
        self._cache_full_blocks(slot)
        if seq.prompt_done:
            self.lengths[slot] = len(seq.prompt)
            # the chunk that completes the prompt hands the span clock
            # to the next phase BEFORE the emit below may release the
            # sequence outright (max_new == 1). ONE timestamp serves
            # the span boundary AND the first-token mark: the emit
            # below appends the first live token at exactly this
            # instant, which is what makes ttft_s reconcile with the
            # pre-first-token span sum (runtime/tracing.py). A
            # replaying sequence already emitted its first token in a
            # previous life — the mark is idempotent and replay never
            # re-marks here (its recorded first token is forced, not
            # picked).
            now = time.time()
            if not seq.replaying:
                self.tracer.mark_first_token(seq.uid, now)
            self.tracer.transition(
                seq.uid, "replay" if seq.replaying else "decode",
                self.global_step, t=now, tokens=c)
            self._emit(slot, int(nxt))
        else:
            # one span per prefill chunk, telescoping across the engine
            # steps spent on other slots in between
            self.tracer.transition(seq.uid, "prefill", self.global_step,
                                   tokens=c)

    def _marshal(self, ready: list[int]):
        """Bucket-pad the dispatch operands for ``ready``: pad rows
        point at the scratch block with zeroed length/token/uid, so
        their writes land in the pad row's designated dump and their
        idle uid never matches a poison operand."""
        b = _bucket_for(len(ready), self.slot_buckets)
        idx = ready + [0] * (b - len(ready))        # pad rows
        tables = self.tables[idx].copy()
        lengths = self.lengths[idx].copy()
        tokens = self.next_token[idx].copy()
        uids = self.uids[idx].copy()
        for j in range(len(ready), b):              # pads -> scratch
            tables[j] = SCRATCH_BLOCK
            lengths[j] = 0
            tokens[j] = 0
            uids[j] = 0
        return b, tables, lengths, tokens, uids

    def _version_groups(self, ready: list[int]) -> list[list[int]]:
        """Split the ready slots by weights-version pin — ONE dispatch
        per resident version (a compiled program runs one params
        operand). Slot order is preserved within each group and the
        common single-version case degenerates to the old whole-batch
        dispatch; token identity is untouched either way because the
        sampling keys and per-slot gathers never reference the batch
        composition (the migration identity argument, applied to the
        mixed-version engine a rolling deploy creates)."""
        groups: dict[int, list[int]] = {}
        for slot in ready:
            groups.setdefault(self.slots[slot].weights_version,
                              []).append(slot)
        return [groups[v] for v in sorted(groups)]

    def _decode_step(self, ready: list[int]) -> None:
        for group in self._version_groups(ready):
            self._decode_dispatch(group)

    def _decode_dispatch(self, ready: list[int]) -> None:
        bs = self.cfg.block_size
        for slot in ready:                  # the CoW write barrier
            self._cow_private(slot, int(self.lengths[slot]) // bs,
                              int(self.lengths[slot]) // bs)
        params = self._params_for(self.slots[ready[0]].weights_version)
        b, tables, lengths, tokens, uids = self._marshal(ready)
        fn = self._program("decode", b)
        args = (params, self.pool, jnp.asarray(tables),
                jnp.asarray(lengths), jnp.asarray(tokens),
                jnp.asarray(uids), jnp.int32(self._poison_uid))
        self._maybe_capture(fn, *args)
        pool, picks, ok = fn(*args)
        self.pool = pool
        picks = np.asarray(picks)
        ok = np.asarray(ok)
        self._step_decode_uids += [self.slots[s].uid for s in ready]
        flags = [bool(ok[j]) for j in range(len(ready))]
        self._step_finite = (flags if self._step_finite is None
                             else self._step_finite + flags)
        for j, slot in enumerate(ready):
            if not bool(ok[j]):      # pad rows are never in `ready`
                self._quarantine(slot, "nonfinite_logits")
                continue
            self.lengths[slot] += 1
            self._emit(slot, int(picks[j]))

    # -- speculative decoding (DESIGN.md section 18) -------------------

    def _draft_for(self, seq: _Seq, budget: int) -> tuple[list[int], int]:
        """Up to ``budget`` draft tokens for one slot, plus how many of
        them are teacher-forced REPLAY tokens. During replay the
        recorded continuation IS the draft (teacher-forcing through
        the verify path — all accepted on a healthy replay, so resume
        re-speculates at full width); past the recorded window (and for
        live sequences) the n-gram prompt-copy drafter proposes from
        the full known history. Both sources are pure functions of
        ``prompt + out`` — the re-draft-identically contract. The
        replay count lets ``_verify_step`` keep teacher-forced tokens
        out of ``drafted_tokens``/``accepted_tokens``: they are
        accepted by construction, not by drafter skill, and a
        crash-resume already restored them into the counters once."""
        if budget <= 0:
            return [], 0
        rec = seq.out[seq.emitted:seq.emitted + budget]
        if len(rec) < budget:
            guess = draft_tokens(seq.prompt + seq.out,
                                 budget - len(rec))
            return rec + guess[:budget - len(rec)], len(rec)
        return rec[:budget], budget

    def _verify_step(self, ready: list[int]) -> None:
        for group in self._version_groups(ready):
            self._verify_dispatch(group)

    def _verify_dispatch(self, ready: list[int]) -> None:
        """The speculative decode dispatch: draft per slot (capped so
        accepted emissions can never outrun ``max_new`` or the block
        reservation — a verify step writes one KV row per emitted
        token, the non-speculative 1:1), run the verify program once,
        then emit each slot's ``1 + accepted`` greedy tokens. A
        non-finite flag anywhere in a slot's USED window (sub-steps
        ``0..accepted``) quarantines the whole step for that uid —
        nothing is emitted, the drafted tail is rolled back whole
        (its masked rows only ever landed in the uid's own blocks,
        which quarantine frees and scrubs)."""
        k = self.cfg.speculate
        bs = self.cfg.block_size
        for slot in ready:
            # the verify window writes positions lengths..lengths+k
            # (rejected rows land on scratch, but the barrier guards
            # the whole window — a masked write must never even AIM at
            # a shared block)
            self._cow_private(slot, int(self.lengths[slot]) // bs,
                              (int(self.lengths[slot]) + k) // bs)
        b, tables, lengths, tokens, uids = self._marshal(ready)
        drafts = np.zeros((b, k), np.int32)
        dlens = np.zeros((b,), np.int32)
        replayed = np.zeros((b,), np.int32)
        for j, slot in enumerate(ready):
            seq = self.slots[slot]
            # emissions this step <= max_new - emitted (the final
            # token of a sequence is returned, never cached, so the
            # row budget works out to exactly the capacity check
            # submit() performed)
            d, n_rec = self._draft_for(
                seq, min(k, seq.max_new - seq.emitted - 1))
            dlens[j] = len(d)
            drafts[j, :len(d)] = d
            replayed[j] = n_rec
            self.drafted_tokens += len(d) - n_rec
        fn = self._program("verify", b)
        params = self._params_for(self.slots[ready[0]].weights_version)
        args = (params, self.pool, jnp.asarray(tables),
                jnp.asarray(lengths), jnp.asarray(tokens),
                jnp.asarray(uids), jnp.asarray(drafts),
                jnp.asarray(dlens), jnp.int32(self._poison_uid))
        self._maybe_capture(fn, *args)
        pool, picks, acc, ok = fn(*args)
        self.pool = pool
        picks = np.asarray(picks)
        acc = np.asarray(acc)
        ok = np.asarray(ok)
        self._step_decode_uids += [self.slots[s].uid for s in ready]
        flags = []
        for j, slot in enumerate(ready):
            m = int(acc[j])
            fine = bool(ok[j, :m + 1].all())
            flags.append(fine)
            if not fine:
                self._quarantine(slot, "nonfinite_logits")
                continue
            self.accepted_tokens += max(0, m - int(replayed[j]))
            self.lengths[slot] += m + 1
            for t in range(m + 1):
                if self.slots[slot] is None:
                    break           # released at its final emission
                self._emit(slot, int(picks[j, t]))
        self._step_finite = (flags if self._step_finite is None
                             else self._step_finite + flags)

    def step(self, prefill_only: bool = False) -> bool:
        """One scheduler iteration: expire deadlines, admit (with
        pool-pressure preemption when armed), at most ONE prefill chunk
        (so a long prompt never stalls running decodes for more than a
        chunk), then one decode dispatch over every ready slot. Returns
        whether any work ran. An armed chaos poison operand applies to
        exactly this step's dispatches.

        ``prefill_only`` skips the decode dispatch — the fleet's
        prefill tier (``decode/fleet.py``): a prompt that completes
        emits its first pick from the prefill program and then PARKS
        until the router ships it to a decode engine, so a
        prefill-tier engine never compiles or dispatches a decode
        program at all (the disaggregation dispatch proof, both
        directions)."""
        # _step_events is NOT reset here: shed/rejected events from
        # between-step submissions (and a prior dispatch-free step)
        # belong to the next digest taken — resetting would drop them
        # from the flight recorder entirely
        self._step_finite = None
        self._step_prefill_uid = None
        self._step_decode_uids = []
        # spill-tier housekeeping: a fresh promotion budget each step
        # (the restore analogue of one-prefill-chunk-per-step), and the
        # proactive low-watermark demotion — keep a cushion of free
        # blocks so admission bursts don't pay the demotion walk inline
        self._restores_left = self.cfg.spill_restore_per_step
        self._step_restores = 0
        if (self.spill is not None and self.cfg.spill_low_water > 0
                and len(self.free_blocks) < self.cfg.spill_low_water):
            self._demote(self.cfg.spill_low_water
                         - len(self.free_blocks))
        self._expire_deadlines()
        self._admit()
        did = False
        pre = next((i for i, s in enumerate(self.slots)
                    if s is not None and not s.prompt_done), None)
        if pre is not None:
            self._prefill_step(pre)
            did = True
        ready = ([] if prefill_only else
                 [i for i, s in enumerate(self.slots)
                  if s is not None and s.prompt_done])
        if ready:
            # speculation on -> every decode dispatch is a verify
            # dispatch (one program kind per bucket; a zero-draft step
            # degenerates to plain decode inside the same program, so
            # the steady-state compile surface stays bounded)
            if self.cfg.speculate:
                self._verify_step(ready)
            else:
                self._decode_step(ready)
            did = True
        if self._step_restores:
            # budget-deferred admission: restores ran compiled implant
            # work this step even if no prefill/decode dispatched —
            # that IS progress (run()'s stall guard must see it; the
            # deferred head admits once the budget catches up)
            did = True
        if did:
            self.steps += 1
            self._poison_uid = POISON_NONE      # one-step fault window
            active = sum(s is not None for s in self.slots)
            self._occ_sum += active / self.cfg.max_slots
            free = len(self.free_blocks)
            self._free_lo = min(self._free_lo, free)
            self._free_hi = max(self._free_hi, free)
        if did or self._step_events:
            # a dispatch-free step that only expired/shed requests is
            # still a scheduler decision the post-mortem needs
            self.flight.append(self._flight_digest())
            self._step_events = []
        if self._dump_reason is not None:
            # a quarantine happened this step: dump now that the step's
            # own digest is in the ring ("the steps UP TO the fault")
            self.dump_flight_recorder(self._dump_reason)
            self._dump_reason = None
        return did

    @property
    def active(self) -> int:
        return sum(s is not None for s in self.slots)

    def tenant_load(self) -> dict[str, int]:
        """Per-tenant LIVE request counts (waiting + resident; None
        tenants excluded) — the in-flight half of the per-tenant ops
        surface (schema v13): rides the handle digest so the fleet
        status doc's tenants block costs zero extra round-trips.
        O(slots + waiting) host work, empty dict single-tenant."""
        load: dict[str, int] = {}
        for seq in list(self.waiting) + [s for s in self.slots
                                         if s is not None]:
            if seq.tenant is not None:
                load[seq.tenant] = load.get(seq.tenant, 0) + 1
        return load

    def mean_occupancy(self) -> float:
        return self._occ_sum / self.steps if self.steps else 0.0

    def kv_pool_utilization(self) -> float:
        """Non-reclaimable fraction of the usable pool. refs-0 CACHED
        blocks count as free: the radix cache retains them off the
        free list, but admission reclaims them LRU on demand, so they
        are admissible capacity — without the correction a long-lived
        prefix-cached engine serving diverse prompts reads as
        permanently exhausted once the pool has cycled through the
        cache. The raw ``free_blocks`` keys keep their literal
        free-list meaning (the watermark window and churn math depend
        on it); ``prefix_evictable_blocks`` rides the record so the
        two readings reconcile."""
        usable = self.cfg.n_blocks - 1
        free = len(self.free_blocks)
        if self.prefix is not None:
            free += self.prefix.evictable_blocks()
        return (usable - free) / usable

    def live_tokens(self) -> int:
        """Cached positions currently holding real KV, summed over
        active slots. ``lengths[slot]`` only starts counting at prompt
        completion (the decode path's position clock), so a
        mid-prefill slot's written positions are its ``prefilled``
        count — take the max of the two clocks."""
        return sum(max(int(self.lengths[i]), s.prefilled)
                   for i, s in enumerate(self.slots) if s is not None)

    def kv_fragmentation(self) -> float:
        """Unused fraction of RESERVED block capacity: reserve-on-admit
        hands each request its whole block budget at admission, so a
        freshly-admitted long request 'holds' capacity it hasn't
        written yet. ``1 - live_tokens / (live_blocks * block_size)``;
        0.0 with nothing resident."""
        live_blocks = sum(len(s.blocks) for s in self.slots
                          if s is not None)
        if not live_blocks:
            return 0.0
        return 1.0 - self.live_tokens() / (live_blocks
                                           * self.cfg.block_size)

    def kv_bytes_stored(self) -> int:
        """Live-token KV bytes at the engine's storage dtype — the
        measured form of the roofline's ``B * kv_bytes`` term."""
        return int(self.live_tokens() * kv_bytes_per_token(
            self.cfg.kv_dtype, self.params.n_layers, self.kv_heads,
            self.dh))

    def telemetry_record(self, tokens_per_sec=None) -> dict:
        """One schema-v5 ``decode`` record (``runtime/telemetry.py``
        ``DECODE_REQUIRED`` contract; the reliability counters ride as
        extra keys). Reading a record CONSUMES the free-block watermark
        window: low/high water describe the span since the previous
        record (the cadence envelope), then reset to the instantaneous
        value."""
        free = len(self.free_blocks)
        lo, hi = self._free_lo, self._free_hi
        self._free_lo = self._free_hi = free
        return {
            "step": self.global_step,
            "tokens_per_sec": tokens_per_sec,
            "batch_occupancy": round(self.active / self.cfg.max_slots, 4),
            "kv_pool_utilization": round(self.kv_pool_utilization(), 4),
            "free_blocks": free,
            "free_blocks_low_water": lo,
            "free_blocks_high_water": hi,
            "block_allocs": self.block_allocs,
            "block_frees": self.block_frees,
            "block_scrubs": self.block_scrubs,
            "kv_fragmentation": round(self.kv_fragmentation(), 4),
            "kv_bytes_stored": self.kv_bytes_stored(),
            "active": self.active,
            "waiting": len(self.waiting),
            "tokens_generated": self.tokens_generated,
            "kv_dtype": self.cfg.kv_dtype,
            # extra (v11): which weights version new admissions take —
            # a deploy shows up as this stepping between records
            "serving_version": self.serving_version,
            "compiled_programs": self.compile_count,
            "drafted_tokens": self.drafted_tokens,
            "accepted_tokens": self.accepted_tokens,
            "accept_rate": (round(self.accepted_tokens
                                  / self.drafted_tokens, 4)
                            if self.drafted_tokens else None),
            # v7 shared-prefix keys: cumulative admission hits / prompt
            # tokens skipped / CoW triggers (0 = the write-barrier
            # invariant held), plus the INSTANTANEOUS count of blocks
            # named by >= 2 live tables right now
            "prefix_hit_blocks": self.prefix_hit_blocks,
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "shared_blocks": (0 if self.prefix is None
                              else self.prefix.shared_blocks()),
            "cow_copies": self.cow_copies,
            # extras (not required keys): the hit-rate pair's
            # denominator, the cached-block inventory, and the prefill
            # dispatch count the ~1-prefill property is proved on
            "prefix_lookup_blocks": self.prefix_lookup_blocks,
            "prefix_hit_rate": (round(self.prefix_hit_blocks
                                      / self.prefix_lookup_blocks, 4)
                                if self.prefix_lookup_blocks else None),
            "prefix_cached_blocks": (0 if self.prefix is None
                                     else len(self.prefix)),
            # reclaimable retention right now — what reconciles the
            # literal free_blocks keys with kv_pool_utilization's
            # cached-blocks-are-free reading
            "prefix_evictable_blocks": (0 if self.prefix is None
                                        else
                                        self.prefix.evictable_blocks()),
            "prefill_dispatches": self.prefill_dispatches,
            # v17 KV-memory-hierarchy keys (pinned): demotion volume
            # (cumulative blocks + wire bytes), promotion wins
            # (restores, the prompt tokens they kept off the prefill
            # path, the host wall-clock they cost — the budgeted
            # stall term), sub-block partial hits, and the host tier's
            # instantaneous occupancy fraction (0.0 with the tier off)
            "spilled_blocks": self.spilled_blocks,
            "spill_bytes": self.spill_bytes,
            "restores": self.restores,
            "restore_tokens_saved": self.restore_tokens_saved,
            "restore_stall_s": round(self.restore_stall_s, 6),
            "partial_hits": self.partial_hits,
            "host_tier_utilization": (
                round(self.spill.utilization(), 4)
                if self.spill is not None else 0.0),
            # extra: the tier's instantaneous entry count (occupancy's
            # numerator — what fleetstat renders beside the pool line)
            "spill_tier_blocks": (0 if self.spill is None
                                  else len(self.spill)),
            "quarantined": self.quarantined,
            "retried": self.retried,
            "preempted": self.preempted,
            "rejected": self.rejected,
            "expired": self.expired,
        }

    # -- flight recorder (DESIGN.md section 17) ------------------------

    def _flight_digest(self) -> dict:
        """One per-executed-step scheduler digest for the bounded ring:
        what the scheduler decided (this step's request events), what
        it dispatched (prefill uid / decode uids), what came back (the
        per-row finite flags), and the pool pressure at step end."""
        return {
            "step": self.global_step,
            "t": round(time.time(), 4),
            "events": list(self._step_events),
            "prefill_uid": self._step_prefill_uid,
            "decode_uids": list(self._step_decode_uids),
            "finite": self._step_finite,
            "slots": [None if s is None else
                      {"uid": s.uid, "pos": int(self.lengths[i]),
                       "blocks": len(s.blocks)}
                      for i, s in enumerate(self.slots)],
            "occupancy": round(self.active / self.cfg.max_slots, 4),
            "free_blocks": len(self.free_blocks),
            "waiting": len(self.waiting),
        }

    def dump_flight_recorder(self, reason: str) -> str | None:
        """Atomically persist the digest ring as ``flight_recorder.json``
        next to the metrics stream (or ``self.flight_dir``) via
        ``runtime/wire.py``'s publish discipline (tmp + fsync + rename
        + dir fsync — one implementation for checkpoints, snapshots,
        wire docs, and this dump). Called on quarantine (engine),
        watchdog latch and chaos kill (supervisor). Returns the path,
        or None when the engine has nowhere to put it (no metrics dir,
        no explicit flight_dir)."""
        out_dir = self.flight_dir
        if out_dir is None and self.metrics is not None:
            out_dir = os.path.dirname(self.metrics.path)
        if out_dir is None:
            return None
        from ..runtime.wire import publish_json
        os.makedirs(out_dir, exist_ok=True)
        doc = {"version": 1, "reason": reason,
               "step": self.global_step, "t": time.time(),
               "kv_dtype": self.cfg.kv_dtype,
               "max_slots": self.cfg.max_slots,
               "n_blocks": self.cfg.n_blocks,
               "digests": list(self.flight)}
        return publish_json(os.path.join(out_dir, FLIGHT_FILENAME),
                            doc)

    # -- static cost attribution (DESIGN.md section 17) ----------------

    def decode_static_report(self, bucket: int | None = None) -> dict:
        """Compile-time attribution of one decode-step program (the
        largest slot bucket by default): a ``runtime.telemetry
        StepReport`` (XLA cost_analysis + lowered collective counts +
        compiled memory) over the REAL program body, cross-checked
        against the hand-side KV accounting — ``kv_pool_bytes`` (the
        device truth, ``paged.pool_bytes``) must equal
        ``kv_bytes_per_token * n_blocks * block_size`` (the DECODE
        roofline's per-dtype prediction) exactly, or the roofline
        prices a layout the engine doesn't run. Lowering is AOT and
        donation-free; the serving program set is untouched."""
        from ..runtime.telemetry import StepReport
        b = self.slot_buckets[-1] if bucket is None else bucket
        if b not in self.slot_buckets:
            raise ValueError(f"bucket {b} not in the engine's slot "
                             f"buckets {self.slot_buckets}")
        tables = jnp.full((b, self.cfg.max_blocks_per_seq),
                          SCRATCH_BLOCK, jnp.int32)
        z = jnp.zeros((b,), jnp.int32)
        rep = StepReport.of(self._wrap(self._decode_fn(b)), self.params,
                            self.pool, tables, z, z, z,
                            jnp.int32(POISON_NONE))
        per_tok = kv_bytes_per_token(self.cfg.kv_dtype,
                                     self.params.n_layers,
                                     self.kv_heads, self.dh)
        kv_bytes, scale_bytes = pool_bytes(self.pool)
        return {
            "slot_bucket": b,
            "kv_dtype": self.cfg.kv_dtype,
            "step_report": rep.as_dict(),
            "kv_bytes_per_token": int(per_tok),
            "kv_pool_bytes": kv_bytes,
            "kv_pool_bytes_predicted": int(
                per_tok * self.cfg.n_blocks * self.cfg.block_size),
            "kv_scale_bytes": scale_bytes,
        }

    def run(self, metrics=None, log_every: int = 0, before_step=None,
            after_step=None) -> dict[int, list[int]]:
        """Drain the queue: step until every submitted sequence finished
        (or failed). ``metrics`` is a ``TelemetryWriter`` (defaults to
        the constructor's — request lifecycle records flow there either
        way); one ``decode`` record lands every ``log_every`` engine
        steps (0 = final only), with throughput measured between records
        (host wall clock, device-synced by the per-step readback of the
        picks). ``before_step(next_local_step)`` /
        ``after_step(local_step)`` are the supervisor's hooks
        (``decode/supervise.py``): chaos injection before, watchdog +
        snapshot + kill after — hook exceptions propagate (the
        supervisor's restart ladder owns them)."""
        if metrics is not None:
            self.metrics = metrics
        metrics = self.metrics
        last_t = time.perf_counter()
        last_tokens = self.tokens_generated
        last_step = self.steps
        while self.waiting or self.active:
            if before_step is not None:
                before_step(self.steps + 1)
            if not self.step():
                # a step may legitimately run no compiled work when it
                # only expired/failed requests — re-check the loop
                # condition before calling it a stall. The after_step
                # hook still fires so the supervisor's final snapshot
                # reflects the expiries (a stale snapshot would resume
                # the dead uids and double-count their records).
                if self.waiting or self.active:
                    raise RuntimeError("decode engine stalled: waiting "
                                       "requests but no admissible work")
                if after_step is not None:
                    after_step(self.steps)
                break
            if after_step is not None:
                after_step(self.steps)
            if (metrics is not None and log_every > 0
                    and self.steps - last_step >= log_every):
                now = time.perf_counter()
                dt = max(now - last_t, 1e-9)
                tps = (self.tokens_generated - last_tokens) / dt
                metrics.decode(self.telemetry_record(round(tps, 2)))
                last_t, last_tokens = now, self.tokens_generated
                last_step = self.steps
        if metrics is not None:
            now = time.perf_counter()
            dt = max(now - last_t, 1e-9)
            tps = ((self.tokens_generated - last_tokens) / dt
                   if self.tokens_generated > last_tokens else None)
            metrics.decode(self.telemetry_record(
                round(tps, 2) if tps is not None else None))
        return dict(self.finished)

    def generate(self, prompts, max_new: int, metrics=None,
                 log_every: int = 0) -> list[list[int] | None]:
        """Convenience batch API: submit every prompt, drain, return
        full token lists in submission order. A request that FAILED
        terminally (quarantine budget exhausted, deadline expiry)
        yields ``None`` in its position — the reason is in
        ``self.failed[uid]`` — and so does one SHED at the door by
        ``queue_limit`` (the ``rejected`` counter/event records it);
        malformed prompts still raise ``ValueError``."""
        uids = []
        for p in prompts:
            try:
                uids.append(self.submit(p, max_new))
            except AdmissionError:
                uids.append(None)
        done = self.run(metrics=metrics, log_every=log_every)
        return [None if u is None else done.get(u) for u in uids]
