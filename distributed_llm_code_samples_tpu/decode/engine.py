"""High-throughput decode engine: paged KV + continuous batching.

The lockstep decoder (``models.lm.generate``) is a fixed-batch program:
every sequence enters together, decodes in step, and the batch ends
when the longest member does — between requests the chip idles and
short sequences pad out long ones. This engine is the Orca-style
answer, hand-rolled in the repo's idiom (explicit state, no framework
wrappers):

- **Paged KV** (``decode/paged.py``): one static-shape block pool for
  every sequence; a finished sequence frees its blocks with a host-side
  table edit — no recompile, no pool reshape.
- **Continuous batching**: a host scheduler admits queued prompts into
  freed slots *between* compiled steps. The compiled surface is a small
  static set — one decode program per power-of-two slot bucket, one
  prefill program per power-of-two chunk bucket — so steady-state steps
  are dispatch-only and the compile count is bounded by the bucket
  count (the ``--log_every`` chunk discipline, recompile-guard-tested).
- **Chunked prefill**: long prompts enter in bounded chunks
  (``models.attention.chunk_attn`` over the gathered cache), so a new
  long prompt costs one chunk per engine step instead of stalling every
  running decode behind a full-prompt pass.
- **Fused sampling** (``decode/sampling.py``): temperature / top-k /
  top-p picked inside the compiled step, keyed on
  ``(engine seed, sequence uid, position)`` — continuous-batching
  output is token-identical to decoding each sequence alone.

Strategies: ``mesh=None`` runs single-device (the ``lm`` family);
passing a model-axis mesh runs the Megatron decode layout
(``parallel.lm``): head-sharded KV pool (each shard caches its own
``H/n`` heads), vocab-parallel tied head, and an in-graph logits
gather feeding the same fused pick on every shard.

Determinism contract: a sequence's output depends only on
``(params, engine seed, uid, prompt, sampling config)`` — never on slot
assignment, admission order, chunk interleaving, or pool layout
(tests/test_decode_engine.py pins paged==contiguous bit-for-bit at f32
and continuous==sequential token-for-token).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.attention import chunk_attn, rope
from ..models.lm import LMParams, decode_attn
from ..ops.norm import layernorm
from .paged import (PagedKV, SCRATCH_BLOCK, gather_layer, init_pool,
                    write_chunk, write_rows)
from .sampling import check_sampling, make_pick


def _buckets(limit: int) -> tuple[int, ...]:
    """Power-of-two sizes up to ``limit`` (``limit`` itself appended
    when it isn't one) — the static shape set for slots and chunks."""
    out = []
    b = 1
    while b < limit:
        out.append(b)
        b *= 2
    out.append(limit)
    return tuple(out)


def _bucket_for(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


@dataclass(frozen=True)
class EngineConfig:
    """Static decode-engine configuration (one compiled program set per
    config). ``block_size`` must be a power of two so power-of-two
    prefill chunks never straddle a block boundary (``paged.write_chunk``).
    ``n_blocks`` includes the reserved scratch block. ``temperature=0``
    is greedy; ``top_k=0`` / ``top_p=0`` disable those truncations."""
    block_size: int = 16
    n_blocks: int = 65
    max_slots: int = 4
    max_blocks_per_seq: int = 8
    prefill_chunk: int = 16
    kv_dtype: str = "f32"
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    use_rope: bool = False

    @property
    def capacity(self) -> int:
        """Max cached positions per sequence."""
        return self.max_blocks_per_seq * self.block_size


@dataclasses.dataclass
class _Seq:
    """Host-side per-sequence record (the scheduler's unit of state)."""
    uid: int
    prompt: list[int]
    max_new: int
    out: list[int] = field(default_factory=list)
    prefilled: int = 0
    blocks: list[int] = field(default_factory=list)

    @property
    def prompt_done(self) -> bool:
        return self.prefilled >= len(self.prompt)

    @property
    def finished(self) -> bool:
        return len(self.out) >= self.max_new


class DecodeEngine:
    """The serving loop. ``submit()`` queues prompts; ``step()`` runs one
    scheduler iteration (admit -> at most one prefill chunk -> one decode
    dispatch over every ready slot); ``run()`` drains everything and
    returns ``{uid: full token list}``. See the module docstring for the
    design; DESIGN.md section 15 for the state machine."""

    def __init__(self, params: LMParams, n_heads: int,
                 config: EngineConfig | None = None, mesh=None):
        cfg = config or EngineConfig()
        if cfg.block_size & (cfg.block_size - 1):
            raise ValueError(f"block_size must be a power of two, got "
                             f"{cfg.block_size}")
        if cfg.max_slots < 1 or cfg.max_blocks_per_seq < 1:
            raise ValueError("max_slots and max_blocks_per_seq must be "
                             ">= 1")
        if cfg.prefill_chunk < 1 or (cfg.prefill_chunk
                                     & (cfg.prefill_chunk - 1)):
            raise ValueError(
                f"prefill_chunk must be a power of two >= 1, got "
                f"{cfg.prefill_chunk} (power-of-two chunks are what "
                "keeps a chunk inside one block — paged.write_chunk)")
        check_sampling(cfg.temperature, cfg.top_k, cfg.top_p, params.vocab)
        self.params = params
        self.n_heads = n_heads
        self.cfg = cfg
        self.mesh = mesh
        self.dh = params.d_model // n_heads
        self.kv_heads = params.blocks.wk.shape[1] // self.dh
        if mesh is not None:
            from ..parallel.lm import tp_shard_params
            from ..parallel.mesh import MODEL_AXIS, require_axes
            from ..parallel.transformer import _validate_tp
            require_axes(mesh, MODEL_AXIS)
            n = mesh.shape[MODEL_AXIS]
            _validate_tp(params.blocks, n_heads, n)
            if params.vocab % n:
                raise ValueError(f"vocab={params.vocab} not divisible by "
                                 f"model-axis size {n}")
            self.params = tp_shard_params(params, mesh)
        self.pool = self._init_pool()
        s, mb = cfg.max_slots, cfg.max_blocks_per_seq
        self.tables = np.full((s, mb), SCRATCH_BLOCK, np.int32)
        self.lengths = np.zeros((s,), np.int32)
        self.next_token = np.zeros((s,), np.int32)
        self.uids = np.zeros((s,), np.int32)
        self.slots: list[_Seq | None] = [None] * s
        self.waiting: collections.deque[_Seq] = collections.deque()
        self.finished: dict[int, list[int]] = {}
        self.free_blocks = list(range(1, cfg.n_blocks))
        self.slot_buckets = _buckets(cfg.max_slots)
        self.chunk_buckets = _buckets(cfg.prefill_chunk)
        self._programs: dict = {}
        self.compile_count = 0       # program builds (recompile guard)
        self.dispatch_count = 0
        self.steps = 0
        self.tokens_generated = 0
        self._occ_sum = 0.0
        self._next_uid = 0

    # -- pool ----------------------------------------------------------

    def _init_pool(self) -> PagedKV:
        cfg = self.cfg
        pool = init_pool(self.params.n_layers, cfg.n_blocks,
                         self.kv_heads, cfg.block_size, self.dh,
                         cfg.kv_dtype)
        if self.mesh is None:
            return pool
        from ..parallel.mesh import MODEL_AXIS
        # head-sharded pool: each model shard caches its own KV heads
        arr = P(None, None, MODEL_AXIS, None, None)
        sc = None if pool.k_scale is None else P(None, None, MODEL_AXIS)
        return PagedKV(*(None if x is None
                         else jax.device_put(x, NamedSharding(self.mesh,
                                                              spec))
                         for x, spec in zip(pool, (arr, arr, sc, sc))))

    def _pool_specs(self) -> PagedKV:
        from ..parallel.mesh import MODEL_AXIS
        arr = P(None, None, MODEL_AXIS, None, None)
        sc = None if self.pool.k_scale is None else P(None, None,
                                                      MODEL_AXIS)
        return PagedKV(arr, arr, sc, sc)

    # -- compiled programs (one per (kind, bucket); bounded) -----------

    def _program(self, kind: str, bucket: int):
        key = (kind, bucket)
        fn = self._programs.get(key)
        if fn is None:
            self.compile_count += 1
            fn = (self._build_decode(bucket) if kind == "decode"
                  else self._build_prefill(bucket))
            self._programs[key] = fn
        self.dispatch_count += 1
        return fn

    def _attn_qkv(self, p: LMParams, l: int, a, positions):
        """Shared q/k/v projection + rotary for one layer: ``a [N, d]``
        -> ``q [N, h_loc, dh], k/v [N, kv_loc, dh]`` (local head counts
        read off the — possibly sharded — weight shapes, the
        ``cached_attn_step`` convention)."""
        blk = p.blocks
        dh = self.dh
        h_loc = blk.wq.shape[1] // dh
        kv_loc = blk.wk.shape[1] // dh
        q = (a @ blk.wq[l].T).reshape(-1, h_loc, dh)
        k = (a @ blk.wk[l].T).reshape(-1, kv_loc, dh)
        v = (a @ blk.wv[l].T).reshape(-1, kv_loc, dh)
        if self.cfg.use_rope:
            rot = jax.vmap(lambda x, pos: rope(x[:, None, :],
                                               pos[None])[:, 0, :])
            q = rot(q, positions)
            k = rot(k, positions)
        return q, k, v

    def _embed(self, p: LMParams, tokens, positions):
        if self.mesh is not None:
            from ..parallel.lm import vp_embed
            return vp_embed(p.wte, tokens) + p.wpe[positions]
        return p.wte[tokens] + p.wpe[positions]

    def _trunk(self, p: LMParams, pool: PagedKV, x, positions,
               write_attn):
        """The shared per-layer forward both compiled programs run —
        ONE definition, so prefill and decode numerics can never drift:
        LN, q/k/v, then the caller's ``write_attn(l, pool, q, k, v) ->
        (pool, y [N, h_loc, dh])`` (the only step where the two programs
        differ: batched single-token writes + per-slot gathers vs one
        slot's chunk write + chunk attention), output projection, FFN
        — with the Megatron psums when a mesh is set."""
        tp = self.mesh is not None
        if tp:
            from ..parallel.collectives import all_reduce
            from ..parallel.mesh import MODEL_AXIS
        blk = p.blocks
        n = x.shape[0]
        for l in range(p.n_layers):
            a = layernorm(blk.ln1[l], x)
            q, k, v = self._attn_qkv(p, l, a, positions)
            pool, y = write_attn(l, pool, q, k, v)
            y = y.reshape(n, -1) @ blk.wo[l].T
            x = x + (all_reduce(y, MODEL_AXIS) if tp else y)
            h = layernorm(blk.ln2[l], x)
            f = jnp.maximum(h @ blk.w1[l].T, 0.0) @ blk.w2[l].T
            x = x + (all_reduce(f, MODEL_AXIS) if tp else f)
        return pool, x

    def _logits(self, p: LMParams, h):
        """Tied head; under TP each shard scores its V/n vocab rows and
        the in-graph gather re-assembles the full row so the fused pick
        (keys fold uid/position, never the shard) draws identically
        everywhere — the output is replicated."""
        logits = h @ p.wte.T
        if self.mesh is not None:
            from ..parallel.collectives import all_gather
            from ..parallel.mesh import MODEL_AXIS
            logits = all_gather(logits, MODEL_AXIS, dim=1)
        return logits

    def _jit(self, run):
        """jit (or shard_map+jit under TP) with the pool donated: the
        engine replaces ``self.pool`` with the returned pool after every
        dispatch, so XLA may update the blocks in place instead of
        copying the whole pool per step — without donation each decode
        step would pay a full-pool allocate+copy, swamping the
        kv_bytes roofline term this engine exists to shrink."""
        if self.mesh is None:
            return jax.jit(run, donate_argnums=(1,))
        from ..parallel.lm import tp_decode_specs
        return jax.jit(jax.shard_map(
            run, mesh=self.mesh,
            in_specs=(tp_decode_specs(), self._pool_specs(), P(), P(),
                      P(), P()),
            out_specs=(self._pool_specs(), P()), check_vma=False),
            donate_argnums=(1,))

    def _build_decode(self, b: int):
        """One decode step for a ``b``-slot bucket: write each slot's
        input token at its own position, attend over its gathered
        blocks, pick the next token in-graph."""
        cfg = self.cfg
        pick = make_pick(cfg.temperature, cfg.top_k, cfg.top_p,
                         self.params.vocab, cfg.seed)

        def run(p: LMParams, pool: PagedKV, tables, lengths, tokens,
                uids):
            x = self._embed(p, tokens, lengths)             # [b, d]
            slot_phys = lengths // cfg.block_size
            off = lengths % cfg.block_size

            def write_attn(l, pool, q, k, v):
                phys = tables[jnp.arange(b), slot_phys]
                pool = write_rows(pool, l, phys, off, k, v, cfg.kv_dtype)
                ck, cv = jax.vmap(
                    lambda t, _l=l, _pool=pool: gather_layer(_pool, _l, t)
                )(tables)                       # [b, Hkv_loc, T_cap, dh]
                return pool, decode_attn(q, ck, cv, lengths + 1)

            pool, x = self._trunk(p, pool, x, lengths, write_attn)
            logits = self._logits(p, layernorm(p.ln_f, x))
            return pool, pick(logits, uids, lengths + 1)

        return self._jit(run)

    def _build_prefill(self, c: int):
        """One prefill chunk for one slot: ``c`` prompt tokens enter the
        cache through the block table; the chunk's own causal attention
        runs against the gathered view (``models.attention.chunk_attn``).
        Returns the in-graph pick from the final row — used by the host
        only when the chunk completes the prompt."""
        cfg = self.cfg
        pick = make_pick(cfg.temperature, cfg.top_k, cfg.top_p,
                         self.params.vocab, cfg.seed)

        def run(p: LMParams, pool: PagedKV, table, pos0, tokens, uid):
            positions = pos0 + jnp.arange(c)
            x = self._embed(p, tokens, positions)           # [c, d]

            def write_attn(l, pool, q, k, v):
                pool = write_chunk(pool, l, table, pos0, k, v,
                                   cfg.kv_dtype)
                ck, cv = gather_layer(pool, l, table)
                y = chunk_attn(q.transpose(1, 0, 2), ck, cv, pos0)
                return pool, y.transpose(1, 0, 2)

            pool, x = self._trunk(p, pool, x, positions, write_attn)
            h = layernorm(p.ln_f, x[-1:])                   # last row
            logits = self._logits(p, h)
            nxt = pick(logits, uid[None], (pos0 + c)[None])
            return pool, nxt[0]

        return self._jit(run)

    # -- scheduler -----------------------------------------------------

    def submit(self, prompt, max_new: int, uid: int | None = None) -> int:
        """Queue one request. ``prompt`` is a list of token ids; the
        capacity checks run here so an impossible request fails at
        submit time, never mid-serve."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if any(not 0 <= t < self.params.vocab for t in prompt):
            raise ValueError("prompt token out of vocab range")
        # the final generated token is returned, never cached or embedded
        # (_blocks_needed counts the same way), so a request may exactly
        # fill its block reservation
        cached = len(prompt) + max_new - 1
        if cached > self.cfg.capacity:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} needs "
                f"{cached} cached positions, exceeding the per-sequence "
                f"cache capacity {self.cfg.capacity} "
                "(max_blocks_per_seq * block_size)")
        if cached > self.params.max_seq_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} needs "
                f"{cached} cached positions, exceeding max_seq_len "
                f"{self.params.max_seq_len}")
        if self._blocks_needed(len(prompt), max_new) > self.cfg.n_blocks - 1:
            raise ValueError("request needs more blocks than the pool "
                             f"holds ({self.cfg.n_blocks - 1} usable)")
        if uid is None:
            uid = self._next_uid
        elif (uid in self.finished
              or any(s is not None and s.uid == uid for s in self.slots)
              or any(s.uid == uid for s in self.waiting)):
            # a duplicate uid would sample in lockstep with its twin
            # (the key folds the uid) and overwrite its finished entry
            raise ValueError(f"uid {uid} already in use")
        self._next_uid = max(self._next_uid, uid) + 1
        self.waiting.append(_Seq(uid=uid, prompt=prompt, max_new=max_new))
        return uid

    def _blocks_needed(self, t0: int, max_new: int) -> int:
        # the final generated token is returned, never cached
        positions = t0 + max_new - 1
        return -(-positions // self.cfg.block_size)

    def _admit(self) -> int:
        """FCFS admission: move waiting requests into free slots while
        both a slot and the request's full block reservation are
        available (reserve-on-admit keeps serving preemption-free). A
        head-of-line request that doesn't fit blocks the queue — strict
        FCFS keeps admission deterministic."""
        admitted = 0
        while self.waiting:
            seq = self.waiting[0]
            need = self._blocks_needed(len(seq.prompt), seq.max_new)
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots or need > len(self.free_blocks):
                break
            self.waiting.popleft()
            slot = free_slots[0]
            seq.blocks = [self.free_blocks.pop(0) for _ in range(need)]
            row = np.full((self.cfg.max_blocks_per_seq,), SCRATCH_BLOCK,
                          np.int32)
            row[:need] = seq.blocks
            self.tables[slot] = row
            self.lengths[slot] = 0
            self.uids[slot] = seq.uid
            self.slots[slot] = seq
            admitted += 1
        return admitted

    def _release(self, slot: int) -> None:
        seq = self.slots[slot]
        self.finished[seq.uid] = seq.prompt + seq.out
        self.free_blocks.extend(seq.blocks)
        self.tables[slot] = SCRATCH_BLOCK
        self.lengths[slot] = 0
        self.next_token[slot] = 0
        self.uids[slot] = 0
        self.slots[slot] = None

    def _prefill_step(self, slot: int) -> None:
        seq = self.slots[slot]
        remaining = len(seq.prompt) - seq.prefilled
        # largest power-of-two bucket that fits the remaining prompt:
        # chunk starts stay multiples of the chunk size, so no chunk
        # ever straddles a block boundary (paged.write_chunk's contract)
        c = max(b for b in self.chunk_buckets if b <= remaining)
        fn = self._program("prefill", c)
        chunk = np.asarray(seq.prompt[seq.prefilled:seq.prefilled + c],
                           np.int32)
        pool, nxt = fn(self.params, self.pool,
                       jnp.asarray(self.tables[slot]),
                       jnp.int32(seq.prefilled), jnp.asarray(chunk),
                       jnp.int32(seq.uid))
        self.pool = pool
        seq.prefilled += c
        if seq.prompt_done:
            self.lengths[slot] = len(seq.prompt)
            tok = int(nxt)
            seq.out.append(tok)
            self.next_token[slot] = tok
            self.tokens_generated += 1
            if seq.finished:
                self._release(slot)

    def _decode_step(self, ready: list[int]) -> None:
        b = _bucket_for(len(ready), self.slot_buckets)
        idx = ready + [0] * (b - len(ready))        # pad rows
        tables = self.tables[idx].copy()
        lengths = self.lengths[idx].copy()
        tokens = self.next_token[idx].copy()
        uids = self.uids[idx].copy()
        for j in range(len(ready), b):              # pads -> scratch
            tables[j] = SCRATCH_BLOCK
            lengths[j] = 0
            tokens[j] = 0
            uids[j] = 0
        fn = self._program("decode", b)
        pool, picks = fn(self.params, self.pool, jnp.asarray(tables),
                         jnp.asarray(lengths), jnp.asarray(tokens),
                         jnp.asarray(uids))
        self.pool = pool
        picks = np.asarray(picks)
        for j, slot in enumerate(ready):
            seq = self.slots[slot]
            tok = int(picks[j])
            seq.out.append(tok)
            self.lengths[slot] += 1
            self.next_token[slot] = tok
            self.tokens_generated += 1
            if seq.finished:
                self._release(slot)

    def step(self) -> bool:
        """One scheduler iteration: admit, at most ONE prefill chunk
        (so a long prompt never stalls running decodes for more than a
        chunk), then one decode dispatch over every ready slot. Returns
        whether any work ran."""
        self._admit()
        did = False
        pre = next((i for i, s in enumerate(self.slots)
                    if s is not None and not s.prompt_done), None)
        if pre is not None:
            self._prefill_step(pre)
            did = True
        ready = [i for i, s in enumerate(self.slots)
                 if s is not None and s.prompt_done]
        if ready:
            self._decode_step(ready)
            did = True
        if did:
            self.steps += 1
            active = sum(s is not None for s in self.slots)
            self._occ_sum += active / self.cfg.max_slots
        return did

    @property
    def active(self) -> int:
        return sum(s is not None for s in self.slots)

    def mean_occupancy(self) -> float:
        return self._occ_sum / self.steps if self.steps else 0.0

    def kv_pool_utilization(self) -> float:
        usable = self.cfg.n_blocks - 1
        return (usable - len(self.free_blocks)) / usable

    def telemetry_record(self, tokens_per_sec=None) -> dict:
        """One schema-v3 ``decode`` record (``runtime/telemetry.py``
        ``DECODE_REQUIRED`` contract)."""
        return {
            "step": self.steps,
            "tokens_per_sec": tokens_per_sec,
            "batch_occupancy": round(self.active / self.cfg.max_slots, 4),
            "kv_pool_utilization": round(self.kv_pool_utilization(), 4),
            "active": self.active,
            "waiting": len(self.waiting),
            "tokens_generated": self.tokens_generated,
            "kv_dtype": self.cfg.kv_dtype,
            "compiled_programs": self.compile_count,
        }

    def run(self, metrics=None, log_every: int = 0) -> dict[int, list[int]]:
        """Drain the queue: step until every submitted sequence
        finished. ``metrics`` is a ``TelemetryWriter``; one ``decode``
        record lands every ``log_every`` engine steps (0 = final only),
        with throughput measured between records (host wall clock,
        device-synced by the per-step readback of the picks)."""
        last_t = time.perf_counter()
        last_tokens = self.tokens_generated
        last_step = self.steps
        while self.waiting or self.active:
            if not self.step():
                raise RuntimeError("decode engine stalled: waiting "
                                   "requests but no admissible work")
            if (metrics is not None and log_every > 0
                    and self.steps - last_step >= log_every):
                now = time.perf_counter()
                dt = max(now - last_t, 1e-9)
                tps = (self.tokens_generated - last_tokens) / dt
                metrics.decode(self.telemetry_record(round(tps, 2)))
                last_t, last_tokens = now, self.tokens_generated
                last_step = self.steps
        if metrics is not None:
            now = time.perf_counter()
            dt = max(now - last_t, 1e-9)
            tps = ((self.tokens_generated - last_tokens) / dt
                   if self.tokens_generated > last_tokens else None)
            metrics.decode(self.telemetry_record(
                round(tps, 2) if tps is not None else None))
        return dict(self.finished)

    def generate(self, prompts, max_new: int, metrics=None,
                 log_every: int = 0) -> list[list[int]]:
        """Convenience batch API: submit every prompt, drain, return
        full token lists in submission order."""
        uids = [self.submit(p, max_new) for p in prompts]
        done = self.run(metrics=metrics, log_every=log_every)
        return [done[u] for u in uids]
