"""Engine worker process + the router-side transport client.

The PR 10 fleet was a simulation of distribution: every replica lived
in the router's process, a "kill" dropped a Python object, and the
handoff doc never crossed a serialization boundary — so none of the
failure modes a real fleet must survive (torn writes, half-shipped
handoffs, silently hung workers, stale liveness) could even occur.
This module makes the fleet span real OS processes:

- ``worker_main`` runs ONE ``DecodeEngine`` in its own process behind
  a small request/response protocol: newline-delimited JSON over an
  ``AF_UNIX`` socket (the worker binds and accepts exactly one
  connection — its router). Control messages are tiny; KV NEVER rides
  the socket — handoff documents cross as versioned wire files
  (``runtime/wire.py``: npz + per-array CRC-32, atomically published
  in the worker's spool directory), the same staging-file pattern a
  multi-host transport would use. Every response carries the worker's
  scheduler-state ``digest`` so the router's routing/migration
  decisions read fresh state with zero extra round-trips.

- ``ProcessEngineHandle`` is the router side: the same driver API as
  the in-process ``EngineHandle`` (``decode/fleet.py``), implemented
  as protocol calls with **per-call deadlines**. The liveness ladder:
  a recv that overruns its deadline retries under bounded exponential
  backoff (``runtime.failure.backoff_delay`` — the training
  supervisor's schedule, reused); exhausted retries raise
  ``TransportTimeout``, EOF/reset raises ``TransportDead``; the router
  converts either into a dead-host declaration (SIGKILL the process so
  a zombie cannot answer a stale request later) and migrates its
  requests from the last snapshot — the identical recovery path an
  explicit kill takes, because "stopped answering" and "dead" must be
  the same thing for recovery to be correct.

- ``spawn_worker`` / ``spawn_fleet_handles`` write each worker's JSON
  config, start ``python -m ...decode.worker CONFIG`` processes, and
  connect with the same bounded backoff (worker startup pays the jax
  import + program compiles; a connect refused while it boots is the
  canonical transient transport error).

Determinism across the boundary: each worker builds its params from
the SAME ``init_lm`` seed the router's config names, and the router
cross-checks ``model_meta()`` fingerprints at construction — so the
process fleet serves bit-identical weights, and the engine's
``(seed, uid, position)`` sampling contract makes every migration
token-identical exactly as in-process. Snapshots ride the protocol
in-band (the router holds them — the recovery state must survive the
WORKER's death, and the router is the survivor) and are additionally
published atomically in the worker's spool dir
(``decode/supervise.py::write_snapshot`` via ``runtime/wire.py``) as
the on-disk post-mortem record.

Round 22 — the network boundary (DESIGN.md section 28). The newline-
JSON protocol is socket-family-agnostic by construction; this round
adds the robustness layer a real network demands:

- **TCP transport** (``family="tcp"``): the worker binds
  ``127.0.0.1:0`` BEFORE the jax import and atomically publishes the
  bound port in its spool (``worker_port.json``); the router's
  connect loop discovers it there. The accept loop re-accepts after a
  dropped connection — on TCP, a broken connection is a fact of the
  network, not a death certificate.

- **Reconnect ladder + sequence-numbered replay**: a send/recv that
  fails at the socket (reset, EOF, partition) triggers a bounded-
  backoff reconnect (``failure.backoff_delay``) instead of an
  immediate dead-host verdict. After reconnecting, the router
  ``sync``s the worker's dedup state (``evict_horizon`` + cached
  response ids) and replays its in-flight requests BY ORIGINAL ID:
  the worker answers an already-executed id from its bounded response
  cache (no duplicate side effects), executes a never-arrived id
  fresh (no lost request), and refuses a non-idempotent id that fell
  past the cache window (``replay_verdict`` — the per-op idempotency
  audit in ``IDEMPOTENT_OPS``/``NON_IDEMPOTENT_OPS``). Only an
  exhausted reconnect budget, a dead process, or a refused replay
  escalates to ``TransportDead``. Per-call deadlines are untouched:
  slow-link (deadline overrun on a live connection) and dead-host
  (connection gone, reconnect exhausted) stay DIFFERENT verdicts.

- **Length-prefixed wire side channel**: under TCP the spool dir is
  (notionally) not shared, so handoff documents stream over the
  socket itself — ``fetch_wire`` answers with a binary frame
  (``runtime/wire.py`` framing) right after its JSON line, and
  ``stage_bytes`` carries one the same way; CRC verification happens
  at the receiving worker via the SAME ``deserialize_doc`` discipline
  the spool path uses. The spool-file path remains the same-host
  fast path under AF_UNIX.

- **Async live migration ops**: ``export_keep`` ships a snapshot
  while the source keeps decoding the sequence; ``stage``/
  ``stage_bytes`` park the verified document on the target;
  ``finish_export`` evicts at commit and returns the delta tokens;
  ``commit_import`` patches the delta in and imports — the target
  teacher-forces the catch-up (``DecodeEngine`` replay contract), so
  the moving request pays one replay and the source engine never
  stalls.
"""

from __future__ import annotations

import collections
import json
import os
import random
import socket
import subprocess
import sys
import time

from .fleet import (HandoffRef, TransportDead, TransportError,
                    TransportTimeout)

WORKER_CONFIG_FILENAME = "worker_config.json"
WORKER_SOCKET_FILENAME = "worker.sock"
WORKER_LOG_FILENAME = "worker.log"
# the TCP worker's atomically-published bound port (written via
# wire.publish_json BEFORE the jax import, like the unix bind)
WORKER_PORT_FILENAME = "worker_port.json"

# per-call deadline defaults (seconds). The first step call after spawn
# may compile XLA programs — its deadline must cover a cold compile;
# the drills that want fast hang detection lower call_deadline_s
# explicitly once their program set is warm.
DEFAULT_CALL_DEADLINE_S = 120.0
DEFAULT_PING_DEADLINE_S = 5.0
DEFAULT_CONNECT_DEADLINE_S = 120.0
# bounded-backoff retries for a timed-out recv before the worker is
# declared silent (failure.backoff_delay schedule, jitter off for
# deterministic drills)
DEFAULT_CALL_RETRIES = 1
# reconnect ladder bounds (TCP family): how many times a dropped
# connection may heal before it IS a dead-host verdict, and how long
# one healing attempt may take (a chaos partition extends the window
# by its own remaining duration — waiting out a partition is the
# point, not a loophole)
DEFAULT_MAX_RECONNECTS = 8
DEFAULT_RECONNECT_DEADLINE_S = 30.0

# ------------------------------------------ protocol idempotency audit
#
# Round 22: after a reconnect the router replays its in-flight
# requests by original id. A replayed id the worker already executed
# is answered from its bounded response cache — but when the cached
# response has been EVICTED, re-execution is the only option, and
# re-execution is only safe for ops that leave the same state when run
# twice. This table is the audit: every protocol op is classified, the
# serve loop and the router's replay_verdict() both consult it, and
# tests/test_worker_protocol.py pins that the two sets exactly cover
# the dispatch table.
#
# Idempotent = repeating the op against the post-execution state
# yields the same state and an equivalent response: pure reads (ping,
# meta, digest, probe, stats, results, sync), the throttled snapshot
# publish, compile warming, absolute-value writes (set_version), and
# the staging ops (staging the same verified document twice, or
# discarding an already-discarded stage, converges).
IDEMPOTENT_OPS = frozenset({
    "ping", "meta", "digest", "snapshot", "probe", "warm", "results",
    "stats", "sync", "fetch_wire", "set_version", "stage",
    "stage_bytes", "discard_stage",
})
# Non-idempotent = re-execution duplicates a side effect or fails
# against the state the first execution left: admissions (submit /
# resume / commit_import — uid-already-in-use on repeat), evictions
# (release / export / finish_export / import), engine steps, the
# telemetry-emitting ops, the chaos ops, shutdown, and load_weights
# (state-convergent but a full checkpoint restore is not a "harmless"
# repeat — the dedup cache answers it instead).
NON_IDEMPOTENT_OPS = frozenset({
    "submit", "resume", "release", "load_weights", "step", "export",
    "export_keep", "finish_export", "import", "commit_import",
    "emit_decode", "hang", "shutdown",
})
WORKER_OPS = IDEMPOTENT_OPS | NON_IDEMPOTENT_OPS

# how many responses the worker keeps for replay dedup — deep enough
# that any in-flight window (a handful of concurrent calls) replays
# from cache; only an id older than 256 completed calls can fall off
RESPONSE_CACHE_DEPTH = 256


def replay_verdict(op: str, rid: int, horizon: int, cached) -> str:
    """The router-side replay decision for one in-flight request after
    a reconnect, against the worker's synced dedup state
    (``horizon`` = highest response id evicted from its cache,
    ``cached`` = ids still held). Returns:

    - ``"cached"``  — the worker executed it and still holds the
      response: re-send the id, the worker answers from cache (no
      re-execution, no duplicate side effects).
    - ``"resend"``  — either the request never reached execution
      (``rid > horizon`` and not cached ⇒ provably never ran, any op
      is safe) or the op is idempotent (re-execution converges).
    - ``"refuse"``  — a non-idempotent op whose response fell past
      the dedup window: it MAY have executed and re-execution is not
      safe, so the only honest verdict is ``TransportDead`` (the
      snapshot-replay recovery path restores correctness).
    """
    if rid in cached:
        return "cached"
    if rid > horizon:
        return "resend"
    if op in IDEMPOTENT_OPS:
        return "resend"
    return "refuse"


# ---------------------------------------------------------------- worker

def worker_main(argv=None) -> int:
    """Run one engine worker: ``python -m
    distributed_llm_code_samples_tpu.decode.worker CONFIG_JSON``."""
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: decode.worker CONFIG_JSON", file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        cfg = json.load(f)

    # bind BEFORE the heavy jax import: the router's connect loop gets
    # a listening socket (slow accept) instead of minutes of refusals
    sock_path = cfg["socket_path"]
    family = cfg.get("family", "unix")
    if family == "tcp":
        # multi-host transport: bind an ephemeral loopback port and
        # atomically publish it where the router's connect loop looks
        # (a torn port file must be impossible — publish_json's
        # tmp+fsync+rename discipline)
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((cfg.get("tcp_host", "127.0.0.1"),
                     int(cfg.get("tcp_port", 0))))
        server.listen(1)
        from ..runtime.wire import publish_json
        os.makedirs(cfg["spool_dir"], exist_ok=True)
        publish_json(os.path.join(cfg["spool_dir"],
                                  WORKER_PORT_FILENAME),
                     {"port": server.getsockname()[1]})
    else:
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        server.bind(sock_path)
        server.listen(1)

    import jax

    from ..models import init_lm
    from ..runtime.telemetry import TelemetryWriter
    from ..runtime.wire import WireError
    from .engine import AdmissionError, DecodeEngine, EngineConfig, \
        ServePolicy
    from .fleet import EngineHandle
    from .supervise import write_snapshot

    m = cfg["model"]
    params = init_lm(jax.random.PRNGKey(m["random_seed"]), m["vocab"],
                     m["model_size"], m["layers"],
                     max_seq_len=m["max_seq_len"], n_heads=m["heads"],
                     n_kv_heads=m.get("kv_heads") or None)
    metrics = None
    if cfg.get("metrics_dir"):
        metrics = TelemetryWriter(cfg["metrics_dir"],
                                  meta=cfg.get("meta") or {})
    qos = None
    if cfg.get("qos"):
        from ..runtime.policy import QosPolicy
        qos = QosPolicy.from_dict(cfg["qos"])
    engine = DecodeEngine(params, m["heads"],
                          EngineConfig(**cfg["config"]),
                          policy=ServePolicy(**cfg["policy"]),
                          metrics=metrics, qos=qos)
    spool = cfg["spool_dir"]
    os.makedirs(spool, exist_ok=True)
    # the worker IS an in-process EngineHandle around its engine (wire
    # exports land in the spool): every read surface the router's
    # policy code consumes — digest, stats, waiting entries, decode
    # cadence, wire export/import — is the ONE implementation in
    # decode/fleet.py, so the transports cannot drift apart on what
    # the router sees
    hd = EngineHandle(cfg["engine_id"], engine, cfg.get("role",
                                                        "decode"),
                      wire_dir=spool)
    last_publish_t = 0.0
    # reconnect dedup state (round 22): every executed response is
    # cached (bounded) keyed by request id; evict_horizon is the
    # highest id whose response fell off — the line between "answer a
    # replay from cache" and "refuse a non-idempotent replay"
    resp_cache: "collections.OrderedDict[int, tuple[bytes, bytes | None]]" \
        = collections.OrderedDict()
    evict_horizon = -1

    def handle(req: dict, blob_in: bytes | None) -> dict:
        nonlocal last_publish_t
        op = req["op"]
        if op == "sync":
            # the reconnect handshake: hand the router this worker's
            # dedup state so replay_verdict() can classify every
            # in-flight request before resending it
            return {"horizon": evict_horizon,
                    "cached": sorted(resp_cache)}
        if op == "ping":
            return {}
        if op == "meta":
            return {"model": engine.model_meta(),
                    "mesh": engine.mesh is not None}
        if op == "digest":
            return {"digest": hd.digest()}
        if op == "submit":
            entry = hd.submit(req["prompt"], req["max_new"],
                              uid=req["uid"], trace=req.get("trace"),
                              tenant=req.get("tenant"))
            return {"entry": entry, "digest": hd.digest()}
        if op == "resume":
            hd.resume_request(req["uid"], req["prompt"],
                              req["max_new"], out=req["out"],
                              retries=req["retries"],
                              t_submit=req.get("t_submit"),
                              t_first=req.get("t_first"),
                              weights_version=req.get(
                                  "weights_version"),
                              trace=req.get("trace"),
                              tenant=req.get("tenant"))
            return {"digest": hd.digest()}
        if op == "release":
            return {"entry": hd.release_request(req["uid"]),
                    "digest": hd.digest()}
        if op == "load_weights":
            # the rolling deploy's swap half: restore the checkpoint
            # step from the SHARED ledger dir (weights never ride the
            # socket) and double-buffer it as the named version; the
            # CRC ladder runs inside restore — a torn step raises and
            # crosses back as the one-line rejection the router's
            # rollback names
            from ..runtime.weights import VersionLedger
            new = VersionLedger(req["ckpt_dir"]).load(req["step"],
                                                      engine.params)
            fp = engine.load_weights(req["version"], new)
            return {"fingerprint": fp, "digest": hd.digest()}
        if op == "set_version":
            engine.set_serving_version(req["version"])
            return {"digest": hd.digest()}
        if op == "step":
            hd.step_begin(prefill_only=req.get("prefill_only", False))
            return {"did": bool(hd.step_end()),
                    "step_s": hd.last_step_s,
                    "digest": hd.digest()}
        if op == "snapshot":
            # in-band to the router (the survivor that migrates from
            # it — recovery NEVER depends on this worker's disk) AND
            # atomically published in the spool as the on-disk
            # post-mortem record, throttled to ~1/s: the router asks
            # every cadence round, and paying tmp+fsync+rename+dirsync
            # per engine per round would put 2N fsyncs/round of pure
            # post-mortem bookkeeping on the drill's hot path
            now = time.monotonic()
            if now - last_publish_t >= 1.0:
                write_snapshot(engine, spool)
                last_publish_t = now
            return {"snapshot": hd.fetch_snapshot()}
        if op == "probe":
            return {"warm": hd.warm_blocks(req["prompt"])}
        if op == "warm":
            # pre-build the full program set (decode/verify per slot
            # bucket, prefill per chunk bucket, the handoff implant) so
            # a drill can tighten per-call deadlines to STEP scale —
            # a compile inside a deadline-bounded step would otherwise
            # read as a silent worker (the in-process kill drill's
            # prebuild discipline, test_fleet.py); the autoscaler's
            # spawn-then-warm path shares the same primitive
            return {"compiled": engine.warm()}
        if op == "export":
            ref = hd.export(req["uid"])     # writes the wire file
            return {"path": ref.path,
                    "position": ref.position,
                    "blocks_written": ref.blocks_written,
                    "digest": hd.digest()}
        if op == "export_keep":
            # async migration ship-half: snapshot the sequence to the
            # wire WITHOUT evicting — this worker keeps decoding it
            # while the document crosses; finish_export settles up
            ref = hd.export(req["uid"], keep=True)
            return {"path": ref.path,
                    "position": ref.position,
                    "blocks_written": ref.blocks_written,
                    "digest": hd.digest()}
        if op == "finish_export":
            # async migration commit-half: evict now and return the
            # full token list (the shipped snapshot + everything
            # decoded during the ship window — the delta the target
            # teacher-forces), or the abort status if the request
            # finished/failed/was preempted mid-ship
            return {"delta": hd.finish_export(req["uid"]),
                    "digest": hd.digest()}
        if op == "import":
            info = hd.import_doc(HandoffRef(
                -1, 0, 0, path=req["path"]))    # raises WireError
            return {"bytes": info["bytes"],
                    "crc_verify_s": info["crc_verify_s"],
                    "digest": hd.digest()}
        if op == "fetch_wire":
            # TCP side channel, source side: read a published wire
            # file out of THIS worker's spool and answer it as a
            # binary frame right after the JSON line. Confined to the
            # spool — the protocol must not be a remote file reader.
            path = os.path.realpath(req["path"])
            if not path.startswith(os.path.realpath(spool) + os.sep):
                raise ValueError(f"fetch_wire path {req['path']!r} "
                                 "escapes the worker spool")
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise ValueError(f"wire doc unreadable: "
                                 f"{type(e).__name__}: {e}") from None
            return {"_blob": data, "nbytes": len(data)}
        if op == "stage":
            # same-host staging: read + CRC-verify the wire file NOW
            # (a corrupt document must be rejected at stage time, not
            # at commit) and park the verified doc for commit_import
            info = hd.stage_ref(HandoffRef(-1, 0, 0,
                                           path=req["path"]))
            return {**info, "digest": hd.digest()}
        if op == "stage_bytes":
            # TCP staging: the frame after the request line IS the
            # wire doc; deserialize_doc runs the same CRC discipline
            # the spool path gets
            info = hd.stage_bytes(blob_in or b"")
            return {**info, "digest": hd.digest()}
        if op == "commit_import":
            info = hd.commit_import(req["uid"], out=req.get("out"))
            return {"bytes": info["bytes"],
                    "crc_verify_s": info["crc_verify_s"],
                    "catchup_tokens": info["catchup_tokens"],
                    "digest": hd.digest()}
        if op == "discard_stage":
            return {"had": hd.discard_stage(req["uid"]),
                    "digest": hd.digest()}
        if op == "results":
            return {"finished": {str(u): t
                                 for u, t in hd.results().items()},
                    "failed": {str(u): i
                               for u, i in hd.failed_map().items()}}
        if op == "stats":
            return {"stats": hd.stats()}
        if op == "emit_decode":
            hd.emit_decode()
            return {}
        if op == "hang":
            # chaos injection: acknowledge FIRST, then go silent — the
            # router's NEXT call overruns its deadline against a worker
            # that is alive but unresponsive, exactly the hung-peer
            # failure the liveness ladder exists for
            return {"_hang_after_reply_s": float(req["secs"])}
        if op == "shutdown":
            return {"_shutdown": True}
        raise ValueError(f"unknown worker op {op!r}")

    from ..runtime import wire as wire_mod

    def serve(conn: socket.socket) -> bool:
        """One connection's request loop. Returns True only on a clean
        shutdown op; False means the peer dropped — the accept loop
        re-accepts (on a real network a broken connection is a retry,
        not a death)."""
        nonlocal evict_horizon
        rfile = conn.makefile("rb")
        try:
            while True:
                try:
                    line = rfile.readline()
                except OSError:
                    return False
                if not line:
                    return False        # EOF: peer gone, re-accept
                if not line.strip():
                    continue
                req = json.loads(line)
                rid = req.get("id")
                # binary request payload: a length-prefixed frame
                # rides the stream right after the JSON line. Read it
                # BEFORE the dedup check — a replayed stage_bytes
                # re-sends its frame too, and leaving it unread would
                # desync the stream into garbage JSON.
                blob_in = None
                if req.get("op") == "stage_bytes":
                    try:
                        prefix = rfile.read(wire_mod.FRAME_PREFIX_LEN)
                        n = wire_mod.unpack_frame_len(prefix)
                        blob_in = rfile.read(n)
                    except (OSError, WireError):
                        return False    # torn mid-frame: re-accept
                    if len(blob_in) != n:
                        # the request never fully arrived — do NOT
                        # execute or advance dedup state; the peer
                        # replays it on the healed connection
                        return False
                # sequence-numbered dedup: an id we already answered
                # replays its CACHED response — the replayed request
                # must not re-execute (exactly-once side effects)
                if rid is not None and rid in resp_cache:
                    payload, frame = resp_cache[rid]
                    try:
                        conn.sendall(payload if frame is None
                                     else payload + frame)
                    except OSError:
                        return False
                    continue
                if (rid is not None and rid <= evict_horizon
                        and req.get("op") not in IDEMPOTENT_OPS):
                    # executed-and-evicted (or unknowable): refusing
                    # is the only honest answer for a non-idempotent
                    # op — the router escalates to TransportDead and
                    # the snapshot-replay recovery restores state
                    resp = {"id": rid, "ok": False,
                            "error": (f"non-idempotent op "
                                      f"{req.get('op')!r} replayed "
                                      f"past the dedup window (id "
                                      f"{rid} <= evict horizon "
                                      f"{evict_horizon})"),
                            "error_kind": "replay_unsafe",
                            "handle_s": 0.0}
                    try:
                        conn.sendall((json.dumps(resp) + "\n")
                                     .encode("utf-8"))
                    except OSError:
                        return False
                    continue
                # worker-side handle duration rides EVERY response
                # (the digest piggyback stance: zero extra round-
                # trips) — the router subtracts it from its own call
                # wall clock to get the pure RPC overhead (socket +
                # JSON marshal), the round-18 transport attribution
                t0 = time.perf_counter()
                blob_out = None
                try:
                    out = handle(req, blob_in)
                    blob_out = out.pop("_blob", None)
                    resp = {"id": rid, "ok": True, **out}
                except AdmissionError as e:
                    resp = {"id": rid, "ok": False, "error": str(e),
                            "error_kind": "admission"}
                except WireError as e:
                    resp = {"id": rid, "ok": False, "error": str(e),
                            "error_kind": "wire"}
                except ValueError as e:
                    resp = {"id": rid, "ok": False, "error": str(e),
                            "error_kind": "value"}
                except Exception as e:  # noqa: BLE001 — protocol boundary
                    resp = {"id": rid, "ok": False,
                            "error": f"{type(e).__name__}: {e}",
                            "error_kind": "runtime"}
                resp["handle_s"] = round(time.perf_counter() - t0, 6)
                hang_s = resp.pop("_hang_after_reply_s", None)
                done = resp.pop("_shutdown", False)
                if blob_out is not None:
                    resp["frame"] = True
                payload = (json.dumps(resp) + "\n").encode("utf-8")
                frame = (None if blob_out is None
                         else wire_mod.pack_frame(blob_out))
                if rid is not None:
                    # cache AFTER execution, BEFORE the send: a
                    # response lost to a dropped connection must
                    # still be answerable on replay
                    resp_cache[rid] = (payload, frame)
                    while len(resp_cache) > RESPONSE_CACHE_DEPTH:
                        old, _ = resp_cache.popitem(last=False)
                        evict_horizon = max(evict_horizon, old)
                try:
                    conn.sendall(payload if frame is None
                                 else payload + frame)
                except OSError:
                    return False        # response waits in the cache
                if hang_s is not None:
                    time.sleep(hang_s)
                if done:
                    return True
        finally:
            try:
                rfile.close()
            except OSError:
                pass

    try:
        while True:
            conn, _ = server.accept()
            try:
                done = serve(conn)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
            if done:
                break
    finally:
        if metrics is not None:
            metrics.close()
        try:
            server.close()
            if family != "tcp":
                os.unlink(sock_path)
        except OSError:
            pass
    return 0


# ----------------------------------------------- router-side transport

class ProcessEngineHandle:
    """The router's view of one engine worker PROCESS — the same driver
    API as the in-process ``EngineHandle``, over the socket protocol.
    Scheduler-state reads come from the digest riding every response
    (cached; exactly as fresh as the last protocol exchange, which is
    the last time the worker's state could have changed)."""

    transport = "process"

    def __init__(self, eid: str, role: str, spool_dir: str, proc,
                 sock_path: str, *, family: str = "unix",
                 call_deadline_s: float = DEFAULT_CALL_DEADLINE_S,
                 ping_deadline_s: float = DEFAULT_PING_DEADLINE_S,
                 call_retries: int = DEFAULT_CALL_RETRIES):
        self.id = eid
        self.role = role
        self.spool_dir = spool_dir
        self.proc = proc
        self.sock_path = sock_path
        self.family = family
        self.call_deadline_s = call_deadline_s
        self.ping_deadline_s = ping_deadline_s
        self.call_retries = call_retries
        # -- reconnect ladder (round 22) -- TCP gets a reconnect
        # budget by default (a dropped connection is a retry, not a
        # death); AF_UNIX keeps the round-16 semantics (EOF = dead)
        # unless a test opts in by raising max_reconnects
        self.max_reconnects = (DEFAULT_MAX_RECONNECTS
                               if family == "tcp" else 0)
        self.reconnect_deadline_s = DEFAULT_RECONNECT_DEADLINE_S
        self.reconnects = 0
        self.reconnect_log: "collections.deque" = collections.deque(
            maxlen=16)
        # router hook: called (handle, info) after every successful
        # reconnect+replay — FleetRouter emits the schema-v16
        # "reconnected" record from it
        self.on_reconnect = None
        # in-flight requests by id, exactly as sent (plus any binary
        # frame) — the reconnect replay re-sends these verbatim
        self._sent_req: dict[int, tuple[dict, bytes | None]] = {}
        # -- network chaos hooks (runtime/chaos.py) --
        self._partition_until = 0.0     # monotonic heal time
        self.slow_link_s = 0.0          # injected per-send latency
        self._drop_after_send = False   # mid-message RST armed
        self.alive = True
        self.snapshot: dict | None = None
        self.killed_at_round: int | None = None
        self.last_step_s = 0.0
        self.engine = None        # no in-process engine behind this id
        self._sock: socket.socket | None = None
        self._buf = b""
        self._next_id = 0
        self._digest: dict | None = None
        self._digest_id = -1      # response id the cached digest is from
        self._pending: dict | None = None   # in-flight step (begin/end)
        # responses that arrived while awaiting a DIFFERENT id (the
        # dead-host recovery path interleaves calls to a survivor whose
        # own step is still in flight) — parked here, never dropped
        self._resp_buf: dict[int, dict] = {}
        # -- RPC cost attribution + postmortem evidence (round 18) --
        # every in-flight call id maps to (op, send time); a parked
        # response stamps its receive time at parse, so call duration
        # = recv - send even when consumed out of order. Per-op
        # (call_s, handle_s) samples feed rpc_stats(); bounded rings
        # hold the postmortem evidence (op log, ping RTTs, backoff
        # sleeps) a dead-host declaration dumps.
        self._sent: dict[int, tuple[str, float]] = {}
        self._recv_t: dict[int, float] = {}
        self.op_samples: dict[str, collections.deque] = {}
        # unbounded run totals (the overhead-share numerator): the
        # per-op sample rings are capped at 4096, but round_wall_s on
        # the router is not — summing the rings would silently
        # understate the share on a long run
        self.call_total_s = 0.0
        self.overhead_total_s = 0.0
        self.op_log: "collections.deque" = collections.deque(maxlen=64)
        self.backoff_log: "collections.deque" = collections.deque(
            maxlen=64)

    # -- wire plumbing -------------------------------------------------

    def connect(self, deadline_s: float = DEFAULT_CONNECT_DEADLINE_S
                ) -> None:
        """Connect to the worker's socket, retrying refusals under
        bounded exponential backoff while it boots (jax import +
        engine build; under TCP also the not-yet-published port file).
        A worker that exits first raises ``TransportDead`` with its
        log tail."""
        from ..runtime.failure import backoff_delay
        t0 = time.monotonic()
        attempt = 0
        while True:
            if self.proc.poll() is not None:
                raise TransportDead(
                    f"worker {self.id} exited rc {self.proc.returncode} "
                    f"before accepting: {self._log_tail()}")
            try:
                self._sock = self._open_sock()
                return
            except (FileNotFoundError, ConnectionRefusedError):
                if time.monotonic() - t0 > deadline_s:
                    raise TransportTimeout(
                        f"worker {self.id} did not accept within "
                        f"{deadline_s:.0f}s") from None
                time.sleep(backoff_delay(attempt, 0.05, 1.0, 0.0,
                                         random.Random(0)))
                attempt += 1

    def _open_sock(self) -> socket.socket:
        """One raw connect attempt on the configured family. TCP
        resolves the worker's atomically-published port file each
        attempt (a restarted worker republished a fresh port)."""
        if self.family == "tcp":
            path = os.path.join(self.spool_dir, WORKER_PORT_FILENAME)
            with open(path) as f:     # FileNotFoundError: still booting
                port = int(json.load(f)["port"])
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.connect(("127.0.0.1", port))
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                s.close()
                raise
            return s
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.connect(self.sock_path)
        except OSError:
            s.close()
            raise
        return s

    def _log_tail(self, n: int = 400) -> str:
        try:
            with open(os.path.join(self.spool_dir,
                                   WORKER_LOG_FILENAME)) as f:
                return f.read()[-n:].replace("\n", " | ")
        except OSError:
            return "(no worker log)"

    def _teardown_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        # partial lines/frames from the torn stream are garbage on
        # the healed one — the replay re-delivers complete responses
        self._buf = b""

    def _conn_lost(self, err) -> None:
        """A CONNECTION-level failure (send error, recv error, EOF) —
        the round-22 fork in the liveness ladder. A dead process or an
        exhausted reconnect budget escalates to ``TransportDead``;
        otherwise the ladder reconnects and replays, and the caller
        carries on against the healed link. Deadline overruns never
        come here — slow-link stays ``TransportTimeout``."""
        if self.proc.poll() is not None:
            raise TransportDead(
                f"worker {self.id} closed its connection (process "
                f"exited rc {self.proc.returncode}): "
                f"{self._log_tail()}")
        if self.reconnects >= self.max_reconnects:
            raise TransportDead(
                f"worker {self.id} connection failed "
                f"({type(err).__name__}: {err}) with no reconnect "
                f"budget left ({self.reconnects}/"
                f"{self.max_reconnects})")
        self._reconnect(err)

    def _reconnect(self, cause) -> None:
        """Heal a dropped connection: bounded-backoff re-connect
        (waiting out any armed chaos partition), then the ``sync``
        handshake and a sequence-numbered replay of every in-flight
        request by original id — the worker answers executed ids from
        its dedup cache, executes never-arrived ids fresh, and a
        non-idempotent id past the cache window is refused here as
        ``TransportDead`` (see ``replay_verdict``)."""
        from ..runtime.failure import backoff_delay
        t_gone = time.monotonic()
        self._teardown_sock()
        # an armed partition extends the window by its remaining
        # duration: waiting the partition out is the drill's point
        deadline = self.reconnect_deadline_s + max(
            0.0, self._partition_until - t_gone)
        attempt = 0
        while True:
            if self.proc.poll() is not None:
                raise TransportDead(
                    f"worker {self.id} died during reconnect "
                    f"(rc {self.proc.returncode}): {self._log_tail()}")
            now = time.monotonic()
            if now - t_gone > deadline:
                raise TransportDead(
                    f"worker {self.id} reconnect deadline "
                    f"({deadline:.1f}s) exhausted after "
                    f"{type(cause).__name__}: {cause}")
            if now < self._partition_until:
                # the link is partitioned BOTH ways: no connect can
                # succeed before the heal time
                time.sleep(min(0.05, self._partition_until - now))
                continue
            try:
                self._sock = self._open_sock()
                break
            except OSError:
                delay = backoff_delay(attempt, 0.05, 1.0, 0.0,
                                      random.Random(0))
                self.backoff_log.append({"t": time.time(),
                                         "attempt": attempt,
                                         "backoff_s": round(delay, 3),
                                         "deadline_s": round(deadline,
                                                             3),
                                         "phase": "reconnect"})
                time.sleep(delay)
                attempt += 1
        sync = self._sync_call()
        horizon, cached = int(sync["horizon"]), set(sync["cached"])
        replayed = []
        for rid in sorted(self._sent_req):
            req, frame = self._sent_req[rid]
            verdict = replay_verdict(req.get("op", "?"), rid, horizon,
                                     cached)
            if verdict == "refuse":
                raise TransportDead(
                    f"worker {self.id}: non-idempotent op "
                    f"{req.get('op')!r} (id {rid}) lost past the "
                    "dedup window — refusing replay without a "
                    "sequence ack")
            payload = (json.dumps(req) + "\n").encode("utf-8")
            if frame is not None:
                payload += frame
            try:
                self._sock.sendall(payload)
            except OSError as e:
                raise TransportDead(
                    f"worker {self.id} reconnect replay failed: "
                    f"{type(e).__name__}: {e}") from None
            replayed.append({"id": rid, "op": req.get("op"),
                             "verdict": verdict})
        self.reconnects += 1
        info = {"attempts": attempt + 1,
                "gap_s": round(time.monotonic() - t_gone, 4),
                "cause": f"{type(cause).__name__}: {cause}",
                "replayed": replayed}
        self.reconnect_log.append({"t": time.time(), **info})
        if self.on_reconnect is not None:
            self.on_reconnect(self, info)

    def _sync_call(self, deadline_s: float = 10.0) -> dict:
        """The reconnect handshake, OUTSIDE the replay bookkeeping (it
        must not itself be replayed). A second failure here is an
        honest dead-host verdict — the link dropped twice inside one
        healing attempt."""
        self._next_id += 1
        rid = self._next_id
        try:
            self._sock.sendall(
                (json.dumps({"op": "sync", "id": rid}) + "\n")
                .encode("utf-8"))
        except OSError as e:
            raise TransportDead(f"worker {self.id} sync send failed: "
                                f"{type(e).__name__}: {e}") from None
        end = time.monotonic() + deadline_s
        while True:
            if b"\n" in self._buf:
                line, self._buf = self._buf.split(b"\n", 1)
                resp = json.loads(line)
                if resp.get("id") == rid:
                    return resp
                continue    # stale pre-replay noise: impossible on a
                # fresh connection, but skipping is strictly safer
            remaining = end - time.monotonic()
            if remaining <= 0:
                raise TransportDead(
                    f"worker {self.id} sync handshake timed out "
                    f"({deadline_s:.1f}s)")
            self._sock.settimeout(remaining)
            try:
                chunk = self._sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError as e:
                raise TransportDead(
                    f"worker {self.id} sync recv failed: "
                    f"{type(e).__name__}: {e}") from None
            if not chunk:
                raise TransportDead(
                    f"worker {self.id} closed during sync handshake")
            self._buf += chunk

    def _send(self, req: dict, frame: bytes | None = None) -> int:
        self._next_id += 1
        # capture the id NOW: a send that trips the reconnect ladder
        # runs the sync handshake, which takes the NEXT id off this
        # counter — returning self._next_id after _send_wire would
        # hand the caller the sync's id and strand the real response
        rid = self._next_id
        req = {**req, "id": rid}
        # stamp the send BEFORE the marshal+sendall so the call
        # duration prices the full router-side cost of the op
        self._sent[rid] = (req.get("op", "?"), time.perf_counter())
        # replay store: the request exactly as sent, until its
        # response is parsed off the stream
        self._sent_req[rid] = (req, frame)
        self._send_wire(req, frame)
        return rid

    def _send_wire(self, req: dict, frame: bytes | None) -> None:
        payload = (json.dumps(req) + "\n").encode("utf-8")
        if frame is not None:
            payload += frame
        if self.slow_link_s > 0:
            time.sleep(self.slow_link_s)  # chaos: injected link latency
        try:
            if self._sock is None:
                raise OSError("connection is down")
            self._sock.sendall(payload)
        except OSError as e:
            # the request is already in the replay store: a
            # successful reconnect re-sends it, so returning here
            # means "sent on the healed link"
            self._conn_lost(e)
            return
        if self._drop_after_send:
            # drop_conn chaos: tear the connection with the response
            # in flight — the canonical mid-message RST
            self._drop_after_send = False
            self._teardown_sock()

    def _recv_line(self, deadline_s: float) -> bytes:
        """One newline-framed response within ``deadline_s``, with
        bounded-backoff retries absorbing transient slowness before the
        silent-worker verdict. Connection failures fork to the
        reconnect ladder (``_conn_lost``) — a healed link restarts the
        deadline window; deadline overruns stay ``TransportTimeout``."""
        from ..runtime.failure import backoff_delay
        for attempt in range(self.call_retries + 1):
            end = time.monotonic() + deadline_s
            while b"\n" not in self._buf:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    break
                if self._sock is None:
                    self._conn_lost(OSError("connection is down"))
                    end = time.monotonic() + deadline_s
                    continue
                self._sock.settimeout(remaining)
                try:
                    chunk = self._sock.recv(1 << 16)
                except socket.timeout:
                    break
                except OSError as e:
                    self._conn_lost(e)
                    end = time.monotonic() + deadline_s
                    continue
                if not chunk:
                    self._conn_lost(
                        EOFError("worker closed its connection"))
                    end = time.monotonic() + deadline_s
                    continue
                self._buf += chunk
            if b"\n" in self._buf:
                line, self._buf = self._buf.split(b"\n", 1)
                return line
            if attempt < self.call_retries:
                delay = backoff_delay(attempt, 0.05, 2.0, 0.0,
                                      random.Random(0))
                # postmortem evidence: the ladder's own retry history
                self.backoff_log.append({"t": time.time(),
                                         "attempt": attempt,
                                         "backoff_s": round(delay, 3),
                                         "deadline_s": deadline_s})
                time.sleep(delay)
        raise TransportTimeout(
            f"worker {self.id} silent past its {deadline_s:.1f}s "
            f"deadline ({self.call_retries + 1} attempt(s) with "
            "backoff)")

    def _recv_exact(self, n: int, deadline_s: float) -> bytes | None:
        """``n`` raw bytes off the stream (a binary frame). Returns
        None when the connection tore mid-frame and the ladder
        reconnected — the replayed request delivers a fresh complete
        response, so the caller discards this torn one."""
        end = time.monotonic() + deadline_s
        out = bytearray()
        while len(out) < n:
            if self._buf:
                take = min(n - len(out), len(self._buf))
                out += self._buf[:take]
                self._buf = self._buf[take:]
                continue
            remaining = end - time.monotonic()
            if remaining <= 0:
                raise TransportTimeout(
                    f"worker {self.id} silent mid-frame past its "
                    f"{deadline_s:.1f}s deadline")
            if self._sock is None:
                self._conn_lost(OSError("connection is down"))
                return None
            self._sock.settimeout(remaining)
            try:
                chunk = self._sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError as e:
                self._conn_lost(e)
                return None
            if not chunk:
                self._conn_lost(EOFError("worker closed mid-frame"))
                return None
            self._buf += chunk
        return bytes(out)

    def _recv_frame(self, deadline_s: float) -> bytes | None:
        from ..runtime import wire as wire_mod
        prefix = self._recv_exact(wire_mod.FRAME_PREFIX_LEN,
                                  deadline_s)
        if prefix is None:
            return None
        return self._recv_exact(wire_mod.unpack_frame_len(prefix),
                                deadline_s)

    def _call(self, op: str, deadline_s: float | None = None,
              frame: bytes | None = None, **payload) -> dict:
        rid = self._send({"op": op, **payload}, frame=frame)
        return self._await(rid, deadline_s)

    def _await(self, rid: int, deadline_s: float | None = None) -> dict:
        deadline = (self.call_deadline_s if deadline_s is None
                    else deadline_s)
        while rid not in self._resp_buf:
            resp = json.loads(self._recv_line(deadline))
            if resp.get("frame"):
                # a binary frame rides right after this line — it
                # MUST come off the stream before the next readline
                blob = self._recv_frame(deadline)
                if blob is None:
                    continue  # torn mid-frame: the replay re-delivers
                resp["_blob"] = blob
            # receive time stamped at PARSE, not at consume: a parked
            # response's call duration must not be charged for the
            # interleaved work that delayed its pop
            self._recv_t[resp.get("id")] = time.perf_counter()
            self._resp_buf[resp.get("id")] = resp
            # answered ⇒ no longer in flight ⇒ out of the replay store
            self._sent_req.pop(resp.get("id"), None)
        resp = self._resp_buf.pop(rid)
        sent = self._sent.pop(rid, None)
        recv_t = self._recv_t.pop(rid, None)
        if sent is not None and recv_t is not None:
            op, t0 = sent
            call_s = recv_t - t0
            self.op_samples.setdefault(
                op, collections.deque(maxlen=4096)).append(
                (call_s, resp.get("handle_s")))
            self.call_total_s += call_s
            if resp.get("handle_s") is not None:
                self.overhead_total_s += call_s - resp["handle_s"]
            self.op_log.append({"op": op, "id": rid,
                                "t": round(time.time(), 4),
                                "call_ms": round(call_s * 1e3, 3),
                                "ok": bool(resp.get("ok"))})
        if "digest" in resp and rid > self._digest_id:
            # the worker answers in order, so the digest from the
            # HIGHEST response id is the freshest scheduler state —
            # an out-of-order consume must not roll the cache back
            self._digest = resp["digest"]
            self._digest_id = rid
        if not resp.get("ok"):
            self._raise_remote(resp)
        return resp

    @staticmethod
    def _raise_remote(resp: dict):
        from ..runtime.wire import WireError
        from .engine import AdmissionError
        kind = resp.get("error_kind")
        msg = resp.get("error", "worker error")
        if kind == "admission":
            raise AdmissionError(msg)
        if kind == "wire":
            raise WireError(msg)
        if kind == "value":
            raise ValueError(msg)
        if kind == "replay_unsafe":
            # the worker itself refused a non-idempotent replay — the
            # same dead-host verdict the router-side refusal takes
            raise TransportDead(msg)
        raise RuntimeError(msg)

    # -- the driver API (EngineHandle's surface) -----------------------

    def model_meta(self) -> dict:
        resp = self._call("meta")
        if resp["mesh"]:
            raise ValueError("fleet replicas are single-device "
                             "(KV handoff has no TP path)")
        return resp["model"]

    def validate_member(self) -> None:
        """Single-device membership is validated by ``model_meta`` (the
        construction-time cross-check calls it on every member)."""

    @property
    def has_work(self) -> bool:
        if not self.alive or self._digest is None:
            return False
        return bool(self._digest["waiting"] or self._digest["active"])

    def digest(self, light: bool = False) -> dict:
        # `light` is the in-process handle's hot-path flag; here the
        # cached digest from the last response is returned either way
        if self._digest is None:
            self._call("digest")
        return self._digest

    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        from .engine import blocks_needed
        return blocks_needed(prompt_len, max_new, self._block_size)

    def max_blocks_per_seq(self) -> int:
        return self._max_blocks_per_seq

    def warm_blocks(self, prompt) -> int | None:
        return self._call("probe", prompt=[int(t) for t in prompt])[
            "warm"]

    def submit(self, prompt, max_new: int, uid: int,
               trace: str | None = None,
               tenant: str | None = None) -> dict:
        return self._call("submit", prompt=[int(t) for t in prompt],
                          max_new=int(max_new), uid=int(uid),
                          trace=trace, tenant=tenant)["entry"]

    def resume_request(self, uid: int, prompt, max_new: int, *, out=(),
                       retries: int = 0, t_submit=None,
                       t_first=None, weights_version=None,
                       trace=None, tenant=None) -> None:
        self._call("resume", uid=int(uid),
                   prompt=[int(t) for t in prompt],
                   max_new=int(max_new), out=[int(t) for t in out],
                   retries=int(retries), t_submit=t_submit,
                   t_first=t_first,
                   weights_version=(None if weights_version is None
                                    else int(weights_version)),
                   trace=trace, tenant=tenant)

    def release_request(self, uid: int) -> dict:
        return self._call("release", uid=int(uid))["entry"]

    # -- weight lifecycle (round 17, DESIGN.md section 23) -------------

    @property
    def serving_version(self) -> int:
        return int(self.digest()["serving_version"])

    def load_weights(self, version: int, ckpt_dir: str, step: int,
                     params=None) -> dict:
        """The swap half of the rolling deploy, worker-side: the
        worker restores checkpoint ``step`` from the SHARED ledger dir
        itself (weights never ride the socket — the spool-file stance)
        and double-buffers it as ``version``. ``params`` is the
        in-process transport's shortcut and is ignored here. A torn
        step fails the worker's own CRC ladder and crosses back as
        the one-line rejection the router's rollback names."""
        return self._call("load_weights", version=int(version),
                          ckpt_dir=ckpt_dir, step=int(step))[
                              "fingerprint"]

    def set_serving_version(self, version: int) -> None:
        self._call("set_version", version=int(version))

    def step_begin(self, prefill_only: bool = False) -> None:
        """SEND the step — every worker's step runs concurrently in its
        own process; ``step_end`` collects."""
        rid = self._send({"op": "step", "prefill_only": prefill_only})
        self._pending = {"rid": rid}

    def step_end(self) -> bool:
        pending, self._pending = self._pending, None
        resp = self._await(pending["rid"])
        self.last_step_s = float(resp["step_s"])
        return bool(resp["did"])

    def fetch_snapshot(self) -> dict:
        return self._call("snapshot")["snapshot"]

    def export(self, uid: int) -> HandoffRef:
        resp = self._call("export", uid=int(uid))
        return HandoffRef(uid, int(resp["position"]),
                          int(resp["blocks_written"]),
                          path=resp["path"])

    def import_doc(self, ref: HandoffRef) -> dict:
        resp = self._call("import", path=ref.path)
        return {"mode": "wire", "bytes": int(resp["bytes"]),
                "crc_verify_s": resp["crc_verify_s"]}

    # -- async migration + TCP side channel (round 22) -----------------

    def export_keep(self, uid: int) -> HandoffRef:
        """Ship-half of an async migration: snapshot ``uid`` to the
        wire WITHOUT evicting — the worker keeps decoding it."""
        resp = self._call("export_keep", uid=int(uid))
        return HandoffRef(uid, int(resp["position"]),
                          int(resp["blocks_written"]),
                          path=resp["path"])

    def finish_export(self, uid: int) -> dict:
        """Commit-half: evict ``uid`` now and return its final token
        list (``{"status": "resident", "out": [...], "position": n}``)
        — or the abort status when the request finished/failed/was
        preempted during the ship window."""
        return self._call("finish_export", uid=int(uid))["delta"]

    def fetch_wire(self, path: str) -> bytes:
        """Pull a published wire file off THIS worker's spool as raw
        bytes (the TCP streaming side channel's source half)."""
        return self._call("fetch_wire", path=path)["_blob"]

    def stage_ref(self, ref: HandoffRef) -> dict:
        """Same-host staging: the target worker reads + CRC-verifies
        the spool file now and parks the doc for ``commit_import``."""
        resp = self._call("stage", path=ref.path)
        return {"uid": int(resp["uid"]), "mode": "wire",
                "bytes": int(resp["bytes"]),
                "crc_verify_s": resp["crc_verify_s"]}

    def stage_bytes(self, data: bytes) -> dict:
        """TCP staging: stream the wire doc over the socket as a
        length-prefixed frame; the worker CRC-verifies on arrival."""
        from ..runtime.wire import pack_frame
        resp = self._call("stage_bytes", frame=pack_frame(data))
        return {"uid": int(resp["uid"]), "mode": "tcp",
                "bytes": int(resp["bytes"]),
                "crc_verify_s": resp["crc_verify_s"]}

    def commit_import(self, uid: int, out=None) -> dict:
        """Import the staged doc; ``out`` (when given) patches the
        token list to the source's final one first — the engine
        teacher-forces the delta (the catch-up replay)."""
        resp = self._call("commit_import", uid=int(uid),
                          out=(None if out is None
                               else [int(t) for t in out]))
        return {"bytes": int(resp["bytes"]),
                "crc_verify_s": resp["crc_verify_s"],
                "catchup_tokens": int(resp["catchup_tokens"])}

    def discard_stage(self, uid: int) -> bool:
        return bool(self._call("discard_stage", uid=int(uid))["had"])

    def _results_resp(self) -> dict:
        """One 'results' round-trip serves both results() and
        failed_map() (the drain path calls them back to back; the op
        returns both halves, and re-shipping every finished token list
        for the failed half would be pure waste). The cache is valid
        only while NO other protocol call intervenes — any call
        advances ``_next_id`` and invalidates it."""
        cached = getattr(self, "_results_cache", None)
        if cached is not None and cached[0] == self._next_id:
            return cached[1]
        resp = self._call("results")
        self._results_cache = (self._next_id, resp)
        return resp

    def results(self) -> dict[int, list[int]]:
        return {int(u): list(t) for u, t
                in self._results_resp()["finished"].items()}

    def failed_map(self) -> dict[int, dict]:
        return {int(u): dict(i) for u, i
                in self._results_resp()["failed"].items()}

    def stats(self) -> dict:
        return self._call("stats")["stats"]

    def emit_decode(self) -> None:
        self._call("emit_decode")

    # -- transport attribution (round 18, DESIGN.md section 24) --------

    def rpc_stats(self) -> dict | None:
        """Per-op RPC cost attribution off the recorded samples:
        router-side call duration percentiles, worker-side handle
        durations (piggybacked on every response), and their
        difference — the pure transport overhead (socket + JSON
        marshal + scheduling). ``ping`` doubles as the heartbeat RTT
        sample set. None until any call completed."""
        if not self.op_samples:
            return None

        def pcts(vals):
            import numpy as np
            arr = np.asarray(vals, np.float64) * 1e3
            return (round(float(np.percentile(arr, 50)), 3),
                    round(float(np.percentile(arr, 99)), 3))

        ops = {}
        for op, samples in sorted(self.op_samples.items()):
            calls = [c for c, _ in samples]
            overheads = [c - h for c, h in samples if h is not None]
            p50, p99 = pcts(calls)
            entry = {"n": len(samples), "call_p50_ms": p50,
                     "call_p99_ms": p99}
            if overheads:
                o50, o99 = pcts(overheads)
                entry["overhead_p50_ms"] = o50
                entry["overhead_p99_ms"] = o99
            ops[op] = entry
        # totals come from the unbounded accumulators, not the capped
        # rings — the overhead share must cover the WHOLE run that
        # round_wall_s covers (percentiles stay over the recent ring)
        out = {"ops": ops,
               "call_total_s": round(self.call_total_s, 6),
               "overhead_total_s": round(self.overhead_total_s, 6)}
        pings = self.op_samples.get("ping")
        if pings:
            p50, p99 = pcts([c for c, _ in pings])
            out["heartbeat_rtt_p50_ms"] = p50
            out["heartbeat_rtt_p99_ms"] = p99
            out["heartbeats"] = len(pings)
        return out

    def evidence(self) -> dict:
        """The router-side postmortem evidence for this worker: the
        last cached digest (and which call delivered it), in-flight
        call ids, the bounded op/backoff/ping history — everything the
        router knew at declaration time. The worker's own flight
        recorder dies with its process; this half survives because the
        router holds it."""
        pings = self.op_samples.get("ping") or ()
        return {
            "transport": self.transport,
            "family": self.family,
            "reconnects": self.reconnects,
            "reconnect_log": list(self.reconnect_log),
            "alive": self.alive,
            "pid": self.proc.pid,
            "process_rc": self.proc.poll(),
            "last_digest": self._digest,
            "last_digest_call_id": self._digest_id,
            "pending_call_ids": sorted(self._sent),
            "pending_step": (None if self._pending is None
                             else self._pending.get("rid")),
            "op_log": list(self.op_log),
            "backoff_log": list(self.backoff_log),
            "ping_rtt_ms": [round(c * 1e3, 3) for c, _ in pings][-16:],
            "last_snapshot_step": (None if self.snapshot is None
                                   else self.snapshot.get("step")),
            "last_snapshot_requests": (
                None if self.snapshot is None
                else len(self.snapshot.get("requests", ()))),
            "log_tail": self._log_tail(),
        }

    # -- liveness ------------------------------------------------------

    def ping(self) -> None:
        self._call("ping", deadline_s=self.ping_deadline_s)

    def warm(self, deadline_s: float = 600.0) -> int:
        """Pre-compile the worker's full program set (generous
        deadline — this IS the compile phase); returns its compile
        count. Tighten ``call_deadline_s`` after this, never before."""
        return int(self._call("warm", deadline_s=deadline_s)["compiled"])

    def hang(self, secs: float) -> None:
        """Chaos: tell the worker to go silent for ``secs`` right after
        acknowledging — its next real call must trip the deadline."""
        self._call("hang", secs=float(secs))

    # -- network chaos (round 22, runtime/chaos.py) --------------------

    def partition(self, secs: float) -> None:
        """Chaos: drop the link BOTH ways for ``secs`` — the socket
        closes now, and no reconnect can complete before the heal
        time; the ladder waits the partition out instead of declaring
        death."""
        self._partition_until = time.monotonic() + float(secs)
        self._teardown_sock()

    def slow_link(self, ms: float) -> None:
        """Chaos: inject ``ms`` of latency ahead of every send — a
        SLOW link, not a dead one; per-call deadlines must absorb it
        without paging the liveness ladder."""
        self.slow_link_s = float(ms) / 1e3

    def drop_conn(self) -> None:
        """Chaos: arm a mid-message connection drop — the next send
        tears the socket with the response in flight; the reconnect
        replay must lose no response and duplicate no side effect
        (the worker's dedup cache answers the replayed id)."""
        self._drop_after_send = True

    def kill(self) -> None:
        """SIGKILL the worker process — a real dead host. Idempotent;
        also the zombie-fencing step of a dead declaration (a hung
        worker that wakes later must not answer anything)."""
        self.alive = False
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        try:
            self.proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — best-effort reap
            pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        """Graceful shutdown: ask the worker to exit (its telemetry
        writer flushes), then reap; SIGKILL if it lingers."""
        if not self.alive:
            return
        try:
            self._call("shutdown", deadline_s=10.0)
        except (TransportError, OSError):
            pass
        try:
            self.proc.wait(timeout=15)
        except Exception:  # noqa: BLE001
            self.proc.kill()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self.alive = False


# --------------------------------------------------------------- spawn

def _start_worker_proc(eid: str, role: str, base_dir: str, *,
                       model: dict, config: dict, policy: dict,
                       qos: dict | None = None, family: str = "unix",
                       metrics_dir=None, meta=None, env=None):
    """Write one worker's config and start its process (detached; log
    in its spool). Returns ``(spool, proc, sock_path)`` — connection
    happens separately so a fleet can boot every jax import in
    parallel before the first (slow) connect. ``qos`` is an optional
    ``QosPolicy.as_dict()`` — the per-tenant scheduling policy rides
    the config file, never the socket. ``family`` picks the socket:
    ``"unix"`` (spool-local, same-host) or ``"tcp"`` (loopback
    ephemeral port, published atomically in the spool)."""
    spool = os.path.join(base_dir, eid)
    os.makedirs(spool, exist_ok=True)
    sock_path = os.path.join(spool, WORKER_SOCKET_FILENAME)
    cfg = {"engine_id": eid, "role": role, "socket_path": sock_path,
           "spool_dir": spool, "metrics_dir": metrics_dir,
           "meta": {**(meta or {}), "engine_id": eid, "role": role},
           "model": model, "config": config, "policy": policy,
           "qos": qos, "family": family}
    cfg_path = os.path.join(spool, WORKER_CONFIG_FILENAME)
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    log = open(os.path.join(spool, WORKER_LOG_FILENAME), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_llm_code_samples_tpu.decode.worker", cfg_path],
        stdout=log, stderr=subprocess.STDOUT,
        env=None if env is None else dict(env), start_new_session=True)
    log.close()
    return spool, proc, sock_path


def _connect_and_prime(h: ProcessEngineHandle, config: dict,
                       connect_deadline_s: float) -> None:
    """Connect a freshly-spawned handle and prime its config-derived
    capacity math + initial digest cache. The capacity fields resolve
    through ``EngineConfig`` itself — the exact defaulting the worker
    applies — so a partial config dict can never make the router's
    ``blocks_needed`` math disagree with the engine's admission
    math."""
    from .engine import EngineConfig
    h.connect(deadline_s=connect_deadline_s)
    ec = EngineConfig(**config)
    h._block_size = ec.block_size
    h._max_blocks_per_seq = ec.max_blocks_per_seq
    h._call("digest")
    # the priming digest's wall clock is the WORKER BOOT (connect
    # lands in the listen backlog before the jax import; the worker
    # only answers once its engine exists) — that is spawn cost, not
    # transport cost, and it must not pollute the per-op RPC
    # percentiles rpc_stats() reports (the op_log keeps it: boot time
    # is legitimate postmortem evidence)
    h.op_samples.clear()
    h.call_total_s = h.overhead_total_s = 0.0


def spawn_worker(eid: str, role: str, base_dir: str, *, model: dict,
                 config: dict, policy: dict, qos: dict | None = None,
                 family: str = "unix",
                 metrics_dir=None, meta=None, env=None,
                 call_deadline_s: float = DEFAULT_CALL_DEADLINE_S,
                 ping_deadline_s: float = DEFAULT_PING_DEADLINE_S,
                 connect_deadline_s: float = DEFAULT_CONNECT_DEADLINE_S,
                 ) -> ProcessEngineHandle:
    """Start one engine worker process and connect to it. ``model`` is
    the ``init_lm`` recipe (vocab/model_size/layers/heads/kv_heads/
    max_seq_len/random_seed — every worker rebuilds the identical
    weights from it); ``config``/``policy`` the EngineConfig/
    ServePolicy kwargs. The worker's spool dir (``base_dir/eid``)
    holds its config, socket, log, wire handoffs, and published
    snapshots."""
    spool, proc, sock_path = _start_worker_proc(
        eid, role, base_dir, model=model, config=config, policy=policy,
        qos=qos, family=family, metrics_dir=metrics_dir, meta=meta,
        env=env)
    h = ProcessEngineHandle(eid, role, spool, proc, sock_path,
                            family=family,
                            call_deadline_s=call_deadline_s,
                            ping_deadline_s=ping_deadline_s)
    try:
        _connect_and_prime(h, config, connect_deadline_s)
    except TransportError:
        h.kill()
        raise
    return h


def spawn_fleet_handles(n_engines: int, prefill_engines: int,
                        base_dir: str, *, model: dict, config: dict,
                        policy: dict, qos: dict | None = None,
                        family: str = "unix",
                        metrics_root=None, meta=None, env=None,
                        call_deadline_s: float = DEFAULT_CALL_DEADLINE_S,
                        ping_deadline_s: float = DEFAULT_PING_DEADLINE_S,
                        connect_deadline_s: float =
                        DEFAULT_CONNECT_DEADLINE_S) -> list:
    """Spawn the whole fleet's worker processes (prefill tier first,
    the router's id convention), launching all of them BEFORE the
    first connect so their jax imports boot in parallel. On any spawn
    failure every already-started worker is killed — no orphans."""
    from .fleet import DECODE_PREFIX, PREFILL_PREFIX
    ids = [(f"{PREFILL_PREFIX}{i}", "prefill")
           for i in range(prefill_engines)]
    ids += [(f"{DECODE_PREFIX}{i}", "decode")
            for i in range(n_engines - prefill_engines)]
    handles: list[ProcessEngineHandle] = []
    procs: list = []
    try:
        # phase 1: start every process (parallel boot)
        for eid, role in ids:
            mdir = (os.path.join(metrics_root, eid)
                    if metrics_root else None)
            spool, proc, sock_path = _start_worker_proc(
                eid, role, base_dir, model=model, config=config,
                policy=policy, qos=qos, family=family,
                metrics_dir=mdir, meta=meta, env=env)
            procs.append((eid, role, spool, proc, sock_path))
        # phase 2: connect to each
        for eid, role, spool, proc, sock_path in procs:
            h = ProcessEngineHandle(eid, role, spool, proc, sock_path,
                                    family=family,
                                    call_deadline_s=call_deadline_s,
                                    ping_deadline_s=ping_deadline_s)
            handles.append(h)
            _connect_and_prime(h, config, connect_deadline_s)
        return handles
    except Exception:
        for h in handles:
            h.kill()
        for tup in procs[len(handles):]:
            try:
                tup[3].kill()
            except OSError:
                pass
        raise


if __name__ == "__main__":
    sys.exit(worker_main())
