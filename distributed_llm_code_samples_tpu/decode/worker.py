"""Engine worker process + the router-side transport client.

The PR 10 fleet was a simulation of distribution: every replica lived
in the router's process, a "kill" dropped a Python object, and the
handoff doc never crossed a serialization boundary — so none of the
failure modes a real fleet must survive (torn writes, half-shipped
handoffs, silently hung workers, stale liveness) could even occur.
This module makes the fleet span real OS processes:

- ``worker_main`` runs ONE ``DecodeEngine`` in its own process behind
  a small request/response protocol: newline-delimited JSON over an
  ``AF_UNIX`` socket (the worker binds and accepts exactly one
  connection — its router). Control messages are tiny; KV NEVER rides
  the socket — handoff documents cross as versioned wire files
  (``runtime/wire.py``: npz + per-array CRC-32, atomically published
  in the worker's spool directory), the same staging-file pattern a
  multi-host transport would use. Every response carries the worker's
  scheduler-state ``digest`` so the router's routing/migration
  decisions read fresh state with zero extra round-trips.

- ``ProcessEngineHandle`` is the router side: the same driver API as
  the in-process ``EngineHandle`` (``decode/fleet.py``), implemented
  as protocol calls with **per-call deadlines**. The liveness ladder:
  a recv that overruns its deadline retries under bounded exponential
  backoff (``runtime.failure.backoff_delay`` — the training
  supervisor's schedule, reused); exhausted retries raise
  ``TransportTimeout``, EOF/reset raises ``TransportDead``; the router
  converts either into a dead-host declaration (SIGKILL the process so
  a zombie cannot answer a stale request later) and migrates its
  requests from the last snapshot — the identical recovery path an
  explicit kill takes, because "stopped answering" and "dead" must be
  the same thing for recovery to be correct.

- ``spawn_worker`` / ``spawn_fleet_handles`` write each worker's JSON
  config, start ``python -m ...decode.worker CONFIG`` processes, and
  connect with the same bounded backoff (worker startup pays the jax
  import + program compiles; a connect refused while it boots is the
  canonical transient transport error).

Determinism across the boundary: each worker builds its params from
the SAME ``init_lm`` seed the router's config names, and the router
cross-checks ``model_meta()`` fingerprints at construction — so the
process fleet serves bit-identical weights, and the engine's
``(seed, uid, position)`` sampling contract makes every migration
token-identical exactly as in-process. Snapshots ride the protocol
in-band (the router holds them — the recovery state must survive the
WORKER's death, and the router is the survivor) and are additionally
published atomically in the worker's spool dir
(``decode/supervise.py::write_snapshot`` via ``runtime/wire.py``) as
the on-disk post-mortem record.
"""

from __future__ import annotations

import collections
import json
import os
import random
import socket
import subprocess
import sys
import time

from .fleet import (HandoffRef, TransportDead, TransportError,
                    TransportTimeout)

WORKER_CONFIG_FILENAME = "worker_config.json"
WORKER_SOCKET_FILENAME = "worker.sock"
WORKER_LOG_FILENAME = "worker.log"

# per-call deadline defaults (seconds). The first step call after spawn
# may compile XLA programs — its deadline must cover a cold compile;
# the drills that want fast hang detection lower call_deadline_s
# explicitly once their program set is warm.
DEFAULT_CALL_DEADLINE_S = 120.0
DEFAULT_PING_DEADLINE_S = 5.0
DEFAULT_CONNECT_DEADLINE_S = 120.0
# bounded-backoff retries for a timed-out recv before the worker is
# declared silent (failure.backoff_delay schedule, jitter off for
# deterministic drills)
DEFAULT_CALL_RETRIES = 1


# ---------------------------------------------------------------- worker

def worker_main(argv=None) -> int:
    """Run one engine worker: ``python -m
    distributed_llm_code_samples_tpu.decode.worker CONFIG_JSON``."""
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: decode.worker CONFIG_JSON", file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        cfg = json.load(f)

    # bind BEFORE the heavy jax import: the router's connect loop gets
    # a listening socket (slow accept) instead of minutes of refusals
    sock_path = cfg["socket_path"]
    if os.path.exists(sock_path):
        os.unlink(sock_path)
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    server.listen(1)

    import jax

    from ..models import init_lm
    from ..runtime.telemetry import TelemetryWriter
    from ..runtime.wire import WireError
    from .engine import AdmissionError, DecodeEngine, EngineConfig, \
        ServePolicy
    from .fleet import EngineHandle
    from .supervise import write_snapshot

    m = cfg["model"]
    params = init_lm(jax.random.PRNGKey(m["random_seed"]), m["vocab"],
                     m["model_size"], m["layers"],
                     max_seq_len=m["max_seq_len"], n_heads=m["heads"],
                     n_kv_heads=m.get("kv_heads") or None)
    metrics = None
    if cfg.get("metrics_dir"):
        metrics = TelemetryWriter(cfg["metrics_dir"],
                                  meta=cfg.get("meta") or {})
    qos = None
    if cfg.get("qos"):
        from ..runtime.policy import QosPolicy
        qos = QosPolicy.from_dict(cfg["qos"])
    engine = DecodeEngine(params, m["heads"],
                          EngineConfig(**cfg["config"]),
                          policy=ServePolicy(**cfg["policy"]),
                          metrics=metrics, qos=qos)
    spool = cfg["spool_dir"]
    os.makedirs(spool, exist_ok=True)
    # the worker IS an in-process EngineHandle around its engine (wire
    # exports land in the spool): every read surface the router's
    # policy code consumes — digest, stats, waiting entries, decode
    # cadence, wire export/import — is the ONE implementation in
    # decode/fleet.py, so the transports cannot drift apart on what
    # the router sees
    hd = EngineHandle(cfg["engine_id"], engine, cfg.get("role",
                                                        "decode"),
                      wire_dir=spool)
    last_publish_t = 0.0

    def handle(req: dict) -> dict:
        nonlocal last_publish_t
        op = req["op"]
        if op == "ping":
            return {}
        if op == "meta":
            return {"model": engine.model_meta(),
                    "mesh": engine.mesh is not None}
        if op == "digest":
            return {"digest": hd.digest()}
        if op == "submit":
            entry = hd.submit(req["prompt"], req["max_new"],
                              uid=req["uid"], trace=req.get("trace"),
                              tenant=req.get("tenant"))
            return {"entry": entry, "digest": hd.digest()}
        if op == "resume":
            hd.resume_request(req["uid"], req["prompt"],
                              req["max_new"], out=req["out"],
                              retries=req["retries"],
                              t_submit=req.get("t_submit"),
                              t_first=req.get("t_first"),
                              weights_version=req.get(
                                  "weights_version"),
                              trace=req.get("trace"),
                              tenant=req.get("tenant"))
            return {"digest": hd.digest()}
        if op == "release":
            return {"entry": hd.release_request(req["uid"]),
                    "digest": hd.digest()}
        if op == "load_weights":
            # the rolling deploy's swap half: restore the checkpoint
            # step from the SHARED ledger dir (weights never ride the
            # socket) and double-buffer it as the named version; the
            # CRC ladder runs inside restore — a torn step raises and
            # crosses back as the one-line rejection the router's
            # rollback names
            from ..runtime.weights import VersionLedger
            new = VersionLedger(req["ckpt_dir"]).load(req["step"],
                                                      engine.params)
            fp = engine.load_weights(req["version"], new)
            return {"fingerprint": fp, "digest": hd.digest()}
        if op == "set_version":
            engine.set_serving_version(req["version"])
            return {"digest": hd.digest()}
        if op == "step":
            hd.step_begin(prefill_only=req.get("prefill_only", False))
            return {"did": bool(hd.step_end()),
                    "step_s": hd.last_step_s,
                    "digest": hd.digest()}
        if op == "snapshot":
            # in-band to the router (the survivor that migrates from
            # it — recovery NEVER depends on this worker's disk) AND
            # atomically published in the spool as the on-disk
            # post-mortem record, throttled to ~1/s: the router asks
            # every cadence round, and paying tmp+fsync+rename+dirsync
            # per engine per round would put 2N fsyncs/round of pure
            # post-mortem bookkeeping on the drill's hot path
            now = time.monotonic()
            if now - last_publish_t >= 1.0:
                write_snapshot(engine, spool)
                last_publish_t = now
            return {"snapshot": hd.fetch_snapshot()}
        if op == "probe":
            return {"warm": hd.warm_blocks(req["prompt"])}
        if op == "warm":
            # pre-build the full program set (decode/verify per slot
            # bucket, prefill per chunk bucket, the handoff implant) so
            # a drill can tighten per-call deadlines to STEP scale —
            # a compile inside a deadline-bounded step would otherwise
            # read as a silent worker (the in-process kill drill's
            # prebuild discipline, test_fleet.py); the autoscaler's
            # spawn-then-warm path shares the same primitive
            return {"compiled": engine.warm()}
        if op == "export":
            ref = hd.export(req["uid"])     # writes the wire file
            return {"path": ref.path,
                    "position": ref.position,
                    "blocks_written": ref.blocks_written,
                    "digest": hd.digest()}
        if op == "import":
            info = hd.import_doc(HandoffRef(
                -1, 0, 0, path=req["path"]))    # raises WireError
            return {"bytes": info["bytes"],
                    "crc_verify_s": info["crc_verify_s"],
                    "digest": hd.digest()}
        if op == "results":
            return {"finished": {str(u): t
                                 for u, t in hd.results().items()},
                    "failed": {str(u): i
                               for u, i in hd.failed_map().items()}}
        if op == "stats":
            return {"stats": hd.stats()}
        if op == "emit_decode":
            hd.emit_decode()
            return {}
        if op == "hang":
            # chaos injection: acknowledge FIRST, then go silent — the
            # router's NEXT call overruns its deadline against a worker
            # that is alive but unresponsive, exactly the hung-peer
            # failure the liveness ladder exists for
            return {"_hang_after_reply_s": float(req["secs"])}
        if op == "shutdown":
            return {"_shutdown": True}
        raise ValueError(f"unknown worker op {op!r}")

    conn, _ = server.accept()
    rfile = conn.makefile("rb")
    try:
        for line in rfile:
            if not line.strip():
                continue
            req = json.loads(line)
            rid = req.get("id")
            # worker-side handle duration rides EVERY response (the
            # digest piggyback stance: zero extra round-trips) — the
            # router subtracts it from its own call wall clock to get
            # the pure RPC overhead (socket + JSON marshal), the
            # round-18 transport attribution
            t0 = time.perf_counter()
            try:
                out = handle(req)
                resp = {"id": rid, "ok": True, **out}
            except AdmissionError as e:
                resp = {"id": rid, "ok": False, "error": str(e),
                        "error_kind": "admission"}
            except WireError as e:
                resp = {"id": rid, "ok": False, "error": str(e),
                        "error_kind": "wire"}
            except ValueError as e:
                resp = {"id": rid, "ok": False, "error": str(e),
                        "error_kind": "value"}
            except Exception as e:  # noqa: BLE001 — protocol boundary
                resp = {"id": rid, "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "error_kind": "runtime"}
            resp["handle_s"] = round(time.perf_counter() - t0, 6)
            hang_s = resp.pop("_hang_after_reply_s", None)
            done = resp.pop("_shutdown", False)
            conn.sendall((json.dumps(resp) + "\n").encode("utf-8"))
            if hang_s is not None:
                time.sleep(hang_s)
            if done:
                break
    finally:
        if metrics is not None:
            metrics.close()
        try:
            conn.close()
            server.close()
            os.unlink(sock_path)
        except OSError:
            pass
    return 0


# ----------------------------------------------- router-side transport

class ProcessEngineHandle:
    """The router's view of one engine worker PROCESS — the same driver
    API as the in-process ``EngineHandle``, over the socket protocol.
    Scheduler-state reads come from the digest riding every response
    (cached; exactly as fresh as the last protocol exchange, which is
    the last time the worker's state could have changed)."""

    transport = "process"

    def __init__(self, eid: str, role: str, spool_dir: str, proc,
                 sock_path: str, *,
                 call_deadline_s: float = DEFAULT_CALL_DEADLINE_S,
                 ping_deadline_s: float = DEFAULT_PING_DEADLINE_S,
                 call_retries: int = DEFAULT_CALL_RETRIES):
        self.id = eid
        self.role = role
        self.spool_dir = spool_dir
        self.proc = proc
        self.sock_path = sock_path
        self.call_deadline_s = call_deadline_s
        self.ping_deadline_s = ping_deadline_s
        self.call_retries = call_retries
        self.alive = True
        self.snapshot: dict | None = None
        self.killed_at_round: int | None = None
        self.last_step_s = 0.0
        self.engine = None        # no in-process engine behind this id
        self._sock: socket.socket | None = None
        self._buf = b""
        self._next_id = 0
        self._digest: dict | None = None
        self._digest_id = -1      # response id the cached digest is from
        self._pending: dict | None = None   # in-flight step (begin/end)
        # responses that arrived while awaiting a DIFFERENT id (the
        # dead-host recovery path interleaves calls to a survivor whose
        # own step is still in flight) — parked here, never dropped
        self._resp_buf: dict[int, dict] = {}
        # -- RPC cost attribution + postmortem evidence (round 18) --
        # every in-flight call id maps to (op, send time); a parked
        # response stamps its receive time at parse, so call duration
        # = recv - send even when consumed out of order. Per-op
        # (call_s, handle_s) samples feed rpc_stats(); bounded rings
        # hold the postmortem evidence (op log, ping RTTs, backoff
        # sleeps) a dead-host declaration dumps.
        self._sent: dict[int, tuple[str, float]] = {}
        self._recv_t: dict[int, float] = {}
        self.op_samples: dict[str, collections.deque] = {}
        # unbounded run totals (the overhead-share numerator): the
        # per-op sample rings are capped at 4096, but round_wall_s on
        # the router is not — summing the rings would silently
        # understate the share on a long run
        self.call_total_s = 0.0
        self.overhead_total_s = 0.0
        self.op_log: "collections.deque" = collections.deque(maxlen=64)
        self.backoff_log: "collections.deque" = collections.deque(
            maxlen=64)

    # -- wire plumbing -------------------------------------------------

    def connect(self, deadline_s: float = DEFAULT_CONNECT_DEADLINE_S
                ) -> None:
        """Connect to the worker's socket, retrying refusals under
        bounded exponential backoff while it boots (jax import +
        engine build). A worker that exits first raises
        ``TransportDead`` with its log tail."""
        from ..runtime.failure import backoff_delay
        t0 = time.monotonic()
        attempt = 0
        while True:
            if self.proc.poll() is not None:
                raise TransportDead(
                    f"worker {self.id} exited rc {self.proc.returncode} "
                    f"before accepting: {self._log_tail()}")
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(self.sock_path)
                self._sock = s
                return
            except (FileNotFoundError, ConnectionRefusedError):
                if time.monotonic() - t0 > deadline_s:
                    raise TransportTimeout(
                        f"worker {self.id} did not accept within "
                        f"{deadline_s:.0f}s") from None
                time.sleep(backoff_delay(attempt, 0.05, 1.0, 0.0,
                                         random.Random(0)))
                attempt += 1

    def _log_tail(self, n: int = 400) -> str:
        try:
            with open(os.path.join(self.spool_dir,
                                   WORKER_LOG_FILENAME)) as f:
                return f.read()[-n:].replace("\n", " | ")
        except OSError:
            return "(no worker log)"

    def _send(self, req: dict) -> int:
        self._next_id += 1
        req = {**req, "id": self._next_id}
        # stamp the send BEFORE the marshal+sendall so the call
        # duration prices the full router-side cost of the op
        self._sent[self._next_id] = (req.get("op", "?"),
                                     time.perf_counter())
        try:
            self._sock.sendall((json.dumps(req) + "\n").encode("utf-8"))
        except OSError as e:
            raise TransportDead(f"worker {self.id} send failed: "
                                f"{type(e).__name__}: {e}") from None
        return self._next_id

    def _recv_line(self, deadline_s: float) -> bytes:
        """One newline-framed response within ``deadline_s``, with
        bounded-backoff retries absorbing transient slowness before the
        silent-worker verdict."""
        from ..runtime.failure import backoff_delay
        for attempt in range(self.call_retries + 1):
            end = time.monotonic() + deadline_s
            while b"\n" not in self._buf:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    break
                self._sock.settimeout(remaining)
                try:
                    chunk = self._sock.recv(1 << 16)
                except socket.timeout:
                    break
                except OSError as e:
                    raise TransportDead(
                        f"worker {self.id} connection failed: "
                        f"{type(e).__name__}: {e}") from None
                if not chunk:
                    state = ("exited rc %s" % self.proc.returncode
                             if self.proc.poll() is not None
                             else "still running")
                    raise TransportDead(
                        f"worker {self.id} closed its connection "
                        f"(process {state}): {self._log_tail()}")
                self._buf += chunk
            if b"\n" in self._buf:
                line, self._buf = self._buf.split(b"\n", 1)
                return line
            if attempt < self.call_retries:
                delay = backoff_delay(attempt, 0.05, 2.0, 0.0,
                                      random.Random(0))
                # postmortem evidence: the ladder's own retry history
                self.backoff_log.append({"t": time.time(),
                                         "attempt": attempt,
                                         "backoff_s": round(delay, 3),
                                         "deadline_s": deadline_s})
                time.sleep(delay)
        raise TransportTimeout(
            f"worker {self.id} silent past its {deadline_s:.1f}s "
            f"deadline ({self.call_retries + 1} attempt(s) with "
            "backoff)")

    def _call(self, op: str, deadline_s: float | None = None,
              **payload) -> dict:
        rid = self._send({"op": op, **payload})
        return self._await(rid, deadline_s)

    def _await(self, rid: int, deadline_s: float | None = None) -> dict:
        deadline = (self.call_deadline_s if deadline_s is None
                    else deadline_s)
        while rid not in self._resp_buf:
            resp = json.loads(self._recv_line(deadline))
            # receive time stamped at PARSE, not at consume: a parked
            # response's call duration must not be charged for the
            # interleaved work that delayed its pop
            self._recv_t[resp.get("id")] = time.perf_counter()
            self._resp_buf[resp.get("id")] = resp
        resp = self._resp_buf.pop(rid)
        sent = self._sent.pop(rid, None)
        recv_t = self._recv_t.pop(rid, None)
        if sent is not None and recv_t is not None:
            op, t0 = sent
            call_s = recv_t - t0
            self.op_samples.setdefault(
                op, collections.deque(maxlen=4096)).append(
                (call_s, resp.get("handle_s")))
            self.call_total_s += call_s
            if resp.get("handle_s") is not None:
                self.overhead_total_s += call_s - resp["handle_s"]
            self.op_log.append({"op": op, "id": rid,
                                "t": round(time.time(), 4),
                                "call_ms": round(call_s * 1e3, 3),
                                "ok": bool(resp.get("ok"))})
        if "digest" in resp and rid > self._digest_id:
            # the worker answers in order, so the digest from the
            # HIGHEST response id is the freshest scheduler state —
            # an out-of-order consume must not roll the cache back
            self._digest = resp["digest"]
            self._digest_id = rid
        if not resp.get("ok"):
            self._raise_remote(resp)
        return resp

    @staticmethod
    def _raise_remote(resp: dict):
        from ..runtime.wire import WireError
        from .engine import AdmissionError
        kind = resp.get("error_kind")
        msg = resp.get("error", "worker error")
        if kind == "admission":
            raise AdmissionError(msg)
        if kind == "wire":
            raise WireError(msg)
        if kind == "value":
            raise ValueError(msg)
        raise RuntimeError(msg)

    # -- the driver API (EngineHandle's surface) -----------------------

    def model_meta(self) -> dict:
        resp = self._call("meta")
        if resp["mesh"]:
            raise ValueError("fleet replicas are single-device "
                             "(KV handoff has no TP path)")
        return resp["model"]

    def validate_member(self) -> None:
        """Single-device membership is validated by ``model_meta`` (the
        construction-time cross-check calls it on every member)."""

    @property
    def has_work(self) -> bool:
        if not self.alive or self._digest is None:
            return False
        return bool(self._digest["waiting"] or self._digest["active"])

    def digest(self, light: bool = False) -> dict:
        # `light` is the in-process handle's hot-path flag; here the
        # cached digest from the last response is returned either way
        if self._digest is None:
            self._call("digest")
        return self._digest

    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        from .engine import blocks_needed
        return blocks_needed(prompt_len, max_new, self._block_size)

    def max_blocks_per_seq(self) -> int:
        return self._max_blocks_per_seq

    def warm_blocks(self, prompt) -> int | None:
        return self._call("probe", prompt=[int(t) for t in prompt])[
            "warm"]

    def submit(self, prompt, max_new: int, uid: int,
               trace: str | None = None,
               tenant: str | None = None) -> dict:
        return self._call("submit", prompt=[int(t) for t in prompt],
                          max_new=int(max_new), uid=int(uid),
                          trace=trace, tenant=tenant)["entry"]

    def resume_request(self, uid: int, prompt, max_new: int, *, out=(),
                       retries: int = 0, t_submit=None,
                       t_first=None, weights_version=None,
                       trace=None, tenant=None) -> None:
        self._call("resume", uid=int(uid),
                   prompt=[int(t) for t in prompt],
                   max_new=int(max_new), out=[int(t) for t in out],
                   retries=int(retries), t_submit=t_submit,
                   t_first=t_first,
                   weights_version=(None if weights_version is None
                                    else int(weights_version)),
                   trace=trace, tenant=tenant)

    def release_request(self, uid: int) -> dict:
        return self._call("release", uid=int(uid))["entry"]

    # -- weight lifecycle (round 17, DESIGN.md section 23) -------------

    @property
    def serving_version(self) -> int:
        return int(self.digest()["serving_version"])

    def load_weights(self, version: int, ckpt_dir: str, step: int,
                     params=None) -> dict:
        """The swap half of the rolling deploy, worker-side: the
        worker restores checkpoint ``step`` from the SHARED ledger dir
        itself (weights never ride the socket — the spool-file stance)
        and double-buffers it as ``version``. ``params`` is the
        in-process transport's shortcut and is ignored here. A torn
        step fails the worker's own CRC ladder and crosses back as
        the one-line rejection the router's rollback names."""
        return self._call("load_weights", version=int(version),
                          ckpt_dir=ckpt_dir, step=int(step))[
                              "fingerprint"]

    def set_serving_version(self, version: int) -> None:
        self._call("set_version", version=int(version))

    def step_begin(self, prefill_only: bool = False) -> None:
        """SEND the step — every worker's step runs concurrently in its
        own process; ``step_end`` collects."""
        rid = self._send({"op": "step", "prefill_only": prefill_only})
        self._pending = {"rid": rid}

    def step_end(self) -> bool:
        pending, self._pending = self._pending, None
        resp = self._await(pending["rid"])
        self.last_step_s = float(resp["step_s"])
        return bool(resp["did"])

    def fetch_snapshot(self) -> dict:
        return self._call("snapshot")["snapshot"]

    def export(self, uid: int) -> HandoffRef:
        resp = self._call("export", uid=int(uid))
        return HandoffRef(uid, int(resp["position"]),
                          int(resp["blocks_written"]),
                          path=resp["path"])

    def import_doc(self, ref: HandoffRef) -> dict:
        resp = self._call("import", path=ref.path)
        return {"mode": "wire", "bytes": int(resp["bytes"]),
                "crc_verify_s": resp["crc_verify_s"]}

    def _results_resp(self) -> dict:
        """One 'results' round-trip serves both results() and
        failed_map() (the drain path calls them back to back; the op
        returns both halves, and re-shipping every finished token list
        for the failed half would be pure waste). The cache is valid
        only while NO other protocol call intervenes — any call
        advances ``_next_id`` and invalidates it."""
        cached = getattr(self, "_results_cache", None)
        if cached is not None and cached[0] == self._next_id:
            return cached[1]
        resp = self._call("results")
        self._results_cache = (self._next_id, resp)
        return resp

    def results(self) -> dict[int, list[int]]:
        return {int(u): list(t) for u, t
                in self._results_resp()["finished"].items()}

    def failed_map(self) -> dict[int, dict]:
        return {int(u): dict(i) for u, i
                in self._results_resp()["failed"].items()}

    def stats(self) -> dict:
        return self._call("stats")["stats"]

    def emit_decode(self) -> None:
        self._call("emit_decode")

    # -- transport attribution (round 18, DESIGN.md section 24) --------

    def rpc_stats(self) -> dict | None:
        """Per-op RPC cost attribution off the recorded samples:
        router-side call duration percentiles, worker-side handle
        durations (piggybacked on every response), and their
        difference — the pure transport overhead (socket + JSON
        marshal + scheduling). ``ping`` doubles as the heartbeat RTT
        sample set. None until any call completed."""
        if not self.op_samples:
            return None

        def pcts(vals):
            import numpy as np
            arr = np.asarray(vals, np.float64) * 1e3
            return (round(float(np.percentile(arr, 50)), 3),
                    round(float(np.percentile(arr, 99)), 3))

        ops = {}
        for op, samples in sorted(self.op_samples.items()):
            calls = [c for c, _ in samples]
            overheads = [c - h for c, h in samples if h is not None]
            p50, p99 = pcts(calls)
            entry = {"n": len(samples), "call_p50_ms": p50,
                     "call_p99_ms": p99}
            if overheads:
                o50, o99 = pcts(overheads)
                entry["overhead_p50_ms"] = o50
                entry["overhead_p99_ms"] = o99
            ops[op] = entry
        # totals come from the unbounded accumulators, not the capped
        # rings — the overhead share must cover the WHOLE run that
        # round_wall_s covers (percentiles stay over the recent ring)
        out = {"ops": ops,
               "call_total_s": round(self.call_total_s, 6),
               "overhead_total_s": round(self.overhead_total_s, 6)}
        pings = self.op_samples.get("ping")
        if pings:
            p50, p99 = pcts([c for c, _ in pings])
            out["heartbeat_rtt_p50_ms"] = p50
            out["heartbeat_rtt_p99_ms"] = p99
            out["heartbeats"] = len(pings)
        return out

    def evidence(self) -> dict:
        """The router-side postmortem evidence for this worker: the
        last cached digest (and which call delivered it), in-flight
        call ids, the bounded op/backoff/ping history — everything the
        router knew at declaration time. The worker's own flight
        recorder dies with its process; this half survives because the
        router holds it."""
        pings = self.op_samples.get("ping") or ()
        return {
            "transport": self.transport,
            "alive": self.alive,
            "pid": self.proc.pid,
            "process_rc": self.proc.poll(),
            "last_digest": self._digest,
            "last_digest_call_id": self._digest_id,
            "pending_call_ids": sorted(self._sent),
            "pending_step": (None if self._pending is None
                             else self._pending.get("rid")),
            "op_log": list(self.op_log),
            "backoff_log": list(self.backoff_log),
            "ping_rtt_ms": [round(c * 1e3, 3) for c, _ in pings][-16:],
            "last_snapshot_step": (None if self.snapshot is None
                                   else self.snapshot.get("step")),
            "last_snapshot_requests": (
                None if self.snapshot is None
                else len(self.snapshot.get("requests", ()))),
            "log_tail": self._log_tail(),
        }

    # -- liveness ------------------------------------------------------

    def ping(self) -> None:
        self._call("ping", deadline_s=self.ping_deadline_s)

    def warm(self, deadline_s: float = 600.0) -> int:
        """Pre-compile the worker's full program set (generous
        deadline — this IS the compile phase); returns its compile
        count. Tighten ``call_deadline_s`` after this, never before."""
        return int(self._call("warm", deadline_s=deadline_s)["compiled"])

    def hang(self, secs: float) -> None:
        """Chaos: tell the worker to go silent for ``secs`` right after
        acknowledging — its next real call must trip the deadline."""
        self._call("hang", secs=float(secs))

    def kill(self) -> None:
        """SIGKILL the worker process — a real dead host. Idempotent;
        also the zombie-fencing step of a dead declaration (a hung
        worker that wakes later must not answer anything)."""
        self.alive = False
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        try:
            self.proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — best-effort reap
            pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        """Graceful shutdown: ask the worker to exit (its telemetry
        writer flushes), then reap; SIGKILL if it lingers."""
        if not self.alive:
            return
        try:
            self._call("shutdown", deadline_s=10.0)
        except (TransportError, OSError):
            pass
        try:
            self.proc.wait(timeout=15)
        except Exception:  # noqa: BLE001
            self.proc.kill()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self.alive = False


# --------------------------------------------------------------- spawn

def _start_worker_proc(eid: str, role: str, base_dir: str, *,
                       model: dict, config: dict, policy: dict,
                       qos: dict | None = None,
                       metrics_dir=None, meta=None, env=None):
    """Write one worker's config and start its process (detached; log
    in its spool). Returns ``(spool, proc, sock_path)`` — connection
    happens separately so a fleet can boot every jax import in
    parallel before the first (slow) connect. ``qos`` is an optional
    ``QosPolicy.as_dict()`` — the per-tenant scheduling policy rides
    the config file, never the socket."""
    spool = os.path.join(base_dir, eid)
    os.makedirs(spool, exist_ok=True)
    sock_path = os.path.join(spool, WORKER_SOCKET_FILENAME)
    cfg = {"engine_id": eid, "role": role, "socket_path": sock_path,
           "spool_dir": spool, "metrics_dir": metrics_dir,
           "meta": {**(meta or {}), "engine_id": eid, "role": role},
           "model": model, "config": config, "policy": policy,
           "qos": qos}
    cfg_path = os.path.join(spool, WORKER_CONFIG_FILENAME)
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    log = open(os.path.join(spool, WORKER_LOG_FILENAME), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_llm_code_samples_tpu.decode.worker", cfg_path],
        stdout=log, stderr=subprocess.STDOUT,
        env=None if env is None else dict(env), start_new_session=True)
    log.close()
    return spool, proc, sock_path


def _connect_and_prime(h: ProcessEngineHandle, config: dict,
                       connect_deadline_s: float) -> None:
    """Connect a freshly-spawned handle and prime its config-derived
    capacity math + initial digest cache. The capacity fields resolve
    through ``EngineConfig`` itself — the exact defaulting the worker
    applies — so a partial config dict can never make the router's
    ``blocks_needed`` math disagree with the engine's admission
    math."""
    from .engine import EngineConfig
    h.connect(deadline_s=connect_deadline_s)
    ec = EngineConfig(**config)
    h._block_size = ec.block_size
    h._max_blocks_per_seq = ec.max_blocks_per_seq
    h._call("digest")
    # the priming digest's wall clock is the WORKER BOOT (connect
    # lands in the listen backlog before the jax import; the worker
    # only answers once its engine exists) — that is spawn cost, not
    # transport cost, and it must not pollute the per-op RPC
    # percentiles rpc_stats() reports (the op_log keeps it: boot time
    # is legitimate postmortem evidence)
    h.op_samples.clear()
    h.call_total_s = h.overhead_total_s = 0.0


def spawn_worker(eid: str, role: str, base_dir: str, *, model: dict,
                 config: dict, policy: dict, qos: dict | None = None,
                 metrics_dir=None, meta=None, env=None,
                 call_deadline_s: float = DEFAULT_CALL_DEADLINE_S,
                 ping_deadline_s: float = DEFAULT_PING_DEADLINE_S,
                 connect_deadline_s: float = DEFAULT_CONNECT_DEADLINE_S,
                 ) -> ProcessEngineHandle:
    """Start one engine worker process and connect to it. ``model`` is
    the ``init_lm`` recipe (vocab/model_size/layers/heads/kv_heads/
    max_seq_len/random_seed — every worker rebuilds the identical
    weights from it); ``config``/``policy`` the EngineConfig/
    ServePolicy kwargs. The worker's spool dir (``base_dir/eid``)
    holds its config, socket, log, wire handoffs, and published
    snapshots."""
    spool, proc, sock_path = _start_worker_proc(
        eid, role, base_dir, model=model, config=config, policy=policy,
        qos=qos, metrics_dir=metrics_dir, meta=meta, env=env)
    h = ProcessEngineHandle(eid, role, spool, proc, sock_path,
                            call_deadline_s=call_deadline_s,
                            ping_deadline_s=ping_deadline_s)
    try:
        _connect_and_prime(h, config, connect_deadline_s)
    except TransportError:
        h.kill()
        raise
    return h


def spawn_fleet_handles(n_engines: int, prefill_engines: int,
                        base_dir: str, *, model: dict, config: dict,
                        policy: dict, qos: dict | None = None,
                        metrics_root=None, meta=None, env=None,
                        call_deadline_s: float = DEFAULT_CALL_DEADLINE_S,
                        ping_deadline_s: float = DEFAULT_PING_DEADLINE_S,
                        connect_deadline_s: float =
                        DEFAULT_CONNECT_DEADLINE_S) -> list:
    """Spawn the whole fleet's worker processes (prefill tier first,
    the router's id convention), launching all of them BEFORE the
    first connect so their jax imports boot in parallel. On any spawn
    failure every already-started worker is killed — no orphans."""
    from .fleet import DECODE_PREFIX, PREFILL_PREFIX
    ids = [(f"{PREFILL_PREFIX}{i}", "prefill")
           for i in range(prefill_engines)]
    ids += [(f"{DECODE_PREFIX}{i}", "decode")
            for i in range(n_engines - prefill_engines)]
    handles: list[ProcessEngineHandle] = []
    procs: list = []
    try:
        # phase 1: start every process (parallel boot)
        for eid, role in ids:
            mdir = (os.path.join(metrics_root, eid)
                    if metrics_root else None)
            spool, proc, sock_path = _start_worker_proc(
                eid, role, base_dir, model=model, config=config,
                policy=policy, qos=qos, metrics_dir=mdir, meta=meta,
                env=env)
            procs.append((eid, role, spool, proc, sock_path))
        # phase 2: connect to each
        for eid, role, spool, proc, sock_path in procs:
            h = ProcessEngineHandle(eid, role, spool, proc, sock_path,
                                    call_deadline_s=call_deadline_s,
                                    ping_deadline_s=ping_deadline_s)
            handles.append(h)
            _connect_and_prime(h, config, connect_deadline_s)
        return handles
    except Exception:
        for h in handles:
            h.kill()
        for tup in procs[len(handles):]:
            try:
                tup[3].kill()
            except OSError:
                pass
        raise


if __name__ == "__main__":
    sys.exit(worker_main())
