"""`generate` — the serving CLI: drive the decode engine end to end.

Mirrors the training CLI's stance (``cli.py``): the model is the LM
family at the flagged shape (``init_lm`` — random weights unless you
wire your own; the engine is the demonstration target, not the
checkpoint plumbing), prompts are either explicit token-id lists
(``--prompts "3,1,4;9,2"``) or deterministic random draws
(``--prompt_lens 5,9,13`` with ``--prompt_seed``), and the run prints
ONE JSON line with every sequence's tokens plus the engine's
throughput/occupancy/reliability stats. ``--metrics_dir`` streams the
schema-versioned ``decode`` / ``request`` / ``span`` (and, under
``--fleet``, ``router`` + ``fleet``) records through the unified
telemetry writer (``runtime/telemetry.py``) — ``report`` folds them
like any other run, and ``report --slo TTFT_S:ITL_S`` computes SLO
attainment over the completed requests (DESIGN.md section 21).

``--tp N`` runs the Megatron decode layout over an N-way model-axis
mesh (``--fake_devices`` makes that work on CPU, as everywhere else).

Reliability flags (round 10, DESIGN.md section 16):

- ``--snapshot_dir`` runs under the engine supervisor
  (``decode/supervise.py``): per-step atomic snapshots, in-process
  restart ladder, and automatic resume — re-running the same command
  after a crash continues from the snapshot, token-identically.
- ``--chaos SPEC`` injects the decode fault grammar
  (``nan_logits@STEP[:UID]``, ``hang_step@STEP[:SECS]``,
  ``corrupt_block@STEP:BLOCK``, ``kill@STEP``; ``runtime/chaos.py``).
  Requires ``--snapshot_dir`` — recovery resumes from snapshots, the
  train CLI's ``--chaos``/``--checkpoint_dir`` coupling.
- ``--max_retries`` / ``--deadline_steps`` / ``--queue_limit`` /
  ``--preempt_after`` set the engine's ``ServePolicy`` (quarantine
  retry budget, per-request TTL, reject-on-full admission,
  pool-pressure preemption). Bad values are rejected cleanly (rc 2),
  the train CLI's parse-rejection discipline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_generate_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="generate",
        description="Continuous-batching decode over the paged KV engine "
                    "(decode/engine.py)")
    # model shape (the cli.py -m 11 family surface)
    p.add_argument("-d", "--model_size", type=int, default=64)
    p.add_argument("-l", "--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv_heads", type=int, default=0,
                   help="GQA KV heads (0 = full MHA); shrinks the KV "
                        "pool by heads/kv_heads")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--max_seq_len", type=int, default=256)
    p.add_argument("-r", "--random_seed", type=int, default=0,
                   help="model init seed (the cli.py convention)")
    p.add_argument("--use_rope", action="store_true",
                   help="rotary attention (must match training)")
    # requests — explicit prompts, random draws, or a workload trace
    # (round 19, DESIGN.md section 25): exactly one source
    p.add_argument("--trace", default=None, metavar="FILE",
                   help="replay a workload trace file "
                        "(runtime/workload.py TRACE_VERSION 1 JSONL): "
                        "arrivals paced on the virtual round clock "
                        "(--trace_pace wall for real seconds), tenants "
                        "and sessions tagged through the whole "
                        "telemetry plane; same (trace, seed) replays "
                        "byte-identically")
    p.add_argument("--trace_gen", default=None, metavar="SPEC",
                   help="generate a trace in-process and serve it "
                        "(grammar: n=INT,arrival=poisson:R|bursty:"
                        "R:ON:OFF|ramp:LO:HI,plen=fixed:N|uniform:"
                        "LO:HI|zipf:A:LO:HI,max_new=...,tenants="
                        "a:3;b:1,sessions=K[:GROW],seed=N); pair with "
                        "--trace_out to persist the trace for replay")
    p.add_argument("--trace_out", default=None, metavar="FILE",
                   help="write the --trace_gen trace to FILE "
                        "(atomic publish) so later runs can --trace "
                        "it — the falsifiability handle")
    p.add_argument("--trace_pace", choices=["virtual", "wall"],
                   default=None,
                   help="trace pacing: 'virtual' (default — offsets "
                        "map onto scheduling rounds, fully "
                        "deterministic, the CPU tier-1 mode) or "
                        "'wall' (offsets are real seconds — the chip "
                        "mode; token identity holds, admission order "
                        "may vary with service speed)")
    p.add_argument("--trace_steps_per_s", type=float, default=None,
                   help="virtual-clock rate: rounds per trace second "
                        "(default 8; higher = the same trace replayed "
                        "onto a denser round grid)")
    p.add_argument("--prompts", default=None,
                   help="semicolon-separated comma-lists of token ids, "
                        'e.g. "3,1,4;9,2,6,5"')
    p.add_argument("--prompt_lens", default=None,
                   help="comma-separated lengths of random prompts "
                        "(deterministic per --prompt_seed), e.g. 5,9,13")
    p.add_argument("--prompt_seed", type=int, default=0)
    p.add_argument("--max_new", type=int, default=16)
    # sampling (fused, in-graph; decode/sampling.py)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy argmax")
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=0.0)
    p.add_argument("--sample_seed", type=int, default=0)
    # engine layout
    p.add_argument("--kv_dtype", choices=["f32", "bf16", "int8"],
                   default="f32")
    p.add_argument("--block_size", type=int, default=16)
    p.add_argument("--n_blocks", type=int, default=0,
                   help="KV pool blocks incl. the scratch block "
                        "(0 = sized for max_slots full sequences)")
    p.add_argument("--max_slots", type=int, default=4)
    p.add_argument("--max_blocks_per_seq", type=int, default=0,
                   help="per-sequence table width (0 = cover "
                        "max_seq_len)")
    p.add_argument("--prefill_chunk", type=int, default=16)
    # raw-latency levers (round 12, DESIGN.md section 18)
    p.add_argument("--speculate", type=int, default=0,
                   help="speculative decoding: draft tokens per decode "
                        "step from the n-gram prompt-copy drafter "
                        "(greedy verification — requires temperature "
                        "0; a step emits 1 + accepted tokens; 0 = off)")
    p.add_argument("--kernel", choices=["gather", "fused"],
                   default="gather",
                   help="decode attention path: 'gather' (two-pass "
                        "oracle) or 'fused' (Pallas block-table walk, "
                        "single-device; ops/pallas_paged_attention.py)")
    # shared-prefix KV reuse (round 13, DESIGN.md section 19)
    p.add_argument("--prefix_cache", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="shared-prefix KV reuse (decode/prefix.py): "
                        "requests sharing a prompt prefix map its "
                        "cached full blocks instead of re-prefilling "
                        "them, refcounted + copy-on-write; output "
                        "stays byte-identical (default on; "
                        "--no-prefix_cache restores the private-"
                        "blocks-only engine)")
    # KV memory hierarchy (round 23, DESIGN.md section 29)
    p.add_argument("--spill_blocks", type=int, default=0,
                   help="host-RAM KV spill tier capacity in blocks "
                        "(decode/spill.py): pool-pressure evictions of "
                        "cached prefix blocks demote their bytes to "
                        "host RAM instead of discarding, and a radix "
                        "hit on the spilled edge restores via the "
                        "compiled implant program instead of "
                        "re-prefilling (0 = tier off; requires "
                        "--prefix_cache)")
    p.add_argument("--spill_restore_per_step", type=int, default=2,
                   help="max spilled blocks promoted back per engine "
                        "step — the restore budget that keeps a "
                        "returning session's promotion from stalling "
                        "running decodes (admission defers past it)")
    p.add_argument("--prefix_partial", default=False,
                   action=argparse.BooleanOptionalAction,
                   help="sub-block prefix sharing: a partial-block "
                        "radix hit CoW-copies the shared leading rows "
                        "into a fresh block so short shared system "
                        "prompts save prefill too (f32/bf16 output "
                        "stays byte-identical; int8 rows reuse the "
                        "donor's frozen scale — deterministic, "
                        "documented in DESIGN.md section 29)")
    # parallel strategy
    p.add_argument("--tp", type=int, default=1,
                   help="model-axis size for the Megatron decode layout "
                        "(1 = single-device)")
    p.add_argument("--fake_devices", type=int, default=0)
    # reliability (decode/supervise.py + engine ServePolicy)
    p.add_argument("--snapshot_dir", default=None,
                   help="run under the engine supervisor: per-step "
                        "atomic snapshots + automatic crash-resume "
                        "(re-run the same command to continue)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic decode fault injection "
                        "(runtime/chaos.py): comma-separated "
                        "KIND@STEP[:ARG] with KIND in nan_logits/"
                        "hang_step/corrupt_block/kill; requires "
                        "--snapshot_dir")
    p.add_argument("--max_retries", type=int, default=0,
                   help="per-request retry budget for quarantined "
                        "sequences (replay-resumed; 0 = fail on first "
                        "fault)")
    p.add_argument("--deadline_steps", type=int, default=0,
                   help="per-request TTL in engine steps from submit "
                        "(0 = none); expired requests are failed with "
                        "reason 'deadline'")
    p.add_argument("--queue_limit", type=int, default=0,
                   help="bounded waiting queue: submissions past it are "
                        "shed (rejected, not an error; 0 = unbounded)")
    p.add_argument("--preempt_after", type=int, default=0,
                   help="pool-pressure preemption: a head-of-line "
                        "request starved of blocks for N steps evicts "
                        "the youngest running sequence (0 = off)")
    p.add_argument("--snapshot_every", type=int, default=1,
                   help="engine-step cadence of the atomic snapshot "
                        "(1 = every step, maximum recoverability; "
                        "raise it to amortize the host-side "
                        "json+fsync on throughput-critical serving — "
                        "resume is equally correct from an older "
                        "snapshot, it just replays more)")
    p.add_argument("--watchdog_ms", type=int, default=0,
                   help="hung-step watchdog deadline (0 = off); latches "
                        "hung_step evidence in the attempt log")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="in-process restart budget for the supervisor")
    # fleet-scale serving (round 14, DESIGN.md section 20)
    p.add_argument("--fleet", type=int, default=0,
                   help="serve through a multi-engine router "
                        "(decode/fleet.py): N single-device engine "
                        "replicas behind least-loaded + session + "
                        "prefix-affinity admission (N >= 2; 0 = the "
                        "single-engine path, byte-identical to a run "
                        "without fleet flags)")
    p.add_argument("--prefill_engines", type=int, default=0,
                   help="disaggregated prefill/decode: dedicate M of "
                        "the --fleet engines to chunked prefill; "
                        "finished prompts ship to the decode tier via "
                        "the single-sequence KV handoff (requires "
                        "--fleet, M < N)")
    p.add_argument("--fleet_kill", default=None, metavar="ENGINE@ROUND",
                   help="deterministic fleet chaos: kill engine id "
                        "ENGINE (e.g. e1) at the start of fleet round "
                        "ROUND; its in-flight requests migrate to the "
                        "survivors and complete token-identically "
                        "(requires --fleet; a real SIGKILL of the "
                        "worker process under --transport process)")
    # process-boundary fleet (round 16, DESIGN.md section 22)
    p.add_argument("--transport", choices=["inproc", "process", "tcp"],
                   default="inproc",
                   help="fleet transport: 'inproc' (replicas in the "
                        "router's process, the PR 10 fleet), "
                        "'process' (each engine in its OWN worker "
                        "process behind an AF_UNIX socket protocol, KV "
                        "handoffs as CRC-verified wire files — "
                        "decode/worker.py), or 'tcp' (the same worker "
                        "protocol over TCP loopback with reconnect + "
                        "sequence-numbered replay and handoffs "
                        "streamed over a framed side channel — the "
                        "multi-host shape; requires --fleet)")
    p.add_argument("--async_migration", action="store_true",
                   help="live migrations ship the KV snapshot WHILE "
                        "the source keeps decoding; the target "
                        "teacher-forces the ship-window delta at "
                        "commit (token-identical; requires --fleet)")
    p.add_argument("--fleet_chaos", default=None, metavar="SPEC",
                   help="fleet-transport fault injection "
                        "(runtime/chaos.py FLEET_KINDS): comma-"
                        "separated KIND@ROUND[:ARG] with KIND in "
                        "kill_worker (SIGKILL decode worker :IDX, "
                        "default e0) / hang_worker (first decode "
                        "worker goes silent :SECS) / corrupt_wire "
                        "(bit-flip the next wire handoff; CRC-"
                        "rejected) / partition_worker (drop the first "
                        "decode worker's link both ways for :SECS, "
                        "then heal — reconnect-and-replay; tcp only) / "
                        "slow_link (inject :MS latency per call on "
                        "the first decode link — must NOT page the "
                        "liveness ladder) / drop_conn (mid-message "
                        "RST on the first decode link; tcp only); "
                        "requires --fleet and --transport "
                        "process/tcp")
    # live weight hot-swap (round 17, DESIGN.md section 23)
    p.add_argument("--deploy_dir", default=None, metavar="CKPT_DIR",
                   help="weight-version ledger: a trainer checkpoint "
                        "dir (the existing atomic fsync+CRC publish "
                        "IS the deploy input); with --deploy_round "
                        "the fleet rolls the newest published step "
                        "through every engine mid-serve (requires "
                        "--fleet)")
    p.add_argument("--deploy_round", type=int, default=None,
                   metavar="ROUND",
                   help="fleet round to START the rolling deploy at "
                        "(drain-by-migration one engine at a time, "
                        "zero shed; requires --deploy_dir)")
    p.add_argument("--deploy_step", type=int, default=None,
                   help="explicit checkpoint step to deploy (default: "
                        "the newest published step at fire time — the "
                        "CRC ladder then accepts it or rolls back to "
                        "latest_verified_step)")
    p.add_argument("--deploy_watch", type=float, default=None,
                   metavar="SECS",
                   help="deploy-on-publish watcher: poll --deploy_dir's "
                        "latest VERIFIED step every SECS seconds "
                        "mid-serve and roll the fleet forward when it "
                        "advances — the trainer's atomic publish "
                        "becomes the deploy trigger (requires --fleet "
                        "and --deploy_dir; mutually exclusive with "
                        "--deploy_round)")
    p.add_argument("--weights_from", default=None, metavar="CKPT_DIR",
                   help="serve weights restored from a checkpoint dir "
                        "instead of the --random_seed init (the "
                        "pinned-version oracle surface: a single "
                        "engine serving exactly what a deploy "
                        "published; single-engine runs only)")
    p.add_argument("--weights_step", type=int, default=None,
                   help="checkpoint step for --weights_from (default: "
                        "newest verified)")
    # closed-loop autoscaling + tenant QoS (round 20, DESIGN.md
    # section 26)
    p.add_argument("--qos", default=None, metavar="SPEC",
                   help="per-tenant scheduling policy (runtime/"
                        "policy.py): discipline=fcfs|wfq,weights="
                        "a:3;b:1,budget=INT,predictive_shed=0|1 — "
                        "virtual-time weighted-fair admission over "
                        "served tokens, per-tenant resident token "
                        "budgets, and predictive deadline-miss shed "
                        "(host-side scheduling only: each request's "
                        "tokens are unchanged, only WHEN it admits)")
    p.add_argument("--autoscale", default=None, metavar="SPEC",
                   help="closed-loop decode-tier autoscaler "
                        "(decode/autoscale.py): min=,max=,up=,down=,"
                        "hysteresis=,cooldown= — spawns WARMED "
                        "engines under sustained queue pressure, "
                        "drains idle ones with zero shed; requires "
                        "--fleet and a trace source (the controller "
                        "ticks on the replay's round clock)")
    p.add_argument("--watch", default=None, metavar="SPEC",
                   help="fleet watchtower (runtime/watch.py): "
                        "deadline=ROUNDS,budget=F,burn=F,fast=N,"
                        "slow=N,queue=N,imbalance=F,collapse=N,"
                        "incidents=N — streaming detectors on the "
                        "replay's round clock emitting `alert` "
                        "records with a fired->resolved lifecycle "
                        "(burn-rate over the round-denominated "
                        "deadline, sustained queue depth/imbalance, "
                        "throughput collapse, incident rate); active "
                        "alerts ride fleet_status.json for fleetstat/"
                        "report --follow; requires --fleet and a "
                        "trace source")
    p.add_argument("--policy", default=None, metavar="LABEL",
                   help="policy label stamped into the run's meta "
                        "records and payload — `report --slo` folds "
                        "per-policy attainment by it (the offline "
                        "policy-search key over a committed trace)")
    # observability
    p.add_argument("--metrics_dir", default=None)
    p.add_argument("--log_every", type=int, default=4,
                   help="decode-record cadence in engine steps")
    p.add_argument("--engine_id", default=None,
                   help="engine label stamped in the run's meta records "
                        "(default: the metrics dir's basename); the "
                        "multi-stream `report A B ...` merge keys "
                        "per-engine percentiles on it")
    return p


def _fleet_main(args, prompts, cfg, policy, params, fleet_kill,
                fleet_chaos, argv, trace_doc=None, qos=None,
                autoscale=None, watch=None) -> int:
    """The ``--fleet N`` run: N engine replicas behind the router
    (``decode/fleet.py``), each with its own metrics stream under
    ``--metrics_dir/<engine_id>`` plus a ``router`` stream for the
    schema-v8 routing records — ``report m/router m/p0 m/e0 ...``
    merges them onto one timeline. Prints the same one-line JSON
    payload shape as the single-engine path, with a ``fleet`` block.

    ``--transport process`` (round 16) runs every replica in its OWN
    worker process (``decode/worker.py``): the same router, the same
    payload shape, but an engine kill is a real SIGKILL, handoffs are
    CRC-verified wire files, and the per-engine metrics streams are
    written by the workers themselves."""
    import json as _json
    import time as _time

    import jax

    from .engine import AdmissionError, DecodeEngine
    from .fleet import FleetRouter

    writers = []
    router_metrics = None

    def _writer(eid):
        from ..decode.fleet import PREFILL_PREFIX
        from ..runtime.telemetry import TelemetryWriter
        role = ("router" if eid == "router" else
                "prefill" if eid.startswith(PREFILL_PREFIX) else
                "decode")
        meta = {"argv": list(argv or []), "subcommand": "generate",
                "engine_id": eid, "role": role, "fleet": args.fleet,
                "prefill_engines": args.prefill_engines,
                "transport": args.transport,
                "kv_dtype": args.kv_dtype,
                "n_prompts": len(prompts), "max_new": args.max_new,
                "device_kind": jax.devices()[0].device_kind}
        if args.policy:
            meta["policy"] = args.policy
        if args.qos:
            meta["qos"] = args.qos
        w = TelemetryWriter(os.path.join(args.metrics_dir, eid),
                            meta=meta)
        writers.append(w)
        return w

    def make_engine(eid):
        return DecodeEngine(params, args.heads, cfg, policy=policy,
                            qos=qos,
                            metrics=(_writer(eid) if args.metrics_dir
                                     else None))

    router = None
    handles = None
    t0 = _time.perf_counter()
    try:
        if args.metrics_dir:
            router_metrics = _writer("router")
        if args.transport in ("process", "tcp"):
            import dataclasses as _dc
            import tempfile as _tempfile

            from .worker import spawn_fleet_handles
            family = "tcp" if args.transport == "tcp" else "unix"
            spool = (os.path.join(args.metrics_dir, "spool")
                     if args.metrics_dir
                     else _tempfile.mkdtemp(prefix="fleet_spool_"))
            model = {"vocab": args.vocab, "model_size": args.model_size,
                     "layers": args.layers, "heads": args.heads,
                     "kv_heads": args.kv_heads or None,
                     "max_seq_len": args.max_seq_len,
                     "random_seed": args.random_seed}
            worker_meta = {"argv": list(argv or []),
                           "subcommand": "generate",
                           "fleet": args.fleet,
                           "transport": args.transport,
                           "prefill_engines": args.prefill_engines,
                           "kv_dtype": args.kv_dtype,
                           "n_prompts": len(prompts),
                           "max_new": args.max_new}
            if args.policy:
                worker_meta["policy"] = args.policy
            if args.qos:
                worker_meta["qos"] = args.qos
            handles = spawn_fleet_handles(
                args.fleet, args.prefill_engines, spool,
                model=model, config=_dc.asdict(cfg),
                policy=_dc.asdict(policy),
                qos=(qos.as_dict() if qos is not None else None),
                metrics_root=args.metrics_dir or None,
                meta=worker_meta, family=family)
            router = FleetRouter(None, args.fleet,
                                 args.prefill_engines,
                                 metrics=router_metrics,
                                 handles=handles,
                                 fleet_chaos=fleet_chaos,
                                 async_migration=args.async_migration)
        else:
            router = FleetRouter(make_engine, args.fleet,
                                 args.prefill_engines,
                                 metrics=router_metrics,
                                 fleet_chaos=fleet_chaos,
                                 async_migration=args.async_migration)
        if fleet_kill is not None:
            router.schedule_kill(*fleet_kill)
        if args.deploy_round is not None:
            router.schedule_deploy(args.deploy_dir, args.deploy_round,
                                   step=args.deploy_step)
        if args.deploy_watch is not None:
            router.deploy_watch(args.deploy_dir, args.deploy_watch)
        controller = None
        if autoscale is not None:
            from .autoscale import AutoscaleController
            if args.transport in ("process", "tcp"):
                from .worker import spawn_worker

                def _spawn(eid):
                    mdir = (os.path.join(args.metrics_dir, eid)
                            if args.metrics_dir else None)
                    return spawn_worker(
                        eid, "decode", spool, model=model,
                        config=_dc.asdict(cfg),
                        policy=_dc.asdict(policy),
                        qos=(qos.as_dict() if qos is not None
                             else None),
                        metrics_dir=mdir,
                        meta={**worker_meta, "engine_id": eid,
                              "role": "decode"},
                        family=family)
            else:
                from .fleet import EngineHandle

                def _spawn(eid):
                    return EngineHandle(eid, make_engine(eid),
                                        "decode")
            controller = AutoscaleController(router, autoscale,
                                             _spawn,
                                             metrics=router_metrics)
        tower = None
        if watch is not None:
            from ..runtime.watch import Watchtower
            tower = Watchtower(router, watch, metrics=router_metrics)
        shed = 0
        workload = None
        if trace_doc is not None:
            from .workload_driver import replay_trace
            workload = replay_trace(
                router, *trace_doc, vocab=args.vocab,
                pace=args.trace_pace or "virtual",
                steps_per_s=(args.trace_steps_per_s
                             if args.trace_steps_per_s is not None
                             else 8.0),
                log_every=args.log_every, metrics=router_metrics,
                autoscale=controller, watch=tower)
            shed = workload["shed"]
        else:
            for pr in prompts:
                try:
                    router.submit(pr, args.max_new)
                except AdmissionError:
                    shed += 1       # the router recorded the shed
            router.run(log_every=args.log_every)
        # fetch outcomes BEFORE close: under the process transport
        # these are protocol calls the shut-down workers can't answer
        finished = router.results()
        failed = router.failed()
        stats = router.fleet_stats()
    except (ValueError, RuntimeError) as e:
        # RuntimeError covers the fleet's own liveness failures (last
        # decode engine killed, fleet stalled) — a clean rc-2 error,
        # not a traceback, with the buffered telemetry flushed and
        # every worker process reaped
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if router is not None:
            router.close()      # workers flush their telemetry + exit
        elif handles is not None:
            # spawn succeeded but router construction raised (e.g. a
            # worker died before the fingerprint cross-check): the
            # detached workers must still be reaped — no orphans
            for h in handles:
                h.kill()
        for w in writers:
            w.close()
    wall = _time.perf_counter() - t0

    sequences = [{"uid": u, "tokens": toks,
                  "prompt_len": (len(router.requests[u]["prompt"])
                                 if u in router.requests else None)}
                 for u, toks in sorted(finished.items())]
    new_tokens = sum(len(s["tokens"]) - (s["prompt_len"] or 0)
                     for s in sequences)
    payload = {
        "sequences": sequences,
        "failed": {str(u): dict(info)
                   for u, info in sorted(failed.items())},
        "tokens_generated": new_tokens,
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(new_tokens / wall, 2),
        "kv_dtype": args.kv_dtype,
        "transport": args.transport,
        "fleet": stats,
        "fleet_rounds": stats["rounds"],
        "shed": shed,
    }
    if workload is not None:
        payload["workload"] = workload
    if controller is not None:
        payload["autoscale"] = {
            "scale_ups": controller.scale_ups,
            "scale_downs": controller.scale_downs,
            "history": [{"round": r, "event": e, "reason": why}
                        for r, e, why in controller.history],
        }
    if tower is not None:
        payload["watch"] = {
            "fired": tower.fired,
            "resolved": tower.resolved,
            "history": [{"round": r, "event": e, "detector": d}
                        for r, e, d in tower.history],
        }
    if args.policy:
        payload["policy"] = args.policy
    if args.metrics_dir:
        # where the live ops plane lives: `fleetstat <this>` renders
        # the router's atomic status doc, mid-run or after
        payload["status_doc"] = os.path.join(args.metrics_dir,
                                             "router")
    print(_json.dumps(payload))
    return 0


def generate_main(argv=None) -> int:
    p = build_generate_parser()
    args = p.parse_args(argv)

    if args.fake_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.fake_devices}").strip()

    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ..models import init_lm
    from .engine import AdmissionError, DecodeEngine, EngineConfig, \
        ServePolicy

    n_sources = sum(x is not None for x in
                    (args.prompts, args.prompt_lens, args.trace,
                     args.trace_gen))
    if n_sources != 1:
        print("error: pass exactly one of --prompts / --prompt_lens / "
              "--trace / --trace_gen", file=sys.stderr)
        return 2
    trace_mode = args.trace is not None or args.trace_gen is not None
    # trace-only knobs reject without a trace source (the fleet-flag
    # discipline: silently ignoring them would break a scripted run)
    if not trace_mode and (args.trace_out or args.trace_pace
                           or args.trace_steps_per_s is not None):
        print("error: --trace_out/--trace_pace/--trace_steps_per_s "
              "shape a trace replay: pass --trace FILE or "
              "--trace_gen SPEC", file=sys.stderr)
        return 2
    if args.trace_out and args.trace_gen is None:
        print("error: --trace_out persists a GENERATED trace: pass "
              "--trace_gen SPEC (a --trace file already exists)",
              file=sys.stderr)
        return 2
    if args.trace_steps_per_s is not None \
            and args.trace_steps_per_s <= 0:
        print(f"error: --trace_steps_per_s must be > 0, got "
              f"{args.trace_steps_per_s}", file=sys.stderr)
        return 2
    if trace_mode and (args.snapshot_dir or args.chaos
                       or args.watchdog_ms):
        print("error: --trace replay drives the engine directly "
              "(chaos composes at the FLEET level: --fleet_kill / "
              "--fleet_chaos); drop --snapshot_dir/--chaos/"
              "--watchdog_ms", file=sys.stderr)
        return 2
    trace_doc = None
    if trace_mode:
        from ..runtime.workload import (TraceError, generate_trace,
                                        materialize_prompt,
                                        read_trace, write_trace)
        try:
            if args.trace is not None:
                trace_doc = read_trace(args.trace)
            else:
                trace_doc = generate_trace(args.trace_gen)
                if args.trace_out:
                    write_trace(args.trace_out, *trace_doc)
            prompts = [materialize_prompt(trace_doc[0], e, args.vocab)
                       for e in trace_doc[1]]
        except (TraceError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    elif args.prompts is not None:
        try:
            prompts = [[int(t) for t in grp.split(",") if t.strip()]
                       for grp in args.prompts.split(";") if grp.strip()]
        except ValueError:
            print(f"error: unparseable --prompts {args.prompts!r}",
                  file=sys.stderr)
            return 2
    else:
        try:
            lens = [int(x) for x in args.prompt_lens.split(",")
                    if x.strip()]
        except ValueError:
            print(f"error: unparseable --prompt_lens "
                  f"{args.prompt_lens!r}", file=sys.stderr)
            return 2
        rng = np.random.default_rng(args.prompt_seed)
        prompts = [rng.integers(0, args.vocab, size=n).tolist()
                   for n in lens]
    if not prompts or any(not pr for pr in prompts):
        print("error: need at least one non-empty prompt",
              file=sys.stderr)
        return 2

    chaos_plan = None
    if args.chaos:
        if not args.snapshot_dir:
            print("error: --chaos requires --snapshot_dir (recovery "
                  "resumes from engine snapshots)", file=sys.stderr)
            return 2
        from ..runtime.chaos import FaultPlan, validate_decode_plan
        try:
            chaos_plan = FaultPlan.parse(args.chaos)
            validate_decode_plan(chaos_plan)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if args.watchdog_ms and not args.snapshot_dir:
        print("error: --watchdog_ms runs inside the supervisor: pass "
              "--snapshot_dir", file=sys.stderr)
        return 2
    if args.snapshot_every < 1:
        print(f"error: --snapshot_every must be >= 1, got "
              f"{args.snapshot_every}", file=sys.stderr)
        return 2
    # the supervisor-only flags reject consistently instead of some
    # silently no-opping: a user who set them expects supervision
    if args.snapshot_every != 1 and not args.snapshot_dir:
        print("error: --snapshot_every is the supervisor's snapshot "
              "cadence: pass --snapshot_dir", file=sys.stderr)
        return 2
    if args.max_restarts != 3 and not args.snapshot_dir:
        print("error: --max_restarts is the supervisor's restart "
              "budget: pass --snapshot_dir", file=sys.stderr)
        return 2

    # fleet flags (round 14): reject cleanly up front — the train-CLI
    # parse-rejection discipline. No --fleet means the single-engine
    # code path below runs UNTOUCHED (byte-identical to a CLI without
    # these flags).
    if not args.fleet and (args.prefill_engines or args.fleet_kill
                           or args.transport != "inproc"
                           or args.async_migration
                           or args.fleet_chaos or args.deploy_dir
                           or args.deploy_round is not None
                           or args.deploy_step is not None
                           or args.deploy_watch is not None
                           or args.autoscale or args.watch):
        print("error: --prefill_engines/--fleet_kill/--transport/"
              "--async_migration/--fleet_chaos/--deploy_*/"
              "--autoscale/--watch are "
              "fleet flags: pass --fleet N (N >= 2)", file=sys.stderr)
        return 2
    if args.autoscale and not trace_mode:
        print("error: --autoscale drives the trace replay loop (the "
              "controller ticks on the round clock between arrivals): "
              "pass --trace FILE or --trace_gen SPEC", file=sys.stderr)
        return 2
    if args.watch and not trace_mode:
        print("error: --watch detectors fold the trace replay's round "
              "clock (that's what makes the alert history replayable): "
              "pass --trace FILE or --trace_gen SPEC", file=sys.stderr)
        return 2
    if args.policy is not None and not args.policy.strip():
        print("error: --policy needs a non-empty label",
              file=sys.stderr)
        return 2
    if args.weights_from is None and args.weights_step is not None:
        print("error: --weights_step names a step of --weights_from — "
              "pass both", file=sys.stderr)
        return 2
    if args.weights_from and args.fleet:
        print("error: --weights_from is the single-engine oracle "
              "surface; a fleet takes new weights through "
              "--deploy_dir/--deploy_round instead", file=sys.stderr)
        return 2
    fleet_kill = None
    fleet_chaos = None
    if args.fleet:
        if args.fleet < 2:
            print(f"error: --fleet needs >= 2 engines, got "
                  f"{args.fleet} (a fleet of one is the default "
                  "single-engine path — drop the flag)",
                  file=sys.stderr)
            return 2
        if not 0 <= args.prefill_engines < args.fleet:
            print(f"error: --prefill_engines must leave >= 1 decode "
                  f"engine: got {args.prefill_engines} of "
                  f"{args.fleet}", file=sys.stderr)
            return 2
        if args.tp > 1:
            print("error: --fleet runs single-device replicas (the KV "
                  "handoff has no TP path); drop --tp", file=sys.stderr)
            return 2
        if args.snapshot_dir or args.chaos or args.watchdog_ms:
            print("error: --snapshot_dir/--chaos/--watchdog_ms drive "
                  "the single-engine supervisor; the fleet owns "
                  "failover in-process (fleet chaos: --fleet_kill "
                  "ENGINE@ROUND)", file=sys.stderr)
            return 2
        if args.engine_id is not None:
            # the fleet names its own streams (p0../e0../router);
            # silently ignoring the flag would break a user scripting
            # per-host labels — same discipline as the flags above
            print("error: --engine_id names a single engine's stream; "
                  "the fleet stamps its replicas p0../e0../router "
                  "under --metrics_dir — drop the flag",
                  file=sys.stderr)
            return 2
        if args.fleet_kill:
            eng_id, sep, rnd = args.fleet_kill.partition("@")
            try:
                at_round = int(rnd)
            except ValueError:
                at_round = -1
            if not eng_id or not sep or at_round < 0:
                print(f"error: unparseable --fleet_kill "
                      f"{args.fleet_kill!r} (want ENGINE@ROUND, e.g. "
                      "e1@6)", file=sys.stderr)
                return 2
            if (args.fleet - args.prefill_engines == 1
                    and eng_id == "e0"):
                # knowable at parse time: killing the sole decode
                # engine leaves the fleet nowhere to migrate
                print("error: --fleet_kill e0 would kill the only "
                      "decode engine in this fleet (the survivors "
                      "have nowhere to migrate its requests) — add "
                      "decode engines or kill a prefill engine",
                      file=sys.stderr)
                return 2
            fleet_kill = (eng_id, at_round)
        if args.deploy_watch is not None:
            if args.deploy_watch <= 0:
                print(f"error: --deploy_watch must be > 0 seconds, "
                      f"got {args.deploy_watch}", file=sys.stderr)
                return 2
            if not args.deploy_dir:
                print("error: --deploy_watch polls --deploy_dir's "
                      "ledger — pass both", file=sys.stderr)
                return 2
            if args.deploy_round is not None:
                print("error: --deploy_watch and --deploy_round are "
                      "two triggers for one deploy: pick one (watch "
                      "polls the ledger; round fires at a fixed "
                      "round)", file=sys.stderr)
                return 2
            if args.deploy_step is not None:
                # the watcher deploys whatever latest_verified
                # advances to — silently dropping a pinned step would
                # be exactly the ignored-flag failure this block
                # exists to reject
                print("error: --deploy_watch tracks the ledger's "
                      "latest verified step; an explicit "
                      "--deploy_step needs --deploy_round",
                      file=sys.stderr)
                return 2
        elif (args.deploy_round is None) != (args.deploy_dir is None):
            print("error: a rolling deploy needs both --deploy_dir "
                  "(the version ledger) and --deploy_round (when to "
                  "roll; or --deploy_watch to poll for publishes)",
                  file=sys.stderr)
            return 2
        if args.deploy_step is not None and not args.deploy_dir:
            print("error: --deploy_step names a step of --deploy_dir "
                  "— pass both", file=sys.stderr)
            return 2
        if args.deploy_round is not None and args.deploy_round < 0:
            print(f"error: --deploy_round must be >= 0, got "
                  f"{args.deploy_round}", file=sys.stderr)
            return 2
        if args.fleet_chaos:
            from ..runtime.chaos import FaultPlan, validate_fleet_plan
            try:
                fleet_chaos = FaultPlan.parse(args.fleet_chaos)
                validate_fleet_plan(fleet_chaos)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            kinds = {f.kind for f in fleet_chaos.faults}
            if (kinds - {"corrupt_deploy"}
                    and args.transport not in ("process", "tcp")):
                # worker faults need a boundary that can actually
                # fail: a worker that can die/go silent, a wire file
                # that can tear — in-process has neither
                # (corrupt_deploy tears a CHECKPOINT file, a surface
                # both transports share)
                print("error: --fleet_chaos drills the process "
                      "boundary: pass --transport process or tcp "
                      "(corrupt_deploy alone runs on either)",
                      file=sys.stderr)
                return 2
            if (kinds & {"partition_worker", "drop_conn"}
                    and args.transport != "tcp"):
                # only the TCP transport carries a reconnect ladder
                # to drill — an AF_UNIX EOF is an honest death
                print("error: partition_worker/drop_conn drill the "
                      "reconnect ladder: pass --transport tcp",
                      file=sys.stderr)
                return 2
            if "corrupt_deploy" in kinds and args.deploy_round is None:
                print("error: corrupt_deploy tears a SCHEDULED "
                      "deploy's checkpoint: pass --deploy_dir/"
                      "--deploy_round", file=sys.stderr)
                return 2
            n_decode = args.fleet - args.prefill_engines
            for f in fleet_chaos.faults:
                if f.kind != "kill_worker":
                    continue
                idx = 0 if f.arg is None else int(f.arg)
                if idx >= n_decode:
                    print(f"error: kill_worker index {idx} names "
                          f"e{idx}, but this fleet has {n_decode} "
                          "decode engine(s)", file=sys.stderr)
                    return 2
                if n_decode == 1:
                    print("error: kill_worker would kill the only "
                          "decode engine in this fleet (the survivors "
                          "have nowhere to migrate its requests)",
                          file=sys.stderr)
                    return 2

    if trace_doc is not None:
        # per-entry max_new: the reservation must cover the LONGEST
        # (prompt + continuation) the trace asks for
        need_tokens = max(len(pr) + int(e["max_new"])
                          for pr, e in zip(prompts, trace_doc[1]))
    else:
        need_tokens = max(len(pr) for pr in prompts) + args.max_new
    mbps = args.max_blocks_per_seq or -(
        -min(args.max_seq_len, need_tokens) // args.block_size)
    n_blocks = args.n_blocks or 1 + args.max_slots * mbps
    try:
        cfg = EngineConfig(
            block_size=args.block_size, n_blocks=n_blocks,
            max_slots=args.max_slots, max_blocks_per_seq=mbps,
            prefill_chunk=args.prefill_chunk, kv_dtype=args.kv_dtype,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, seed=args.sample_seed,
            use_rope=args.use_rope, speculate=args.speculate,
            kernel=args.kernel, prefix_cache=args.prefix_cache,
            spill_blocks=args.spill_blocks,
            spill_restore_per_step=args.spill_restore_per_step,
            prefix_partial=args.prefix_partial)
        policy = ServePolicy(
            queue_limit=args.queue_limit,
            deadline_steps=args.deadline_steps,
            max_retries=args.max_retries,
            preempt_after_steps=args.preempt_after)
        # the serving-policy layer (round 20): both specs are
        # validated HERE so a malformed one rejects rc 2 with the
        # parser's one-line named offense, never mid-run
        qos = None
        if args.qos:
            from ..runtime.policy import parse_qos_spec
            qos = parse_qos_spec(args.qos)
        autoscale_policy = None
        if args.autoscale:
            from ..runtime.policy import parse_autoscale_spec
            autoscale_policy = parse_autoscale_spec(args.autoscale)
        watch_policy = None
        if args.watch:
            from ..runtime.watch import parse_watch_spec
            watch_policy = parse_watch_spec(args.watch)
        # under the process transport the router never touches weights
        # — each worker rebuilds them from the recipe (same seed, same
        # bits) — so building them here would just double peak host
        # memory for nothing
        params = None
        if not (args.fleet and args.transport in ("process", "tcp")):
            params = init_lm(jax.random.PRNGKey(args.random_seed),
                             args.vocab, args.model_size, args.layers,
                             max_seq_len=args.max_seq_len,
                             n_heads=args.heads,
                             n_kv_heads=args.kv_heads or None)
        if args.weights_from:
            # serve FROM a published checkpoint (the deploy drill's
            # pinned-version oracle): the init above is the
            # architecture template the ledger restores into — a
            # mismatched shape rejects rc 2 like any other bad flag
            from ..runtime.weights import VersionLedger
            ledger = VersionLedger(args.weights_from)
            w_step = args.weights_step
            if w_step is None:
                w_step = ledger.latest_verified()
                if w_step is None:
                    raise ValueError("no verified checkpoint under "
                                     f"{args.weights_from}")
            try:
                params = ledger.load(w_step, params)
            except (OSError, RuntimeError) as e:
                raise ValueError(f"--weights_from: {e}") from None
        mesh = None
        tp = 1
        if args.tp > 1:
            from ..parallel import MODEL_AXIS, make_mesh
            # the payload/meta report the EFFECTIVE mesh size, never the
            # request — a clamped run must not masquerade as N-way TP
            tp = min(args.tp, jax.device_count())
            if tp < args.tp:
                print(f"generate: --tp {args.tp} clamped to {tp} "
                      f"({jax.device_count()} device(s) visible; use "
                      "--fake_devices on CPU)", file=sys.stderr)
            if tp > 1:
                mesh = make_mesh({MODEL_AXIS: tp})
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if chaos_plan is not None:
        # the pool size is known here: a block id typo must reject rc 2
        # instead of burning the supervisor's whole restart ladder on a
        # deterministic ValueError at fire time
        for f in chaos_plan.faults:
            if f.kind == "corrupt_block" and int(f.arg) >= cfg.n_blocks:
                print(f"error: corrupt_block block {int(f.arg)} outside "
                      f"the pool ({cfg.n_blocks} block(s) incl. "
                      "scratch)", file=sys.stderr)
                return 2

    if args.fleet:
        return _fleet_main(args, prompts, cfg, policy, params,
                           fleet_kill, fleet_chaos, argv,
                           trace_doc=trace_doc, qos=qos,
                           autoscale=autoscale_policy,
                           watch=watch_policy)

    metrics = None
    engine_id = args.engine_id
    if args.metrics_dir:
        from ..runtime.telemetry import TelemetryWriter
        if engine_id is None:
            engine_id = os.path.basename(
                os.path.normpath(args.metrics_dir))
        meta = {
            "argv": list(argv or []), "subcommand": "generate",
            "engine_id": engine_id,
            "vocab": args.vocab, "model_size": args.model_size,
            "layers": args.layers, "heads": args.heads,
            "kv_dtype": args.kv_dtype, "max_slots": args.max_slots,
            "block_size": args.block_size, "tp": tp,
            "speculate": args.speculate, "kernel": args.kernel,
            "prefix_cache": args.prefix_cache,
            "n_prompts": len(prompts), "max_new": args.max_new,
            "device_kind": jax.devices()[0].device_kind}
        if args.policy:
            # the offline policy-search key: `report --slo` folds
            # per-policy attainment by this meta label
            meta["policy"] = args.policy
        if args.qos:
            meta["qos"] = args.qos
        if args.snapshot_dir:
            meta["snapshot_dir"] = args.snapshot_dir
            meta["attempt_log"] = os.path.join(
                args.snapshot_dir, "serve_supervise.jsonl")
        metrics = TelemetryWriter(args.metrics_dir, meta=meta)

    mesh_kw = dict(mesh=mesh, policy=policy, qos=qos)
    shed = 0
    workload = None
    prior_tokens = 0
    resumed_from = None
    t0 = time.perf_counter()
    try:
        if args.snapshot_dir:
            from .supervise import load_snapshot, supervise_decode
            snap = load_snapshot(args.snapshot_dir)
            if snap is not None:
                resumed_from = int(snap["step"])
                prior_tokens = int(
                    snap["counters"]["tokens_generated"])
                print(f"generate: resuming from snapshot step "
                      f"{resumed_from} in {args.snapshot_dir} (prompt "
                      "flags ignored — the snapshot is authoritative)",
                      file=sys.stderr)
            engine = supervise_decode(
                lambda: DecodeEngine(params, args.heads, cfg, **mesh_kw),
                [(pr, args.max_new) for pr in prompts],
                snapshot_dir=args.snapshot_dir, chaos=chaos_plan,
                watchdog_ms=args.watchdog_ms, metrics=metrics,
                log_every=args.log_every,
                snapshot_every=args.snapshot_every,
                max_restarts=args.max_restarts)
            shed = engine.rejected
        elif trace_doc is not None:
            from .workload_driver import replay_trace
            engine = DecodeEngine(params, args.heads, cfg,
                                  metrics=metrics, **mesh_kw)
            workload = replay_trace(
                engine, *trace_doc, vocab=args.vocab,
                pace=args.trace_pace or "virtual",
                steps_per_s=(args.trace_steps_per_s
                             if args.trace_steps_per_s is not None
                             else 8.0),
                log_every=args.log_every, metrics=metrics)
            shed = workload["shed"]
        else:
            engine = DecodeEngine(params, args.heads, cfg,
                                  metrics=metrics, **mesh_kw)
            for pr in prompts:
                try:
                    engine.submit(pr, args.max_new)
                except AdmissionError:
                    shed += 1       # recorded as a `rejected` event
            engine.run(metrics=metrics, log_every=args.log_every)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        if metrics is not None:
            metrics.close()
        return 2
    wall = time.perf_counter() - t0
    if metrics is not None:
        metrics.close()

    new_tokens = engine.tokens_generated - prior_tokens
    sequences = []
    for u, toks in sorted(engine.finished.items()):
        # prompt_len from the engine's own per-uid record (snapshot-
        # persisted): immune to shed submissions skewing uid/index
        # alignment and to a resume invoked with different flags
        sequences.append({"uid": u, "tokens": toks,
                          "prompt_len": engine.prompt_lens.get(u)})
    payload = {
        "sequences": sequences,
        "failed": {str(u): info
                   for u, info in sorted(engine.failed.items())},
        "tokens_generated": engine.tokens_generated,
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(new_tokens / wall, 2),
        "engine_steps": engine.global_step,
        "mean_occupancy": round(engine.mean_occupancy(), 4),
        "compiled_programs": engine.compile_count,
        "dispatches": engine.dispatch_count,
        "kv_dtype": args.kv_dtype,
        "tp": tp,
        "speculate": args.speculate,
        "kernel": args.kernel,
        "drafted_tokens": engine.drafted_tokens,
        "accepted_tokens": engine.accepted_tokens,
        "accept_rate": (round(engine.accepted_tokens
                              / engine.drafted_tokens, 4)
                        if engine.drafted_tokens else None),
        "prefix_cache": args.prefix_cache,
        "prefix_hit_blocks": engine.prefix_hit_blocks,
        "prefill_tokens_saved": engine.prefill_tokens_saved,
        "prefill_dispatches": engine.prefill_dispatches,
        "cow_copies": engine.cow_copies,
        "spill_blocks": args.spill_blocks,
        "spilled_blocks": engine.spilled_blocks,
        "restores": engine.restores,
        "restore_tokens_saved": engine.restore_tokens_saved,
        "partial_hits": engine.partial_hits,
        "quarantined": engine.quarantined,
        "retried": engine.retried,
        "preempted": engine.preempted,
        "rejected": engine.rejected,
        "expired": engine.expired,
        "shed": shed,
    }
    if workload is not None:
        payload["workload"] = workload
    if resumed_from is not None:
        payload["resumed_from_step"] = resumed_from
    if engine_id is not None:
        payload["engine_id"] = engine_id
    if args.policy:
        payload["policy"] = args.policy
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(generate_main())
