"""`generate` — the serving CLI: drive the decode engine end to end.

Mirrors the training CLI's stance (``cli.py``): the model is the LM
family at the flagged shape (``init_lm`` — random weights unless you
wire your own; the engine is the demonstration target, not the
checkpoint plumbing), prompts are either explicit token-id lists
(``--prompts "3,1,4;9,2"``) or deterministic random draws
(``--prompt_lens 5,9,13`` with ``--prompt_seed``), and the run prints
ONE JSON line with every sequence's tokens plus the engine's
throughput/occupancy stats. ``--metrics_dir`` streams schema-v3
``decode`` records through the unified telemetry writer
(``runtime/telemetry.py``) — ``report`` folds them like any other run.

``--tp N`` runs the Megatron decode layout over an N-way model-axis
mesh (``--fake_devices`` makes that work on CPU, as everywhere else).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_generate_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="generate",
        description="Continuous-batching decode over the paged KV engine "
                    "(decode/engine.py)")
    # model shape (the cli.py -m 11 family surface)
    p.add_argument("-d", "--model_size", type=int, default=64)
    p.add_argument("-l", "--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv_heads", type=int, default=0,
                   help="GQA KV heads (0 = full MHA); shrinks the KV "
                        "pool by heads/kv_heads")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--max_seq_len", type=int, default=256)
    p.add_argument("-r", "--random_seed", type=int, default=0,
                   help="model init seed (the cli.py convention)")
    p.add_argument("--use_rope", action="store_true",
                   help="rotary attention (must match training)")
    # requests
    p.add_argument("--prompts", default=None,
                   help="semicolon-separated comma-lists of token ids, "
                        'e.g. "3,1,4;9,2,6,5"')
    p.add_argument("--prompt_lens", default=None,
                   help="comma-separated lengths of random prompts "
                        "(deterministic per --prompt_seed), e.g. 5,9,13")
    p.add_argument("--prompt_seed", type=int, default=0)
    p.add_argument("--max_new", type=int, default=16)
    # sampling (fused, in-graph; decode/sampling.py)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy argmax")
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=0.0)
    p.add_argument("--sample_seed", type=int, default=0)
    # engine layout
    p.add_argument("--kv_dtype", choices=["f32", "bf16", "int8"],
                   default="f32")
    p.add_argument("--block_size", type=int, default=16)
    p.add_argument("--n_blocks", type=int, default=0,
                   help="KV pool blocks incl. the scratch block "
                        "(0 = sized for max_slots full sequences)")
    p.add_argument("--max_slots", type=int, default=4)
    p.add_argument("--max_blocks_per_seq", type=int, default=0,
                   help="per-sequence table width (0 = cover "
                        "max_seq_len)")
    p.add_argument("--prefill_chunk", type=int, default=16)
    # parallel strategy
    p.add_argument("--tp", type=int, default=1,
                   help="model-axis size for the Megatron decode layout "
                        "(1 = single-device)")
    p.add_argument("--fake_devices", type=int, default=0)
    # observability
    p.add_argument("--metrics_dir", default=None)
    p.add_argument("--log_every", type=int, default=4,
                   help="decode-record cadence in engine steps")
    return p


def generate_main(argv=None) -> int:
    p = build_generate_parser()
    args = p.parse_args(argv)

    if args.fake_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.fake_devices}").strip()

    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ..models import init_lm
    from .engine import DecodeEngine, EngineConfig

    if (args.prompts is None) == (args.prompt_lens is None):
        print("error: pass exactly one of --prompts / --prompt_lens",
              file=sys.stderr)
        return 2
    if args.prompts is not None:
        try:
            prompts = [[int(t) for t in grp.split(",") if t.strip()]
                       for grp in args.prompts.split(";") if grp.strip()]
        except ValueError:
            print(f"error: unparseable --prompts {args.prompts!r}",
                  file=sys.stderr)
            return 2
    else:
        try:
            lens = [int(x) for x in args.prompt_lens.split(",")
                    if x.strip()]
        except ValueError:
            print(f"error: unparseable --prompt_lens "
                  f"{args.prompt_lens!r}", file=sys.stderr)
            return 2
        rng = np.random.default_rng(args.prompt_seed)
        prompts = [rng.integers(0, args.vocab, size=n).tolist()
                   for n in lens]
    if not prompts or any(not pr for pr in prompts):
        print("error: need at least one non-empty prompt",
              file=sys.stderr)
        return 2

    longest = max(len(pr) for pr in prompts)
    mbps = args.max_blocks_per_seq or -(
        -min(args.max_seq_len, longest + args.max_new) // args.block_size)
    n_blocks = args.n_blocks or 1 + args.max_slots * mbps
    try:
        cfg = EngineConfig(
            block_size=args.block_size, n_blocks=n_blocks,
            max_slots=args.max_slots, max_blocks_per_seq=mbps,
            prefill_chunk=args.prefill_chunk, kv_dtype=args.kv_dtype,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, seed=args.sample_seed,
            use_rope=args.use_rope)
        params = init_lm(jax.random.PRNGKey(args.random_seed),
                         args.vocab, args.model_size, args.layers,
                         max_seq_len=args.max_seq_len,
                         n_heads=args.heads,
                         n_kv_heads=args.kv_heads or None)
        mesh = None
        tp = 1
        if args.tp > 1:
            from ..parallel import MODEL_AXIS, make_mesh
            # the payload/meta report the EFFECTIVE mesh size, never the
            # request — a clamped run must not masquerade as N-way TP
            tp = min(args.tp, jax.device_count())
            if tp < args.tp:
                print(f"generate: --tp {args.tp} clamped to {tp} "
                      f"({jax.device_count()} device(s) visible; use "
                      "--fake_devices on CPU)", file=sys.stderr)
            if tp > 1:
                mesh = make_mesh({MODEL_AXIS: tp})
        engine = DecodeEngine(params, args.heads, cfg, mesh=mesh)
        uids = [engine.submit(pr, args.max_new) for pr in prompts]
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    metrics = None
    if args.metrics_dir:
        from ..runtime.telemetry import TelemetryWriter
        metrics = TelemetryWriter(args.metrics_dir, meta={
            "argv": list(argv or []), "subcommand": "generate",
            "vocab": args.vocab, "model_size": args.model_size,
            "layers": args.layers, "heads": args.heads,
            "kv_dtype": args.kv_dtype, "max_slots": args.max_slots,
            "block_size": args.block_size, "tp": tp,
            "n_prompts": len(prompts), "max_new": args.max_new,
            "device_kind": jax.devices()[0].device_kind})

    t0 = time.perf_counter()
    done = engine.run(metrics=metrics, log_every=args.log_every)
    wall = time.perf_counter() - t0
    if metrics is not None:
        metrics.close()

    payload = {
        "sequences": [
            {"uid": u, "prompt_len": len(pr),
             "tokens": done[u]}
            for u, pr in zip(uids, prompts)],
        "tokens_generated": engine.tokens_generated,
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(engine.tokens_generated / wall, 2),
        "engine_steps": engine.steps,
        "mean_occupancy": round(engine.mean_occupancy(), 4),
        "compiled_programs": engine.compile_count,
        "dispatches": engine.dispatch_count,
        "kv_dtype": args.kv_dtype,
        "tp": tp,
    }
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(generate_main())
