"""n-gram / prompt-copy drafter for speculative decoding.

The engine's speculative path (``decode/engine.py``, ``speculate=k``)
needs a cheap proposal distribution: up to ``k`` guesses for the next
tokens, verified in one batched compiled step. The first-principles
answer — no second model, no learned parameters — is **prompt-copy
n-gram lookup** (the "prompt lookup decoding" observation): real
serving traffic is dominated by continuations that repeat something
already in the context (quoted prompt spans, code identifiers, and —
on this repo's tiny random-weight models — the constant/cyclic
attractors greedy decode falls into), so the best free guess for "what
comes next" is "what came after the last time this suffix appeared".

Contract (the whole reliability story hangs on it): a draft is a PURE
FUNCTION of the token history ``prompt + out`` — no carried state, no
randomness, no clock. Quarantine-retry, preemption replay, and
crash-resume therefore re-draft identically: a resumed engine at the
same history proposes the same tokens, verifies them against the same
greedy picks, and rebuilds the same KV write history
(tests/test_spec_decode.py pins it at every kv_dtype).

Scale note: the scan below is O(n·len(history)) per call — exactly
right for the max_seq_len-bounded engine histories this repo serves.
A production router would amortize it with a suffix automaton per
sequence; that is an optimization of this function's contract, not a
change to it.
"""

from __future__ import annotations


def draft_tokens(history, k: int, max_n: int = 3) -> list[int]:
    """Propose up to ``k`` continuation tokens for ``history``.

    Finds the LONGEST suffix of ``history`` (length ``max_n`` down to
    1) that occurred earlier, preferring the MOST RECENT earlier
    occurrence (recency beats frequency for loop-shaped continuations),
    and copies the tokens that followed it. Returns ``[]`` when no
    token of the suffix ever occurred before — the verify step then
    degenerates to a plain decode step (one token, nothing risked).
    May return fewer than ``k`` tokens when the match sits near the
    end of the history."""
    if k <= 0:
        return []
    h = [int(t) for t in history]
    n_h = len(h)
    if n_h < 2:
        return []
    for n in range(min(max_n, n_h - 1), 0, -1):
        suffix = h[n_h - n:]
        # j is the END index of a candidate earlier occurrence; scan
        # right-to-left so the first hit is the most recent one
        for j in range(n_h - 2, n - 2, -1):
            if h[j - n + 1:j + 1] == suffix:
                return h[j + 1:j + 1 + k]
    return []
