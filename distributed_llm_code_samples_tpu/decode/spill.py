"""Host-RAM KV spill tier: the second rung of the KV memory hierarchy.

The prefix cache (``decode/prefix.py``) keeps refs-0 blocks device-
resident only until pool pressure LRU-evicts them, so "millions of
sessions" capacity is bounded by device pool size: a returning session
whose prefix was evicted pays a full re-prefill. This module is the
bounded host-side store those evictions DEMOTE into instead — one wire
document per block, at the storage dtype plus the int8 per-block
scales, so a later radix hit on the spilled edge PROMOTES the bytes
back through the one compiled donated implant program
(``decode/engine.py``) instead of re-prefilling.

Integrity is ``runtime/wire.py``'s CRC discipline, reused verbatim:
``put`` serializes the block document with per-array CRC-32 headers
and ``take`` verifies them on the way out, so a spilled block
corrupted in host RAM is DETECTED at restore (one ``WireError`` line
naming the damaged array) — never decoded. The engine quarantines the
restoring request; survivors never read the bytes.

Watermark policy (the tier's half — the engine owns demotion):

- **High watermark = capacity.** ``put`` past ``capacity_blocks``
  drops the oldest-spilled entries (LRU by spill time; a spilled
  node's clock cannot advance — nothing touches it until restore) and
  returns their nodes so the caller detaches the now-unrestorable
  edges from the radix tree. The host tier is BOUNDED, never a leak.
- **Promotion consumes the entry.** ``take`` removes the host copy
  whether the CRC verdict is clean (the bytes are device-resident
  again) or corrupt (the bytes are evidence, not cache).

Lifetime: the tier is process memory, nothing more. A kill loses it;
the engine snapshot records the radix TREE SHAPE only (never spilled
bytes), and resume rebuilds the share graph via replay exactly as it
does for device blocks. There is deliberately no persistence path —
a second durability discipline for bytes that replay reconstructs
for free would be complexity without a failure mode to pay for it.

Plain host Python + numpy (via ``runtime/wire``): the device never
sees this module; the engine owns all pool writes and free-list edits.
"""

from __future__ import annotations

from ..runtime import wire


class SpillTier:
    """Bounded host-RAM store of spilled KV blocks, keyed by a
    monotone spill id. Entries are ``wire.serialize_doc`` bytes
    (CRC-32 per array), insertion-ordered for LRU-by-spill-time
    overflow drops."""

    def __init__(self, capacity_blocks: int):
        if int(capacity_blocks) < 1:
            raise ValueError(f"spill tier capacity must be >= 1 block, "
                             f"got {capacity_blocks}")
        self.capacity = int(capacity_blocks)
        self._store: dict[int, bytes] = {}    # spill_id -> wire bytes
        self._nodes: dict[int, object] = {}   # spill_id -> PrefixNode
        self._next_id = 0
        # lifetime counters (the engine folds these into telemetry)
        self.spills = 0          # entries ever admitted
        self.drops = 0           # entries removed without a restore
        self.restores = 0        # clean CRC-verified promotions
        self.bytes_spilled = 0   # cumulative wire bytes admitted
        self.bytes_resident = 0  # wire bytes held right now

    def __len__(self) -> int:
        return len(self._store)

    def utilization(self) -> float:
        """Occupancy fraction (``host_tier_utilization``)."""
        return len(self._store) / self.capacity

    def put(self, node, doc: dict) -> tuple[int, list]:
        """Admit one block document for ``node``; returns ``(spill_id,
        overflow_victims)`` where the victims are the oldest-spilled
        NODES whose entries were dropped to hold the capacity bound —
        the caller must detach them (their edges are no longer
        restorable). ``doc`` carries the ``extract_blocks`` arrays for
        ONE block (k/v at the storage dtype, scales or None)."""
        data = wire.serialize_doc(doc)
        sid = self._next_id
        self._next_id += 1
        self._store[sid] = data
        self._nodes[sid] = node
        self.spills += 1
        self.bytes_spilled += len(data)
        self.bytes_resident += len(data)
        victims = []
        while len(self._store) > self.capacity:
            old = next(iter(self._store))
            victims.append(self._nodes[old])
            self.drop(old)
        return sid, victims

    def drop(self, spill_id: int) -> bool:
        """Remove an entry without restoring it (overflow, a detached
        node, corruption evidence consumed). Idempotent."""
        data = self._store.pop(spill_id, None)
        self._nodes.pop(spill_id, None)
        if data is None:
            return False
        self.bytes_resident -= len(data)
        self.drops += 1
        return True

    def take(self, spill_id: int) -> dict:
        """Promote: CRC-verify and return the block document, removing
        the host copy either way. Raises ``wire.WireError`` (one line
        naming the damage) when the stored bytes fail any integrity
        check — the caller's quarantine path; the entry is consumed so
        the damage cannot be re-served. ``KeyError`` if absent."""
        data = self._store.pop(spill_id)
        self._nodes.pop(spill_id, None)
        self.bytes_resident -= len(data)
        try:
            doc = wire.deserialize_doc(data)
        except wire.WireError:
            self.drops += 1
            raise
        self.restores += 1
        return doc

    def corrupt(self, spill_id: int) -> bool:
        """Chaos injection (``corrupt_spill@s:id``): flip one byte in
        the stored wire bytes — the host-RAM bit flip the CRC ladder
        exists to catch. Flips in the back half of the buffer (array
        payload, not the zip directory) so the damage reaches the
        per-array CRC check rather than dying as an unreadable file —
        either way ``take`` raises ``WireError``. False if absent
        (already restored or dropped — the fault found nothing)."""
        data = self._store.get(spill_id)
        if data is None:
            return False
        buf = bytearray(data)
        buf[(3 * len(buf)) // 4] ^= 0xFF
        self._store[spill_id] = bytes(buf)
        return True
