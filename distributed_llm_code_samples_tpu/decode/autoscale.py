"""Closed-loop decode-tier autoscaler (DESIGN.md section 26).

The controller that finally ACTS on what the fleet already records:
between fleet rounds it reads the router's own light digests (queue
depth per alive decode engine — zero extra round-trips, the same reads
every routing decision makes), compares the mean waiting depth against
the ``AutoscalePolicy`` thresholds, and scales the decode tier —

- **up**: mint the next engine id, call the caller-provided ``spawn``
  factory, WARM the new member (full program prebuild) before
  ``add_engine`` admits it — a joining engine never pays a compile
  under live load, so the steady state stays at zero new compiles;
- **down**: retire the least-loaded member through the rolling-deploy
  drain (live residents ship KV to peers, the rest replay-resume —
  ZERO shed, enforced here with an explicit check, not assumed).

Flapping and scale-to-zero are structurally impossible: the policy
validates ``up_queue > down_queue`` (a dead band), ``hysteresis``
consecutive rounds must agree before any action, ``cooldown`` rounds
must pass between actions, and ``min_engines >= 1`` floors the tier.
The one exception that IGNORES cooldown is the below-min floor repair:
a dead worker mid-burst is replaced immediately — waiting out a
cooldown with the fleet under its floor would be the controller
protecting itself from the exact event it exists for.

Determinism: every decision folds only the round clock and the
digests' integer queue depths — never wall time — so the same
``(trace, seed, policy)`` replays the same scaling episode and the
tokens stay byte-identical (wall-clock fields on the records, like
``spawn_s``, are attribution, not decision inputs). The controller is
pure host-side control flow; it never touches a compiled program or a
sampling key.
"""

from __future__ import annotations

import time

from ..runtime.policy import AutoscalePolicy


class AutoscaleController:
    """Drives one ``FleetRouter``'s decode tier against an
    ``AutoscalePolicy``. ``spawn(eid)`` is the caller's factory
    returning a CONNECTED decode handle for a fresh engine (in-process
    ``EngineHandle`` or a ``spawn_worker`` process handle) — the
    controller warms it before it takes traffic. ``tick()`` runs
    between fleet rounds (the workload driver calls it after each
    round step); it returns the action taken ("scale_up" /
    "scale_down") or None."""

    def __init__(self, router, policy: AutoscalePolicy, spawn, *,
                 metrics=None):
        self.router = router
        self.policy = policy
        self.spawn = spawn
        self.metrics = metrics
        self.cooldown_until = 0         # round clock, not wall clock
        self.scale_ups = 0
        self.scale_downs = 0
        self.history: list[tuple] = []  # (round, event, reason)
        self._up_streak = 0
        self._down_streak = 0
        self._held_logged = False       # one "held" record per episode
        self._last = (None, None, None)  # (event, reason, round)
        self._mirror()

    # -- telemetry -----------------------------------------------------

    def _emit(self, event: str, reason: str, *, engines: int,
              target: int, **extra) -> None:
        if self.metrics is not None:
            self.metrics.autoscale({
                "step": self.router.rounds, "event": event,
                "reason": reason, "engines": engines,
                "target_engines": target, **extra})

    def _mirror(self) -> None:
        """Mirror live controller state onto the router for the status
        doc (``fleet_status.json``'s ``autoscale`` block)."""
        r = self.router
        event, reason, rnd = self._last
        r.autoscale_state = {
            "engines": len(r.alive_handles("decode")),
            "target_engines": self._target(),
            "min_engines": self.policy.min_engines,
            "max_engines": self.policy.max_engines,
            "last_event": event,
            "last_reason": reason,
            "last_round": rnd,
            "cooldown_remaining": max(0, self.cooldown_until
                                      - r.rounds),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
        }

    def _target(self) -> int:
        """What the controller currently WANTS: the alive count plus
        the pending intent a completed streak expresses (clamped to
        the policy's floor/ceiling)."""
        n = len(self.router.alive_handles("decode"))
        want = n
        if n < self.policy.min_engines:
            want = self.policy.min_engines
        elif self._up_streak >= self.policy.hysteresis:
            want = n + 1
        elif self._down_streak >= self.policy.hysteresis:
            want = n - 1
        return max(self.policy.min_engines,
                   min(self.policy.max_engines, want))

    # -- the control loop ----------------------------------------------

    def tick(self):
        """One controller decision on the router's round clock.
        Returns "scale_up" / "scale_down" when the fleet changed, else
        None (a "held" decision — streak complete but cooldown or a
        bound blocks — is recorded once per episode, not returned: the
        fleet did not change)."""
        r = self.router
        alive = r.alive_handles("decode")
        n = len(alive)
        if n < self.policy.min_engines:
            # floor repair beats cooldown: dead capacity is replaced
            # NOW (the chaos drill's kill_worker path)
            return self._scale_up("below_min_floor")
        waiting = sum(h.digest(light=True)["waiting"] for h in alive)
        pressure = waiting / n
        if pressure >= self.policy.up_queue:
            self._up_streak += 1
            self._down_streak = 0
        elif pressure < self.policy.down_queue:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # inside the dead band: both streaks reset — hysteresis
            # counts CONSECUTIVE rounds, not rounds ever
            if self._up_streak or self._down_streak:
                self._held_logged = False
            self._up_streak = self._down_streak = 0
        in_cooldown = r.rounds < self.cooldown_until
        action = None
        if self._up_streak >= self.policy.hysteresis:
            if n >= self.policy.max_engines:
                self._held("queue_pressure", "at_max_engines", n)
            elif in_cooldown:
                self._held("queue_pressure", "cooldown", n)
            else:
                action = self._scale_up("queue_pressure")
        elif self._down_streak >= self.policy.hysteresis:
            if n <= self.policy.min_engines:
                self._held("queue_idle", "at_min_engines", n)
            elif in_cooldown:
                self._held("queue_idle", "cooldown", n)
            else:
                action = self._scale_down("queue_idle")
        if action is None:
            self._mirror()      # keep cooldown_remaining live
        return action

    def _held(self, want_reason: str, blocked_by: str, n: int) -> None:
        """A completed streak the controller is NOT acting on — record
        it once per episode so the drill can see the dead band and
        cooldown doing their job (a per-round record would spam one
        line per held round)."""
        if self._held_logged:
            return
        self._held_logged = True
        reason = f"{want_reason}:{blocked_by}"
        self.history.append((self.router.rounds, "held", reason))
        self._last = ("held", reason, self.router.rounds)
        self._emit("held", reason, engines=n, target=self._target())

    def _scale_up(self, reason: str):
        r = self.router
        eid = r.next_decode_eid()
        t0 = time.perf_counter()
        handle = self.spawn(eid)
        try:
            compiled = handle.warm()    # BEFORE any traffic
            r.add_engine(handle)
        except Exception:
            handle.kill()
            raise
        spawn_s = time.perf_counter() - t0
        self.scale_ups += 1
        self.cooldown_until = r.rounds + self.policy.cooldown
        self._up_streak = self._down_streak = 0
        self._held_logged = False
        self.history.append((r.rounds, "scale_up", reason))
        self._last = ("scale_up", reason, r.rounds)
        self._emit("scale_up", reason,
                   engines=len(r.alive_handles("decode")),
                   target=self._target(), engine=eid,
                   compiled=compiled, spawn_s=round(spawn_s, 6))
        self._mirror()
        return "scale_up"

    def _scale_down(self, reason: str):
        r = self.router
        victim = min(r.alive_handles("decode"), key=r._load_key)
        sheds_before = r.sheds
        drained = r.retire_engine(victim.id)
        if r.sheds != sheds_before:
            raise RuntimeError(
                "scale-down drain shed requests — the zero-shed "
                "drain contract is broken")
        self.scale_downs += 1
        self.cooldown_until = r.rounds + self.policy.cooldown
        self._up_streak = self._down_streak = 0
        self._held_logged = False
        self.history.append((r.rounds, "scale_down", reason))
        self._last = ("scale_down", reason, r.rounds)
        self._emit("scale_down", reason,
                   engines=len(r.alive_handles("decode")),
                   target=self._target(), engine=victim.id,
                   drained=drained)
        self._mirror()
        return "scale_down"
