"""Serving supervisor: snapshot-resume, chaos injection, hung-step
watchdog — the decode engine's crash-recovery loop.

The training side earned its recovery discipline in rounds 6 and 8
(``runtime/failure.py``: restart ladder, jittered backoff, per-attempt
JSONL). This module is the serving twin, built around one observation:
the engine's whole device state is RECOMPUTABLE from host metadata.
A sequence's continuation is a pure function of ``(params, engine
seed, uid, prompt, emitted tokens)`` — the sampling keys fold
``(seed, uid, position)`` and never the slot — so the **snapshot** is
a small JSON document (waiting queue, per-slot uid/position/block-table
state, finished/failed maps, counters), not a KV-pool dump. Recovery
re-prefills each in-flight prompt and teacher-forces its recorded
tokens through the decode path (``_Seq.emitted``), which replays the
exact KV **write history** — so the rebuilt cache is bit-identical at
every kv_dtype, int8 quantization history included, and the resumed
run's remaining tokens match an uninterrupted run token for token.

The supervisor wraps ``DecodeEngine.run`` with two hooks:

- ``before_step``: fire due decode chaos faults (``runtime/chaos.py``
  decode grammar) — ``hang_step`` sleeps, ``nan_logits`` arms the
  in-graph poison operand, ``corrupt_block`` poisons a pool block;
- ``after_step``: watchdog latch check + kick (a step that overran
  ``watchdog_ms`` leaves ``hung_step`` evidence in the attempt log and
  the telemetry stream), atomic snapshot persist, then ``kill`` faults
  (SIGKILL right AFTER the step's snapshot — the deterministic
  crash-between-steps fault; a resumed run starts past that step and
  never re-fires it).

In-process failures (anything ``engine.run`` raises) take the restart
rung: reload the last snapshot into a fresh engine, with the SAME
jittered-backoff schedule and attempt-log record shapes as the
training supervisor (``runtime.failure.backoff_delay``). SIGKILL-class
deaths are recovered by the next invocation of the same command — the
generate CLI resumes automatically when its ``--snapshot_dir`` holds a
snapshot.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import time

from ..runtime.failure import _head, backoff_delay
from .engine import AdmissionError, DecodeEngine, POISON_ALL

SNAPSHOT_FILENAME = "engine_snapshot.json"
# v2 (round 11): counters grow the KV-pool churn trio (block_allocs /
# block_frees / block_scrubs) so the schema-v5 decode records stay
# monotonic across crash-resume
# v3 (round 12): counters grow the speculation pair (drafted_tokens /
# accepted_tokens) — same monotonic-across-resume contract; the
# drafter itself needs NO snapshot state (drafts are a pure function
# of prompt + out, decode/draft.py)
# v4 (round 13): counters grow the shared-prefix set (prefix_hit_blocks
# / prefill_tokens_saved / cow_copies / prefix_lookup_blocks /
# prefill_dispatches) and the snapshot persists ``prefix_tree`` — the
# radix share graph (``PrefixCache.snapshot()``: per-node token edge,
# physical block, refcount, LRU clock, poison flag). Block CONTENT
# dies with the process, so restore deliberately does NOT rebuild the
# tree: replay re-prefills each live request and re-INSERTS its full
# prompt blocks, so the share graph reassembles organically (the first
# replayed sharer prefills, later ones hit — the ~1-prefill property
# survives the crash) and the persisted tree is the certificate tests
# pin the rebuild against.
# v5 (round 15): request entries carry ``t_first`` — the first-token
# timestamp (``SpanTracer.mark_first_token``) — so a crash-resumed
# request's completed record keeps its TRUE ``ttft_s`` (schema v9).
# The crash gap itself stays visibly unaccounted in the span stream;
# only the first-token FACT survives, never invented wall time.
# v6 (round 17): the live-weight hot-swap state (DESIGN.md section 23).
# Request entries carry ``weights_version`` — the pin a resumed
# request replays and finishes on (None = never admitted, pins at
# admission) — and the snapshot pins ``serving_version`` plus
# ``weights_versions`` (version id -> model fingerprint for every
# resident version, the ledger-sourced identity restore validates: a
# mixed-version engine's snapshot can only restore onto an engine
# that HOLDS those versions). ``model`` remains the serving version's
# fingerprint (the pre-v6 readers' key).
# v7 (round 18): request entries carry ``trace_id`` — the causal
# identity minted once at admission (schema v12) — so a crash-resumed
# request's records keep stitching into the SAME cross-process trace
# waterfall (the crash gap itself stays visibly unaccounted, exactly
# the ``t_first`` stance).
# v8 (round 19): request entries carry ``tenant`` — the tenant tag
# (schema v13, None single-tenant) — so a crash-resumed or
# kill-migrated request keeps its per-tenant attribution (the
# workload plane's noisy-tenant numbers survive the death).
# v9 (round 23): counters grow the KV-spill set (spilled_blocks /
# spill_bytes / restores / restore_tokens_saved / restore_stall_s /
# partial_hits — schema v17) and the persisted ``prefix_tree`` nodes
# carry ``spilled``. The host tier's BYTES are deliberately NOT
# persisted: the spill tier is process memory (decode/spill.py), so
# resume restores an engine whose tier is EMPTY and replay re-prefills
# — exactly the v4 stance on device block content. The tree's
# ``spilled`` flags are certificate, not restore input.
SNAPSHOT_VERSION = 9


# ---------------------------------------------------------------- snapshot

def _model_meta(engine: DecodeEngine) -> dict:
    """Model identity the snapshot pins — shared with the KV handoff
    (round 14): ``DecodeEngine.model_meta()`` is the one fingerprint
    both resume-replay and cross-engine sequence import check, so the
    two can never drift apart on what "the same model" means."""
    return engine.model_meta()


def snapshot_state(engine: DecodeEngine) -> dict:
    """The host-side engine state as one JSON-serializable document.
    ``requests`` lists in-flight sequences first (admission order, each
    with its slot / position / block-table view — the observable the
    snapshot certifies, even though resume recomputes the pool) and
    then the waiting queue in queue order, so a restore re-queues them
    in scheduling priority order."""
    requests = []
    running = sorted(
        ((seq.admit_index, slot, seq)
         for slot, seq in enumerate(engine.slots) if seq is not None))
    for _, slot, seq in running:
        requests.append({
            "uid": seq.uid, "prompt": seq.prompt, "out": seq.out,
            "max_new": seq.max_new, "retries": seq.retries,
            "t_submit": seq.t_submit, "submit_step": seq.submit_step,
            "t_first": engine.tracer.first_token_t(seq.uid),
            "weights_version": seq.weights_version,
            "trace_id": seq.trace_id,
            "tenant": seq.tenant,
            "state": "RUNNING", "slot": slot,
            "position": int(engine.lengths[slot]),
            "prefilled": seq.prefilled,
            "block_table": engine.tables[slot].tolist(),
            "blocks": list(seq.blocks),
        })
    for seq in engine.waiting:
        requests.append({
            "uid": seq.uid, "prompt": seq.prompt, "out": seq.out,
            "max_new": seq.max_new, "retries": seq.retries,
            "t_submit": seq.t_submit, "submit_step": seq.submit_step,
            "t_first": engine.tracer.first_token_t(seq.uid),
            "weights_version": seq.weights_version,
            "trace_id": seq.trace_id,
            "tenant": seq.tenant,
            "state": "WAITING",
        })
    snap = {
        "version": SNAPSHOT_VERSION,
        "step": engine.global_step,
        "t": time.time(),
        "config": dataclasses.asdict(engine.cfg),
        "policy": dataclasses.asdict(engine.policy),
        "model": _model_meta(engine),
        "serving_version": engine.serving_version,
        "weights_versions": {str(v): engine.model_meta(v)
                             for v in sorted(engine.weights)},
        "requests": requests,
        "finished": {str(u): t for u, t in engine.finished.items()},
        "failed": {str(u): dict(info)
                   for u, info in engine.failed.items()},
        "prompt_lens": {str(u): n
                        for u, n in engine.prompt_lens.items()},
        "counters": {
            "tokens_generated": engine.tokens_generated,
            "quarantined": engine.quarantined,
            "retried": engine.retried,
            "preempted": engine.preempted,
            "rejected": engine.rejected,
            "expired": engine.expired,
            "block_allocs": engine.block_allocs,
            "block_frees": engine.block_frees,
            "block_scrubs": engine.block_scrubs,
            "drafted_tokens": engine.drafted_tokens,
            "accepted_tokens": engine.accepted_tokens,
            "prefix_hit_blocks": engine.prefix_hit_blocks,
            "prefill_tokens_saved": engine.prefill_tokens_saved,
            "cow_copies": engine.cow_copies,
            "prefix_lookup_blocks": engine.prefix_lookup_blocks,
            "prefill_dispatches": engine.prefill_dispatches,
            "spilled_blocks": engine.spilled_blocks,
            "spill_bytes": engine.spill_bytes,
            "restores": engine.restores,
            "restore_tokens_saved": engine.restore_tokens_saved,
            "restore_stall_s": engine.restore_stall_s,
            "partial_hits": engine.partial_hits,
        },
        "prefix_tree": (None if engine.prefix is None
                        else engine.prefix.snapshot()),
    }
    if engine.pool.k_scale is not None:
        # int8 scales metadata: shape/dtype of the per-block scale
        # arrays the replay rebuilds (values are write-history-derived,
        # so recording the layout is the honest full description)
        snap["int8_scales"] = {
            "shape": list(engine.pool.k_scale.shape),
            "dtype": str(engine.pool.k_scale.dtype),
            "note": "values recomputed bit-identically by replay "
                    "(quantization history == write history)",
        }
    return snap


def snapshot_path(snapshot_dir: str) -> str:
    return os.path.join(snapshot_dir, SNAPSHOT_FILENAME)


def write_snapshot(engine: DecodeEngine, snapshot_dir: str) -> str:
    """Atomic publish through ``runtime/wire.py`` (the one home of the
    tmp + fsync + rename + dir-fsync discipline this module used to
    hand-roll): a SIGKILL between any two instructions leaves either
    the old or the new snapshot, never a torn one. The same call is the
    engine-WORKER snapshot publisher (``decode/worker.py``)."""
    from ..runtime.wire import publish_json
    os.makedirs(snapshot_dir, exist_ok=True)
    return publish_json(snapshot_path(snapshot_dir),
                        snapshot_state(engine))


def load_snapshot(snapshot_dir: str) -> dict | None:
    """The latest engine snapshot, or None when none was ever
    published. A snapshot is only ever replaced atomically, so a
    parse failure is real corruption and raises."""
    path = snapshot_path(snapshot_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        snap = json.load(f)
    if snap.get("version") != SNAPSHOT_VERSION:
        raise ValueError(f"engine snapshot version "
                         f"{snap.get('version')!r} != {SNAPSHOT_VERSION}")
    return snap


def restore_engine_state(engine: DecodeEngine, snap: dict) -> None:
    """Load a snapshot into a FRESH engine: finished/failed maps and
    counters restored, every live request re-queued for replay-resume
    (``DecodeEngine.resume_request``), ``step_base`` set so the global
    step keeps counting from the crash point (chaos schedules and
    request records stay monotonic across the death). The engine must
    have been built with the snapshot's exact config/policy — resuming
    onto a different compiled surface would silently change numerics,
    so a mismatch raises."""
    cfg = dataclasses.asdict(engine.cfg)
    if cfg != snap["config"]:
        diff = {k: (snap["config"].get(k), cfg.get(k))
                for k in set(cfg) | set(snap["config"])
                if snap["config"].get(k) != cfg.get(k)}
        raise ValueError(f"engine config != snapshot config: {diff} "
                         "(snapshot resume requires the identical "
                         "EngineConfig)")
    pol = dataclasses.asdict(engine.policy)
    if pol != snap["policy"]:
        raise ValueError(f"serve policy != snapshot policy: "
                         f"{snap['policy']} vs {pol}")
    # per-version identity (snapshot v6): the engine must HOLD every
    # version the snapshot pins, with the identical fingerprint —
    # resume replays each request through its pinned version's
    # weights, so any missing/mismatched version silently breaks the
    # token-identical contract. A v0-only snapshot degenerates to the
    # old single-model check.
    for ver_s, want in snap["weights_versions"].items():
        ver = int(ver_s)
        if ver not in engine.weights:
            raise ValueError(
                f"engine does not hold weights version {ver} pinned "
                f"by the snapshot (held: {sorted(engine.weights)}) — "
                "load_weights the version before restoring")
        model = engine.model_meta(ver)
        if model != want:
            diff = {k: (want.get(k), model.get(k))
                    for k in set(model) | set(want)
                    if want.get(k) != model.get(k)}
            raise ValueError(
                f"model != snapshot model for weights version {ver}: "
                f"{diff} — resume replays recorded tokens through the "
                "pinned weights, so the identical model (same shape "
                "AND same init) is required for the token-identical "
                "contract")
    engine.set_serving_version(int(snap["serving_version"]))
    engine.step_base = int(snap["step"])
    engine.finished = {int(u): list(t)
                       for u, t in snap["finished"].items()}
    engine.failed = {int(u): dict(info)
                     for u, info in snap["failed"].items()}
    engine.prompt_lens = {int(u): int(n)
                          for u, n in snap["prompt_lens"].items()}
    c = snap["counters"]
    engine.tokens_generated = int(c["tokens_generated"])
    engine.quarantined = int(c["quarantined"])
    engine.retried = int(c["retried"])
    engine.preempted = int(c["preempted"])
    engine.rejected = int(c["rejected"])
    engine.expired = int(c["expired"])
    engine.block_allocs = int(c["block_allocs"])
    engine.block_frees = int(c["block_frees"])
    engine.block_scrubs = int(c["block_scrubs"])
    engine.drafted_tokens = int(c["drafted_tokens"])
    engine.accepted_tokens = int(c["accepted_tokens"])
    engine.prefix_hit_blocks = int(c["prefix_hit_blocks"])
    engine.prefill_tokens_saved = int(c["prefill_tokens_saved"])
    engine.cow_copies = int(c["cow_copies"])
    engine.prefix_lookup_blocks = int(c["prefix_lookup_blocks"])
    engine.prefill_dispatches = int(c["prefill_dispatches"])
    engine.spilled_blocks = int(c["spilled_blocks"])
    engine.spill_bytes = int(c["spill_bytes"])
    engine.restores = int(c["restores"])
    engine.restore_tokens_saved = int(c["restore_tokens_saved"])
    engine.restore_stall_s = float(c["restore_stall_s"])
    engine.partial_hits = int(c["partial_hits"])
    # snap["prefix_tree"] is deliberately NOT loaded: the pool content
    # it indexed died with the process, so a fresh engine's tree starts
    # empty and replay re-inserts as it re-prefills — the persisted
    # tree is the share-graph certificate, not restore input
    for req in snap["requests"]:
        engine.resume_request(req["uid"], req["prompt"], req["max_new"],
                              out=req["out"], retries=req["retries"],
                              t_submit=req.get("t_submit"),
                              submit_step=req.get("submit_step"),
                              t_first=req.get("t_first"),
                              weights_version=req.get("weights_version"),
                              trace=req.get("trace_id"),
                              tenant=req.get("tenant"))
    # auto-uid assignment must clear EVERY restored uid, not just the
    # live ones resume_request walked — a fresh submit colliding with a
    # finished uid would sample in lockstep with its twin and overwrite
    # the finished entry
    for uid in list(engine.finished) + list(engine.failed):
        engine._next_uid = max(engine._next_uid, int(uid) + 1)


# --------------------------------------------------------------- supervisor

def supervise_decode(make_engine, requests=(), *, snapshot_dir: str,
                     chaos=None, watchdog_ms: int = 0, metrics=None,
                     log_every: int = 0, snapshot_every: int = 1,
                     max_restarts: int = 3, backoff_base_s: float = 0.5,
                     backoff_max_s: float = 30.0,
                     backoff_jitter: float = 0.5, backoff_seed: int = 0,
                     log_path: str | None = None) -> DecodeEngine:
    """Drain a decode engine under failure supervision.

    ``make_engine`` is a zero-arg factory for a fresh ``DecodeEngine``
    (a restart needs a clean pool — and a resumed process needs any
    engine at all); ``requests`` is the ``(prompt, max_new)`` list
    submitted on a FRESH start (a resumed run's requests come from the
    snapshot; shed submissions — ``AdmissionError`` — are recorded by
    the engine's own ``rejected`` event and skipped). Returns the
    drained engine: ``engine.finished`` / ``engine.failed`` carry the
    outcome per uid.

    The attempt log (default ``{snapshot_dir}/serve_supervise.jsonl``)
    uses the training supervisor's record shapes — ``attempt_failed``
    rows carry the exception head, backoff and restarts left;
    ``hung_step`` rows the watchdog latch; ``completed`` the final
    verdict — so ``report`` folds both supervisors the same way.
    """
    os.makedirs(snapshot_dir, exist_ok=True)
    if log_path is None:
        log_path = os.path.join(snapshot_dir, "serve_supervise.jsonl")
    rng = random.Random(backoff_seed)
    history: list[BaseException] = []

    def log(record: dict) -> None:
        record.setdefault("t", time.time())
        try:
            with open(log_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            pass  # logging must never take down the supervised run

    attempt = 0
    while True:
        engine = make_engine()
        if metrics is not None:
            engine.metrics = metrics
        snap = load_snapshot(snapshot_dir)
        if snap is not None:
            restore_engine_state(engine, snap)
            if chaos is not None:
                chaos.mark_decode_fired_through(engine.step_base)
            log({"event": "resumed", "attempt": attempt,
                 "step": engine.step_base,
                 "live_requests": len(engine.waiting),
                 "finished": len(engine.finished),
                 "failed": len(engine.failed)})
        else:
            if chaos is not None:
                # a restart with no snapshot replays from step 1: every
                # decode fault must fire again (same alignment as the
                # snapshot path)
                chaos.mark_decode_fired_through(0)
            for req in requests:
                try:
                    engine.submit(*req)
                except AdmissionError:
                    pass        # engine recorded the rejected event
            # publish the step-0 snapshot NOW: a crash before the first
            # per-step snapshot then restores this one instead of
            # resubmitting from scratch (which would re-emit the
            # admission/rejection records and re-shed at the door)
            write_snapshot(engine, snapshot_dir)
            log({"event": "started", "attempt": attempt,
                 "submitted": len(engine.waiting),
                 "shed": engine.rejected})

        dog = None
        hung = 0
        if watchdog_ms > 0:
            from ..runtime import native
            dog = native.Watchdog(watchdog_ms)

        def before_step(local_step: int, _eng=engine) -> None:
            if chaos is None:
                return
            g = _eng.step_base + local_step
            for f in chaos.decode_due(g):
                if f.kind == "hang_step":
                    secs = 0.25 if f.arg is None else float(f.arg)
                    chaos._note(f, sleep_s=secs)
                    time.sleep(secs)
                elif f.kind == "nan_logits":
                    uid = (POISON_ALL if f.arg is None else int(f.arg))
                    chaos._note(f, uid=None if f.arg is None
                                else int(f.arg))
                    _eng.arm_poison(uid)
                elif f.kind == "corrupt_block":
                    chaos._note(f, block=int(f.arg))
                    _eng.corrupt_block(int(f.arg))
                elif f.kind == "corrupt_spill":
                    chaos._note(f, spill_id=int(f.arg),
                                hit=_eng.corrupt_spill(int(f.arg)))
                # kill fires in after_step, behind the snapshot

        def after_step(local_step: int, _eng=engine, _dog=dog) -> None:
            nonlocal hung
            g = _eng.step_base + local_step
            if _dog is not None:
                # latch check BEFORE the kick (the kick clears it)
                if _dog.expired:
                    hung += 1
                    rec = {"event": "hung_step", "step": g,
                           "watchdog_expired": True,
                           "watchdog_ms": watchdog_ms}
                    log(rec)
                    if metrics is not None:
                        metrics.event(rec)
                    # what was the engine doing before it stalled —
                    # the flight recorder is the watchdog's evidence
                    _eng.dump_flight_recorder(f"watchdog step {g}")
                _dog.kick()
            due_kill = (chaos is not None and any(
                f.kind == "kill" for f in chaos.decode_due(g)))
            if due_kill or snapshot_every <= 1 \
                    or g % snapshot_every == 0 \
                    or not (_eng.waiting or _eng.active):
                write_snapshot(_eng, snapshot_dir)
            if due_kill:
                for f in chaos.decode_due(g):
                    if f.kind == "kill":
                        chaos._note(f, snapshot_step=g)
                        log({"event": "chaos_kill", "step": g})
                        # the post-mortem the dead process can't write
                        # later: dump BEFORE the SIGKILL
                        _eng.dump_flight_recorder(f"chaos_kill step {g}")
                        os.kill(os.getpid(), signal.SIGKILL)

        t0 = time.monotonic()
        try:
            engine.run(metrics=metrics, log_every=log_every,
                       before_step=before_step, after_step=after_step)
            log({"event": "completed", "attempt": attempt,
                 "elapsed_s": round(time.monotonic() - t0, 3),
                 "hung_steps": hung,
                 "watchdog_expired": bool(hung),
                 "finished": len(engine.finished),
                 "failed": len(engine.failed)})
            return engine
        except Exception as e:  # noqa: BLE001 — supervisor catches all
            history.append(e)
            record = {"event": "attempt_failed", "rung": "restart",
                      "attempt": attempt, "error": _head(e),
                      "elapsed_s": round(time.monotonic() - t0, 3),
                      "watchdog_expired": bool(hung),
                      "restarts_left": max_restarts - attempt,
                      "backoff_s": None}
            if attempt == max_restarts:
                log(record)
                break
            backoff = backoff_delay(attempt, backoff_base_s,
                                    backoff_max_s, backoff_jitter, rng)
            record["backoff_s"] = round(backoff, 3)
            log(record)
            if backoff > 0:
                time.sleep(backoff)
            attempt += 1
        finally:
            if dog is not None:
                dog.close()
    heads = "; ".join(f"attempt {i}: {_head(e)}"
                      for i, e in enumerate(history))
    raise RuntimeError(
        f"serving failed after {max_restarts} restarts; "
        f"attempt history: [{heads}]") from history[-1]
