"""Radix prefix cache: content-keyed, refcounted shared KV blocks.

The serving reality behind "millions of users" is that most requests
share a long system prompt, so N admissions pay N identical prefills
and N copies of the same KV bytes. The paged pool (``decode/paged.py``)
already indirects every KV read through per-slot int32 block tables, so
sharing is a HOST-side bookkeeping problem: this module is the radix
tree (RadixAttention, Zheng et al. 2023) the scheduler walks at
admission, mapping every cached full block of the prompt straight into
the new slot's table instead of re-prefilling it (PagedAttention's
block indirection is what makes the mapping free, Kwon et al. 2023).

Granularity and the identity argument:

- A node caches exactly ONE full block of ``block_size`` tokens; its
  key is the token path from the root (the radix edge is the block's
  token tuple). A full prompt block's stored bytes are a pure function
  of ``(tokens <= block end, EngineConfig)``: KV rows depend only on
  causally-earlier tokens, chunk boundaries inside a full block are
  position-determined (the engine's greedy power-of-two chunking is
  block-aligned), and an int8 block's requant history is that fixed
  chunk grouping — so a hit block holds BIT-IDENTICAL bytes to what the
  admitting sequence's own prefill would have written, at every
  kv_dtype. That is the whole bit-identity proof: sharing changes which
  physical block a table names, never a byte the gather returns.
- The walk is capped at ``(len(prompt) - 1) // block_size`` blocks so
  at least one prompt token is ALWAYS prefilled — the engine's first
  pick must come from the prefill program (the same program the
  unshared engine used), never a numerically different path.

Refcounts and lifetime:

- ``refs`` counts LIVE sequences whose table names the node's block
  (lock at admission, release on any evict). Because a sequence locks
  its whole matched path, ``child.refs > 0`` implies ``parent.refs >
  0`` — refcounts are monotone non-increasing root-to-leaf, so every
  refs-0 node is eventually reclaimable leaf-by-leaf.
- refs-0 nodes STAY cached (that is the cross-request reuse) until
  pool pressure evicts them: ``evict_lru`` frees least-recently-used
  refs-0 LEAVES (leaf-only keeps every cached path reachable).
- ``poisoned`` marks a node whose block the chaos layer corrupted: it
  is excluded from matching immediately (no new sharer inherits NaN)
  but its bytes are left alone while sharers remain — a poisoned
  sharer must never zero an innocent survivor's prefix. The engine
  scrubs-and-detaches at refs == 0.

The spill tier (``decode/spill.py``, round 19):

- A ``spilled`` node's bytes live in host RAM (``spill_id`` keys the
  tier entry); ``block`` is -1 and the node leaves ``_by_block``, so
  every block-indexed view (eviction, evictable/shared counts,
  ``node_for_block``) sees residents only. The node STAYS in the tree
  and still MATCHES — that is the whole point: a radix hit on a
  spilled edge restores bytes instead of re-prefilling them.
- Demotion picks DEVICE-LEAVES (refs-0 residents whose children are
  all spilled), so a resident node's ancestors are always resident
  and the spilled nodes of any matched path form a SUFFIX — restore
  walks the suffix root-outward with no ordering puzzles.
- Poisoned nodes NEVER spill (the engine detaches-and-scrubs them as
  before): the tier stores only bytes the purity argument certifies.
- Detach in any form forgets the tier entry — the host copy of an
  unreachable edge is garbage, not cache.

Everything here is plain host Python (the device never sees the tree);
the engine owns all pool writes and free-list edits.
"""

from __future__ import annotations

import heapq


class PrefixNode:
    """One cached full block. ``edge`` is the block's token tuple (the
    radix edge from ``parent``), ``block`` the physical pool block id,
    ``refs`` the live-sequence lock count, ``last_use`` the engine step
    of the last lock/insert (the LRU clock)."""

    __slots__ = ("edge", "block", "refs", "last_use", "poisoned",
                 "spilled", "spill_id", "parent", "children")

    def __init__(self, edge, block, parent, step):
        self.edge = edge
        self.block = int(block)
        self.refs = 0
        self.last_use = int(step)
        self.poisoned = False
        self.spilled = False
        self.spill_id: int | None = None
        self.parent = parent
        self.children: dict[tuple[int, ...], PrefixNode] = {}

    def path_tokens(self) -> list[int]:
        """The full token path from the root (tests/snapshots)."""
        toks: list[int] = []
        node = self
        while node.parent is not None:
            toks = list(node.edge) + toks
            node = node.parent
        return toks


class PrefixCache:
    """The host-side radix tree over full prompt blocks."""

    def __init__(self, block_size: int, spill=None):
        self.block_size = int(block_size)
        # host-RAM spill tier (decode/spill.py) or None: when set,
        # pool-pressure demotion spills refs-0 device-leaves into it
        # instead of discarding them, and detach drops their entries.
        self.spill = spill
        # one root per WEIGHTS VERSION (round 17, DESIGN.md section
        # 23): a cached block's bytes are a pure function of (tokens,
        # EngineConfig, WEIGHTS) — under live hot-swap two versions'
        # blocks for the same token path differ byte-for-byte, so a
        # match must never cross versions. Versioned roots partition
        # the tree; the pool-level accounting (_by_block, eviction,
        # refcounts) stays global — a retired version's refs-0 blocks
        # are reclaimed by the same LRU as everything else.
        self.root = PrefixNode((), -1, None, 0)     # version-0 root
        self._roots: dict[int, PrefixNode] = {0: self.root}
        self._by_block: dict[int, PrefixNode] = {}

    def _root(self, version: int) -> PrefixNode:
        root = self._roots.get(int(version))
        if root is None:
            root = PrefixNode((), -1, None, 0)
            self._roots[int(version)] = root
        return root

    # -- introspection --------------------------------------------------

    def __len__(self) -> int:
        return len(self._by_block)

    def nodes(self):
        """Every cached node, preorder per version root (stable for
        snapshots/tests)."""
        out = []
        for version in sorted(self._roots):
            root = self._roots[version]
            stack = [root]
            while stack:
                node = stack.pop()
                if node is not root:
                    out.append(node)
                # reversed-sorted push -> sorted preorder pop
                for edge in sorted(node.children, reverse=True):
                    stack.append(node.children[edge])
        return out

    def evictable_blocks(self) -> int:
        """refs-0 cached blocks — reclaimable capacity the admission
        math adds to the free list (monotone refs make every refs-0
        node reachable leaf-by-leaf)."""
        return sum(1 for n in self._by_block.values() if n.refs == 0)

    def shared_blocks(self) -> int:
        """Blocks named by >= 2 live tables right now — the instantaneous
        sharing the schema-v7 decode record reports."""
        return sum(1 for n in self._by_block.values() if n.refs >= 2)

    def node_for_block(self, block: int) -> PrefixNode | None:
        return self._by_block.get(int(block))

    # -- the radix walk -------------------------------------------------

    def match_cap(self, prompt_len: int) -> int:
        """Max hit blocks for a prompt: every full block EXCEPT the one
        holding the final token — at least one token always prefills,
        so the first pick comes from the same prefill program the
        unshared engine ran."""
        return max(0, (int(prompt_len) - 1) // self.block_size)

    def match(self, prompt, version: int = 0) -> list[PrefixNode]:
        """Longest cached path of full prompt blocks (capped by
        ``match_cap``), root-outward UNDER ``version``'s root — a
        block prefilled by other weights is never a hit. Stops at the
        first miss or poisoned node; does NOT lock — admission locks
        only once the block reservation is certain. SPILLED nodes
        match like residents (restoring host bytes beats a
        re-prefill); by the device-leaf demotion rule they form a
        suffix of the returned path, which the engine restores
        root-outward before locking."""
        blk = self.block_size
        node = self._roots.get(int(version))
        if node is None:
            return []
        out = []
        for i in range(self.match_cap(len(prompt))):
            child = node.children.get(tuple(prompt[i * blk:(i + 1) * blk]))
            if child is None or child.poisoned:
                break
            out.append(child)
            node = child
        return out

    def warm_blocks(self, prompt, version: int = 0) -> int:
        """How many leading full blocks of ``prompt`` this tree holds
        right now under ``version`` — the fleet router's
        prefix-affinity score
        (``decode/fleet.py``). Read-only (no lock, no LRU touch): the
        router probes every engine's tree per admission, and a probe
        must not perturb eviction order or pin anything. In-process the
        router reads the live tree directly — this IS the shadow index,
        with zero mirror drift; a multi-host deployment would mirror
        inserts/evictions over the telemetry stream instead."""
        return len(self.match(prompt, version))

    def partial_match(self, prompt, hits,
                      version: int = 0) -> tuple[PrefixNode, int] | None:
        """Sub-block probe past the full-block walk: among the children
        of the last hit node (the root when ``hits`` is empty), find
        the RESIDENT, non-poisoned edge sharing the longest leading
        run of the remaining prompt tokens. Returns ``(donor, m)`` —
        the borrower CoW-copies the donor block's first ``m`` rows
        into a private block and prefills from row ``m`` — or None
        when no edge shares at least one token. ``m`` is capped at
        ``len(remaining) - 1`` so at least one token ALWAYS prefills
        (the engine's first-pick rule), and is strictly < block_size
        (a full-edge match would have been a full-block hit). Spilled
        donors are skipped: a partial hit never forces a restore —
        the row copy needs device-resident source bytes. Read-only,
        like ``match``."""
        blk = self.block_size
        node = hits[-1] if hits else self._roots.get(int(version))
        if node is None:
            return None
        rest = [int(t) for t in prompt[len(hits) * blk:]]
        best, best_m = None, 0
        for edge, child in node.children.items():
            if child.poisoned or child.spilled:
                continue
            m = 0
            for a, b in zip(edge, rest):
                if a != b:
                    break
                m += 1
            if m > best_m:
                best, best_m = child, m
        best_m = min(best_m, len(rest) - 1)
        if best is None or best_m < 1:
            return None
        return best, best_m

    def lock(self, nodes, step: int) -> None:
        for n in nodes:
            n.refs += 1
            n.last_use = int(step)

    def release(self, node: PrefixNode, step: int) -> None:
        if node.refs <= 0:
            raise RuntimeError(f"release of unlocked prefix block "
                               f"{node.block}")
        node.refs -= 1
        node.last_use = int(step)

    # -- insertion (prefill-complete transfer) --------------------------

    def insert(self, prompt, block_index: int, block: int,
               step: int, version: int = 0) -> PrefixNode | None:
        """Cache prompt block ``block_index`` (just fully prefilled into
        physical ``block``). Returns the node now backing that logical
        block: a NEW node owning ``block`` (caller keeps the block in
        its table, holding one ref), or the EXISTING node when another
        sequence already cached this exact path (late dedup — the
        caller remaps its table onto the cached block and frees its
        duplicate; the bytes are identical by the purity argument).
        Returns None when the parent path is not cached (a parent was
        evicted mid-prefill) — the block simply stays private.
        ``version`` selects the root: an insert under weights version
        v is only ever matchable by version-v admissions."""
        blk = self.block_size
        node = self._root(version)
        for i in range(block_index):
            node = node.children.get(tuple(prompt[i * blk:(i + 1) * blk]))
            if node is None or node.poisoned:
                return None
        edge = tuple(int(t) for t in
                     prompt[block_index * blk:(block_index + 1) * blk])
        if len(edge) != blk:
            raise ValueError(f"block {block_index} of a {len(prompt)}-"
                             f"token prompt is not full (block {blk})")
        child = node.children.get(edge)
        if child is not None:
            return child if not child.poisoned else None
        child = PrefixNode(edge, block, node, step)
        node.children[edge] = child
        self._by_block[child.block] = child
        return child

    # -- eviction / detach ----------------------------------------------

    def evict_lru(self, n_blocks: int, step: int) -> list[int]:
        """Reclaim up to ``n_blocks`` physical blocks from refs-0 cached
        LEAVES, least-recently-used first (pool pressure: cached-free
        capacity converts back to free-list capacity on demand). Leaf-
        only eviction keeps every remaining cached path reachable; a
        parent becomes a leaf once its children are gone, so one call
        drains whole cold paths oldest-outward. ONE scan builds the
        candidate heap and each victim's parent is pushed as it turns
        into an evictable leaf — O(cached + k log cached), not a
        rescan per reclaimed block (this runs inside the admission/CoW
        hot path)."""
        heap = [(n.last_use, n.block, n) for n in self._by_block.values()
                if n.refs == 0 and not n.children]
        heapq.heapify(heap)
        out: list[int] = []
        while heap and len(out) < n_blocks:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            self._detach(victim)
            out.append(victim.block)
            # a real node's edge is a full block (nonempty); version
            # roots carry the empty edge and are never eviction
            # candidates
            if parent.edge and parent.refs == 0 and not parent.children:
                heapq.heappush(heap,
                               (parent.last_use, parent.block, parent))
        return out

    # -- spill tier demotion / promotion (decode/spill.py) --------------

    def spill_victims(self, n_blocks: int, step: int) -> list[PrefixNode]:
        """LRU selection of up to ``n_blocks`` demotion victims: refs-0
        RESIDENT nodes that are device-leaves (every child already
        spilled), least-recently-used first — the same reclamation
        order as ``evict_lru``, but NON-DETACHING: the engine decides
        per victim whether the bytes spill to the host tier
        (``mark_spilled``) or detach-and-scrub (poisoned/corrupted —
        those never spill). Device-leaf-only selection is what keeps
        spilled nodes a SUFFIX of every path: a parent is only
        eligible once all its children are off-device, so a resident
        node's ancestors are resident. As in ``evict_lru``, a parent
        is pushed as its last resident child is picked, so one call
        drains whole cold paths oldest-outward."""
        heap = [(n.last_use, n.block, n) for n in self._by_block.values()
                if n.refs == 0
                and all(c.spilled for c in n.children.values())]
        heapq.heapify(heap)
        picked: list[PrefixNode] = []
        picked_ids: set[int] = set()
        while heap and len(picked) < n_blocks:
            _, _, victim = heapq.heappop(heap)
            picked.append(victim)
            picked_ids.add(id(victim))
            parent = victim.parent
            if (parent.edge and parent.refs == 0
                    and all(c.spilled or id(c) in picked_ids
                            for c in parent.children.values())):
                heapq.heappush(heap,
                               (parent.last_use, parent.block, parent))
        return picked

    def mark_spilled(self, node: PrefixNode, spill_id: int) -> int:
        """Demote: the node's bytes now live in tier entry
        ``spill_id``; its device block (returned, for the free list)
        is no longer backing it. The node leaves every block-indexed
        view but keeps its place in the tree — it still matches."""
        block = node.block
        self._by_block.pop(block, None)
        node.block = -1
        node.spilled = True
        node.spill_id = int(spill_id)
        return block

    def mark_restored(self, node: PrefixNode, block: int,
                      step: int) -> None:
        """Promote: the tier entry's bytes were implanted into device
        ``block``; the node is resident again with a fresh LRU clock
        (a just-restored edge is the warmest thing in the tree)."""
        node.spilled = False
        node.spill_id = None
        node.block = int(block)
        node.last_use = int(step)
        self._by_block[node.block] = node

    # -- internal detach plumbing ---------------------------------------

    def _forget(self, node: PrefixNode) -> None:
        """Drop a detaching node's spill-tier entry: the host copy of
        an unreachable edge is garbage, not cache."""
        if node.spilled and self.spill is not None:
            self.spill.drop(node.spill_id)
        node.spilled = False
        node.spill_id = None

    def _detach(self, node: PrefixNode) -> None:
        del node.parent.children[node.edge]
        self._by_block.pop(node.block, None)
        self._forget(node)
        node.parent = None

    def detach_subtree(self, node: PrefixNode) -> list[int]:
        """Remove ``node`` and every descendant, returning their DEVICE
        block ids (all refs-0 by the monotone-refs invariant — callers
        only detach at refs == 0; spilled descendants hold no device
        block and their tier entries are dropped). Used when a block
        can no longer be trusted (quarantine with no sharers left,
        chaos corruption): descendants stay physically clean but
        become unreachable once the path through ``node`` is gone, so
        they return to the free list with it."""
        if node.refs != 0:
            raise RuntimeError(f"detach of live prefix block "
                               f"{node.block} (refs {node.refs})")
        out: list[int] = []
        stack = [node]
        self._detach(node)
        while stack:
            cur = stack.pop()
            if cur.block >= 0:
                out.append(cur.block)
            self._by_block.pop(cur.block, None)
            if cur is not node:
                self._forget(cur)
            stack.extend(cur.children.values())
            cur.children = {}
        return out

    # -- snapshot (decode/supervise.py, snapshot v4) --------------------

    def snapshot(self) -> list[dict]:
        """JSON-serializable preorder node list. Block CONTENT dies with
        the process — a resumed engine's pool is zeros — so restore
        drops the tree and lets replay rebuild the share graph
        organically (the first replayed sharer re-prefills and
        re-inserts, later ones hit: the ~1-prefill property survives
        the crash). The persisted list is the share graph the snapshot
        certifies; tests pin the rebuild against it."""
        order = self.nodes()
        index = {id(n): i for i, n in enumerate(order)}
        version_of = {id(root): v for v, root in self._roots.items()}

        def _version(n: PrefixNode) -> int:
            while n.parent is not None:
                n = n.parent
            return version_of.get(id(n), 0)

        return [{
            "tokens": list(n.edge),
            "block": n.block,
            "refs": n.refs,
            "last_use": n.last_use,
            "poisoned": n.poisoned,
            # tree SHAPE only: a spilled node's host bytes die with
            # the process (the tier is never persisted) — resume
            # replay re-prefills the edge like any other lost block
            "spilled": n.spilled,
            "version": _version(n),
            "parent": (None if not n.parent.edge
                       else index[id(n.parent)]),
        } for n in order]
