"""Model families. The flagship is the Transformer FFN stack (the reference's
entire model surface); attention/long-context extensions live here too."""

from .ffn_stack import (FFNStackParams, init_ffn_stack, clone_params,
                        params_size_gb)
from .attention import (attention, chunk_attn, gather_paged_kv, mha)
from .moe import MoEStackParams, init_moe_stack
from .moe_transformer import (MoETransformerParams,
                              init_moe_transformer,
                              moe_transformer_fwd_aux)
from .transformer import (TransformerParams, init_transformer,
                          transformer_fwd)
from .lm import (LMParams, init_lm, lm_logits, lm_loss, KVCache,
                 decode_attn, init_cache, decode_step, generate, sample)
from .moe_lm import (MoELMParams, init_moe_lm, moe_lm_loss_aux,
                     moe_lm_logits, moe_generate, moe_sample)

__all__ = ["FFNStackParams", "init_ffn_stack", "clone_params",
           "params_size_gb", "attention", "chunk_attn",
           "gather_paged_kv", "mha",
           "MoEStackParams", "init_moe_stack",
           "MoETransformerParams", "init_moe_transformer",
           "moe_transformer_fwd_aux",
           "TransformerParams", "init_transformer", "transformer_fwd",
           "LMParams", "init_lm", "lm_logits", "lm_loss", "KVCache",
           "decode_attn", "init_cache", "decode_step", "generate",
           "sample",
           "MoELMParams", "init_moe_lm", "moe_lm_loss_aux",
           "moe_lm_logits", "moe_generate", "moe_sample"]
