"""The flagship model family: a stack of Transformer FFN sublayers.

The reference's "model" is a plain list of ``[W1, W2]`` pairs with no module
abstraction (``train_ffns.py:38-39, :361``). Here the same stance is kept —
params are raw arrays in a NamedTuple pytree — but the per-layer lists are
stacked on a leading layer axis so the whole model lives under a single
``NamedSharding`` and can be scanned over.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.linear import init_linear


class FFNStackParams(NamedTuple):
    """``w1 [L, ffn_dim, d_model]``, ``w2 [L, d_model, ffn_dim]``.

    ``w1[l]`` / ``w2[l]`` correspond to the reference's
    ``layers_params[l][0] / [1]`` (``train_ffns.py:38-39``): weights stored
    transposed ``[out, in]``, no biases.
    """
    w1: jax.Array
    w2: jax.Array

    @property
    def n_layers(self) -> int:
        return self.w1.shape[0]

    @property
    def d_model(self) -> int:
        return self.w1.shape[2]

    @property
    def ffn_dim(self) -> int:
        return self.w1.shape[1]

    def num_params(self) -> int:
        return self.w1.size + self.w2.size


def init_ffn_stack(key: jax.Array, d_model: int, n_layers: int,
                   ffn_dim: int | None = None, scale: float = 2e-2,
                   dtype=jnp.float32) -> FFNStackParams:
    """Initialize the stack; ``ffn_dim`` defaults to ``4 * d_model``
    (``train_ffns.py:361``)."""
    ffn_dim = 4 * d_model if ffn_dim is None else ffn_dim
    keys = jax.random.split(key, 2 * n_layers)
    w1 = jnp.stack([init_linear(keys[2 * l], d_model, ffn_dim, scale, dtype)
                    for l in range(n_layers)])
    w2 = jnp.stack([init_linear(keys[2 * l + 1], ffn_dim, d_model, scale, dtype)
                    for l in range(n_layers)])
    return FFNStackParams(w1=w1, w2=w2)


@jax.jit
def _fresh_copy(tree):
    return jax.tree_util.tree_map(jnp.copy, tree)


def clone_params(params: FFNStackParams) -> FFNStackParams:
    """Fresh buffers for a launcher to own (and donate into its step loop)
    without consuming the caller's copy — the reference's
    ``clone_layers_params`` (``train_ffns.py:177-181``), needed because
    ``--method 0`` feeds the same initial params to every strategy.

    Implemented as a jitted copy: jit outputs never alias non-donated
    inputs, whereas ``device_put(..., may_alias=False)`` can still share
    buffers through a replicating reshard on some backends."""
    return _fresh_copy(params)


def reshard_copy(params: FFNStackParams, out_shardings) -> FFNStackParams:
    """Reshard + fresh-copy in one compiled step: the launcher-side param
    layout surgery (``train_ffns.py:265-272, :316-323``) expressed as an
    ``out_shardings`` constraint, with the same non-aliasing guarantee as
    ``clone_params``."""
    return jax.jit(_fresh_copy, out_shardings=out_shardings)(params)


def params_size_gb(params) -> float:
    """fp32 GB for any params container with ``num_params()`` (FFN stack,
    MoE stack), matching the reference's report (``train_ffns.py:363-366``)."""
    return 4 * params.num_params() / (1024 ** 3)
