"""Mixture-of-Experts FFN stack — the expert-parallel model family.

The reference has no MoE (its entire model surface is the dense FFN stack,
``train_ffns.py:38-39``); expert parallelism is a first-class extension of
this framework, in the same no-module-abstraction style: params are raw
stacked arrays in a NamedTuple pytree. Each MoE layer replaces the dense FFN
with ``n_experts`` independent expert FFNs (same ``[ffn, d] / [d, ffn]``
transposed no-bias weights as ``FFNStackParams``) plus a top-1 router.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.linear import init_linear


class MoEStackParams(NamedTuple):
    """``wg [L, E, d]`` router, ``w1 [L, E, ffn, d]``, ``w2 [L, E, d, ffn]``.

    ``w1[l, e] / w2[l, e]`` are expert ``e``'s FFN weights, identical layout
    to the dense stack's ``w1[l] / w2[l]`` — an MoE layer with ``E=1`` and
    its router ignored *is* the dense block.
    """
    wg: jax.Array
    w1: jax.Array
    w2: jax.Array

    @property
    def n_layers(self) -> int:
        return self.w1.shape[0]

    @property
    def n_experts(self) -> int:
        return self.w1.shape[1]

    @property
    def d_model(self) -> int:
        return self.w1.shape[3]

    @property
    def ffn_dim(self) -> int:
        return self.w1.shape[2]

    def num_params(self) -> int:
        return self.wg.size + self.w1.size + self.w2.size


def init_moe_stack(key: jax.Array, d_model: int, n_layers: int,
                   n_experts: int, ffn_dim: int | None = None,
                   scale: float = 2e-2, dtype=jnp.float32) -> MoEStackParams:
    """Initialize the MoE stack; ``ffn_dim`` defaults to ``4 * d_model``
    like the dense stack (``train_ffns.py:361``)."""
    ffn_dim = 4 * d_model if ffn_dim is None else ffn_dim
    kg, k1, k2 = jax.random.split(key, 3)

    def grid(k, m, n):
        keys = jax.random.split(k, n_layers * n_experts)
        w = jnp.stack([init_linear(keys[i], m, n, scale, dtype)
                       for i in range(n_layers * n_experts)])
        return w.reshape(n_layers, n_experts, n, m)

    wg = (scale * jax.random.normal(kg, (n_layers, n_experts, d_model))
          ).astype(dtype)
    return MoEStackParams(wg=wg, w1=grid(k1, d_model, ffn_dim),
                          w2=grid(k2, ffn_dim, d_model))
