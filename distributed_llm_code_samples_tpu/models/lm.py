"""Language model: token/position embeddings + transformer stack + tied head.

The reference trains on mocked data with a mocked upstream gradient — no
tokens, no loss (``train_ffns.py:12, :144-151``). This family completes the
path from token ids to a real scalar objective while keeping the framework's
stance: raw stacked arrays in a NamedTuple (``train_ffns.py:38-39``), no
biases (``:35``), hand-written VJPs for every nonlinear op (blocks:
``models.transformer``; loss: ``ops.xent``) with the linear pieces — the
embedding gather and the tied-head matmul — left to ``jax.vjp``'s exact
transposes (gather <-> scatter-add).

GPT-2 shape conventions: learned positional embeddings, pre-LN blocks, a
final LayerNorm, and the LM head tied to the token embedding
(``logits = h @ wte.T``) so ``wte`` receives gradient from both ends.

Decode (``generate``) is inference-only — a jitted ``lax.scan`` over
positions with a static-shape KV cache updated via
``dynamic_update_slice`` — so it uses plain jnp ops (no VJP rules needed)
and never retraces as the sequence grows: the TPU-native shape discipline
(one compiled program, no per-token recompilation).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.norm import layernorm
from ..ops.xent import xent_loss
from .transformer import (TransformerParams, init_transformer,
                          transformer_fwd)


class LMParams(NamedTuple):
    """``wte [V, d]`` token embedding (tied LM head); ``wpe [T_max, d]``
    learned positions; ``blocks`` the pre-LN transformer stack; ``ln_f [d]``
    the final LayerNorm gain."""
    wte: jax.Array
    wpe: jax.Array
    blocks: TransformerParams
    ln_f: jax.Array

    @property
    def vocab(self) -> int:
        return self.wte.shape[0]

    @property
    def d_model(self) -> int:
        return self.wte.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.wpe.shape[0]

    @property
    def n_layers(self) -> int:
        return self.blocks.n_layers

    def num_params(self) -> int:
        return (self.wte.size + self.wpe.size + self.ln_f.size +
                self.blocks.num_params())

    # The CLI's uniform per-layer report reads ``.w1``/``.w2``
    # (train_ffns.py:370-371 prints layers_params[0]); delegate to the
    # block stack's FFN pair.
    @property
    def w1(self) -> jax.Array:
        return self.blocks.w1

    @property
    def w2(self) -> jax.Array:
        return self.blocks.w2


def init_lm(key: jax.Array, vocab: int, d_model: int, n_layers: int,
            max_seq_len: int, ffn_dim: int | None = None,
            scale: float = 2e-2, dtype=jnp.float32,
            n_heads: int | None = None,
            n_kv_heads: int | None = None) -> LMParams:
    """Same init family as the rest of the framework: ``scale * normal``
    (``train_ffns.py:35-36``), LN gains at 1.

    ``n_kv_heads`` (with ``n_heads``) initializes grouped-query attention
    weights: wk/wv project to ``n_kv_heads * head_dim`` dims, shrinking
    the KV cache by ``n_heads/n_kv_heads`` — the forward/decode paths
    pick up the grouping from the shapes alone."""
    kv_dim = None
    if n_heads is not None and d_model % n_heads:
        raise ValueError(f"d_model={d_model} not divisible by "
                         f"n_heads={n_heads}")
    if n_kv_heads is not None:
        if n_heads is None:
            raise ValueError("n_kv_heads needs n_heads (head_dim = "
                             "d_model / n_heads)")
        if n_kv_heads < 1:
            raise ValueError(f"n_kv_heads must be >= 1, got {n_kv_heads}")
        if n_heads % n_kv_heads:
            raise ValueError(
                f"n_heads={n_heads} not divisible by "
                f"n_kv_heads={n_kv_heads}")
        kv_dim = (d_model // n_heads) * n_kv_heads
    ke, kp, kb = jax.random.split(key, 3)
    return LMParams(
        wte=scale * jax.random.normal(ke, (vocab, d_model), dtype),
        wpe=scale * jax.random.normal(kp, (max_seq_len, d_model), dtype),
        blocks=init_transformer(kb, d_model, n_layers, ffn_dim, scale,
                                dtype, kv_dim=kv_dim),
        ln_f=jnp.ones((d_model,), dtype))


def lm_hidden(params: LMParams, tokens: jax.Array, n_heads: int,
              attn=None) -> jax.Array:
    """Embed + blocks + final LN. ``tokens [B, T]`` int -> ``[B, T, d]``."""
    t = tokens.shape[1]
    x = params.wte[tokens] + params.wpe[:t]
    x = transformer_fwd(params.blocks, x, n_heads, causal=True, attn=attn)
    return layernorm(params.ln_f, x)


def lm_logits(params: LMParams, tokens: jax.Array, n_heads: int,
              attn=None) -> jax.Array:
    """``tokens [B, T]`` -> logits ``[B, T, V]`` via the tied head."""
    h = lm_hidden(params, tokens, n_heads, attn)
    return h @ params.wte.T


def lm_loss(params: LMParams, tokens: jax.Array, targets: jax.Array,
            n_heads: int, attn=None, head=None,
            mixed: bool = False) -> jax.Array:
    """Mean next-token cross-entropy. ``tokens, targets [B, T]`` int.

    ``head`` swaps the tied-head + loss computation: None materializes
    ``[N, V]`` logits and runs the hand-VJP xent (the oracle);
    a callable ``(h [N, d], wte [V, d], targets [N]) -> scalar`` takes
    the trunk output directly — the fused Pallas head
    (``ops.pallas_xent.head_xent`` via ``parallel.lm.resolve_head``)
    never builds the logits at all.

    ``mixed`` is the LM family's bf16 policy (the ``train_single(
    mixed=True)`` stance extended over the transformer trunk): the
    TRUNK — embedding gather, blocks, final LN — runs on a bf16 cast of
    the params with a bf16 residual stream in HBM (half the activation
    traffic; MXU time is unchanged since default-precision f32 matmuls
    are single bf16 passes anyway), while the head + cross-entropy stay
    f32 on the f32 master ``wte``. Params, grads, and the update remain
    f32 end to end — the embedding contribution to ``wte``'s gradient
    arrives through the bf16 cast's transpose (a cast back to f32),
    summing with the head's f32 contribution."""
    if mixed:
        trunk = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16), params)
        h = lm_hidden(trunk, tokens, n_heads, attn)
        h = h.reshape(-1, h.shape[-1]).astype(jnp.float32)
        if head is not None:
            return head(h, params.wte, targets.reshape(-1))
        logits = h @ params.wte.T
        return xent_loss(logits, targets.reshape(-1))
    if head is not None:
        h = lm_hidden(params, tokens, n_heads, attn)
        return head(h.reshape(-1, h.shape[-1]), params.wte,
                    targets.reshape(-1))
    logits = lm_logits(params, tokens, n_heads, attn)
    v = logits.shape[-1]
    return xent_loss(logits.reshape(-1, v), targets.reshape(-1))


# ---------------------------------------------------------------------------
# Decode: static-shape KV cache + greedy generation under one jitted scan.


class KVCache(NamedTuple):
    """Per-layer key/value blocks, ``[L, B, H, T_max, dh]`` each, written
    in place (functionally) at the current position each decode step."""
    k: jax.Array
    v: jax.Array


def init_cache(params: LMParams, batch: int, n_heads: int,
               dtype=None) -> KVCache:
    """Cache sized by the model's KV head count (``wk``'s output dim over
    the head dim) — under GQA that is ``n_kv_heads``, so cache bytes
    shrink by the group factor with no other change."""
    dh = params.d_model // n_heads
    kv_heads = params.blocks.wk.shape[1] // dh
    shape = (params.n_layers, batch, kv_heads, params.max_seq_len, dh)
    dtype = params.wte.dtype if dtype is None else dtype
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def decode_attn(q, ck, cv, lengths):
    """Single-query attention over the cache. ``q [B, H, dh]``,
    ``ck/cv [B, H_kv, T_max, dh]`` with ``H % H_kv == 0`` (GQA groups;
    ``H_kv == H`` is plain MHA); positions ``>= lengths`` are masked
    (the cache beyond the write head is zeros — or, under the decode
    engine's block tables, stale bytes — never probability mass).
    ``lengths`` is the per-sequence live-token count: a scalar for the
    lockstep ``generate`` scan, or ``[B]`` for the decode engine's
    continuously-batched slots, each at its own position."""
    b, h, dh = q.shape
    hkv = ck.shape[1]
    qg = q.reshape(b, hkv, h // hkv, dh)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, ck) / jnp.sqrt(
        jnp.asarray(dh, q.dtype))
    lengths = jnp.asarray(lengths)
    mask = jnp.arange(ck.shape[2]) < lengths[..., None]  # [T] or [B, T]
    if mask.ndim == 2:
        mask = mask[:, None, None, :]                    # -> [B, 1, 1, T]
    s = jnp.where(mask, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bkgt,bktd->bkgd", p, cv).reshape(b, h, dh)


def _decode_attn(q, ck, cv, pos):
    """The lockstep form: every sequence at the same scalar ``pos``."""
    return decode_attn(q, ck, cv, jnp.asarray(pos) + 1)


def cached_attn_step(ln1_l, wq_l, wk_l, wv_l, wo_l, cache_k, cache_v,
                     layer: int, x: jax.Array, pos,
                     use_rope: bool = False):
    """One decode attention sublayer, shared by the dense, MoE, and TP
    decode paths: LN, QKV projection of this path's (possibly
    head-sharded) weights, cache write at ``pos``, single-query attention
    over the cache, output projection. Returns ``(y_proj, cache_k,
    cache_v)`` with the residual add (and, under TP, the psum) left to
    the caller — ``y_proj`` may be a partial sum over sharded heads.
    Head counts (query AND kv — GQA falls out) and head dim come from
    the weight/cache shapes. ``use_rope`` rotates q and the new k by
    ``pos`` before the cache write — the cache then stores rotated keys,
    exactly matching training under ``attn_impl="rope"``."""
    from .attention import rope
    b = x.shape[0]
    dh = cache_k.shape[-1]
    h_loc = wq_l.shape[0] // dh
    kv_loc = wk_l.shape[0] // dh
    a = layernorm(ln1_l, x)
    q = (a @ wq_l.T).reshape(b, h_loc, dh)
    k = (a @ wk_l.T).reshape(b, kv_loc, dh)
    v = (a @ wv_l.T).reshape(b, kv_loc, dh)
    if use_rope:
        p1 = jnp.asarray(pos)[None]
        q = rope(q[:, :, None, :], p1)[:, :, 0, :]
        k = rope(k[:, :, None, :], p1)[:, :, 0, :]
    cache_k = lax.dynamic_update_slice(
        cache_k, k[None, :, :, None, :], (layer, 0, 0, pos, 0))
    cache_v = lax.dynamic_update_slice(
        cache_v, v[None, :, :, None, :], (layer, 0, 0, pos, 0))
    y = _decode_attn(q, cache_k[layer], cache_v[layer], pos)
    return y.reshape(b, h_loc * dh) @ wo_l.T, cache_k, cache_v


def decode_step(params: LMParams, cache: KVCache, token: jax.Array,
                pos: jax.Array, n_heads: int, use_rope: bool = False):
    """One token through the stack at position ``pos`` (traced scalar).

    ``token [B]`` int -> ``(logits [B, V], cache')``. Static shapes
    throughout: the cache is written at ``pos`` via
    ``dynamic_update_slice``, attention masks the unwritten tail.
    """
    p = params.blocks
    if cache.k.shape[-1] * n_heads != params.d_model:
        raise ValueError(
            f"cache head dim {cache.k.shape[-1]} inconsistent with "
            f"n_heads={n_heads} at d_model={params.d_model}")
    x = params.wte[token] + params.wpe[pos]                  # [B, d]
    new_k, new_v = cache.k, cache.v
    for l in range(p.n_layers):
        y, new_k, new_v = cached_attn_step(
            p.ln1[l], p.wq[l], p.wk[l], p.wv[l], p.wo[l],
            new_k, new_v, l, x, pos, use_rope)
        x = x + y
        h = layernorm(p.ln2[l], x)
        x = x + jnp.maximum(h @ p.w1[l].T, 0.0) @ p.w2[l].T
    h = layernorm(params.ln_f, x)
    return h @ params.wte.T, KVCache(new_k, new_v)


def decode_loop(step_fn, cache, prompt: jax.Array, n_new: int,
                max_seq_len: int, pick) -> jax.Array:
    """Shared prefill+generate scan for any cached decoder.
    ``step_fn(cache, token [B], pos) -> (logits [B, V], cache)`` runs one
    token through the stack; ``pick(logits, pos) -> [B]`` chooses the next
    token (argmax for greedy, a categorical draw for sampling). One
    ``lax.scan`` covers prefill and generation: step ``t`` feeds the
    prompt token while ``t < T0`` (teacher-forced prefill filling the
    cache) and the previous pick after — so the compiled program is
    independent of where the prompt ends, and a whole batch decodes in
    one dispatch."""
    b, t0 = prompt.shape
    total = t0 + n_new
    if total > max_seq_len:
        raise ValueError(f"prompt {t0} + n_new {n_new} exceeds "
                         f"max_seq_len {max_seq_len}")
    padded = jnp.concatenate(
        [prompt, jnp.zeros((b, n_new), prompt.dtype)], axis=1)

    def step(carry, pos):
        cache, toks, prev = carry
        token = jnp.where(pos < t0, toks[:, pos], prev)
        logits, cache = step_fn(cache, token, pos)
        nxt = pick(logits, pos).astype(toks.dtype)
        toks = lax.dynamic_update_slice(
            toks, jnp.where(pos + 1 < t0, toks[:, pos + 1], nxt)[:, None],
            (0, pos + 1))
        return (cache, toks, nxt), None

    init = (cache, padded, padded[:, 0])
    (_, toks, _), _ = lax.scan(step, init, jnp.arange(total - 1))
    return toks


def _decode_loop(params: LMParams, prompt: jax.Array, n_new: int,
                 n_heads: int, pick, use_rope: bool = False) -> jax.Array:
    return decode_loop(
        lambda cache, token, pos: decode_step(params, cache, token, pos,
                                              n_heads, use_rope),
        init_cache(params, prompt.shape[0], n_heads), prompt, n_new,
        params.max_seq_len, pick)


def generate(params: LMParams, prompt: jax.Array, n_new: int,
             n_heads: int, *, use_rope: bool = False) -> jax.Array:
    """Greedy decode: ``prompt [B, T0]`` -> ``[B, T0 + n_new]``.
    ``use_rope`` must match how the model was trained
    (``attn_impl="rope"``)."""
    return _decode_loop(params, prompt, n_new, n_heads,
                        lambda z, pos: jnp.argmax(z, axis=-1), use_rope)


def sample_pick(temperature: float, top_k: int, vocab: int, seed: int):
    """Build the stochastic ``pick(logits, pos)`` for ``decode_loop``:
    temperature-scaled, optionally top-k-truncated categorical draws.
    Deterministic given ``seed`` — the per-position key is
    ``fold_in(fold_in(base, seed), pos)``, the same counter-RNG contract
    as the data layer, so a sampled continuation is reproducible without
    any carried RNG state. Shared by the dense and MoE samplers."""
    if temperature <= 0:
        raise ValueError(f"temperature must be > 0, got {temperature} "
                         "(use the greedy decoder — generate/"
                         "moe_generate — for the argmax limit)")
    if top_k < 0 or top_k > vocab:
        raise ValueError(f"top_k={top_k} outside [0, vocab={vocab}]")
    base = jax.random.fold_in(jax.random.PRNGKey(0x5A3), seed)

    def pick(logits, pos):
        z = logits / temperature
        if top_k:
            kth = lax.top_k(z, top_k)[0][:, -1:]
            z = jnp.where(z < kth, -jnp.inf, z)
        return jax.random.categorical(jax.random.fold_in(base, pos), z,
                                      axis=-1)

    return pick


def sample(params: LMParams, prompt: jax.Array, n_new: int, n_heads: int,
           *, temperature: float = 1.0, top_k: int = 0,
           seed: int = 0, use_rope: bool = False) -> jax.Array:
    """Stochastic decode (see ``sample_pick``). ``top_k=0`` samples the
    full distribution; ``top_k=1`` degenerates to greedy."""
    return _decode_loop(params, prompt, n_new, n_heads,
                        sample_pick(temperature, top_k, params.vocab,
                                    seed), use_rope)
