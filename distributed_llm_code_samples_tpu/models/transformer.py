"""Full transformer block stack — pre-LN attention + FFN with residuals.

The reference's model surface is FFN sublayers only (``README.md:6``); this
module completes the transformer block the TPU-first way while keeping the
framework's stance: raw stacked arrays in a NamedTuple (no module
abstraction, ``train_ffns.py:38-39``), no biases (``:35``), every nonlinear
op differentiated by a hand-written ``custom_vjp`` rule (attention:
``models.attention``; FFN: ``ops.ffn``; LayerNorm: ``ops.norm``) with the
linear projections left to ``jax.vjp``'s exact transposes.

Block (pre-LN): ``x += W_o · attn(split_heads(W_q a, W_k a, W_v a))`` with
``a = LN1(x)``, then ``x += FFN(LN2(x))``. Sequence structure matters here
(unlike the FFN stack, where seq folds into batch, ``train_ffns.py:379``):
activations are ``[B, T, d]`` and attention runs per batch element over
``n_heads`` heads.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.linear import init_linear
from ..ops.ffn import ffn_block
from ..ops.norm import layernorm
from .attention import gqa, mha


class TransformerParams(NamedTuple):
    """Stacked per-layer weights, all ``[out, in]`` transposed, no biases.

    ``ln1, ln2 [L, d]`` gains; ``wq, wk, wv, wo [L, d, d]``;
    ``w1 [L, ffn, d]``, ``w2 [L, d, ffn]`` (the FFN pair is laid out
    exactly like ``FFNStackParams`` — the dense stack embeds in this model).
    """
    ln1: jax.Array
    wq: jax.Array
    wk: jax.Array
    wv: jax.Array
    wo: jax.Array
    ln2: jax.Array
    w1: jax.Array
    w2: jax.Array

    @property
    def n_layers(self) -> int:
        return self.w1.shape[0]

    @property
    def d_model(self) -> int:
        return self.w1.shape[2]

    def num_params(self) -> int:
        return sum(l.size for l in self)


def init_transformer(key: jax.Array, d_model: int, n_layers: int,
                     ffn_dim: int | None = None, scale: float = 2e-2,
                     dtype=jnp.float32,
                     kv_dim: int | None = None) -> TransformerParams:
    """Init all stacks; ``ffn_dim`` defaults to ``4 * d_model``. LN gains
    start at 1. ``kv_dim`` (default ``d_model``) sets the wk/wv output
    dim — pass ``n_kv_heads * head_dim`` for grouped-query attention."""
    ffn_dim = 4 * d_model if ffn_dim is None else ffn_dim
    kv_dim = d_model if kv_dim is None else kv_dim
    keys = jax.random.split(key, 6 * n_layers)

    def stack(off, m, n):
        return jnp.stack([init_linear(keys[6 * l + off], m, n, scale, dtype)
                          for l in range(n_layers)])

    ones = jnp.ones((n_layers, d_model), dtype)
    return TransformerParams(
        ln1=ones, wq=stack(0, d_model, d_model), wk=stack(1, d_model, kv_dim),
        wv=stack(2, d_model, kv_dim), wo=stack(3, d_model, d_model),
        ln2=ones, w1=stack(4, d_model, ffn_dim), w2=stack(5, ffn_dim, d_model))


def split_heads(t: jax.Array, n_heads: int) -> jax.Array:
    """``[B, T, d] -> [B, H, T, d/H]``."""
    b, s, d = t.shape
    return t.reshape(b, s, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def merge_heads(t: jax.Array) -> jax.Array:
    """``[B, H, T, dh] -> [B, T, H*dh]``."""
    b, h, s, dh = t.shape
    return t.transpose(0, 2, 1, 3).reshape(b, s, h * dh)


def attn_sublayer(wq, wk, wv, wo, a: jax.Array, n_heads: int,
                  causal: bool = True, attn=None) -> jax.Array:
    """Projections + multi-head hand-VJP attention. ``a [B, T, d]``;
    weights ``[d_out, d]`` (``d_out`` may be a head-sharded slice under
    TP — heads live on the leading output dim).

    Grouped-query attention falls out of the shapes: the KV head count
    is ``wk``'s output dim over the head dim (``wq``'s output dim over
    ``n_heads``), so models initialized with a smaller ``kv_dim``
    (``init_transformer``/``init_lm``) run GQA with no flag — ``mha``
    when the counts match, the grouped kernel otherwise.

    ``attn`` swaps the per-batch multi-head attention op
    (``(q, k, v, causal) -> y`` on ``[H, T, dh]``); None uses the
    quadratic hand-VJP oracles (``mha``/``gqa``), trainers pass the fused
    Pallas ``flash_mha`` via ``attn_impl="flash"`` (GQA shapes via its
    repeat-KV fan-out)."""
    dh = wq.shape[0] // n_heads
    n_kv = wk.shape[0] // dh
    q = split_heads(a @ wq.T, n_heads)
    k = split_heads(a @ wk.T, n_kv)
    v = split_heads(a @ wv.T, n_kv)
    if attn is None:
        op = mha if n_kv == n_heads else gqa
    elif n_kv != n_heads and not getattr(attn, "supports_gqa", False):
        raise ValueError("this attn impl expects full-MHA shapes; "
                         f"got {n_heads} query vs {n_kv} kv heads")
    else:
        op = attn
    y = jax.vmap(lambda q, k, v: op(q, k, v, causal))(q, k, v)
    return merge_heads(y) @ wo.T


def transformer_block(ln1, wq, wk, wv, wo, ln2, w1, w2, x: jax.Array,
                      n_heads: int, causal: bool = True,
                      attn=None) -> jax.Array:
    """One pre-LN block. ``x [B, T, d]`` -> ``[B, T, d]``."""
    b, s, d = x.shape
    x = x + attn_sublayer(wq, wk, wv, wo, layernorm(ln1, x), n_heads,
                          causal, attn)
    f = layernorm(ln2, x).reshape(b * s, d)
    return x + ffn_block(w1, w2, f).reshape(b, s, d)


def transformer_fwd(params: TransformerParams, x: jax.Array, n_heads: int,
                    causal: bool = True, attn=None) -> jax.Array:
    """Stack forward. ``x [B, T, d]``."""
    for l in range(params.n_layers):
        x = transformer_block(params.ln1[l], params.wq[l], params.wk[l],
                              params.wv[l], params.wo[l], params.ln2[l],
                              params.w1[l], params.w2[l], x, n_heads,
                              causal, attn)
    return x
