"""MoE transformer — pre-LN attention blocks with Mixture-of-Experts FFNs.

The GShard/Switch architecture, built from this framework's existing
pieces in the same no-module-abstraction style: ``attn_sublayer`` (hand-
VJP attention + projections, ``models.transformer``) for the first
sublayer, ``ops.moe.moe_layer`` (top-k router, capacity dispatch,
per-expert hand-VJP FFN) for the second. The reference has neither
attention nor MoE (``README.md:6``); this family exists so expert
parallelism composes with a real sequence model, not just the flat MoE
stack — the trainers in ``parallel/moe_transformer.py`` run attention
data-parallel and the FFN expert-parallel over one mesh axis, exactly
GShard's layout.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.linear import init_linear
from ..ops.moe import moe_layer, router_aux_loss
from ..ops.norm import layernorm
from .transformer import attn_sublayer


class MoETransformerParams(NamedTuple):
    """Per-layer stacks: ``ln1, ln2 [L, d]``; ``wq/wk/wv/wo [L, d, d]``;
    ``wg [L, E, d]`` router; ``w1 [L, E, ffn, d]``, ``w2 [L, E, d, ffn]``
    expert FFNs (``MoEStackParams`` layout inside ``TransformerParams``
    structure)."""
    ln1: jax.Array
    wq: jax.Array
    wk: jax.Array
    wv: jax.Array
    wo: jax.Array
    ln2: jax.Array
    wg: jax.Array
    w1: jax.Array
    w2: jax.Array

    @property
    def n_layers(self) -> int:
        return self.w1.shape[0]

    @property
    def n_experts(self) -> int:
        return self.w1.shape[1]

    @property
    def d_model(self) -> int:
        return self.w1.shape[3]

    def num_params(self) -> int:
        return sum(l.size for l in self)


def init_moe_transformer(key: jax.Array, d_model: int, n_layers: int,
                         n_experts: int, ffn_dim: int | None = None,
                         scale: float = 2e-2,
                         dtype=jnp.float32) -> MoETransformerParams:
    ffn_dim = 4 * d_model if ffn_dim is None else ffn_dim
    keys = jax.random.split(key, 7 * n_layers)

    def stack(off, m, n):
        return jnp.stack([init_linear(keys[7 * l + off], m, n, scale,
                                      dtype) for l in range(n_layers)])

    def estack(off, m, n):
        return jnp.stack([
            jnp.stack([init_linear(
                jax.random.fold_in(keys[7 * l + off], e), m, n, scale,
                dtype) for e in range(n_experts)])
            for l in range(n_layers)])

    ones = jnp.ones((n_layers, d_model), dtype)
    kg = jax.random.fold_in(key, 7 * n_layers)
    wg = (scale * jax.random.normal(kg, (n_layers, n_experts, d_model))
          ).astype(dtype)
    return MoETransformerParams(
        ln1=ones, wq=stack(0, d_model, d_model),
        wk=stack(1, d_model, d_model), wv=stack(2, d_model, d_model),
        wo=stack(3, d_model, d_model), ln2=ones, wg=wg,
        w1=estack(5, d_model, ffn_dim), w2=estack(6, ffn_dim, d_model))


def moe_transformer_fwd_aux(params: MoETransformerParams, x: jax.Array,
                            n_heads: int, causal: bool = True,
                            capacity_factor: float | None = None,
                            k: int | None = None,
                            capacity: int | None = None,
                            moe_fn=None, attn=None):
    """Stack forward. ``x [B, T, d]``. Returns ``(y, aux)`` with ``aux``
    the summed load-balancing loss over layers (one walk computes both,
    the ``ops.moe.moe_stack_fwd_aux`` convention). ``moe_fn`` swaps the
    MoE sublayer core (the EP trainer passes its all_to_all form); the
    default is the dense ``ops.moe.moe_layer``."""
    if moe_fn is not None and (capacity is not None or k is not None
                               or capacity_factor is not None):
        raise ValueError("moe_fn supplies its own routing/dispatch; the "
                         "explicit capacity_factor/k/capacity arguments "
                         "would be silently ignored — configure them on "
                         "the moe_fn itself")
    capacity_factor = 2.0 if capacity_factor is None else capacity_factor
    k = 1 if k is None else k
    b, t, d = x.shape
    aux = jnp.asarray(0.0, jnp.float32)
    for l in range(params.n_layers):
        x = x + attn_sublayer(params.wq[l], params.wk[l], params.wv[l],
                              params.wo[l], layernorm(params.ln1[l], x),
                              n_heads, causal, attn)
        h = layernorm(params.ln2[l], x).reshape(b * t, d)
        aux = aux + router_aux_loss(params.wg[l], h)
        if moe_fn is None:
            y = moe_layer(params.wg[l], params.w1[l], params.w2[l], h,
                          capacity_factor, k, capacity)
        else:
            y = moe_fn(params.wg[l], params.w1[l], params.w2[l], h)
        x = x + y.reshape(b, t, d)
    return x, aux
