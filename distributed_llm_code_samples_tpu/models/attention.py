"""Hand-written scaled-dot-product attention (single-device oracle).

The reference has **no attention at all** — FFN sublayers only
(``README.md:6``; SURVEY.md section 5 "long-context: absent"). Long-context
support is a first-class extension of this framework, so the model family
grows an attention op built in the same first-principles style as the FFN
core: forward written out, backward derived by hand and installed as the
``custom_vjp`` rule.

Shapes are single-head ``[T, d]``; multi-head is ``jax.vmap`` over a heads
axis (kept out of the op to keep the math readable). The distributed
sequence-parallel form (ring attention over ``ppermute``) lives in
``parallel.sequence``; this module is its correctness oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def causal_mask(Tq: int, Tk: int, q_offset: int = 0, k_offset: int = 0):
    """True where query position may attend key position (q_pos >= k_pos).

    Offsets give the *global* positions of the local blocks — the thing a
    sequence-sharded ring step needs (``parallel.sequence``)."""
    q_pos = q_offset + jnp.arange(Tq)[:, None]
    k_pos = k_offset + jnp.arange(Tk)[None, :]
    return q_pos >= k_pos


def attn_fwd(q: jax.Array, k: jax.Array, v: jax.Array,
             causal: bool = True):
    """Softmax attention forward; returns ``(y, (p,))`` with the probability
    matrix saved for the manual backward."""
    d = q.shape[-1]
    s = (q @ k.T) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if causal:
        s = jnp.where(causal_mask(q.shape[0], k.shape[0]), s,
                      jnp.asarray(-jnp.inf, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    return p @ v, (p,)


def attn_bwd(dy: jax.Array, q, k, v, p, causal: bool = True):
    """Manual attention VJP.

    With ``y = p v``, ``p = softmax(s)``, ``s = q k^T / sqrt(d)``:
    ``dv = p^T dy``; ``dp = dy v^T``;
    ``ds = p * (dp - rowsum(dp * p))`` (softmax VJP);
    ``dq = ds k / sqrt(d)``; ``dk = ds^T q / sqrt(d)``.
    """
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    dv = p.T @ dy
    dp = dy @ v.T
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = (ds @ k) * scale
    dk = (ds.T @ q) * scale
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True) -> jax.Array:
    """Attention whose differentiation rule is the hand-written VJP.

    ``causal`` is a static (nondiff) argument: it selects the mask at trace
    time, so the op works identically in eager code and under jit/shard_map
    (as an operand it would be traced and break the Python branch)."""
    y, _ = attn_fwd(q, k, v, causal)
    return y


def _attention_fwd(q, k, v, causal):
    y, (p,) = attn_fwd(q, k, v, causal)
    return y, (q, k, v, p)


def _attention_bwd(causal, res, dy):
    q, k, v, p = res
    dq, dk, dv = attn_bwd(dy, q, k, v, p, causal)
    return dq, dk, dv


attention.defvjp(_attention_fwd, _attention_bwd)


def mha(q: jax.Array, k: jax.Array, v: jax.Array,
        causal: bool = True) -> jax.Array:
    """Multi-head convenience: vmap ``attention`` over a leading heads axis
    (``[H, T, d] -> [H, T, d]``)."""
    return jax.vmap(lambda q, k, v: attention(q, k, v, causal))(q, k, v)


def rope(x: jax.Array, positions: jax.Array,
         base: float = 10000.0) -> jax.Array:
    """Rotary position embedding (Su et al.): rotate each head-dim pair
    ``(x_i, x_{i+dh/2})`` by ``pos * base^(-2i/dh)`` — attention scores
    then depend only on *relative* position. ``x [..., T, dh]`` (``dh``
    even), ``positions [T]`` (absolute indices; decode passes the single
    write position). Linear in ``x``, so ``jax.vjp``'s exact transpose
    (the inverse rotation) differentiates it — the framework's stance for
    linear ops."""
    dh = x.shape[-1]
    if dh % 2:
        raise ValueError(f"rope needs an even head dim, got {dh}")
    half = dh // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions[..., :, None].astype(jnp.float32) * freqs   # [T, half]
    cos = jnp.cos(ang).astype(x.dtype)
    sin = jnp.sin(ang).astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def rope_mha(q: jax.Array, k: jax.Array, v: jax.Array,
             causal: bool = True) -> jax.Array:
    """Multi-head attention with rotary positions: rotates q and k by
    their in-window indices (``0..T-1``) before the hand-VJP kernel.
    Plugs into the trainers' ``attn`` hook (``attn_impl="rope"``); GQA
    shapes (fewer k heads) compose — the rotation is per-head-pair.

    Note: the relative-position property holds for this op; the LM
    family still adds its learned absolute embeddings (``wpe``) to the
    residual stream, so a rope-trained LM is rotary-IN-ATTENTION layered
    on learned positions, not relative-only."""
    t = q.shape[-2]
    pos = jnp.arange(t)
    op = mha if q.shape[0] == k.shape[0] else gqa
    return op(rope(q, pos), rope(k, pos), v, causal)


rope_mha.supports_gqa = True  # handles fewer k heads (see attn_sublayer)


# ---------------------------------------------------------------------------
# Paged-KV reads (the decode engine's block-table layout, decode/paged.py):
# the cache lives as a pool of fixed-size blocks and each sequence names
# its blocks through an int32 table — the KV read is a gather, so
# sequences of different lengths share one static-shape pool and freeing
# a sequence is a table edit, never a recompile.


def gather_paged_kv(pool_k: jax.Array, pool_v: jax.Array,
                    table: jax.Array):
    """Materialize one sequence's contiguous KV view from the block pool.

    ``pool_k/pool_v [n_blocks, H_kv, block, dh]`` (one layer's pool),
    ``table [max_blocks]`` int32 physical block ids, in sequence order.
    Returns ``(k, v)`` each ``[H_kv, max_blocks * block, dh]`` — exactly
    the contiguous cache layout ``_decode_attn`` reads, so downstream
    attention is bit-identical to a contiguous cache holding the same
    values (the gather only moves bytes). Positions beyond the sequence
    length read whatever the table's tail blocks hold (the engine points
    unassigned table slots at the reserved scratch block); callers mask
    them, as with the zero tail of a contiguous cache.

    This gather + ``decode_attn`` two-pass is the decode engine's
    DIFFERENTIAL ORACLE for the fused Pallas block-walk kernel
    (``ops/pallas_paged_attention.py``, ``EngineConfig(kernel=``): the
    kernel streams the same blocks through VMEM without ever
    materializing this layout in HBM, and must match this path
    bit-for-bit at f32 under jit (tests/test_pallas_paged_attention.py
    pins it)."""
    k = pool_k[table]                      # [MB, H_kv, block, dh]
    v = pool_v[table]
    mb, hkv, blk, dh = k.shape
    k = k.transpose(1, 0, 2, 3).reshape(hkv, mb * blk, dh)
    v = v.transpose(1, 0, 2, 3).reshape(hkv, mb * blk, dh)
    return k, v


def chunk_attn(q: jax.Array, ck: jax.Array, cv: jax.Array,
               q_offset) -> jax.Array:
    """Prefill-chunk attention of ``Tq`` queries against a (gathered)
    cache that already holds the chunk's own keys: ``q [H, Tq, dh]``,
    ``ck/cv [H_kv, T_cap, dh]`` with ``H % H_kv == 0`` (GQA groups).
    The mask is the global causal rule via ``causal_mask(Tq, T_cap,
    q_offset)`` — query ``i`` (global position ``q_offset + i``) sees
    cache positions ``<= q_offset + i``, which also hides every
    not-yet-written pool position. ``q_offset`` may be a traced scalar
    (the chunked-prefill loop passes the running write head)."""
    h, tq, dh = q.shape
    hkv, tcap, _ = ck.shape
    if h % hkv:
        raise ValueError(f"query heads {h} not divisible by kv heads "
                         f"{hkv}")
    qg = q.reshape(hkv, h // hkv, tq, dh)
    s = jnp.einsum("kgqd,ktd->kgqt", qg, ck) / jnp.sqrt(
        jnp.asarray(dh, q.dtype))
    mask = causal_mask(tq, tcap, q_offset=q_offset)
    s = jnp.where(mask, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("kgqt,ktd->kgqd", p, cv).reshape(h, tq, dh)


def gqa(q: jax.Array, k: jax.Array, v: jax.Array,
        causal: bool = True) -> jax.Array:
    """Grouped-query attention: ``q [H, T, dh]``, ``k/v [H_kv, T, dh]``
    with ``H % H_kv == 0`` — each KV head serves ``H/H_kv`` query heads
    (the decode-memory optimization: KV-cache bytes drop by the group
    factor). Runs the same hand-VJP ``attention`` kernel per (kv-head,
    group) pair; ``H_kv == H`` reduces exactly to ``mha``."""
    hq, hkv = q.shape[0], k.shape[0]
    if hq % hkv:
        raise ValueError(f"query heads {hq} not divisible by kv heads "
                         f"{hkv}")
    qg = q.reshape(hkv, hq // hkv, *q.shape[1:])
    y = jax.vmap(lambda qs, k1, v1: jax.vmap(
        lambda q1: attention(q1, k1, v1, causal))(qs))(qg, k, v)
    return y.reshape(hq, *q.shape[1:])
