"""MoE language model — GShard blocks under a real LM objective.

Composes the two newest families: ``models.lm``'s embedding / tied-head /
hand-VJP cross-entropy shell around ``models.moe_transformer``'s pre-LN
attention + Mixture-of-Experts FFN blocks. The reference has none of
these pieces (``README.md:6``); this family exists so expert parallelism
composes with the *real* training objective — router, capacity dispatch,
load-balancing auxiliary loss and all — not just the mocked upstream
gradient.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.norm import layernorm
from ..ops.xent import xent_loss
from .moe_transformer import (MoETransformerParams, init_moe_transformer,
                              moe_transformer_fwd_aux)


class MoELMParams(NamedTuple):
    """``wte [V, d]`` tied token embedding; ``wpe [T_max, d]`` positions;
    ``blocks`` the MoE-transformer stack; ``ln_f [d]`` final LN gain."""
    wte: jax.Array
    wpe: jax.Array
    blocks: MoETransformerParams
    ln_f: jax.Array

    @property
    def vocab(self) -> int:
        return self.wte.shape[0]

    @property
    def d_model(self) -> int:
        return self.wte.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.wpe.shape[0]

    @property
    def n_experts(self) -> int:
        return self.blocks.n_experts

    @property
    def n_layers(self) -> int:
        return self.blocks.n_layers

    def num_params(self) -> int:
        return (self.wte.size + self.wpe.size + self.ln_f.size +
                self.blocks.num_params())

    # CLI uniform per-layer report (train_ffns.py:370-371): the expert
    # FFN pair, like the MoE families
    @property
    def w1(self) -> jax.Array:
        return self.blocks.w1

    @property
    def w2(self) -> jax.Array:
        return self.blocks.w2


def init_moe_lm(key: jax.Array, vocab: int, d_model: int, n_layers: int,
                n_experts: int, max_seq_len: int,
                ffn_dim: int | None = None, scale: float = 2e-2,
                dtype=jnp.float32) -> MoELMParams:
    ke, kp, kb = jax.random.split(key, 3)
    return MoELMParams(
        wte=scale * jax.random.normal(ke, (vocab, d_model), dtype),
        wpe=scale * jax.random.normal(kp, (max_seq_len, d_model), dtype),
        blocks=init_moe_transformer(kb, d_model, n_layers, n_experts,
                                    ffn_dim, scale, dtype),
        ln_f=jnp.ones((d_model,), dtype))


def moe_lm_hidden_aux(params: MoELMParams, tokens: jax.Array,
                      n_heads: int, causal: bool = True,
                      capacity_factor: float | None = None,
                      k: int | None = None, capacity: int | None = None,
                      moe_fn=None, attn=None):
    """Embed + MoE blocks + final LN: ``tokens [B, T]`` ->
    ``(h [B, T, d], aux)`` — the shared forward under both the logits
    and the loss (the ``lm_hidden`` convention)."""
    t = tokens.shape[1]
    x = params.wte[tokens] + params.wpe[:t]
    x, aux = moe_transformer_fwd_aux(params.blocks, x, n_heads, causal,
                                     capacity_factor, k, capacity,
                                     moe_fn, attn)
    return layernorm(params.ln_f, x), aux


def moe_lm_logits(params: MoELMParams, tokens: jax.Array, n_heads: int,
                  causal: bool = True,
                  capacity_factor: float | None = None,
                  k: int | None = None,
                  capacity: int | None = None, attn=None) -> jax.Array:
    """``tokens [B, T]`` -> logits ``[B, T, V]`` (teacher-forced full
    forward through the MoE stack; the decode oracle). ``attn`` swaps
    the attention op (e.g. ``rope_mha``)."""
    h, _ = moe_lm_hidden_aux(params, tokens, n_heads, causal,
                             capacity_factor, k, capacity, attn=attn)
    return h @ params.wte.T


def moe_lm_loss_aux(params: MoELMParams, tokens: jax.Array,
                    targets: jax.Array, n_heads: int, causal: bool = True,
                    capacity_factor: float | None = None,
                    k: int | None = None, capacity: int | None = None,
                    moe_fn=None, attn=None, head=None):
    """Mean next-token cross-entropy + the stack's summed router aux loss.
    ``tokens, targets [B, T]`` int. ``moe_fn`` swaps the MoE sublayer
    core (the EP trainer passes its all_to_all form); see
    ``moe_transformer_fwd_aux``. ``head`` swaps the tied-head + xent
    computation for the fused Pallas kernels (``models.lm.lm_loss``
    contract)."""
    h, aux = moe_lm_hidden_aux(params, tokens, n_heads, causal,
                               capacity_factor, k, capacity, moe_fn, attn)
    if head is not None:
        return head(h.reshape(-1, h.shape[-1]), params.wte,
                    targets.reshape(-1)), aux
    logits = h @ params.wte.T
    loss = xent_loss(logits.reshape(-1, params.wte.shape[0]),
                     targets.reshape(-1))
    return loss, aux


# ---------------------------------------------------------------------------
# Decode: per-token top-k routing over the KV-cache loop. Capacity is a
# training-time batching artifact (tokens competing for expert slots);
# at decode each position routes independently, so with enough capacity
# the teacher-forced full forward and the cached decode agree exactly
# (pinned in tests/test_moe_lm.py).


def moe_decode_step(params: MoELMParams, cache, token: jax.Array,
                    pos, n_heads: int, k: int = 1,
                    use_rope: bool = False):
    """One token through the MoE stack at ``pos``. ``token [B]`` ->
    ``(logits [B, V], cache')``. Expert weights for each token's top-k
    choices are gathered (``[B, k, ffn, d]``) and the gate-weighted FFNs
    computed directly — no dispatch tensor at batch-of-one-position
    scale."""
    from ..ops.moe import route_topk
    from .lm import KVCache, cached_attn_step
    blk = params.blocks
    if cache.k.shape[-1] * n_heads != params.d_model:
        raise ValueError(
            f"cache head dim {cache.k.shape[-1]} inconsistent with "
            f"n_heads={n_heads} at d_model={params.d_model}")
    x = params.wte[token] + params.wpe[pos]
    new_k, new_v = cache.k, cache.v
    for l in range(blk.n_layers):
        y, new_k, new_v = cached_attn_step(
            blk.ln1[l], blk.wq[l], blk.wk[l], blk.wv[l], blk.wo[l],
            new_k, new_v, l, x, pos, use_rope)
        x = x + y
        h = layernorm(blk.ln2[l], x)
        # per-token routing, the training router's exact semantics
        # (k=1: raw top-1 probability gate; k>1: renormalized top-k)
        idx, gates = route_topk(blk.wg[l], h, k, renormalize=k > 1)
        w1s = blk.w1[l][idx]                       # [B, k, ffn, d]
        w2s = blk.w2[l][idx]                       # [B, k, d, ffn]
        ff = jnp.maximum(jnp.einsum("bd,bkfd->bkf", h, w1s), 0.0)
        y = jnp.einsum("bkf,bkdf->bkd", ff, w2s)
        x = x + jnp.einsum("bk,bkd->bd", gates, y)
    h = layernorm(params.ln_f, x)
    return h @ params.wte.T, KVCache(new_k, new_v)


def _moe_decode(params: MoELMParams, prompt, n_new: int, n_heads: int,
                k: int, pick, use_rope: bool = False):
    from .lm import decode_loop, init_cache
    cache = init_cache(params, prompt.shape[0], n_heads)
    return decode_loop(
        lambda cache, token, pos: moe_decode_step(params, cache, token,
                                                  pos, n_heads, k,
                                                  use_rope),
        cache, prompt, n_new, params.max_seq_len, pick)


def moe_generate(params: MoELMParams, prompt: jax.Array, n_new: int,
                 n_heads: int, k: int = 1, *,
                 use_rope: bool = False) -> jax.Array:
    """Greedy decode through the MoE stack: ``prompt [B, T0]`` ->
    ``[B, T0 + n_new]`` (one jitted scan, static shapes — the
    ``models.lm.decode_loop`` contract). ``use_rope`` must match the
    training ``attn_impl``."""
    return _moe_decode(params, prompt, n_new, n_heads, k,
                       lambda z, pos: jnp.argmax(z, axis=-1), use_rope)


def moe_sample(params: MoELMParams, prompt: jax.Array, n_new: int,
               n_heads: int, k: int = 1, *, temperature: float = 1.0,
               top_k: int = 0, seed: int = 0,
               use_rope: bool = False) -> jax.Array:
    """Stochastic decode through the MoE stack — the dense sampler's
    exact contract (``models.lm.sample_pick``) over the routed stack."""
    from .lm import sample_pick
    return _moe_decode(params, prompt, n_new, n_heads, k,
                       sample_pick(temperature, top_k, params.vocab,
                                   seed), use_rope)
