"""MoE language model — GShard blocks under a real LM objective.

Composes the two newest families: ``models.lm``'s embedding / tied-head /
hand-VJP cross-entropy shell around ``models.moe_transformer``'s pre-LN
attention + Mixture-of-Experts FFN blocks. The reference has none of
these pieces (``README.md:6``); this family exists so expert parallelism
composes with the *real* training objective — router, capacity dispatch,
load-balancing auxiliary loss and all — not just the mocked upstream
gradient.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.norm import layernorm
from ..ops.xent import xent_loss
from .moe_transformer import (MoETransformerParams, init_moe_transformer,
                              moe_transformer_fwd_aux)


class MoELMParams(NamedTuple):
    """``wte [V, d]`` tied token embedding; ``wpe [T_max, d]`` positions;
    ``blocks`` the MoE-transformer stack; ``ln_f [d]`` final LN gain."""
    wte: jax.Array
    wpe: jax.Array
    blocks: MoETransformerParams
    ln_f: jax.Array

    @property
    def vocab(self) -> int:
        return self.wte.shape[0]

    @property
    def d_model(self) -> int:
        return self.wte.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.wpe.shape[0]

    @property
    def n_experts(self) -> int:
        return self.blocks.n_experts

    def num_params(self) -> int:
        return (self.wte.size + self.wpe.size + self.ln_f.size +
                self.blocks.num_params())

    # CLI uniform per-layer report (train_ffns.py:370-371): the expert
    # FFN pair, like the MoE families
    @property
    def w1(self) -> jax.Array:
        return self.blocks.w1

    @property
    def w2(self) -> jax.Array:
        return self.blocks.w2


def init_moe_lm(key: jax.Array, vocab: int, d_model: int, n_layers: int,
                n_experts: int, max_seq_len: int,
                ffn_dim: int | None = None, scale: float = 2e-2,
                dtype=jnp.float32) -> MoELMParams:
    ke, kp, kb = jax.random.split(key, 3)
    return MoELMParams(
        wte=scale * jax.random.normal(ke, (vocab, d_model), dtype),
        wpe=scale * jax.random.normal(kp, (max_seq_len, d_model), dtype),
        blocks=init_moe_transformer(kb, d_model, n_layers, n_experts,
                                    ffn_dim, scale, dtype),
        ln_f=jnp.ones((d_model,), dtype))


def moe_lm_loss_aux(params: MoELMParams, tokens: jax.Array,
                    targets: jax.Array, n_heads: int, causal: bool = True,
                    capacity_factor: float | None = None,
                    k: int | None = None, capacity: int | None = None,
                    moe_fn=None, attn=None):
    """Mean next-token cross-entropy + the stack's summed router aux loss.
    ``tokens, targets [B, T]`` int. ``moe_fn`` swaps the MoE sublayer
    core (the EP trainer passes its all_to_all form); see
    ``moe_transformer_fwd_aux``."""
    t = tokens.shape[1]
    x = params.wte[tokens] + params.wpe[:t]
    x, aux = moe_transformer_fwd_aux(params.blocks, x, n_heads, causal,
                                     capacity_factor, k, capacity,
                                     moe_fn, attn)
    h = layernorm(params.ln_f, x)
    logits = h @ params.wte.T
    loss = xent_loss(logits.reshape(-1, params.wte.shape[0]),
                     targets.reshape(-1))
    return loss, aux
