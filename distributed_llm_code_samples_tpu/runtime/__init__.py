"""Runtime layer: process bootstrap + native C++ components.

The TPU-native replacement for the reference's L0/L4 runtime surface
(SURVEY.md): ``init`` wraps the multi-host bootstrap
(``jax.distributed``); ``native`` binds the in-tree C++ engines (host ring
collectives, prefetching data loader, TCP rendezvous/barrier, XLA FFI
custom calls).
"""

from . import native
from .init import initialize, runtime_info, DEFAULT_COORDINATOR

__all__ = ["native", "initialize", "runtime_info", "DEFAULT_COORDINATOR"]
