"""Runtime layer: process bootstrap + native C++ components + failure
supervision.

The TPU-native replacement for the reference's L0/L4 runtime surface
(SURVEY.md): ``init`` wraps the multi-host bootstrap
(``jax.distributed``); ``native`` binds the in-tree C++ engines (host ring
collectives, prefetching data loader, TCP rendezvous/barrier with timeout,
watchdog, XLA FFI custom calls); ``failure`` adds hang/peer/device failure
detection and checkpoint-based elastic recovery; ``chaos`` injects
deterministic faults so that story is continuously tested; and
``backend_probe`` walks an env-shape matrix to tell a dead accelerator
relay from a self-broken environment (the round-5 outage); ``telemetry``
is the unified metrics stream (schema-versioned per-step JSONL records +
the ``StepReport`` static fold) every run/bench/report shares;
``tracing`` is the per-request span layer on top of it (the serving
waterfall's telescoping clock).
"""

from . import backend_probe, chaos, native, telemetry, tracing, weights
from .chaos import FaultPlan
from .failure import (HealthCheckError, device_healthcheck, supervise)
from .init import initialize, runtime_info, DEFAULT_COORDINATOR
from .telemetry import StepReport, TelemetryWriter
from .tracing import SpanTracer
from .weights import VersionLedger, model_fingerprint

__all__ = ["backend_probe", "chaos", "native", "telemetry", "tracing",
           "weights", "initialize", "runtime_info",
           "DEFAULT_COORDINATOR", "FaultPlan", "HealthCheckError",
           "device_healthcheck", "supervise", "StepReport",
           "TelemetryWriter", "SpanTracer", "VersionLedger",
           "model_fingerprint"]
