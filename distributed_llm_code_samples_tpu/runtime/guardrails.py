"""In-graph anomaly guardrails: skip-step, dynamic loss scaling, clipping.

Rung 1 of the self-healing ladder (DESIGN.md section 14). PR 1's only
remedy for a poisoned step was segment-granular: the checkpoint layer's
``tree_finite`` readback either dropped a whole ``every``-step segment
(``nonfinite="skip"``) or raised for a restart — re-paying restore and
up to ``every - 1`` good steps for one bad gradient. Production stacks
(PaLM's spike handling, every serious mixed-precision recipe) treat the
single bad step inside the compiled program: check the update for
NaN/Inf *in-graph* and ``jnp.where``-select the previous state, so a
poisoned step costs exactly one update and zero host round-trips.

The machinery is strategy-agnostic: ``guarded_scan_step`` wraps any
``(carry, seed) -> carry`` scan step (the shape every trainer in
``parallel/`` already has). The wrapped step

- computes the candidate carry,
- derives one scalar *all-finite* flag over its float leaves (reduced
  with a ``psum`` across the mesh axes so every shard takes the SAME
  branch — a per-shard decision would silently fork replicated params),
- ``jnp.where``-selects candidate vs previous carry leaf-by-leaf: a bad
  step leaves params AND optimizer state untouched,
- advances a tiny ``GuardState`` (skip/overflow counters, the dynamic
  loss scale) that rides the scan carry and comes back to the host only
  at the chunk boundary — steady-state steps stay dispatch-only, per
  PR 2's ``log_every`` chunking contract.

``mixed=True`` paths additionally get **dynamic loss scaling**
(``loss_scale > 0``): the upstream gradient is multiplied by the scale
before the bf16 backward, grads are unscaled in f32 after the
reduction, and the scale grows ``scale_growth``x after
``growth_interval`` consecutive finite steps / shrinks ``scale_backoff``x
on overflow — the standard grow/shrink recipe, expressed in-graph so an
overflowed step is simultaneously skipped and re-scaled. Optional
global-norm clipping (``clip_norm``) rides the same hook for trainers
that run the stateless inline SGD (stateful optimizers already compose
clipping via ``optim.clipped``).

Counters flow to the telemetry stream as ``anomaly`` records
(``runtime/telemetry.py`` schema v2) via the chunk drivers
(``checkpoint.run_with_checkpointing``, ``cli``'s metrics loop).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclass(frozen=True)
class GuardrailConfig:
    """Static guardrail knobs. Frozen/hashable on purpose: trainers pass
    it as a static jit argument (``parallel/single.py``), so two runs
    with the same config share one compiled program.

    ``loss_scale`` is the *initial* dynamic loss scale (0 = scaling
    off); ``clip_norm`` clips gradients to that global L2 norm before
    the update (0 = off). The remaining fields parameterize the
    grow/shrink schedule."""

    clip_norm: float = 0.0
    loss_scale: float = 0.0
    scale_growth: float = 2.0
    scale_backoff: float = 0.5
    growth_interval: int = 200
    min_scale: float = 1.0

    @property
    def scaling(self) -> bool:
        return self.loss_scale > 0


class GuardState(NamedTuple):
    """The in-graph guardrail carry: three counters and the live scale.
    All scalars — it rides every scan step for the cost of a handful of
    registers, and is read back on the host only at chunk boundaries."""

    skipped: jax.Array      # i32: updates dropped by the finite check
    overflows: jax.Array    # i32: skips while loss scaling was active
    loss_scale: jax.Array   # f32: current dynamic scale (1.0 when off)
    good_steps: jax.Array   # i32: consecutive finite steps since shrink


def init_state(cfg: GuardrailConfig) -> GuardState:
    return GuardState(
        skipped=jnp.zeros((), jnp.int32),
        overflows=jnp.zeros((), jnp.int32),
        loss_scale=jnp.asarray(cfg.loss_scale if cfg.scaling else 1.0,
                               jnp.float32),
        good_steps=jnp.zeros((), jnp.int32))


def summarize(state: GuardState) -> dict:
    """Host-side view of a ``GuardState`` (one readback per field —
    call at chunk/segment cadence only, never per step)."""
    return {"skipped": int(state.skipped),
            "overflows": int(state.overflows),
            "loss_scale": float(state.loss_scale),
            "good_steps": int(state.good_steps)}


def finite_flag(tree: Any) -> jax.Array:
    """One scalar bool: every float/complex leaf of ``tree`` is free of
    NaN/Inf. Integer leaves (Adam counts, seeds) are always finite and
    skipped — same rule as ``checkpoint._leaf_finite``, but in-graph."""
    flags = [jnp.all(jnp.isfinite(leaf))
             for leaf in jax.tree_util.tree_leaves(tree)
             if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)]
    ok = jnp.asarray(True)
    for f in flags:
        ok = jnp.logical_and(ok, f)
    return ok


def rows_finite(logits: jax.Array) -> jax.Array:
    """Per-ROW all-finite flag over a logits block ``[..., V] -> [...]``
    — the serving twin of ``finite_flag``: the decode engine computes it
    inside every compiled step (``decode/engine.py``) so a poisoned
    sequence is detected at the step it happens, per sequence, with
    zero extra host round-trips (the flag rides the same readback as
    the sampled picks). Under TP the flag is computed on the gathered
    full-vocab logits, which are replicated — every shard sees the
    same verdict by construction, the in-graph-skip psum stance
    without needing the psum."""
    return jnp.all(jnp.isfinite(logits), axis=-1)


def advance(cfg: GuardrailConfig, state: GuardState,
            ok: jax.Array) -> GuardState:
    """Fold one step's finite flag into the guard state: count the skip
    and (with scaling on) run the grow/shrink schedule."""
    ok_i = ok.astype(jnp.int32)
    skipped = state.skipped + (1 - ok_i)
    if not cfg.scaling:
        return state._replace(skipped=skipped)
    overflows = state.overflows + (1 - ok_i)
    good = jnp.where(ok, state.good_steps + 1, 0)
    grown = jnp.logical_and(ok, good >= cfg.growth_interval)
    scale = jnp.where(
        ok,
        jnp.where(grown, state.loss_scale * cfg.scale_growth,
                  state.loss_scale),
        jnp.maximum(state.loss_scale * cfg.scale_backoff, cfg.min_scale))
    good = jnp.where(grown, jnp.zeros_like(good), good)
    return GuardState(skipped=skipped, overflows=overflows,
                      loss_scale=scale, good_steps=good)


def unscale_grads(grads: Any, scale: jax.Array) -> Any:
    """Divide every grad leaf by the live loss scale — in f32, after the
    reduction (grads leave the mixed blocks f32 already)."""
    inv = (1.0 / scale).astype(jnp.float32)
    return jax.tree_util.tree_map(
        lambda g: (g * inv.astype(g.dtype)), grads)


def clip_by_global_norm(grads: Any, max_norm: float,
                        axis: str | tuple | None = None) -> Any:
    """Global-norm clipping for the stateless-SGD paths (the stateful
    ones compose ``optim.clipped``). ``axis``: pass the mesh axis the
    grads are *sharded* over (FSDP) so the squared norm is ``psum``-med
    into the true global norm before the scale is computed."""
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
             for g in jax.tree_util.tree_leaves(grads))
    if axis is not None:
        sq = lax.psum(sq, axis)
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-16))
    return jax.tree_util.tree_map(lambda g: g * scale.astype(g.dtype),
                                  grads)


def anomaly_delta(prev: dict, cur: dict, step: int,
                  steps: list) -> dict | None:
    """The one place the ``anomaly`` record shape is built (telemetry
    ``ANOMALY_REQUIRED`` contract): compare two ``summarize`` snapshots
    and return the per-chunk record, or None when nothing advanced.
    ``skipped``/``overflows`` are per-chunk DELTAS; the running totals
    travel as ``total_*`` — both chunk drivers (cli's metrics loop and
    ``checkpoint.run_with_checkpointing``) emit through here, so the
    record shape cannot fork."""
    if (cur["skipped"] <= prev["skipped"]
            and cur["overflows"] <= prev["overflows"]):
        return None
    return {"step": int(step), "steps": list(steps),
            "skipped": cur["skipped"] - prev["skipped"],
            "total_skipped": cur["skipped"],
            "overflows": cur["overflows"] - prev["overflows"],
            "total_overflows": cur["overflows"],
            "loss_scale": cur["loss_scale"]}


def finalize_grads(grads: Any, scale, guard: GuardrailConfig | None,
                   axis: str | tuple | None = None) -> Any:
    """The shared post-reduction epilogue of a guarded strategy step:
    unscale by the live loss scale (when scaling ran), then clip to the
    configured global norm. ``axis`` is the mesh axis the grads are
    SHARDED over (FSDP), so the clip computes the true global norm —
    one implementation for every strategy, so the DDP/FSDP
    differentials can't drift on the scaling recipe."""
    if scale is not None:
        grads = unscale_grads(grads, scale)
    if guard is not None and guard.clip_norm > 0:
        grads = clip_by_global_norm(grads, guard.clip_norm, axis=axis)
    return grads


def require_mixed_for_scaling(guard, mixed: bool) -> None:
    """Dynamic loss scaling protects a narrow-precision backward; the
    f32 paths have none — shared precondition of every strategy that
    takes the ``(guard, mixed)`` pair."""
    if guard is not None and guard.scaling and not mixed:
        raise ValueError("dynamic loss scaling (guard.loss_scale > 0) "
                         "applies to the mixed=True path: the f32 path "
                         "has no narrow-precision backward to protect")


def guarded_scan_step(step: Callable, cfg: GuardrailConfig,
                      axis_names: tuple = (), world: int = 1,
                      takes_scale: bool = False) -> Callable:
    """Wrap a scan step ``(carry, seed) -> carry`` into
    ``((carry, GuardState), seed) -> (carry, GuardState)`` implementing
    the in-graph skip (module docstring).

    ``axis_names``/``world``: the shard_map mesh axes to ``psum`` the
    finite flag over (every shard must take the same branch; the summed
    flag equals ``world`` iff every shard saw finite leaves — replicated
    leaves sum their identical flags, sharded leaves each contribute
    their own view). ``takes_scale=True`` calls
    ``step(carry, seed, loss_scale)`` — the hook the mixed-precision
    strategies use to scale the upstream gradient in-graph."""

    def gstep(carry_g, seed):
        carry, g = carry_g
        with jax.named_scope("guardrails"):
            new = (step(carry, seed, g.loss_scale) if takes_scale
                   else step(carry, seed))
            ok = finite_flag(new)
            if axis_names:
                ok = lax.psum(ok.astype(jnp.int32), axis_names) == world
            sel = jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, carry)
            return sel, advance(cfg, g, ok)

    return gstep


def mesh_world(mesh) -> tuple[tuple, int]:
    """``(axis_names, total shards)`` of a mesh — the reduction domain
    for the finite flag under ``shard_map``."""
    if mesh is None:
        return (), 1
    shape = dict(mesh.shape)
    return tuple(shape.keys()), int(math.prod(shape.values())) or 1


def check_guard_args(guard, guard_state, return_guard) -> None:
    """The guarded-trainer surface contract (mirrors
    ``optim.check_state_args``): guard state in/out needs a config."""
    if guard is None and (return_guard or guard_state is not None):
        raise ValueError("guard_state/return_guard need a guard config")
    if guard is not None and not isinstance(guard, GuardrailConfig):
        raise TypeError(f"guard must be a GuardrailConfig, got "
                        f"{type(guard).__name__}")


def host_state(state_or_none, cfg: GuardrailConfig) -> GuardState:
    """Resolve the incoming guard state for a trainer call: a fresh
    ``init_state(cfg)`` when None, else the caller's (threading the
    scale/counters across chunked calls)."""
    if state_or_none is None:
        return init_state(cfg)
    if isinstance(state_or_none, GuardState):
        return state_or_none
    # tolerate a plain tuple (e.g. round-tripped through numpy)
    return GuardState(*[jnp.asarray(x) for x in state_or_none])


def delta_norm(old_params, new_params) -> float:
    """Host-side global L2 norm of a params update — the segment-level
    spike signal (``checkpoint.run_with_checkpointing(spike_factor=)``).
    Runs at segment cadence only; NaN-safe (a non-finite delta returns
    inf so the caller's nonfinite guard keeps precedence)."""
    sq = 0.0
    for o, n in zip(jax.tree_util.tree_leaves(old_params),
                    jax.tree_util.tree_leaves(new_params)):
        a = np.asarray(o)
        if a.dtype.kind in "iub":
            continue
        d = np.asarray(n, np.float64) - np.asarray(a, np.float64)
        sq += float(np.sum(d * d))
    return math.sqrt(sq) if np.isfinite(sq) else float("inf")
