"""Multi-host process bootstrap — the ``init_process`` analogue.

The reference's per-process rendezvous (``train_ffns.py:121-127``) sets
MASTER_ADDR/PORT and calls ``dist.init_process_group("nccl", rank,
world_size)``. In SPMD JAX the per-device process model collapses to one
process per *host*; this module wraps ``jax.distributed.initialize`` with
the same ergonomics, and exposes the runtime facts the reference's workers
read from their args.
"""

from __future__ import annotations

import os

import jax

DEFAULT_COORDINATOR = "127.0.0.1:29500"  # the reference's addr:port (:123-124)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join the multi-host runtime. No-op on a single-process run.

    Arguments fall back to the standard env vars
    (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``)
    the way the reference fell back to MASTER_ADDR/PORT.
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address or DEFAULT_COORDINATOR,
        num_processes=num_processes, process_id=process_id)


def runtime_info() -> dict:
    """The facts every reference worker carried in its args: rank/world."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": jax.device_count(),
    }
