"""Deterministic fault injection: run any strategy under fault load.

Production resilience work (Oobleck's pipeline-template recovery,
CheckFreq's atomically-published checkpoints) injects failures
DELIBERATELY in tests, detects them cheaply at runtime, and recovers
from checkpoints whose publish path is itself crash-safe. The reference
has no failure story at all (no try/except around workers, no join
timeout — ``train_ffns.py:190-191``); this module supplies the
injection half of ours, and ``tests/test_chaos.py`` proves the
detection + recovery half lands on the same final params as an
uninterrupted run.

A ``FaultPlan`` is a deterministic schedule of faults keyed on absolute
1-based training-step indices (the same indices checkpoint ``step_{N}``
dirs use), parsed from the CLI ``--chaos`` spec grammar::

    spec  := fault ("," fault)* ("," "seed=" INT)?
    fault := KIND "@" STEP (":" ARG)?
    KIND  := nan_grad | inf_grad | loss_spike | slow_step | hang
           | kill | corrupt_ckpt
           | nan_logits | hang_step | corrupt_block      # decode faults
           | corrupt_spill                               # decode faults
           | kill_worker | hang_worker | corrupt_wire    # fleet faults

- ``nan_grad@s`` / ``inf_grad@s`` — step ``s`` trains on a poisoned
  (NaN/Inf) upstream gradient. With in-graph guardrails armed
  (``begin_segment(in_graph=True)``) the poison rides the STEP'S OWN
  SEED (``data.POISON_NAN_BIT``) so it fires *inside* the compiled
  chunk — the skip-step guardrail must neutralize exactly that step.
  Without guardrails the segment's returned params are poisoned
  post-hoc, and the supervisor's non-finite guard refuses to
  checkpoint them (the PR 1 behavior, unchanged).
- ``loss_spike@s:mult`` — the PaLM-scenario loss spike: the segment
  that trains step ``s`` returns params whose update is scaled by
  ``mult`` (default 100) — finite, so no finite-check rung catches it;
  the checkpoint layer's segment-delta spike guard
  (``run_with_checkpointing(spike_factor=...)``) must detect it and
  the supervisor's rollback rung must rewind to the last verified
  checkpoint.
- ``slow_step@s[:secs]`` — a straggler, not a hang: the segment sleeps
  ``secs`` (default 1.0) and then completes normally. Deterministic
  trigger for step-time anomalies in the telemetry stream (and for the
  watchdog, when armed with a shorter deadline).
- ``hang@s[:secs]`` — a hung collective: the segment sleeps ``secs``
  (default 0.25) without returning, long enough to latch a native
  ``Watchdog`` armed by the supervisor.
- ``kill@s`` — a killed worker: SIGKILL this process right AFTER the
  checkpoint for step ``s`` is published (the crash-between-segments
  failure mode). Keying on the publish boundary makes the fault
  deterministic ACROSS process restarts: the resumed run starts past
  ``s`` and never re-fires it.
- ``corrupt_ckpt@s[:frac]`` — truncate step ``s``'s freshly-published
  array file mid-file (default: to half its bytes), simulating a torn
  write that slipped past rename atomicity (lost page cache, dying
  disk). The checkpoint layer's per-file checksum must send the next
  restore to the previous verified step.

In-segment faults (nan/inf/hang) fire once per process; publish faults
(kill/corrupt) fire once per publish of their step. ``seed`` feeds an
internal RNG reserved for randomized plans; the default plan is fully
deterministic so test oracles can be exact.

**Decode faults** (round 10 — the serving engine, ``decode/``). Steps
are GLOBAL 1-based engine-step indices (``step_base + engine.steps``,
the index the serving snapshot records), consumed by the engine
supervisor (``decode/supervise.py``) around each ``DecodeEngine.step``:

- ``nan_logits@s[:uid]`` — step ``s`` computes non-finite logits for
  the sequence with uid ``uid`` (every active sequence when omitted),
  injected IN-GRAPH through the compiled step's poison operand — the
  per-row logits guardrail must quarantine exactly that sequence.
- ``hang_step@s[:secs]`` — engine step ``s`` stalls ``secs`` (default
  0.25) before dispatch: the supervisor's hung-step watchdog must latch.
- ``corrupt_block@s:block`` — physical KV-pool block ``block`` is
  poisoned (NaN values — or NaN scales under int8) before step ``s``,
  simulating an HBM/DMA corruption. The sequence whose table names the
  block reads NaN through its gather (masked positions included —
  ``0 * nan`` is ``nan`` inside the attention reduction), fails the
  logits guardrail, and is quarantined; its blocks are SCRUBBED on
  release (``paged.scrub_blocks``), so a retry observes a
  factory-fresh pool. A corrupted free block is caught by the next
  request that reserves it — quarantined once, scrubbed, clean on
  retry.
- ``corrupt_spill@s:id`` — spill-tier entry ``id`` (the monotone spill
  id ``decode/spill.py`` minted at demotion — ids count from 0 in
  spill order) has one byte flipped in host RAM before step ``s``,
  simulating host-memory rot in the KV spill tier. The wire CRC
  (``runtime/wire.py``) catches it at RESTORE: the promoting request
  is quarantined with reason ``corrupt_spill``, the damaged edge is
  detached from the radix tree, and survivors decode bit-identically
  — the corrupted bytes are never implanted. A miss (entry already
  restored or dropped) is a no-op, noted with ``hit: false``.
- ``kill@s`` — SIGKILL right AFTER the engine snapshot for step ``s``
  is persisted (the crash-between-steps failure mode). As with the
  training-side kill, keying on the snapshot boundary makes the fault
  deterministic across restarts: a resumed run starts past ``s`` and
  never re-fires it (``mark_decode_fired_through``).
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp


IN_SEGMENT_KINDS = ("nan_grad", "inf_grad", "loss_spike", "slow_step",
                    "hang")
PUBLISH_KINDS = ("corrupt_ckpt", "kill")
# serving-engine faults (kill is shared: publish boundary in training,
# snapshot boundary in serving — decode/supervise.py)
DECODE_KINDS = ("nan_logits", "hang_step", "corrupt_block",
                "corrupt_spill", "kill")
# fleet-transport faults (round 16, decode/fleet.py + decode/worker.py):
# steps are FLEET ROUNDS (the router's clock), fired by the router at
# the start of the round —
# - ``kill_worker@ROUND[:IDX]`` — SIGKILL decode engine e{IDX}
#   (default e0) at the start of that round: a REAL dead host under the
#   process transport (the worker process dies mid-stream), the
#   dropped-object simulation in-process; recovery migrates from the
#   router's last snapshot either way.
# - ``hang_worker@ROUND[:SECS]`` — the first alive decode worker goes
#   silent for SECS (default 30): its next call overruns the per-call
#   deadline, the liveness ladder declares it dead, SIGKILLs it, and
#   the same migration path recovers. Process transport only (an
#   in-process engine cannot hang without hanging the router).
# - ``corrupt_wire@ROUND`` — the next wire-serialized KV handoff at or
#   after that round is bit-flipped in transit: the per-array CRC-32
#   (runtime/wire.py) must reject it with a named reason and the
#   request must be replay-rerouted, no engine importing partial state.
# - ``corrupt_deploy@ROUND[:FRAC]`` (round 17) — the NEXT rolling
#   deploy at or after that round reads a torn target checkpoint (its
#   primary array file truncated to FRAC, default 0.5, just before the
#   ledger reads it): the checkpoint CRC ladder must reject the step
#   with a one-line named reason, the fleet must roll back to
#   ``latest_verified_step`` — deploy aborted, no engine left serving
#   a mixed version, nothing shed (decode/fleet.py rolling_deploy).
# - ``partition_worker@ROUND[:SECS]`` (round 22) — the link to the
#   first alive decode worker drops BOTH WAYS for SECS (default 2):
#   the router's next call fails at the socket, the reconnect ladder
#   (bounded backoff + sequence-numbered replay) waits out the
#   partition and resumes on the healed link — zero declared deaths,
#   one ``reconnected`` router record. Process transport only.
# - ``slow_link@ROUND[:MS]`` (round 22) — every call to the first
#   alive decode worker pays MS (default 50) of injected one-way
#   latency from that round on: calls slow down but stay inside their
#   deadline, so the liveness ladder must NOT page — slow-link and
#   dead-host are different verdicts. Process transport only.
# - ``drop_conn@ROUND`` (round 22) — the connection to the first alive
#   decode worker is closed mid-message right after the next request
#   is sent: the response is lost in flight, reconnect replays the
#   sequence-numbered request, and the worker's dedup cache answers
#   it without re-executing — no duplicate side effects, no lost
#   response. Process transport only.
FLEET_KINDS = ("kill_worker", "hang_worker", "corrupt_wire",
               "corrupt_deploy", "partition_worker", "slow_link",
               "drop_conn")
KINDS = IN_SEGMENT_KINDS + PUBLISH_KINDS + tuple(
    k for k in DECODE_KINDS if k not in PUBLISH_KINDS) + FLEET_KINDS


@dataclass
class Fault:
    kind: str
    step: int          # absolute 1-based training step index
    arg: float | None = None
    fired: bool = False


@dataclass
class FaultPlan:
    """A deterministic, seeded schedule of injected faults."""

    faults: list = field(default_factory=list)
    seed: int = 0
    events: list = field(default_factory=list)  # fired-fault audit trail
    _armed: list = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``--chaos`` grammar (see module docstring)."""
        faults, seed = [], 0
        for entry in (e.strip() for e in spec.split(",") if e.strip()):
            if entry.startswith("seed="):
                seed = int(entry[len("seed="):])
                continue
            if "@" not in entry:
                raise ValueError(
                    f"bad --chaos entry {entry!r}: expected KIND@STEP"
                    f"[:ARG] with KIND in {KINDS} (or seed=N)")
            kind, _, rest = entry.partition("@")
            if kind not in KINDS:
                raise ValueError(
                    f"bad --chaos kind {kind!r}: known kinds {KINDS}")
            step_s, _, arg_s = rest.partition(":")
            try:
                step = int(step_s)
            except ValueError:
                raise ValueError(
                    f"bad --chaos step {step_s!r} in {entry!r}: "
                    "steps are absolute 1-based integers") from None
            if step < 1:
                raise ValueError(
                    f"bad --chaos step {step} in {entry!r}: must be >= 1")
            try:
                arg = float(arg_s) if arg_s else None
            except ValueError:
                raise ValueError(
                    f"bad --chaos arg {arg_s!r} in {entry!r}: the "
                    "optional :ARG is a number (seconds, multiplier, "
                    "fraction)") from None
            faults.append(Fault(kind, step, arg))
        if not faults:
            raise ValueError(f"empty --chaos spec {spec!r}")
        return cls(faults=faults, seed=seed)

    def _note(self, fault: Fault, **extra):
        fault.fired = True
        self.events.append({"kind": fault.kind, "step": fault.step,
                            "t": time.time(), **extra})

    # ---------------------------------------------- segment integration
    def begin_segment(self, start: int, n: int,
                      in_graph: bool = False) -> None:
        """Arm the in-segment faults whose step the upcoming segment
        ``(start, start+n]`` trains (0-based ``start``, 1-based steps).
        ``in_graph=True`` (set when the run compiles guardrails into its
        steps) routes nan/inf faults through seed poisoning
        (``poison_segment_seeds``) instead of the post-hoc params
        poison — the fault then fires at its exact step INSIDE the
        compiled chunk, which is what the in-graph skip must catch."""
        self._start = start
        self._in_graph = in_graph
        self._armed = [f for f in self.faults
                       if f.kind in IN_SEGMENT_KINDS and not f.fired
                       and start < f.step <= start + n]

    def poison_segment_seeds(self, seg_seeds):
        """Apply armed nan/inf faults to the segment's seed slice (the
        in-graph injection path; no-op unless ``begin_segment`` armed
        with ``in_graph=True``). Returns the (possibly modified) seeds;
        poisoned faults are consumed here so ``wrap`` won't re-fire
        them."""
        if not getattr(self, "_in_graph", False):
            return seg_seeds
        from ..data import POISON_INF_BIT, POISON_NAN_BIT
        import numpy as np
        seeds = None
        for f in list(self._armed):
            if f.kind not in ("nan_grad", "inf_grad"):
                continue
            if seeds is None:
                seeds = np.array(seg_seeds)
            idx = f.step - self._start - 1
            bit = (POISON_NAN_BIT if f.kind == "nan_grad"
                   else POISON_INF_BIT)
            seeds[idx] = int(seeds[idx]) | bit
            self._note(f, mode="in_graph")
            self._armed.remove(f)
        return seg_seeds if seeds is None else jnp.asarray(seeds)

    def wrap(self, train_fn):
        """A train_fn that injects this plan's armed in-segment faults
        around the real one. ``begin_segment`` must be called first."""
        def chaotic(params, seeds, *args, **kwargs):
            for f in list(self._armed):
                if f.kind in ("hang", "slow_step"):
                    default = 0.25 if f.kind == "hang" else 1.0
                    secs = default if f.arg is None else f.arg
                    self._note(f, sleep_s=secs)
                    time.sleep(secs)
            out = train_fn(params, seeds, *args, **kwargs)
            for f in list(self._armed):
                if f.kind in ("nan_grad", "inf_grad"):
                    poison = jnp.nan if f.kind == "nan_grad" else jnp.inf
                    self._note(f)
                    leaves, treedef = jax.tree_util.tree_flatten(out)
                    leaves[0] = jnp.full_like(leaves[0], poison)
                    out = jax.tree_util.tree_unflatten(treedef, leaves)
                elif f.kind == "loss_spike":
                    mult = 100.0 if f.arg is None else f.arg
                    self._note(f, mult=mult)
                    # scale the PARAMS update: new = old + mult*(new-old).
                    # With a threaded optimizer `out` is (params, state)
                    # and `params` is the params alone — the params
                    # leaves come first in the flatten order, so pair
                    # the input leaves against the output's prefix.
                    in_leaves = jax.tree_util.tree_leaves(params)
                    leaves, treedef = jax.tree_util.tree_flatten(out)
                    for i, old in enumerate(in_leaves):
                        leaves[i] = old + mult * (leaves[i] - old)
                    out = jax.tree_util.tree_unflatten(treedef, leaves)
            self._armed = []
            return out

        return chaotic

    # ---------------------------------------------- decode integration
    def decode_due(self, step: int) -> list:
        """Unfired decode faults scheduled for GLOBAL engine step
        ``step`` (the supervisor fires and ``_note``s them itself —
        injection mechanics live in ``decode/supervise.py``)."""
        return [f for f in self.faults
                if f.kind in DECODE_KINDS and not f.fired
                and f.step == step]

    def mark_decode_fired_through(self, step: int) -> None:
        """Resume bookkeeping: align every decode fault's fired flag
        with a resume from engine snapshot ``step`` — faults at or
        before it already happened (a freshly-parsed plan must not
        re-fire them: the decode twin of kill's keyed-on-publish
        determinism), and faults AFTER it must fire again on replay
        (an in-process restart restores a snapshot that may predate a
        fault it already injected once — leaving it marked fired would
        silently skip it on the replayed step, diverging from both the
        pre-crash history and a fresh-process resume). The events
        audit trail keeps the original firing either way."""
        for f in self.faults:
            if f.kind in DECODE_KINDS:
                f.fired = f.step <= step

    # ---------------------------------------------- fleet integration
    def fleet_due(self, round_: int) -> list:
        """Unfired fleet-transport faults scheduled for router round
        ``round_`` (``decode/fleet.py`` fires and ``_note``s them at
        the start of the round — before any engine steps, so the
        round's snapshot cadence has not yet run and replay honestly
        fills the gap since the last one)."""
        return [f for f in self.faults
                if f.kind in FLEET_KINDS and not f.fired
                and f.step == round_]

    # ---------------------------------------------- publish integration
    def after_publish(self, step: int, path: str) -> None:
        """Fire publish-boundary faults for ``step`` on its freshly
        published checkpoint ``path``. Corruption fires before kill, so
        a combined ``corrupt_ckpt@s,kill@s`` leaves a torn latest
        checkpoint behind a dead process — the CheckFreq scenario."""
        due = [f for f in self.faults
               if f.kind in PUBLISH_KINDS and not f.fired and f.step == step]
        for f in sorted(due, key=lambda f: PUBLISH_KINDS.index(f.kind)):
            if f.kind == "corrupt_ckpt":
                self._note(f, path=path)
                truncate_checkpoint(path, frac=0.5 if f.arg is None
                                    else f.arg)
            elif f.kind == "kill":
                self._note(f, path=path)
                os.kill(os.getpid(), signal.SIGKILL)


def validate_decode_plan(plan: FaultPlan) -> None:
    """Reject a ``--chaos`` spec the SERVING path cannot honor: training
    faults have no decode-step anchor, ``corrupt_block`` needs its
    ``:BLOCK`` id, and uid/block args must be non-negative integers —
    the generate CLI's parse-rejection discipline (mirrors the train
    CLI's ``--chaos`` guards)."""
    for f in plan.faults:
        if f.kind not in DECODE_KINDS:
            raise ValueError(
                f"--chaos kind {f.kind!r} is not a decode fault; the "
                f"decode engine accepts {DECODE_KINDS} (training "
                "faults run under the train CLI, fleet-transport "
                "faults under --fleet_chaos)")
        if f.kind == "corrupt_block":
            if f.arg is None:
                raise ValueError(
                    "corrupt_block requires :BLOCK (the physical pool "
                    "block id to poison), e.g. corrupt_block@3:2")
            if f.arg != int(f.arg) or f.arg < 0:
                raise ValueError(
                    f"corrupt_block arg {f.arg!r} must be a "
                    "non-negative integer block id")
        if f.kind == "corrupt_spill":
            if f.arg is None:
                raise ValueError(
                    "corrupt_spill requires :ID (the monotone spill-"
                    "tier entry id to damage), e.g. corrupt_spill@9:0")
            if f.arg != int(f.arg) or f.arg < 0:
                raise ValueError(
                    f"corrupt_spill arg {f.arg!r} must be a "
                    "non-negative integer spill id")
        if f.kind == "nan_logits" and f.arg is not None and (
                f.arg != int(f.arg) or f.arg < 0):
            raise ValueError(
                f"nan_logits arg {f.arg!r} must be a non-negative "
                "integer sequence uid (omit it to poison every "
                "active sequence)")
        if f.kind == "hang_step" and f.arg is not None and f.arg < 0:
            raise ValueError(
                f"hang_step arg {f.arg!r} must be a non-negative "
                "sleep in seconds")
        if f.kind == "kill" and f.arg is not None:
            raise ValueError(
                f"kill takes no :ARG (got {f.arg!r}) — it SIGKILLs "
                "after the step's snapshot; did you mean "
                "corrupt_block@STEP:BLOCK?")


def validate_fleet_plan(plan: FaultPlan) -> None:
    """Reject a ``--fleet_chaos`` spec the fleet router cannot honor:
    only the fleet-transport kinds belong here (training/decode faults
    have no fleet-round anchor), ``kill_worker``'s optional :IDX is a
    non-negative integer decode-engine index, ``hang_worker``'s
    optional :SECS a non-negative sleep, and ``corrupt_wire`` takes no
    argument — the generate CLI's parse-rejection discipline."""
    for f in plan.faults:
        if f.kind not in FLEET_KINDS:
            raise ValueError(
                f"--fleet_chaos kind {f.kind!r} is not a fleet-"
                f"transport fault; the fleet router accepts "
                f"{FLEET_KINDS} (engine-level faults run under the "
                "single-engine supervisor's --chaos)")
        if f.kind == "kill_worker" and f.arg is not None and (
                f.arg != int(f.arg) or f.arg < 0):
            raise ValueError(
                f"kill_worker arg {f.arg!r} must be a non-negative "
                "integer decode-engine index (kill_worker@R:1 kills "
                "e1; omit it to kill e0)")
        if f.kind == "hang_worker" and f.arg is not None and f.arg < 0:
            raise ValueError(
                f"hang_worker arg {f.arg!r} must be a non-negative "
                "sleep in seconds")
        if f.kind == "corrupt_wire" and f.arg is not None:
            raise ValueError(
                f"corrupt_wire takes no :ARG (got {f.arg!r}) — it "
                "corrupts the next wire handoff after its round; the "
                "CRC layer decides what is detected")
        if f.kind == "corrupt_deploy" and f.arg is not None and not (
                0 < f.arg < 1):
            raise ValueError(
                f"corrupt_deploy arg {f.arg!r} must be a truncation "
                "fraction in (0, 1) (omit it for 0.5) — the torn "
                "checkpoint the deploy's CRC ladder must reject")
        if f.kind == "partition_worker" and f.arg is not None \
                and f.arg < 0:
            raise ValueError(
                f"partition_worker arg {f.arg!r} must be a non-"
                "negative partition duration in seconds (omit it "
                "for 2)")
        if f.kind == "slow_link" and f.arg is not None and f.arg < 0:
            raise ValueError(
                f"slow_link arg {f.arg!r} must be a non-negative "
                "per-call latency in milliseconds (omit it for 50)")
        if f.kind == "drop_conn" and f.arg is not None:
            raise ValueError(
                f"drop_conn takes no :ARG (got {f.arg!r}) — it drops "
                "the connection mid-message once; reconnect-and-"
                "replay decides the rest")


def truncate_checkpoint(path: str, frac: float = 0.5) -> str:
    """Truncate a published checkpoint's primary array file mid-file
    (also used directly by tests): ``arrays.npz`` when present, else the
    first ``*.raw`` leaf (native backend; listdir not glob — path-keyed
    leaf names start with '.' and glob skips dotfiles). Returns the
    damaged file."""
    candidates = ([os.path.join(path, "arrays.npz")]
                  if os.path.exists(os.path.join(path, "arrays.npz"))
                  else sorted(os.path.join(path, name)
                              for name in os.listdir(path)
                              if name.endswith(".raw")))
    if not candidates:
        raise FileNotFoundError(f"no array file to corrupt under {path}")
    target = candidates[0]
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(max(1, int(size * frac)))
    return target
