"""Weight lifecycle: the version ledger + model fingerprint both the
trainer's checkpoint dir and the serving engines read.

Before round 17 the trainer and the engines kept twins of every weight
fact: the checkpoint layer owned publish/verify (atomic fsync + CRC,
``checkpoint.py``), while the serving side computed its own coarse
model fingerprint in THREE call sites (engine ``model_meta``, the
supervise snapshot, the handoff doc) and had no notion of "which
weights" at all — publishing a new checkpoint into a running fleet
meant a restart. This module is the one home ROADMAP item 3 demanded:

- **``model_fingerprint``** — THE fingerprint (shapes + the coarse
  embedding-row sum that catches a changed init at the same shape).
  ``DecodeEngine.model_meta`` re-binds to it (the ``wire.py``
  re-binding pattern from round 16), so snapshot-resume, the KV
  handoff, and the version ledger can never drift on what "the same
  model" means. ``same_architecture`` splits the shape keys from the
  value fingerprint: two VERSIONS of one model share every key except
  ``wte0_sum``.

- **``VersionLedger``** — the version ledger over an existing
  checkpoint directory. A weights VERSION is simply a published
  checkpoint step (``step_{N}/``): ``latest_step`` is the newest
  publish (what a deploy targets), ``latest_verified`` the newest step
  that passes the CRC ladder (what a failed deploy falls back to —
  ``checkpoint.latest_verified_step``, verbatim), ``verify`` the
  per-step integrity check, and ``load`` restores a step into an
  architecture template (the engine's own params tree) with the
  fresh-ownership device_put ``restore_checkpoint`` already performs.
  Publish-for-serving is deliberately NOT re-implemented: the
  trainer's existing atomic publish IS the deploy input.

Version id conventions: ``BOOT_VERSION`` (0) names the weights an
engine was CONSTRUCTED with; deployed versions carry their checkpoint
step. The serving side's pin/swap machinery (double-buffered engine
weights, per-request ``weights_version`` pins, the fleet's rolling
deploy) lives with the engine and router (``decode/engine.py``,
``decode/fleet.py``, DESIGN.md section 23) — this module owns only
what trainer and server must AGREE on: identity and the ladder.
"""

from __future__ import annotations

import os

# the version id of the weights an engine was constructed with (a
# deployed version's id is its checkpoint step — trainer steps are
# 1-based for real publishes, and a step_0 deploy to a just-booted
# engine is a no-op by fingerprint equality)
BOOT_VERSION = 0

# the fingerprint key that carries VALUE identity (init seed / training
# progress); every other model_fingerprint key is architecture
VALUE_KEYS = ("wte0_sum",)


def model_fingerprint(params, n_heads: int) -> dict:
    """Model identity snapshots, KV handoffs, and the version ledger
    all pin — THE one definition (the engine/snapshot/handoff call
    sites re-bind to it). Shapes catch a changed architecture; the
    embedding-row fingerprint catches a changed init seed (or a
    different training step) at the same shape — rounded coarsely so
    the float reduction order, which legitimately varies across TP
    layouts, can't cause a false mismatch."""
    import jax.numpy as jnp
    dh = params.d_model // int(n_heads)
    return {
        "vocab": int(params.vocab),
        "d_model": int(params.d_model),
        "n_layers": int(params.n_layers),
        "max_seq_len": int(params.max_seq_len),
        "n_heads": int(n_heads),
        "kv_heads": int(params.blocks.wk.shape[1] // dh),
        "wte0_sum": round(float(jnp.sum(params.wte[0])), 2),
    }


def same_architecture(a: dict, b: dict) -> bool:
    """True when two fingerprints describe the same MODEL SHAPE —
    every key except the value fingerprint matches. Two versions of
    one model are same-architecture with different ``wte0_sum``; a
    hot-swap between different architectures is never legal (the KV
    pool layout and the compiled program set are shape functions)."""
    keys = (set(a) | set(b)) - set(VALUE_KEYS)
    return all(a.get(k) == b.get(k) for k in keys)


def architecture_diff(a: dict, b: dict) -> dict:
    """The mismatching architecture keys (for one-line error text)."""
    keys = (set(a) | set(b)) - set(VALUE_KEYS)
    return {k: (a.get(k), b.get(k)) for k in sorted(keys)
            if a.get(k) != b.get(k)}


class VersionLedger:
    """The weight-version view of one trainer checkpoint directory.

    Thin by design: every integrity rule is the checkpoint layer's
    (per-file CRC-32, ``latest_verified_step`` fallback) — the ledger
    adds only the serving-side vocabulary (versions, targets,
    fallbacks) and the fingerprint cache a router consults when it
    records a deploy. Imports are lazy so the jax-free callers
    (``report``, the worker transport client) can import this module
    without paying the checkpoint layer's jax import."""

    def __init__(self, ckpt_dir: str):
        self.ckpt_dir = ckpt_dir
        self._fingerprints: dict[int, dict] = {}

    def step_path(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, f"step_{int(step)}")

    def latest_step(self) -> int | None:
        """Newest PUBLISHED step (unverified) — what a deploy with no
        explicit step targets; the CRC ladder then accepts or rejects
        it."""
        from ..checkpoint import latest_step
        return latest_step(self.ckpt_dir)

    def latest_verified(self) -> int | None:
        """Newest step that passes the CRC ladder — the rollback
        anchor a rejected deploy names."""
        from ..checkpoint import latest_verified_step
        return latest_verified_step(self.ckpt_dir)

    def verify(self, step: int) -> tuple[bool, str]:
        """Integrity-check one step (``checkpoint.verify_checkpoint``
        — meta parses, every payload CRC matches). The reason string
        is ONE line: it becomes the deploy record's named rollback
        reason verbatim."""
        from ..checkpoint import verify_checkpoint
        path = self.step_path(step)
        if not os.path.isdir(path):
            return False, f"step_{int(step)} not published"
        return verify_checkpoint(path)

    def load(self, step: int, template):
        """Restore step ``step`` into ``template``'s tree (the
        engine's own params — same architecture or the restore's
        shape/dtype checks reject it). Integrity-verified; raises
        ``checkpoint.CorruptCheckpointError`` with the one-line
        reason on a torn/bit-flipped step. Leaves arrive as FRESH
        exclusively-owned device buffers (``restore_checkpoint``'s
        jitted-copy ownership contract) — the swap's one device_put."""
        from ..checkpoint import restore_checkpoint
        params, got_step, _ = restore_checkpoint(self.ckpt_dir, template,
                                                 step=int(step))
        assert got_step == int(step)
        return params

    def fingerprint(self, step: int, params, n_heads: int) -> dict:
        """Fingerprint of a loaded version, cached per step (the
        router records it on every deploy event for the step)."""
        fp = self._fingerprints.get(int(step))
        if fp is None:
            fp = model_fingerprint(params, n_heads)
            self._fingerprints[int(step)] = fp
        return fp
