"""Env-matrix backend probe: distinguish a dead accelerator relay from a
self-broken environment.

Round 5's postmortem (VERDICT r5, "What's missing" #2): the relay outage
signature changed in the same round the wholesale ``PYTHONPATH`` scrub
landed — ``Unable to initialize backend 'axon': ... not in the list of
known backends: ['cpu', 'tpu']`` — and nothing in the artifact could say
whether the relay was dead or the scrub had de-registered the plugin,
because every waiting loop (``bench.py``, ``auto_bench_on_relay.sh``,
``run_hw_artifacts.sh``) probed exactly ONE environment shape. The error
message literally named the untried fix.

This module is the shared answer (one implementation for all three
callers, ending the recovery-path monoculture — VERDICT r5 weak #5). A
probe run walks a MATRIX of environment shapes, each a single-dimension
variant of the inherited environment:

- ``as_is``             — the environment exactly as inherited;
- ``pythonpath_minus_repo`` — ``PYTHONPATH`` preserved but with the repo
  root removed (the known pitfall: ``PYTHONPATH=/root/repo`` shadows the
  relay plugin discovery; a WHOLESALE scrub may instead drop the
  ``sitecustomize`` path that registers the plugin — so this shape keeps
  every other entry);
- ``jax_platforms_unset``  — ``JAX_PLATFORMS`` removed (jax autodetects);
- ``jax_platforms_tpu``    — ``JAX_PLATFORMS=tpu`` pinned.

Each shape is asked, in a FRESH subprocess (a hung or failed init there
cannot poison the caller), whether ``jax.devices()`` answers with the
required platform. Every attempt records ``(env_shape, exception_head)``
so the artifact of a failed round is diagnosable from the JSON alone:
four identical heads = the relay is dead; one shape succeeding = we had
broken our own env and the matrix names the fix.

Standalone by design: NO package-relative imports and no top-level
``import jax``, so the shell watchers can run it by file path
(``python .../backend_probe.py``) even when the package or the backend
env is itself the broken thing.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import subprocess
import sys
import tempfile
import time

# Repo root = two levels above this file (runtime/ -> package -> repo).
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Ordered: cheapest hypothesis first (nothing wrong), then the r5
# suspects in the order the postmortem ranked them.
ENV_SHAPES = ("as_is", "pythonpath_minus_repo", "jax_platforms_unset",
              "jax_platforms_tpu")


def scrub_pythonpath(value: str, repo_root: str = REPO_ROOT) -> str:
    """Drop the repo root (and its trailing-slash spelling) from a
    PYTHONPATH value, preserving every other entry — the surgical form of
    the r5 wholesale scrub that is suspected of de-registering the relay
    plugin's sitecustomize."""
    root = os.path.abspath(repo_root)
    kept = [e for e in value.split(os.pathsep)
            if e and os.path.abspath(e) != root]
    return os.pathsep.join(kept)


def build_env(shape: str, base_env: dict | None = None) -> dict:
    """The environment for one matrix shape — a copy of ``base_env``
    (default ``os.environ``) with exactly one dimension changed."""
    env = dict(os.environ if base_env is None else base_env)
    if shape == "as_is":
        pass
    elif shape == "pythonpath_minus_repo":
        pp = env.get("PYTHONPATH")
        if pp is not None:
            scrubbed = scrub_pythonpath(pp)
            if scrubbed:
                env["PYTHONPATH"] = scrubbed
            else:
                env.pop("PYTHONPATH", None)
    elif shape == "jax_platforms_unset":
        env.pop("JAX_PLATFORMS", None)
    elif shape == "jax_platforms_tpu":
        env["JAX_PLATFORMS"] = "tpu"
    else:
        raise ValueError(f"unknown env shape {shape!r}; "
                         f"known: {ENV_SHAPES}")
    return env


# The child prints exactly one line we parse; the exception HEAD (first
# line, type included) is what past outages were diagnosed from.
_CHILD_CODE = r"""
import sys
require = sys.argv[1]
try:
    import jax
    d = jax.devices()
    plat = d[0].platform if d else "none"
    if require != "any" and plat != require:
        raise RuntimeError(f"platform {plat!r} != required {require!r}")
    print("PROBE_OK " + plat)
except BaseException as e:  # noqa: BLE001 — the head is the datum
    head = f"{type(e).__name__}: {e}".splitlines()[0][:300]
    print("PROBE_ERR " + head)
    sys.exit(1)
"""


def probe_shape(shape: str, timeout_s: float = 150.0, require: str = "tpu",
                base_env: dict | None = None) -> dict:
    """Probe ONE env shape in a fresh subprocess. Returns a record:
    ``{"shape", "ok", "platform"|None, "error"|None, "elapsed_s"}``.

    The child runs from a neutral cwd: ``python -c`` puts the cwd on
    ``sys.path`` at startup, and probing from the repo root would
    re-introduce the exact shadowing the ``pythonpath_minus_repo`` shape
    exists to remove.
    """
    env = build_env(shape, base_env)
    t0 = time.monotonic()
    record = {"shape": shape, "ok": False, "platform": None, "error": None}
    try:
        r = subprocess.run(
            [sys.executable, "-c", _CHILD_CODE, require], env=env,
            timeout=timeout_s, cwd=tempfile.gettempdir(),
            capture_output=True, text=True)
        out = (r.stdout or "").strip().splitlines()
        tagged = [ln for ln in out if ln.startswith("PROBE_")]
        if tagged and tagged[-1].startswith("PROBE_OK"):
            record["ok"] = True
            record["platform"] = tagged[-1].split(" ", 1)[1]
        elif tagged:
            record["error"] = tagged[-1].split(" ", 1)[1]
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or ["(no output)"]
            record["error"] = f"probe child died rc={r.returncode}: {tail[0][:300]}"
    except subprocess.TimeoutExpired:
        record["error"] = f"TimeoutExpired: probe hung > {timeout_s:.0f}s"
    except Exception as e:  # noqa: BLE001 — spawn failure is also a datum
        record["error"] = f"{type(e).__name__}: {e}"[:300]
    record["elapsed_s"] = round(time.monotonic() - t0, 2)
    return record


def probe_matrix(timeout_s: float = 150.0, require: str = "tpu",
                 base_env: dict | None = None,
                 shapes: tuple = ENV_SHAPES) -> tuple[str | None, list]:
    """Walk the matrix in order; stop at the first shape that answers
    with the required platform. Returns ``(winner_or_None, records)`` —
    ``records`` holds one entry per ATTEMPTED shape (the winner's
    included), each with its exception head on failure."""
    records = []
    for shape in shapes:
        rec = probe_shape(shape, timeout_s=timeout_s, require=require,
                          base_env=base_env)
        records.append(rec)
        if rec["ok"]:
            return shape, records
    return None, records


def env_shell_lines(shape: str, base_env: dict | None = None) -> list:
    """Shell lines a caller can ``eval`` to adopt the winning shape —
    how the shell watchers re-shape their own environment before running
    the artifact sweep."""
    base = dict(os.environ if base_env is None else base_env)
    target = build_env(shape, base)
    lines = [f"# backend_probe: env shape '{shape}'"]
    for var in ("PYTHONPATH", "JAX_PLATFORMS"):
        if var in target and target.get(var) != base.get(var):
            lines.append(f"export {var}={shlex.quote(target[var])}")
        elif var not in target and var in base:
            lines.append(f"unset {var}")
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="env-matrix backend probe (shared by bench.py and the "
                    "shell watchers)")
    p.add_argument("--require", default="tpu",
                   help="platform the winning shape must present "
                        "('tpu', 'cpu', or 'any')")
    p.add_argument("--timeout", type=float, default=150.0,
                   help="per-shape subprocess timeout (seconds)")
    p.add_argument("--json", default=None,
                   help="write {winner, matrix} to this path")
    p.add_argument("--emit-env", action="store_true",
                   help="on success, print eval-able shell lines adopting "
                        "the winning shape on STDOUT (diagnostics go to "
                        "stderr)")
    args = p.parse_args(argv)

    winner, records = probe_matrix(timeout_s=args.timeout,
                                   require=args.require)
    for rec in records:
        status = f"OK ({rec['platform']})" if rec["ok"] else rec["error"]
        print(f"probe[{rec['shape']}] {rec['elapsed_s']}s: {status}",
              file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"winner": winner, "require": args.require,
                       "matrix": records}, f, indent=1)
    if winner is None:
        print("backend_probe: every env shape failed (relay dead or "
              "unfixable env)", file=sys.stderr)
        return 1
    if args.emit_env:
        print("\n".join(env_shell_lines(winner)))
    else:
        print(winner)
    return 0


if __name__ == "__main__":
    sys.exit(main())
