"""Unified run telemetry: the per-step metrics stream every subsystem
shares.

The reference's observability surface is a rank-0 chrome trace plus
ad-hoc wall-clock prints (``train_ffns.py:129-141, :378-382``). This
repo had grown real instrumentation — collective counting
(``utils/hlo.py``), trace span analysis (``bench_trace.py``),
supervise's per-attempt JSONL (``runtime/failure.py``) — but each piece
was an island with its own format. This module is the common spine
(MegaScale's in-depth per-step observability stance): one
schema-versioned JSONL stream, one writer, one FLOP/peak accounting,
and a static ``StepReport`` that folds the compiler's own numbers
(``cost_analysis`` + collective counts + compiled memory) into a single
cross-checked object.

Design rules:

- **Non-blocking**: ``TelemetryWriter`` enqueues records (values may be
  live device scalars) and a daemon thread does the ``float()``
  readbacks + file appends — the training loop never blocks on
  telemetry I/O, and device readbacks happen at the logging cadence,
  never per step.
- **Schema-stable**: every record carries ``schema`` =
  ``SCHEMA_VERSION``; ``STEP_KEYS`` is the step-record contract and the
  schema-contract test (tests/test_telemetry.py) pins it — changing the
  key set without bumping the version fails the suite.
- **Crash-safe enough**: one JSON object per line, flushed per record;
  a torn final line is skipped by ``read_metrics``, never fatal.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

# v2 (round 8): adds the self-healing record kinds — "anomaly"
# (in-graph guardrail counters per compiled chunk) and "rollback"
# (supervisor ladder rungs) — with their own pinned key contracts.
# v3 (round 9): adds the "decode" kind — the serving engine's per-cadence
# throughput/occupancy/KV-pool record (decode/engine.py) with its own
# pinned required-key contract (DECODE_REQUIRED).
# v4 (round 10): adds the "request" kind — one record per serving
# request lifecycle transition (admitted / preempted / retried /
# quarantined / completed / rejected / expired, decode/engine.py) with
# its own pinned required-key contract (REQUEST_REQUIRED).
# v5 (round 11): adds the "span" kind — per-request lifecycle spans
# (queued / prefill / replay / decode / quarantine / preempt_gap,
# runtime/tracing.py) with pinned SPAN_REQUIRED — and grows the
# "decode" contract with the KV-pool internals (free-block watermarks,
# block churn, fragmentation, per-dtype stored-KV bytes).
# v6 (round 12): grows the "decode" contract with the speculative-
# decoding trio — cumulative ``drafted_tokens`` / ``accepted_tokens``
# and the derived ``accept_rate`` (decode/engine.py verify dispatches;
# null-rate when nothing was drafted) — so a serving stream shows
# tokens-per-step > 1 as measured data, not inference.
# v7 (round 13): grows the "decode" contract with the shared-prefix
# set — cumulative ``prefix_hit_blocks`` (radix-cache hit blocks
# mapped at admission) / ``prefill_tokens_saved`` (prompt tokens those
# hits skipped) / ``cow_copies`` (copy-on-write privatizations; 0 is
# the write-barrier invariant) and the instantaneous
# ``shared_blocks`` (physical blocks named by >= 2 live tables) — the
# measured form of the prefix cache's capacity/throughput claim
# (decode/prefix.py, DESIGN.md section 19).
# v8 (round 14): adds the "router" kind — one record per fleet-router
# decision (routed / handoff / migrated / shed, decode/fleet.py) with
# its own pinned required-key contract (ROUTER_REQUIRED); source and
# target carry engine ids (null where the decision has none — a routed
# request has no source engine, a shed request no target).
# v9 (round 15): the serving-SLO measurement layer. (1) completed
# "request" records additionally pin ``latency_s`` AND ``ttft_s``
# (time to first token; null when the first token predates a
# crash-resume — the decomposition is then visibly unreconstructable,
# never invented). (2) the "router" contract pins ``policy`` — WHY the
# router placed a request where it did (session / prefix /
# least_loaded / spill on routed records; null on decisions that have
# no placement policy), with the candidate scores the decision saw
# riding as an extra key — and handoff/migrated records carry the
# migration-stall instrumentation (``blocks`` / ``bytes`` /
# ``duration_s`` measured around export_sequence/import_sequence).
# (3) adds the "fleet" kind — one per-round fleet health record
# (per-engine waiting/active/free-blocks/utilization + a
# load-imbalance scalar, decode/fleet.py) with its own pinned
# required-key contract (FLEET_REQUIRED).
# v10 (round 16): the process-boundary transport layer. "router"
# handoff/migrated records now PIN the move instrumentation —
# ``blocks`` / ``bytes`` / ``duration_s`` (extras since v9) plus the
# new ``transport`` attribution object ({mode: inproc|wire|replay,
# bytes: the SERIALIZED npz size — what actually crosses the boundary,
# never an in-memory nbytes sum; crc_verify_s: wire integrity-check
# wall clock, null off the wire; retries: wire rejections this uid
# survived before the move}) — enforced conditionally by
# validate_record (the REQUEST_COMPLETED_REQUIRED pattern: routed/shed
# decisions move nothing, so pinning kind-wide would force meaningless
# nulls). A rejected wire doc (CRC mismatch / torn npz / version skew,
# runtime/wire.py) emits a ``wire_rejected`` router record whose
# ``reason`` carries the one-line rejection.
# v11 (round 17): the live-weight hot-swap layer (DESIGN.md
# section 23). (1) adds the "deploy" kind — one record per rolling-
# deploy lifecycle event (started / engine_swapped / completed /
# rolled_back, decode/fleet.py) pinning the version pair
# (``from_version``/``to_version``); ``engine_swapped`` additionally
# pins ``engine``, ``completed`` and ``rolled_back`` pin
# ``duration_s``, and ``rolled_back`` pins ``reason`` (the one-line
# named cause + the ``latest_verified_step`` fallback) — enforced
# conditionally per event (the REQUEST_COMPLETED_REQUIRED pattern).
# (2) every "request" record grows ``weights_version`` — the uid's
# weights-version pin (null before first admission), the per-version
# attribution mixed-version fleet reports dedup completions by.
# v12 (round 18): the fleet trace spine (DESIGN.md section 24).
# Every per-request record kind — "request", "span", "router" — PINS
# ``trace_id``: the fleet-unique causal identity minted ONCE at
# admission (the router under a fleet, the engine itself single-
# engine) and carried through replay, preemption, quarantine,
# migration (handoff doc v5), crash-resume (snapshot v7), and version
# pins — so ``report --trace UID`` stitches one cross-engine,
# cross-process waterfall by the id itself instead of uid heuristics.
# Null only where the record concerns no traceable request (the
# anonymous rejected uid -1). "deploy" records pin the key too (the
# issue's uniform-envelope stance) with a null value — a deploy event
# concerns the fleet, not one request. Transport cost attribution
# rides the existing "event" kind (``transport_stats``: per-worker
# per-op RPC call/handle durations, decode/fleet.py) and the live
# status doc (STATUS_FILENAME) is a wire-published JSON document, not
# a stream record.
# v13 (round 19): the trace-driven workload plane (DESIGN.md
# section 25). (1) every "request" AND "span" record pins ``tenant``
# — the request's tenant tag (null single-tenant; null on the
# anonymous rejected uid -1), minted at submit and carried through
# replay, preemption, migration (handoff doc v6), and crash-resume
# (snapshot v8) exactly like ``trace_id`` — the per-tenant
# attribution ``report``'s workload block and the per-tenant SLO
# slice fold. (2) adds the "workload" kind — one record per replay
# interval from the workload driver (``decode/workload_driver.py``):
# ``trace`` pins the trace identity ({id, version} — the
# runtime/workload.py header), ``offered``/``admitted`` the
# PER-INTERVAL submission counts (offered - admitted = sheds this
# interval), and ``tenants`` the CUMULATIVE per-tenant
# offered/completed/shed counts (monotonic across the run, so the
# final record is the totals and the sums reconcile against the
# per-request records — pinned by test).
# v14 (round 20): the closed control loop (DESIGN.md section 26).
# (1) adds the "autoscale" kind — one record per decode-tier scale
# decision from the between-rounds controller
# (``decode/autoscale.py``): ``step`` the router's round clock,
# ``event`` one of AUTOSCALE_EVENTS (scale_up / scale_down / held),
# ``reason`` the named trigger (queue_pressure / queue_idle /
# below_min_floor / cooldown), ``engines`` the alive decode count
# AFTER the decision, ``target_engines`` what the controller wants.
# ``scale_up`` conditionally pins ``engine`` (the spawned id);
# ``scale_down`` pins ``engine`` + ``drained`` (the zero-shed drain's
# migrated-resident count) — the DEPLOY_EVENT_REQUIRED pattern.
# (2) adds the "qos" kind — one record per tenant-QoS scheduling
# decision (``decode/engine.py``): ``step`` the engine step, ``event``
# one of QOS_EVENTS, ``tenant`` the tenant acted on (null
# single-tenant). Per-event pins: ``predicted_miss_shed`` carries
# ``uid``/``eta_steps``/``deadline_steps`` (the admission-time ETA
# that blew the deadline), ``budget_deferred`` carries
# ``uid``/``resident_tokens``/``token_budget`` (the budget that
# deferred the admit), ``wfq_pick`` carries ``uid``/``virtual_time``
# (the virtual-time value that won a NON-head-of-line admit).
# Every pinned value is derived from the deterministic round/step
# clocks and served-token counters — never the wall clock — so qos
# and autoscale decision streams replay identically with the tokens.
# v15 (round 21): the watchtower plane (DESIGN.md section 27). Adds
# the "alert" kind — one record per streaming-detector lifecycle
# transition (``runtime/watch.py``, ticked on the fleet round clock):
# ``step`` the router's round clock at the transition, ``event`` one
# of ALERT_EVENTS (fired / resolved), ``detector`` the detector that
# transitioned (ALERT_DETECTORS), ``severity`` its page/warn class,
# ``window`` the [start_round, end_round) round window that justified
# the transition. Per-detector conditional pins
# (ALERT_DETECTOR_REQUIRED, the QOS_EVENT_REQUIRED pattern): each
# alert carries exactly the numbers that justified it — the fast/slow
# burn rates with the violation/completion counts behind them, the
# queue depth vs its threshold, the imbalance reading, the stalled
# round count, the incident count, the drifted percentile vs its
# declared baseline. Every pinned value is ROUND-denominated (counts
# and round arithmetic only — wall clock lives in the unpinned ``t``
# envelope and in the latency_drift detector, which only runs against
# an explicitly declared wall-clock baseline), so the alert history of
# a virtual-clock replay is byte-identical across replays and
# transports, exactly like the autoscale/qos decision streams.
# v16 (round 22): the network boundary (DESIGN.md section 28). The
# router-record vocabulary gains the ``reconnected`` event — one
# record per transport reconnect (the liveness ladder's non-death
# verdict: a dropped connection that healed under bounded backoff and
# sequence-numbered replay, with ``attempts`` / ``gap_s`` / the
# replayed op list as extras and the anonymous uid -1 — a reconnect
# belongs to the link, not a request). ``transport.mode`` on move
# records gains "tcp" (a handoff streamed over the length-prefixed
# TCP side channel, CRC-verified at the target). ``migrated`` records
# now ALSO pin ``ship_s`` (the async-migration ship window: export to
# commit wall clock; null on a sync or replay migration — nothing
# overlapped) and ``catchup_tokens`` (tokens teacher-forced on the
# target after arrival: the delta emitted during an async ship
# window, the full replay length on a replay-migration, 0 on a sync
# handoff) — the numbers behind the "a handoff costs the moving
# request one replay, never a source-engine stall" contract.
# v17 (round 23): the KV memory hierarchy (DESIGN.md section 29).
# Decode records gain the ``kv_spill`` key family —
# ``spilled_blocks`` / ``spill_bytes`` / ``restores`` /
# ``restore_tokens_saved`` cumulative (snapshot-persisted, monotonic
# across crash-resume like the churn trio; the BYTES are not — the
# host tier dies with the process and resume rebuilds via replay),
# ``restore_stall_s`` the cumulative wall clock spent inside the
# donated implant path (the stall budget the restore-per-step cap
# bounds), ``partial_hits`` cumulative sub-block CoW shares, and
# ``host_tier_utilization`` the instantaneous spill-tier occupancy
# fraction (0.0 when the tier is off). All keys are pinned even with
# the tier disabled (zeros) — the uniform-envelope stance.
SCHEMA_VERSION = 17

METRICS_FILENAME = "metrics.jsonl"

# the atomic fleet status document the router publishes each round
# (throttled; decode/fleet.py via wire.publish_json) — defined here so
# the router, the `fleetstat` entry point, and `report --follow` share
# one name without the readers importing the (jax-heavy) fleet module
STATUS_FILENAME = "fleet_status.json"

# router-side dead-host postmortem dumps (decode/fleet.py publishes
# one per declared-dead engine; report --postmortem discovers them by
# this prefix next to the router's metrics stream)
ROUTER_POSTMORTEM_PREFIX = "router_postmortem_"

# the flight-recorder dump the decode engine publishes next to the
# metrics stream (decode/engine.py writes it; report --postmortem
# discovers it) — defined here so the writer and the reader share one
# name without the report tool importing the (jax-heavy) engine
FLIGHT_FILENAME = "flight_recorder.json"

# The step-record contract: every "step" record carries exactly these
# keys (values may be null when a source can't measure them — a CPU run
# has no HBM stats, the FFN family has no scalar loss). Adding/removing
# a key REQUIRES a SCHEMA_VERSION bump; tests/test_telemetry.py pins
# the (version, key-set) pair.
STEP_KEYS = (
    "schema", "kind", "t", "step", "strategy", "loss", "grad_norm",
    "tokens_per_sec", "step_time_s", "mfu", "hbm_high_water_bytes",
)

# The anomaly-record contract: keys every "anomaly" record MUST carry
# (it may carry more — e.g. the [a, b] step window). Same version-bump
# discipline as STEP_KEYS.
ANOMALY_REQUIRED = ("step", "skipped", "loss_scale")

# The rollback-record contract: "rung" names the ladder rung taken
# (rollback / restart), "resume_step" the verified checkpoint it
# rewound to (null when none existed yet).
ROLLBACK_REQUIRED = ("rung", "resume_step")

# The decode-record contract: keys every "decode" record MUST carry
# (``tokens_per_sec`` may be null on a record with no throughput delta
# — the null stance of STEP_KEYS). ``batch_occupancy`` is active slots
# over max slots; ``kv_pool_utilization`` is NON-RECLAIMABLE
# non-scratch blocks over usable blocks (decode/engine.py) — refs-0
# prefix-cached blocks count as free since v7 (admission reclaims them
# on demand; the extra ``prefix_evictable_blocks`` key reconciles this
# reading with the literal free-list keys below). Same version-bump
# discipline as STEP_KEYS.
#
# v5 KV-pool internals (decode/engine.py ``telemetry_record``):
# ``free_blocks`` the instantaneous free count,
# ``free_blocks_low_water``/``free_blocks_high_water`` the min/max free
# count since the previous decode record (the pressure envelope a
# cadence record would otherwise alias over), ``block_allocs`` /
# ``block_frees`` / ``block_scrubs`` cumulative churn counters
# (snapshot-persisted, so they stay monotonic across crash-resume),
# ``kv_fragmentation`` the unused fraction of RESERVED block capacity
# (1 - live tokens / (live blocks * block_size); reserve-on-admit means
# a young sequence holds its whole reservation), and
# ``kv_bytes_stored`` the live-token KV bytes at the engine's dtype
# (``paged.kv_bytes_per_token`` — the roofline's kv_bytes numerator).
#
# v6 speculation keys (decode/engine.py verify dispatches):
# ``drafted_tokens`` / ``accepted_tokens`` cumulative (snapshot-
# persisted, monotonic across crash-resume like the churn trio) and
# ``accept_rate`` = accepted / drafted (null when nothing drafted —
# speculation off, or no drafter hits yet). Both count the LIVE
# n-gram drafter only: replay teacher-forced tokens are accepted by
# construction, so counting them would inflate accept_rate toward
# 1.0 on exactly the churn-heavy runs where the drafter's real score
# matters (and double-count across a crash-resume).
# v7 shared-prefix keys (decode/engine.py ``telemetry_record``):
# ``prefix_hit_blocks`` / ``prefill_tokens_saved`` cumulative
# (snapshot-persisted, monotonic across crash-resume like the churn
# trio), ``shared_blocks`` the instantaneous >= 2-live-table block
# count, ``cow_copies`` cumulative copy-on-write privatizations (the
# tests pin 0 in steady state — no scheduler write ever aims at a
# shared block).
DECODE_REQUIRED = ("step", "tokens_per_sec", "batch_occupancy",
                   "kv_pool_utilization", "free_blocks",
                   "free_blocks_low_water", "free_blocks_high_water",
                   "block_allocs", "block_frees", "block_scrubs",
                   "kv_fragmentation", "kv_bytes_stored",
                   "drafted_tokens", "accepted_tokens", "accept_rate",
                   "prefix_hit_blocks", "prefill_tokens_saved",
                   "shared_blocks", "cow_copies",
                   "spilled_blocks", "spill_bytes", "restores",
                   "restore_tokens_saved", "restore_stall_s",
                   "partial_hits", "host_tier_utilization")

# The request-record contract: one record per serving-request lifecycle
# transition (``decode/engine.py``). ``step`` is the GLOBAL engine step
# (snapshot ``step_base`` + in-process steps — stable across
# crash-resume), ``uid`` the request's sequence uid, ``event`` the
# transition (admitted / preempted / retried / quarantined / completed
# / rejected / expired), ``reason`` why (null where the transition
# needs none — e.g. admitted). Completed records additionally PIN
# (since v9) ``latency_s`` (submit -> finish wall clock; the report
# tool's per-request latency percentiles read it) and ``ttft_s``
# (submit -> first emitted token; null when the first token predates a
# crash-resume, in which case the decomposition is honestly
# unreconstructable). Same version-bump discipline as STEP_KEYS.
# v11: ``weights_version`` — the uid's weights-version pin (null
# before first admission pins it; the anonymous rejected uid -1 is
# always null) — so a mixed-version fleet's per-version completion
# counts are recorded data, not inference.
# v12: ``trace_id`` — the request's fleet-unique causal identity
# (minted once at admission, carried through every move; null only on
# the anonymous rejected uid -1).
# v13: ``tenant`` — the request's tenant tag (null single-tenant and
# on the anonymous rejected uid -1), set at submit and carried like
# ``trace_id`` — the per-tenant accounting key the workload plane
# slices on.
REQUEST_REQUIRED = ("step", "uid", "event", "reason",
                    "weights_version", "trace_id", "tenant")

# the extra keys a COMPLETED request record must also carry (v9) —
# enforced conditionally by validate_record (other events never
# measure a completion, so pinning them kind-wide would force
# meaningless nulls onto every admitted/preempted/... record)
REQUEST_COMPLETED_REQUIRED = ("latency_s", "ttft_s")

# The span-record contract (``runtime/tracing.py``): one record per
# CLOSED per-request lifecycle span. ``span`` names the phase (queued /
# prefill / replay / decode / quarantine / preempt_gap), ``step`` the
# GLOBAL engine step the span closed at, ``start_step`` where it
# opened, ``duration_s`` its wall-clock length. Spans tile a request's
# life (each opens exactly when its predecessor closes, the first at
# submit time), so a completed request's span durations sum to its
# ``latency_s`` — the reconciliation ``report``'s waterfall view pins.
# Replayed spans after a snapshot-resume restart are deduplicated by
# ``(uid, span, start_step, step)``, the request-record dedup stance.
# v12: ``trace_id`` — the owning request's causal identity (the
# stitch key of the cross-process trace waterfall).
# v13: ``tenant`` — the owning request's tenant tag (null
# single-tenant), so per-tenant ITL percentiles come straight off the
# decode-segment spans.
# Same version-bump discipline as STEP_KEYS.
SPAN_REQUIRED = ("step", "uid", "span", "start_step", "duration_s",
                 "trace_id", "tenant")

# The span vocabulary (runtime/tracing.py callers use these; report
# renders any name, so a new phase is additive)
SPAN_NAMES = ("queued", "prefill", "replay", "decode", "quarantine",
              "preempt_gap")

# The router-record contract (``decode/fleet.py``): one record per
# fleet-router decision. ``step`` is the ROUTER's step clock (fleet
# scheduling rounds — each engine keeps its own engine-step clock),
# ``uid`` the fleet-global request uid, ``event`` the decision
# (routed / handoff / migrated / shed), ``source``/``target`` the
# engine ids involved — null where the decision has none: a freshly
# routed request has no source engine, a shed request no target.
# ``reason`` rides as an extra key (least_loaded / session / prefix /
# pool_pressure / engine_killed / queue_full).
#
# v9 decision attribution: ``policy`` is pinned — the placement policy
# a ``routed`` decision took (one of ROUTER_POLICIES; null on events
# that place nothing: handoff / migrated / shed) — and routed records
# carry ``candidates`` as an extra (the per-engine scores the decision
# saw: warm-block depth, queue depth, active slots, pool utilization).
# ``handoff``/``migrated`` records carry the migration-stall
# instrumentation as extras: ``blocks`` / ``bytes`` shipped and
# ``duration_s`` measured around export_sequence/import_sequence
# (0 blocks/bytes on a replay-migration off a dead engine's snapshot —
# nothing ships but the token history). Same version-bump discipline
# as STEP_KEYS.
# v12: ``trace_id`` — the moved/placed request's causal identity.
ROUTER_REQUIRED = ("step", "uid", "event", "source", "target", "policy",
                   "trace_id")

# The router decision vocabulary (decode/fleet.py emits these; report
# renders any name, so a new decision kind is additive).
# ``wire_rejected`` (v10): a handoff wire doc failed integrity checks
# (reason = the one-line WireError) and the request was replay-rerouted
# ``reconnected`` (v16): a dropped worker connection healed under the
# reconnect ladder instead of becoming a dead-host declaration
ROUTER_EVENTS = ("routed", "handoff", "migrated", "shed",
                 "wire_rejected", "reconnected")

# the extra keys a HANDOFF or MIGRATED router record must also carry
# (v10) — the migration-stall + transport attribution, enforced
# conditionally by validate_record (other router events move nothing)
ROUTER_MOVE_REQUIRED = ("blocks", "bytes", "duration_s", "transport")

# the extra keys a MIGRATED record must ALSO carry (v16) — the async-
# migration contract: how long the snapshot shipped while the source
# kept decoding (``ship_s``, null when nothing overlapped) and how
# many tokens the target teacher-forced to catch up
# (``catchup_tokens``) — enforced conditionally by validate_record
ROUTER_MIGRATED_REQUIRED = ("ship_s", "catchup_tokens")

# The routed-record policy vocabulary: session / prefix affinity,
# least-loaded admission, or spill (the probed target shed and the
# request landed on the next engine by load — affinity lost)
ROUTER_POLICIES = ("session", "prefix", "least_loaded", "spill")

# The fleet-health-record contract (``decode/fleet.py``): one record
# per fleet scheduling round from the router's own writer. ``step`` is
# the router's round clock, ``engines`` maps engine id -> per-engine
# health ({alive, role, waiting, active, free_blocks, utilization};
# dead engines report {alive: false}), ``load_imbalance`` is the
# (max - min) / max load spread over alive decode engines (load =
# active + waiting; 0.0 = balanced or idle, -> 1.0 = one engine holds
# everything). Same version-bump discipline as STEP_KEYS.
FLEET_REQUIRED = ("step", "engines", "load_imbalance")

# The deploy-record contract (``decode/fleet.py`` rolling_deploy,
# v11): one record per rolling-deploy lifecycle event. ``step`` is the
# router's round clock, ``event`` one of DEPLOY_EVENTS,
# ``from_version``/``to_version`` the weights-version pair (the
# checkpoint step being deployed; ``to_version`` may be null when no
# checkpoint was ever published). Per-event conditional pins (the
# REQUEST_COMPLETED_REQUIRED pattern, enforced by validate_record):
# ``engine_swapped`` carries ``engine``; ``completed`` and
# ``rolled_back`` carry ``duration_s``; ``rolled_back`` carries
# ``reason`` — the ONE-line named cause naming the CRC rejection or
# mid-roll failure plus the latest_verified_step fallback. Same
# version-bump discipline as STEP_KEYS.
# v12: ``trace_id`` pinned for the uniform per-kind envelope — always
# null (a deploy event concerns the fleet, not one request; the
# per-request deploy-drain moves carry theirs on ``migrated`` router
# records).
DEPLOY_REQUIRED = ("step", "event", "from_version", "to_version",
                   "trace_id")

# the deploy lifecycle vocabulary (report renders any name; a new
# event is additive)
DEPLOY_EVENTS = ("started", "engine_swapped", "completed",
                 "rolled_back")

# per-event conditional pins for deploy records (validate_record)
DEPLOY_EVENT_REQUIRED = {
    "engine_swapped": ("engine",),
    "completed": ("duration_s",),
    "rolled_back": ("duration_s", "reason"),
}

# The workload-record contract (``decode/workload_driver.py``, v13):
# one record per trace-replay interval. ``step`` is the driver's
# virtual round clock at emit time, ``trace`` the trace identity
# ({id, version} — the runtime/workload.py header's stable hash, so
# two replays of one trace pin the same identity), ``offered`` /
# ``admitted`` the PER-INTERVAL submission counts (offered - admitted
# = sheds this interval), ``tenants`` the CUMULATIVE per-tenant
# {offered, completed, shed} counts (monotonic — the final record is
# the run's totals, and the per-tenant sums must reconcile with the
# request records' per-tenant counts). Same version-bump discipline
# as STEP_KEYS.
WORKLOAD_REQUIRED = ("step", "trace", "offered", "admitted",
                     "tenants")

# The autoscale-record contract (``decode/autoscale.py``, v14): one
# record per decode-tier scale decision. ``step`` is the router's
# round clock, ``event`` one of AUTOSCALE_EVENTS, ``reason`` the named
# trigger, ``engines`` the alive decode-engine count AFTER the
# decision, ``target_engines`` the controller's target. Deterministic
# by construction (round clock + queue-depth counters — wall clock
# only in the unpinned ``t`` envelope and extras like ``spawn_s``), so
# the decision stream replays identically with the tokens. Same
# version-bump discipline as STEP_KEYS.
AUTOSCALE_REQUIRED = ("step", "event", "reason", "engines",
                      "target_engines")

# the autoscale decision vocabulary (report renders any name; a new
# event is additive)
AUTOSCALE_EVENTS = ("scale_up", "scale_down", "held")

# per-event conditional pins for autoscale records (validate_record;
# the DEPLOY_EVENT_REQUIRED pattern): only a scale names the engine it
# spawned/drained, and only a scale-down measures a drain
AUTOSCALE_EVENT_REQUIRED = {
    "scale_up": ("engine",),
    "scale_down": ("engine", "drained"),
}

# The qos-record contract (``decode/engine.py``, v14): one record per
# tenant-QoS scheduling decision. ``step`` is the GLOBAL engine step,
# ``event`` one of QOS_EVENTS, ``tenant`` the tenant acted on (null
# single-tenant). Same version-bump discipline as STEP_KEYS.
QOS_REQUIRED = ("step", "event", "tenant")

# the qos decision vocabulary (report renders any name; a new event is
# additive)
QOS_EVENTS = ("predicted_miss_shed", "budget_deferred", "wfq_pick")

# per-event conditional pins for qos records (validate_record): each
# decision pins exactly the numbers that justified it — the ETA that
# blew the deadline, the budget that deferred, the virtual time that
# won a non-FIFO admit
QOS_EVENT_REQUIRED = {
    "predicted_miss_shed": ("uid", "eta_steps", "deadline_steps"),
    "budget_deferred": ("uid", "resident_tokens", "token_budget"),
    "wfq_pick": ("uid", "virtual_time"),
}

# The alert-record contract (``runtime/watch.py``, v15): one record
# per detector lifecycle transition. ``step`` is the router's round
# clock at the transition, ``event`` one of ALERT_EVENTS, ``detector``
# the detector name, ``severity`` its class, ``window`` the
# [start_round, end_round) window the justifying numbers were folded
# over. Deterministic by construction (round clock + integer counters
# — wall clock only in the unpinned ``t`` envelope), so the alert
# history replays identically with the tokens; the one wall-clock
# detector (latency_drift) only runs against an explicitly declared
# baseline. Same version-bump discipline as STEP_KEYS.
ALERT_REQUIRED = ("step", "event", "detector", "severity", "window")

# the alert lifecycle vocabulary: a detector FIRES once when its
# windows cross threshold and RESOLVES once when they recover — never
# a per-round repeat (report renders any name; a new event is
# additive)
ALERT_EVENTS = ("fired", "resolved")

# the detector vocabulary (runtime/watch.py; report renders any name,
# so a new detector is additive)
ALERT_DETECTORS = ("burn_rate", "queue_growth", "imbalance",
                   "collapse", "incident_rate", "latency_drift")

# the severity vocabulary: "page" = goodput is burning NOW (SLO
# budget, dead capacity, stalled tokens), "warn" = trending toward it
ALERT_SEVERITIES = ("warn", "page")

# per-detector conditional pins for alert records (validate_record;
# the QOS_EVENT_REQUIRED pattern): every transition pins exactly the
# numbers that justified it, on BOTH fired and resolved records (the
# resolved record shows the recovered reading)
ALERT_DETECTOR_REQUIRED = {
    "burn_rate": ("burn_fast", "burn_slow", "violations",
                  "completions"),
    "queue_growth": ("waiting", "threshold"),
    "imbalance": ("imbalance", "threshold"),
    "collapse": ("stalled_rounds", "live"),
    "incident_rate": ("incidents", "threshold"),
    "latency_drift": ("p95_s", "baseline_s", "metric"),
}

# Non-step record kinds the stream also carries: run headers ("meta"),
# recovery/chaos/checkpoint events ("event"), bench measurement rows
# ("bench" — bench.py's per-measurement plumbing rides the same
# writer), the self-healing kinds ("anomaly", "rollback"), and the
# serving engine's "decode" cadence + "request" lifecycle + "span"
# per-request phase records.
RECORD_KINDS = ("step", "meta", "event", "bench", "anomaly", "rollback",
                "decode", "request", "span", "router", "fleet",
                "deploy", "workload", "autoscale", "qos", "alert")

# kind -> the pinned required-key set validate_record enforces (step
# records additionally pin their FULL key set via STEP_KEYS)
REQUIRED_KEYS = {
    "step": STEP_KEYS,
    "anomaly": ANOMALY_REQUIRED,
    "rollback": ROLLBACK_REQUIRED,
    "decode": DECODE_REQUIRED,
    "request": REQUEST_REQUIRED,
    "span": SPAN_REQUIRED,
    "router": ROUTER_REQUIRED,
    "fleet": FLEET_REQUIRED,
    "deploy": DEPLOY_REQUIRED,
    "workload": WORKLOAD_REQUIRED,
    "autoscale": AUTOSCALE_REQUIRED,
    "qos": QOS_REQUIRED,
    "alert": ALERT_REQUIRED,
}

# bf16 peak matmul FLOP/s by chip generation (public spec sheets; the
# default f32 jnp matmul on TPU lowers to single-pass bf16 MXU ops, so
# bf16 peak is the honest MFU denominator — bench.py's convention, now
# shared). Unknown kinds (CPU, new chips) return None: an honest null
# MFU beats a guessed one in a persistent artifact.
PEAK_BF16_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v5": 459e12,
    "v6 lite": 918e12, "v6e": 918e12,
}


def peak_flops(device_kind: str) -> float | None:
    """bf16 peak FLOP/s for a ``device_kind`` string, or None when the
    chip generation is unrecognized (CPU hosts, future TPUs)."""
    kind = (device_kind or "").lower()
    for key in sorted(PEAK_BF16_FLOPS, key=len, reverse=True):
        if key in kind:
            return PEAK_BF16_FLOPS[key]
    return None


def ffn_model_flops(tokens: int, model_size: int, n_layers: int,
                    ffn_dim: int | None = None) -> int:
    """Hand-counted model matmul FLOPs of ONE training step of the
    reference FFN stack: fwd 2 matmuls = 4Tdf, bwd 4 matmuls = 8Tdf per
    layer (bench.py's 12Tdf convention — the recompute policy's extra
    executed matmul is HFU, never MFU)."""
    f = 4 * model_size if ffn_dim is None else ffn_dim
    return 12 * tokens * model_size * f * n_layers


def transformer_model_flops(tokens: int, model_size: int, n_layers: int,
                            seq_len: int) -> int:
    """Per-step model FLOPs of the pre-LN transformer family (bench.py's
    families convention): attention projections 8Td^2, scores+AV 2T^2d
    (causal halving is applied by bench_attention's convention only for
    its causal benchmark — the trainer accounting here matches
    bench.py's families section), FFN 16Td^2; fwd 1x + bwd 2x."""
    b = tokens // seq_len
    per_layer = (8 * seq_len * model_size ** 2
                 + 2 * seq_len ** 2 * model_size
                 + 16 * model_size ** 2 * seq_len)
    return 3 * b * n_layers * per_layer


def lm_model_flops(tokens: int, model_size: int, n_layers: int,
                   seq_len: int, vocab: int) -> int:
    """Transformer blocks + the tied LM head (2TdV, fwd 1x + bwd 2x)."""
    return (transformer_model_flops(tokens, model_size, n_layers, seq_len)
            + 3 * 2 * tokens * model_size * vocab)


def hand_flops_per_step(family: str, *, tokens: int, model_size: int,
                        n_layers: int, seq_len: int = 0,
                        vocab: int = 0) -> int | None:
    """The hand FLOP count for a CLI model family, or None for families
    without an agreed accounting yet (MoE variants: routed FLOPs depend
    on capacity/dropping, so a static count would be dishonest)."""
    if family == "ffn":
        return ffn_model_flops(tokens, model_size, n_layers)
    if family == "transformer" and seq_len:
        return transformer_model_flops(tokens, model_size, n_layers,
                                       seq_len)
    if family == "lm" and seq_len and vocab:
        return lm_model_flops(tokens, model_size, n_layers, seq_len, vocab)
    return None


def hbm_high_water() -> dict[str, int] | None:
    """Per-device HBM high-water (``peak_bytes_in_use``) from
    ``memory_stats()``, or None where the backend doesn't track it
    (CPU). Keys are device ids as strings (JSON object keys)."""
    import jax
    stats = {}
    for d in jax.devices():
        try:
            m = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-backend API surface
            m = None
        if not m:
            continue
        peak = m.get("peak_bytes_in_use", m.get("bytes_in_use"))
        if peak is not None:
            stats[str(d.id)] = int(peak)
    return stats or None


def _json_default(o):
    """Last-resort JSON coercion for event payloads from other
    subsystems: numpy scalars/arrays become numbers/lists, anything
    else its repr — a stringly-typed field beats a dropped record."""
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return repr(o)


def _scalar(v) -> float | None:
    """Host float of a (possibly device) scalar — the readback the
    writer thread performs OFF the training thread."""
    if v is None:
        return None
    try:
        # NaN/Inf pass through deliberately: a poisoned loss is exactly
        # what a chaos-run record should show (json round-trips them)
        return float(np.asarray(v))
    except (TypeError, ValueError):
        return None


class TelemetryWriter:
    """Non-blocking JSONL metrics writer.

    ``step()``/``event()``/``bench()`` enqueue and return immediately;
    a daemon thread performs device readbacks (``float()`` of any jax
    scalar in the record) and the file append. ``close()`` drains the
    queue — records enqueued before close are never lost (the flush is
    the batched host sync, at call sites that already sync).

    One writer owns one ``metrics.jsonl``; a fresh writer APPENDS (a
    supervised run restarts the process mid-stream — the record stream
    spans attempts, which is exactly what the report tool wants).
    """

    def __init__(self, metrics_dir: str, meta: dict | None = None,
                 filename: str = METRICS_FILENAME):
        os.makedirs(metrics_dir, exist_ok=True)
        self.path = os.path.join(metrics_dir, filename)
        self._q: queue.Queue = queue.Queue()
        self._err: str | None = None
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()
        # a run that dies mid-stream (supervise exhausting its restarts,
        # an uncaught trainer error) must not lose its tail — the final
        # fault evidence is exactly what the report tool folds. close()
        # is idempotent, so the normal explicit close stays cheap.
        import atexit
        atexit.register(self.close)
        if meta is not None:
            self.meta(meta)

    # -- producers (training thread; never block on I/O or readbacks) --

    def step(self, step: int, *, strategy=None, loss=None, grad_norm=None,
             step_time_s=None, tokens=None, model_flops=None,
             peak=None, hbm=None, t=None) -> None:
        """Enqueue one per-logged-step record. ``strategy`` names the
        trainer the step belongs to (multi-method CLI runs share one
        stream); ``loss``/``grad_norm`` may be live device scalars (read
        back on the writer thread); ``tokens``/``model_flops`` are
        per-step counts from which throughput and MFU are derived;
        ``hbm`` is a pre-collected ``hbm_high_water()`` dict (collect it
        at the logging cadence — it is itself a host call)."""
        self._put({"kind": "step", "t": time.time() if t is None else t,
                   "step": int(step), "strategy": strategy, "loss": loss,
                   "grad_norm": grad_norm,
                   "step_time_s": step_time_s, "_tokens": tokens,
                   "_model_flops": model_flops, "_peak": peak,
                   "hbm_high_water_bytes": hbm})

    def event(self, record: dict) -> None:
        """Enqueue a recovery/chaos/checkpoint event record (the
        supervise/checkpoint ``on_event`` stream, verbatim plus the
        schema envelope)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "event"
        self._put(rec)

    def bench(self, record: dict) -> None:
        """Enqueue one bench measurement row (bench.py's per-measurement
        plumbing — metric name, value, unit, shape)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "bench"
        self._put(rec)

    def anomaly(self, record: dict) -> None:
        """Enqueue one in-graph guardrail anomaly record: the per-chunk
        skip/overflow counters + live loss scale
        (``runtime/guardrails.py``; ``ANOMALY_REQUIRED`` contract)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "anomaly"
        self._put(rec)

    def rollback(self, record: dict) -> None:
        """Enqueue one supervisor ladder record (a rollback or restart
        rung, ``runtime/failure.py``; ``ROLLBACK_REQUIRED`` contract)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec.setdefault("resume_step", None)
        rec["kind"] = "rollback"
        self._put(rec)

    def decode(self, record: dict) -> None:
        """Enqueue one serving-engine cadence record: tokens/s, batch
        occupancy, KV-pool utilization (``decode/engine.py``;
        ``DECODE_REQUIRED`` contract)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "decode"
        self._put(rec)

    def request(self, record: dict) -> None:
        """Enqueue one serving-request lifecycle record: admitted /
        preempted / retried / quarantined / completed / rejected /
        expired (``decode/engine.py``; ``REQUEST_REQUIRED`` contract)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec.setdefault("reason", None)
        rec.setdefault("weights_version", None)
        rec.setdefault("trace_id", None)
        rec.setdefault("tenant", None)
        rec["kind"] = "request"
        self._put(rec)

    def deploy(self, record: dict) -> None:
        """Enqueue one rolling-deploy lifecycle record: started /
        engine_swapped / completed / rolled_back
        (``decode/fleet.py``; ``DEPLOY_REQUIRED`` contract plus the
        per-event conditional pins)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec.setdefault("trace_id", None)
        rec["kind"] = "deploy"
        self._put(rec)

    def router(self, record: dict) -> None:
        """Enqueue one fleet-router decision record: routed / handoff /
        migrated / shed (``decode/fleet.py``; ``ROUTER_REQUIRED``
        contract — source/target/policy default to null so a caller
        only names the engines and the placement policy the decision
        involves)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec.setdefault("source", None)
        rec.setdefault("target", None)
        rec.setdefault("policy", None)
        rec.setdefault("trace_id", None)
        rec["kind"] = "router"
        self._put(rec)

    def workload(self, record: dict) -> None:
        """Enqueue one trace-replay interval record: trace identity,
        per-interval offered/admitted, cumulative per-tenant counts
        (``decode/workload_driver.py``; ``WORKLOAD_REQUIRED``
        contract)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "workload"
        self._put(rec)

    def autoscale(self, record: dict) -> None:
        """Enqueue one decode-tier scale decision record: scale_up /
        scale_down / held (``decode/autoscale.py``;
        ``AUTOSCALE_REQUIRED`` contract plus the per-event conditional
        pins)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "autoscale"
        self._put(rec)

    def qos(self, record: dict) -> None:
        """Enqueue one tenant-QoS scheduling decision record:
        predicted_miss_shed / budget_deferred / wfq_pick
        (``decode/engine.py``; ``QOS_REQUIRED`` contract plus the
        per-event conditional pins — tenant defaults to null, the
        single-tenant stance of request records)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec.setdefault("tenant", None)
        rec["kind"] = "qos"
        self._put(rec)

    def alert(self, record: dict) -> None:
        """Enqueue one watchtower detector transition record: fired /
        resolved (``runtime/watch.py``; ``ALERT_REQUIRED`` contract
        plus the per-detector conditional pins — severity defaults to
        "warn" so an experimental detector need not pick a page
        class)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec.setdefault("severity", "warn")
        rec["kind"] = "alert"
        self._put(rec)

    def fleet(self, record: dict) -> None:
        """Enqueue one per-round fleet health record: per-engine
        waiting/active/free-blocks/utilization plus the load-imbalance
        scalar (``decode/fleet.py``; ``FLEET_REQUIRED`` contract)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "fleet"
        self._put(rec)

    def span(self, record: dict) -> None:
        """Enqueue one per-request lifecycle span record (a CLOSED
        phase: queued / prefill / replay / decode / quarantine /
        preempt_gap; ``runtime/tracing.py``; ``SPAN_REQUIRED``
        contract). Callers pass ``t`` explicitly (the span's close
        time) so span sums reconcile with request latencies."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec.setdefault("trace_id", None)
        rec.setdefault("tenant", None)
        rec["kind"] = "span"
        self._put(rec)

    def meta(self, record: dict) -> None:
        """Enqueue a run-header record (shapes, strategy, flags, paths
        to sibling logs — the report tool reads these to fold streams)."""
        rec = dict(record)
        rec.setdefault("t", time.time())
        rec["kind"] = "meta"
        self._put(rec)

    # -- lifecycle --

    def flush(self) -> None:
        """Block until every enqueued record is on disk."""
        self._q.join()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.flush()
        self._q.put(None)
        self._thread.join(timeout=10)
        if self._err is not None:
            # telemetry never kills a run, but a lossy stream must not
            # stay silent either: name the last drop on the way out
            import sys
            print(f"telemetry: record(s) dropped while writing "
                  f"{self.path} (last error: {self._err})",
                  file=sys.stderr)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- writer thread --

    def _put(self, rec: dict) -> None:
        if self._closed:
            raise RuntimeError("TelemetryWriter is closed")
        rec["schema"] = SCHEMA_VERSION
        self._q.put(rec)

    def _finalize(self, rec: dict) -> dict:
        """Readbacks + derived fields — runs on the writer thread."""
        if rec.get("kind") == "step":
            rec["loss"] = _scalar(rec.get("loss"))
            rec["grad_norm"] = _scalar(rec.get("grad_norm"))
            rec["step_time_s"] = _scalar(rec.get("step_time_s"))
            tokens = rec.pop("_tokens", None)
            flops = rec.pop("_model_flops", None)
            peak = rec.pop("_peak", None)
            dt = rec["step_time_s"]
            rec["tokens_per_sec"] = (
                round(tokens / dt, 2) if tokens and dt else None)
            rec["mfu"] = (round(flops / dt / peak, 4)
                          if flops and dt and peak else None)
            # contract: a step record carries exactly STEP_KEYS
            rec = {k: rec.get(k) for k in STEP_KEYS}
        return rec

    def _drain(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                self._q.task_done()
                return
            try:
                # default=: event payloads originate in other subsystems
                # (checkpoint/supervise) and may carry numpy scalars —
                # coerce instead of dropping the record
                line = json.dumps(self._finalize(rec),
                                  default=_json_default)
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            except Exception as e:  # noqa: BLE001 — telemetry never kills a run
                self._err = f"{type(e).__name__}: {e}"
            finally:
                self._q.task_done()


def validate_record(rec: Any) -> tuple[bool, str]:
    """Schema check for one parsed record: the envelope (``schema``,
    ``kind``, ``t``) on every record, plus the kind's pinned
    ``REQUIRED_KEYS`` contract. Every failure message is ONE line
    naming the record kind and the offending/missing key — the problems
    list a report renders must be actionable without opening the file."""
    if not isinstance(rec, dict):
        return False, "record is not a JSON object"
    kind = rec.get("kind")
    label = f"{kind} record" if kind in RECORD_KINDS else "record"
    if rec.get("schema") != SCHEMA_VERSION:
        return False, (f"{label}: key 'schema' is {rec.get('schema')!r}, "
                       f"expected {SCHEMA_VERSION} (version mismatch)")
    if kind not in RECORD_KINDS:
        return False, (f"record: key 'kind' is {kind!r}, not one of "
                       f"{RECORD_KINDS}")
    if "t" not in rec:
        return False, f"{label} missing key 't' (timestamp)"
    missing = [k for k in REQUIRED_KEYS.get(kind, ()) if k not in rec]
    if missing:
        return False, f"{label} missing required key(s) {missing}"
    if kind == "request" and rec.get("event") == "completed":
        # v9 conditional pin: only a completion measures a latency, so
        # the decomposition pair is required there and nowhere else
        missing = [k for k in REQUEST_COMPLETED_REQUIRED if k not in rec]
        if missing:
            return False, (f"request record (event completed) missing "
                           f"required key(s) {missing}")
    if kind == "router" and rec.get("event") in ("handoff", "migrated"):
        # v10 conditional pin: only a move ships blocks/bytes and has a
        # transport to attribute — routed/shed records place or drop a
        # request without moving KV
        missing = [k for k in ROUTER_MOVE_REQUIRED if k not in rec]
        if missing:
            return False, (f"router record (event {rec['event']}) "
                           f"missing required key(s) {missing}")
    if kind == "router" and rec.get("event") == "migrated":
        # v16 conditional pin: every migration names its ship window
        # and catch-up cost — the async-migration contract's numbers
        missing = [k for k in ROUTER_MIGRATED_REQUIRED if k not in rec]
        if missing:
            return False, (f"router record (event migrated) missing "
                           f"required key(s) {missing}")
    if kind == "deploy" and rec.get("event") in DEPLOY_EVENT_REQUIRED:
        # v11 conditional pins: only a swap names an engine, only a
        # terminal event measures a duration, only a rollback has a
        # named reason — pinning kind-wide would force nulls
        missing = [k for k in DEPLOY_EVENT_REQUIRED[rec["event"]]
                   if k not in rec]
        if missing:
            return False, (f"deploy record (event {rec['event']}) "
                           f"missing required key(s) {missing}")
    if kind == "autoscale" and rec.get("event") in \
            AUTOSCALE_EVENT_REQUIRED:
        # v14 conditional pins: only a scale names the engine it
        # spawned/drained, only a scale-down measures a drain
        missing = [k for k in AUTOSCALE_EVENT_REQUIRED[rec["event"]]
                   if k not in rec]
        if missing:
            return False, (f"autoscale record (event {rec['event']}) "
                           f"missing required key(s) {missing}")
    if kind == "qos" and rec.get("event") in QOS_EVENT_REQUIRED:
        # v14 conditional pins: each qos decision carries exactly the
        # numbers that justified it
        missing = [k for k in QOS_EVENT_REQUIRED[rec["event"]]
                   if k not in rec]
        if missing:
            return False, (f"qos record (event {rec['event']}) "
                           f"missing required key(s) {missing}")
    if kind == "alert" and rec.get("detector") in \
            ALERT_DETECTOR_REQUIRED:
        # v15 conditional pins: every detector transition carries
        # exactly the numbers that justified it (fired AND resolved —
        # the resolved record shows the recovered reading)
        missing = [k for k in ALERT_DETECTOR_REQUIRED[rec["detector"]]
                   if k not in rec]
        if missing:
            return False, (f"alert record (detector {rec['detector']}) "
                           f"missing required key(s) {missing}")
    if kind == "step" and not isinstance(rec["step"], int):
        return False, (f"step record key 'step' is "
                       f"{type(rec['step']).__name__}, not int")
    return True, "ok"


def read_metrics(path: str) -> tuple[list[dict], list[str]]:
    """Parse a metrics JSONL: ``(records, problems)``. A torn final
    line (crash mid-append) is reported, not fatal; schema-invalid
    records are reported and skipped — the report tool renders what
    verifies and names what doesn't."""
    records, problems = [], []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                problems.append(f"line {i}: unparseable JSON "
                                "(torn write?)")
                continue
            ok, reason = validate_record(rec)
            if not ok:
                problems.append(f"line {i}: {reason}")
                continue
            records.append(rec)
    return records, problems


@dataclass(frozen=True)
class StepReport:
    """Static (compile-time) report of one training step program: the
    compiler's own cost/memory numbers and the lowered collective
    schedule in one object, cross-checked against the hand FLOP count.

    ``flops`` is XLA's ``cost_analysis()["flops"]`` (None where the
    backend doesn't report it); ``hand_flops`` is the model's
    hand-counted matmul FLOPs (the MFU numerator); ``flops_vs_hand``
    is their ratio — ~1x for saved-activation policies, >1x for
    recompute policies (executed > model FLOPs), and a number far from
    either flags a broken accounting before a single step runs."""

    collectives: dict[str, int] = field(default_factory=dict)
    flops: float | None = None
    bytes_accessed: float | None = None
    memory: dict[str, Any] | None = None
    hand_flops: int | None = None
    flops_vs_hand: float | None = None

    @classmethod
    def of(cls, fn: Callable, *args, hand_flops: int | None = None,
           **kwargs) -> "StepReport":
        """Lower + compile ``fn`` for ``args`` and fold the static
        analyses. One lowering feeds both the collective count and the
        compile (the ``utils/hlo.py`` helpers re-lower per call — this
        path does the work once)."""
        import jax

        from ..utils.hlo import count_collectives_text

        lowered = jax.jit(fn).lower(*args, **kwargs)
        collectives = {op: n for op, n
                       in count_collectives_text(lowered.as_text()).items()
                       if n}
        compiled = lowered.compile()
        flops = bytes_accessed = None
        try:
            cost = compiled.cost_analysis()
            # older jax returns a list of dicts (one per program)
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            if cost:
                flops = float(cost.get("flops", 0)) or None
                bytes_accessed = float(cost.get("bytes accessed", 0)) or None
        except Exception:  # noqa: BLE001 — per-backend API surface
            pass
        memory = None
        try:
            m = compiled.memory_analysis()
            if m is not None:
                memory = {
                    "argument_bytes": m.argument_size_in_bytes,
                    "output_bytes": m.output_size_in_bytes,
                    "temp_bytes": m.temp_size_in_bytes,
                    "peak_bytes": getattr(m, "peak_memory_in_bytes", None),
                }
        except Exception:  # noqa: BLE001
            pass
        ratio = (round(flops / hand_flops, 4)
                 if flops and hand_flops else None)
        return cls(collectives=collectives, flops=flops,
                   bytes_accessed=bytes_accessed, memory=memory,
                   hand_flops=hand_flops, flops_vs_hand=ratio)

    def as_dict(self) -> dict:
        return {"collectives": dict(self.collectives), "flops": self.flops,
                "bytes_accessed": self.bytes_accessed,
                "memory": self.memory, "hand_flops": self.hand_flops,
                "flops_vs_hand": self.flops_vs_hand}
