"""Serving policy layer: the parsed, validated knobs the closed
control loop acts on (DESIGN.md section 26).

Two policies, both plain host-side data — nothing here ever enters a
compiled program or a sampling key, so a policy change can never
change a request's tokens, only WHEN requests are admitted and how
many engines serve them:

- ``QosPolicy``: per-tenant scheduling discipline for the engine's
  admission order. ``fcfs`` is the historical strict head-of-line
  queue; ``wfq`` is virtual-time weighted fairness over SERVED tokens
  (each tenant's virtual time advances by served_tokens / weight; the
  waiting head with the smallest virtual time admits next), plus an
  optional per-tenant resident token budget and predictive
  deadline-miss shedding at the door.
- ``AutoscalePolicy``: the between-rounds decode-tier controller's
  thresholds. Scale up when the mean per-engine waiting depth holds
  at or above ``up_queue`` for ``hysteresis`` consecutive rounds;
  scale down when it holds strictly below ``down_queue`` (and the
  fleet is above ``min_engines``). ``up_queue > down_queue`` is
  REQUIRED (a dead band, so flapping is structurally impossible) and
  ``min_engines >= 1`` (scale-to-zero likewise). ``cooldown`` rounds
  must pass after any scale action before the next.

**Spec grammars** (comma-separated ``key=value``, the ``--trace_gen``
parse-rejection discipline — every malformed entry is ONE ValueError
naming the offense, which the CLI maps to rc 2)::

    --qos       discipline=fcfs|wfq          default wfq
                weights=NAME:W(;NAME:W)*     default none (weight 1)
                budget=INT                   default 0 (off)
                predictive_shed=0|1          default 1
    --autoscale min=INT                      default 1
                max=INT                      default 4
                up=INT                       default 4
                down=INT                     default 1
                hysteresis=INT               default 2
                cooldown=INT                 default 8

Deliberately jax-free (stdlib only): parsing a policy must not pay a
backend import, and the controller itself is pure host-side control
flow.
"""

from __future__ import annotations

from dataclasses import dataclass, field

QOS_DISCIPLINES = ("fcfs", "wfq")


@dataclass(frozen=True)
class QosPolicy:
    """Per-tenant admission-order policy (``decode/engine.py`` reads
    it in ``_admit``/``submit``; never inside a compiled program).

    ``weights`` maps tenant name -> positive weight (an unlisted
    tenant gets weight 1.0); ``token_budget`` caps a tenant's RESIDENT
    reserved tokens (sum of admitted-but-unfinished ``max_new``; 0 =
    no cap); ``predictive_shed`` sheds a request at submit when its
    queue-position ETA already blows ``deadline_steps``."""

    discipline: str = "wfq"
    weights: tuple = field(default_factory=tuple)  # ((name, w), ...)
    token_budget: int = 0
    predictive_shed: bool = True

    def __post_init__(self):
        if self.discipline not in QOS_DISCIPLINES:
            raise ValueError(f"bad QosPolicy discipline "
                             f"{self.discipline!r}: known disciplines "
                             f"{QOS_DISCIPLINES}")
        for name, w in self.weights:
            if not name or not isinstance(name, str):
                raise ValueError(f"bad QosPolicy weight name {name!r}")
            if not isinstance(w, (int, float)) or w <= 0:
                raise ValueError(f"bad QosPolicy weight for "
                                 f"{name!r}: {w!r} must be > 0")
        if len({n for n, _ in self.weights}) != len(self.weights):
            raise ValueError("bad QosPolicy weights: duplicate tenant")
        if not isinstance(self.token_budget, int) \
                or self.token_budget < 0:
            raise ValueError(f"bad QosPolicy token_budget "
                             f"{self.token_budget!r}: must be an "
                             "integer >= 0")

    def weight_of(self, tenant_key: str) -> float:
        for name, w in self.weights:
            if name == tenant_key:
                return float(w)
        return 1.0

    def as_dict(self) -> dict:
        return {"discipline": self.discipline,
                "weights": [[n, w] for n, w in self.weights],
                "token_budget": self.token_budget,
                "predictive_shed": self.predictive_shed}

    @classmethod
    def from_dict(cls, doc: dict) -> "QosPolicy":
        return cls(discipline=doc["discipline"],
                   weights=tuple((n, float(w))
                                 for n, w in doc["weights"]),
                   token_budget=int(doc["token_budget"]),
                   predictive_shed=bool(doc["predictive_shed"]))


@dataclass(frozen=True)
class AutoscalePolicy:
    """The decode-tier controller's thresholds
    (``decode/autoscale.py`` acts on them between fleet rounds)."""

    min_engines: int = 1
    max_engines: int = 4
    up_queue: int = 4
    down_queue: int = 1
    hysteresis: int = 2
    cooldown: int = 8

    def __post_init__(self):
        for name in ("min_engines", "max_engines", "up_queue",
                     "down_queue", "hysteresis", "cooldown"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool):
                raise ValueError(f"bad AutoscalePolicy {name} {v!r}: "
                                 "must be an integer")
        if self.min_engines < 1:
            raise ValueError(f"bad AutoscalePolicy min_engines "
                             f"{self.min_engines}: must be >= 1 "
                             "(scale-to-zero is structurally "
                             "impossible)")
        if self.max_engines < self.min_engines:
            raise ValueError(f"bad AutoscalePolicy max_engines "
                             f"{self.max_engines}: must be >= "
                             f"min_engines {self.min_engines}")
        if self.up_queue <= self.down_queue:
            raise ValueError(f"bad AutoscalePolicy thresholds: up "
                             f"{self.up_queue} must be > down "
                             f"{self.down_queue} (the dead band that "
                             "makes flapping impossible)")
        if self.down_queue < 0:
            raise ValueError(f"bad AutoscalePolicy down_queue "
                             f"{self.down_queue}: must be >= 0")
        if self.hysteresis < 1:
            raise ValueError(f"bad AutoscalePolicy hysteresis "
                             f"{self.hysteresis}: must be >= 1")
        if self.cooldown < 0:
            raise ValueError(f"bad AutoscalePolicy cooldown "
                             f"{self.cooldown}: must be >= 0")


def _policy_int(flag: str, key: str, val: str) -> int:
    try:
        return int(val)
    except ValueError:
        raise ValueError(f"bad {flag} {key} {val!r}: must be an "
                         "integer") from None


def parse_qos_spec(spec: str) -> QosPolicy:
    """Parse + validate one ``--qos`` spec (module-docstring grammar).
    Every malformed entry is ONE ValueError naming the offense."""
    out = {"discipline": "wfq", "weights": (), "token_budget": 0,
           "predictive_shed": True}
    seen = set()
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        if "=" not in entry:
            raise ValueError(f"bad --qos entry {entry!r}: expected "
                             "key=value with key in discipline/"
                             "weights/budget/predictive_shed")
        key, _, val = entry.partition("=")
        if key in seen:
            raise ValueError(f"bad --qos spec: duplicate key {key!r}")
        seen.add(key)
        if key == "discipline":
            if val not in QOS_DISCIPLINES:
                raise ValueError(f"bad --qos discipline {val!r}: "
                                 f"known disciplines {QOS_DISCIPLINES}")
            out["discipline"] = val
        elif key == "weights":
            mix = []
            for part in (p.strip() for p in val.split(";")
                         if p.strip()):
                name, sep, w = part.partition(":")
                if not name or not sep:
                    raise ValueError(
                        f"bad --qos weights entry {part!r}: expected "
                        "NAME:WEIGHT (e.g. weights=a:3;b:1)")
                try:
                    weight = float(w)
                except ValueError:
                    raise ValueError(f"bad --qos weights weight "
                                     f"{w!r}: must be a number") \
                        from None
                if weight <= 0:
                    raise ValueError(f"bad --qos weights weight "
                                     f"{weight}: must be > 0")
                mix.append((name, weight))
            if not mix:
                raise ValueError("bad --qos weights: empty mix")
            if len({n for n, _ in mix}) != len(mix):
                raise ValueError("bad --qos weights: duplicate tenant "
                                 "name")
            out["weights"] = tuple(mix)
        elif key == "budget":
            b = _policy_int("--qos", "budget", val)
            if b < 0:
                raise ValueError(f"bad --qos budget {b}: must be "
                                 ">= 0 (0 = off)")
            out["token_budget"] = b
        elif key == "predictive_shed":
            if val not in ("0", "1"):
                raise ValueError(f"bad --qos predictive_shed {val!r}: "
                                 "must be 0 or 1")
            out["predictive_shed"] = val == "1"
        else:
            raise ValueError(f"bad --qos key {key!r}: known keys "
                             "discipline/weights/budget/"
                             "predictive_shed")
    return QosPolicy(**out)


def parse_autoscale_spec(spec: str) -> AutoscalePolicy:
    """Parse + validate one ``--autoscale`` spec (module-docstring
    grammar). Every malformed entry is ONE ValueError naming the
    offense; the cross-field constraints (up > down, min >= 1) are
    enforced by ``AutoscalePolicy`` itself."""
    names = {"min": "min_engines", "max": "max_engines",
             "up": "up_queue", "down": "down_queue",
             "hysteresis": "hysteresis", "cooldown": "cooldown"}
    out = {}
    seen = set()
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        if "=" not in entry:
            raise ValueError(f"bad --autoscale entry {entry!r}: "
                             "expected key=value with key in "
                             "min/max/up/down/hysteresis/cooldown")
        key, _, val = entry.partition("=")
        if key in seen:
            raise ValueError(f"bad --autoscale spec: duplicate key "
                             f"{key!r}")
        seen.add(key)
        if key not in names:
            raise ValueError(f"bad --autoscale key {key!r}: known "
                             "keys min/max/up/down/hysteresis/"
                             "cooldown")
        out[names[key]] = _policy_int("--autoscale", key, val)
    return AutoscalePolicy(**out)
