"""Wire-format serialization + the crash-safe publish discipline.

Round 6 earned the durability rules on the checkpoint path (fsync'd
payloads, per-file CRC-32, tmp-rename-publish) and round 10 copied the
JSON half of them into the serving snapshot writer — two implementations
of one discipline. Round 16 needs the SAME rules a third time, at a
harder boundary: the fleet's KV handoff documents now cross a real
process boundary as files, where every failure mode the checkpoint
layer defends against (torn write, bit flip, version skew) can actually
occur mid-transfer. This module is the single home for all three
callers:

- **Integrity primitives** (``crc_file`` / ``fsync_file`` /
  ``fsync_dir`` / ``np_dtype``): lifted verbatim from ``checkpoint.py``
  (which now re-exports them) so the trainer's checkpoint verify and
  the serving wire verify share one CRC and one fsync posture.

- **Atomic JSON publish** (``publish_json``): write tmp, fsync, rename
  over the target, fsync the directory — a SIGKILL between any two
  instructions leaves either the old document or the new one, never a
  torn one. ``decode/supervise.py``'s engine snapshots (and therefore
  every engine-worker process's snapshot publisher) go through this.

- **The handoff wire format** (``write_doc`` / ``read_doc``): one
  ``export_sequence`` document serialized to a single npz file. Arrays
  ride as raw uint8 byte buffers (dtype + shape recorded in the
  header, so int8 codes and ml_dtypes bf16 round-trip bit-exactly
  without numpy dtype-registry games); every array carries its own
  CRC-32 in the header; the header itself is a JSON object embedded as
  one more npz entry. ``read_doc`` REJECTS — with a one-line named
  reason, wrapped in ``WireError`` — a truncated file, an unparseable
  header, a wire-version mismatch, a missing array, or a per-array CRC
  mismatch. The doc-level checks (handoff version, model fingerprint,
  config compatibility) stay in ``DecodeEngine.import_sequence``,
  which validates everything BEFORE touching any engine state — so a
  rejected document can never leave a partial import behind.

The module is deliberately jax-free (numpy + stdlib only): the report
tool and the router-side transport client import it without paying the
jax import, and the worker protocol stays testable without a backend.
"""

from __future__ import annotations

import io
import json
import os
import time
import zlib

import numpy as np

# Version of the WIRE ENVELOPE (file layout: header entry name, byte-
# buffer encoding, CRC placement) — distinct from the handoff DOCUMENT
# version (``decode/engine.py::HANDOFF_VERSION``, the payload schema
# import_sequence checks). Either mismatch is a one-line rejection.
WIRE_VERSION = 1

# the npz entry holding the JSON header (array names must not collide
# with it; handoff docs use short lowercase names)
_HEADER_ENTRY = "__wire_header__"


class WireError(ValueError):
    """A wire document failed integrity/version checks. The message is
    ONE line naming what failed (truncation, header, version, array,
    CRC) — the reason telemetry records and tests pin."""


# ------------------------------------------------- integrity primitives

def crc_file(path: str, chunk: int = 1 << 20) -> int:
    """Streaming CRC-32 of a file (the checkpoint verify primitive)."""
    crc = 0
    with open(path, "rb") as f:
        while block := f.read(chunk):
            crc = zlib.crc32(block, crc)
    return crc


def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename still atomic
    finally:
        os.close(fd)


def np_dtype(name: str) -> np.dtype:
    """Resolve a saved dtype name, including the ml_dtypes ones
    (bfloat16, float8_*) numpy can't look up by string."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


# ---------------------------------------------------- atomic publishing

def publish_bytes(path: str, data: bytes) -> None:
    """Atomically publish ``data`` at ``path``: tmp + fsync + rename +
    dir fsync. A crash between any two instructions leaves either the
    old file or the new one — never a torn hybrid."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def publish_json(path: str, doc: dict) -> str:
    """Atomic JSON publish (the engine-snapshot discipline,
    ``decode/supervise.py``). Returns ``path``."""
    publish_bytes(path, json.dumps(doc).encode("utf-8"))
    return path


# ------------------------------------------------- handoff wire format

def _split_doc(doc: dict) -> tuple[dict, dict]:
    """``(meta, arrays)``: numpy values go on the wire as byte buffers,
    everything else (ints, floats, strings, lists, dicts, None) rides
    in the JSON header verbatim."""
    meta, arrays = {}, {}
    for key, val in doc.items():
        if isinstance(val, np.ndarray):
            arrays[key] = val
        else:
            meta[key] = val
    return meta, arrays


def serialize_doc(doc: dict) -> bytes:
    """One handoff document -> the npz wire bytes. Array entries are
    C-contiguous uint8 views of the raw storage bytes; the header
    records each array's dtype/shape/CRC-32 plus the non-array keys."""
    meta, arrays = _split_doc(doc)
    header = {"wire_version": WIRE_VERSION, "meta": meta, "arrays": {}}
    payload = {}
    for name, arr in arrays.items():
        if name == _HEADER_ENTRY:
            raise ValueError(f"array name {name!r} collides with the "
                             "wire header entry")
        buf = np.ascontiguousarray(arr)
        raw = buf.view(np.uint8).reshape(-1)
        header["arrays"][name] = {
            "dtype": str(arr.dtype), "shape": list(arr.shape),
            "crc32": zlib.crc32(raw.tobytes()),
        }
        payload[name] = raw
    hdr = np.frombuffer(json.dumps(header).encode("utf-8"), np.uint8)
    out = io.BytesIO()
    np.savez(out, **{_HEADER_ENTRY: hdr}, **payload)
    return out.getvalue()


def deserialize_doc(data: bytes, stats: dict | None = None) -> dict:
    """The npz wire bytes -> the handoff document, integrity-verified.
    Raises ``WireError`` with a one-line reason on a torn/truncated
    file, missing or unparseable header, wire-version mismatch,
    missing array, or per-array CRC mismatch. ``stats`` (optional,
    filled in place) reports ``bytes`` and ``crc_verify_s`` — the
    transport instrumentation telemetry records."""
    t0 = time.perf_counter()
    try:
        npz = np.load(io.BytesIO(data), allow_pickle=False)
    except Exception as e:  # noqa: BLE001 — any load failure is a torn doc
        raise WireError(f"unreadable wire doc (torn/truncated npz): "
                        f"{type(e).__name__}: {e}") from None
    def entry(name: str):
        # the zip container checks its own per-entry CRC at READ time:
        # damage inside an entry surfaces here as BadZipFile/zlib
        # errors, which are torn-doc rejections like any other
        try:
            return npz[name]
        except WireError:
            raise
        except Exception as e:  # noqa: BLE001 — any read failure
            raise WireError(f"array {name!r} unreadable (corrupted "
                            f"npz entry): {type(e).__name__}: "
                            f"{e}") from None

    with npz:
        if _HEADER_ENTRY not in npz.files:
            raise WireError("wire doc missing its header entry "
                            f"({_HEADER_ENTRY!r})")
        try:
            header = json.loads(bytes(entry(_HEADER_ENTRY))
                                .decode("utf-8"))
        except WireError:
            raise
        except (ValueError, UnicodeDecodeError) as e:
            raise WireError(f"wire doc header unparseable: "
                            f"{type(e).__name__}: {e}") from None
        if header.get("wire_version") != WIRE_VERSION:
            raise WireError(f"wire version "
                            f"{header.get('wire_version')!r} != "
                            f"{WIRE_VERSION}")
        doc = dict(header.get("meta", {}))
        for name, spec in header.get("arrays", {}).items():
            if name not in npz.files:
                raise WireError(f"wire doc missing array {name!r}")
            raw = entry(name)
            got = zlib.crc32(raw.tobytes())
            if got != int(spec["crc32"]):
                raise WireError(
                    f"array {name!r} CRC-32 mismatch ({got:#010x} != "
                    f"recorded {int(spec['crc32']):#010x}) — corrupted "
                    "in transit")
            doc[name] = raw.view(np_dtype(spec["dtype"])) \
                           .reshape(spec["shape"])
    if stats is not None:
        stats["bytes"] = len(data)
        stats["crc_verify_s"] = round(time.perf_counter() - t0, 6)
    return doc


def write_doc(path: str, doc: dict) -> int:
    """Serialize + atomically publish one handoff document at ``path``;
    returns the wire byte count (the serialized size — what actually
    crosses the boundary)."""
    data = serialize_doc(doc)
    publish_bytes(path, data)
    return len(data)


def read_doc(path: str, stats: dict | None = None) -> dict:
    """Load + verify one published wire document. ``WireError`` (one
    line, named reason) on any integrity failure — including a file
    torn below the npz container's own structure."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise WireError(f"wire doc unreadable: {type(e).__name__}: "
                        f"{e}") from None
    return deserialize_doc(data, stats)


def doc_wire_bytes(doc: dict) -> int:
    """The serialized size of a handoff document — the honest ``bytes``
    for an in-process move (``FleetRouter._doc_bytes`` previously
    summed in-memory nbytes, undercounting scales + metadata and
    ignoring the container)."""
    return len(serialize_doc(doc))


# ------------------------------------- length-prefixed stream framing
#
# The TCP side channel (round 22): the worker protocol's newline-JSON
# control plane cannot carry the npz wire bytes (binary, embedded
# newlines), so a handoff streamed over the SAME socket rides as a
# length-prefixed binary frame immediately after the JSON line that
# announces it. The frame is just the prefix — integrity stays with
# the npz payload's own per-array CRC-32 (deserialize_doc verifies at
# the receiving end, exactly as it does for a spool file), so the
# framing layer never invents a second checksum discipline.

# 8-byte big-endian unsigned length — one prefix, no magic, no flags
# (version/identity live inside the npz header it frames)
FRAME_PREFIX_LEN = 8
# a frame larger than this is a protocol desync, not a handoff (the
# largest real doc is a few MB of KV blocks) — reject before
# allocating the claimed size
MAX_FRAME_BYTES = 1 << 31


def pack_frame(data: bytes) -> bytes:
    """``data`` as one length-prefixed frame (prefix + payload)."""
    if len(data) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(data)} bytes exceeds the "
                         f"{MAX_FRAME_BYTES}-byte cap")
    return len(data).to_bytes(FRAME_PREFIX_LEN, "big") + data


def unpack_frame_len(prefix: bytes) -> int:
    """Decode a frame's length prefix; ``WireError`` on a short read
    or an implausible length (protocol desync — the peer is not
    speaking the frame discipline)."""
    if len(prefix) != FRAME_PREFIX_LEN:
        raise WireError(f"frame prefix truncated ({len(prefix)} of "
                        f"{FRAME_PREFIX_LEN} bytes) — stream torn "
                        "mid-frame")
    n = int.from_bytes(prefix, "big")
    if n > MAX_FRAME_BYTES:
        raise WireError(f"frame length {n} exceeds the "
                        f"{MAX_FRAME_BYTES}-byte cap — protocol "
                        "desync, not a handoff")
    return n
