"""Failure detection + elastic recovery supervisor.

The reference has none of this: no try/except around workers, no timeout on
``join`` (``train_ffns.py:190-191``), no restart, no health checks
(SURVEY.md section 5). This module is the framework's answer, built from
the pieces the other subsystems provide:

- **detection**: the native ``Watchdog`` (hang detection,
  ``native/watchdog.cpp``), ``Rendezvous.barrier_timeout`` (dead/wedged
  peer detection at sync points), and ``device_healthcheck`` (a tiny
  compiled program proves each device still executes);
- **recovery**: ``supervise`` wraps ``checkpoint.run_with_checkpointing``
  — on failure it restarts the run, which resumes from the last published
  checkpoint and (by the checkpoint subsystem's exact-resume contract)
  lands on the same final params as an uninterrupted run.
"""

from __future__ import annotations

import json
import os
import random
import time
from typing import Callable

import jax
import numpy as np

from ..checkpoint import (LossSpikeError, NonFiniteParamsError,
                          latest_verified_step, run_with_checkpointing)

# The ladder's cheap rung catches exactly the failures whose remedy is
# "rewind to the last verified checkpoint and retrain": a poisoned
# segment (nonfinite="raise") and a loss spike (spike_factor). Anything
# else — real crashes, hung collectives, backend deaths — goes to the
# restart rung with backoff + healthcheck.
RECOVERABLE = (NonFiniteParamsError, LossSpikeError)


def _head(exc: BaseException) -> str:
    """First line of ``Type: message`` — the diagnosable core of an
    exception, the same convention the backend probe matrix records."""
    return f"{type(exc).__name__}: {exc}".splitlines()[0][:300]


def backoff_delay(attempt: int, base_s: float, max_s: float,
                  jitter: float, rng: random.Random) -> float:
    """Jittered exponential backoff: ``base_s * 2^attempt`` capped at
    ``max_s``, scaled by ``uniform(1-j, 1+j)`` from the caller's seeded
    RNG — deterministic in tests, thundering-herd-safe in fleets.
    Shared by the training supervisor below, the serving supervisor
    (``decode/supervise.py``), and every transport ladder in
    ``decode/worker.py`` (boot connect, timed-out recv retries, and
    the round-22 reconnect state machine) so the restart and
    reconnect schedules cannot drift apart. Bounds contract (pinned by
    tests/test_failure.py): with jitter ``j`` the delay stays within
    ``[(1-j) * min(base_s * 2^attempt, max_s), (1+j) * ...]``, and the
    jitter-free schedule is monotone non-decreasing in ``attempt``."""
    b = min(base_s * (2 ** attempt), max_s)
    return b * (1.0 + jitter * (2.0 * rng.random() - 1.0))


class HealthCheckError(RuntimeError):
    """A device failed the liveness probe."""


def device_healthcheck(devices=None, timeout_s: float = 30.0,
                       allow_degraded: bool = False) -> list:
    """Prove each device still compiles and executes: run ``x + 1`` on a
    tiny buffer per device and check the result. Returns the healthy
    devices; raises ``HealthCheckError`` naming the first failure.

    ``allow_degraded=True`` is the topology-elastic posture: failing
    devices are *recorded and skipped* instead of fatal, and the
    surviving list comes back (raising only when NOTHING survives) —
    feed it to ``parallel.mesh.elastic_mesh`` to rebuild a smaller mesh
    and resume from the last checkpoint (``checkpoint.py``'s elastic
    resume restrides the schedule automatically).

    (A hung device surfaces as the jit call blocking — pair the probe with
    a ``Watchdog`` when that matters; XLA offers no portable async cancel.)
    """
    devices = list(devices if devices is not None else jax.devices())
    healthy, dead = [], []
    for d in devices:
        t0 = time.monotonic()
        reason = None
        try:
            y = jax.device_put(np.ones((8,), np.float32), d) + 1.0
            if not bool(np.all(np.asarray(y) == 2.0)):
                reason = f"device {d} returned wrong result"
            elif time.monotonic() - t0 > timeout_s:
                reason = f"device {d} probe exceeded {timeout_s}s"
        except Exception as e:  # noqa: BLE001 — any backend error is a failure
            reason = f"device {d} failed liveness probe: {e}"
        if reason is None:
            healthy.append(d)
        elif allow_degraded:
            dead.append(reason)
        else:
            raise HealthCheckError(reason)
    if not healthy:
        raise HealthCheckError(
            "no healthy devices survived the probe: " + "; ".join(dead))
    return healthy


def supervise(train_fn: Callable, params, seeds, *args,
              ckpt_dir: str, every: int, max_restarts: int = 3,
              max_rollbacks: int = 2,
              on_failure: Callable[[int, BaseException], None] | None = None,
              healthcheck: bool = False,
              backoff_base_s: float = 0.5, backoff_max_s: float = 30.0,
              backoff_jitter: float = 0.5, backoff_seed: int = 0,
              log_path: str | None = None, chaos=None,
              nonfinite: str | None = "skip", watchdog_ms: int = 0,
              **kwargs):
    """Run a strategy launcher under failure supervision.

    Remedies escalate up the **rollback ladder** (round 8, DESIGN.md
    section 14) — each rung strictly cheaper than the next:

    1. **in-graph skip** (``guard=GuardrailConfig()`` in ``kwargs``):
       a non-finite step is ``jnp.where``-skipped inside the compiled
       chunk — costs one update, the supervisor never sees it (it shows
       up as an ``anomaly`` event);
    2. **loss-scale shrink** (``mixed`` runs with dynamic scaling): an
       overflowed step simultaneously skips and shrinks the scale,
       still in-graph;
    3. **in-process rollback** (this function, ``max_rollbacks``): a
       *recoverable* failure — ``NonFiniteParamsError`` from the
       segment guard, ``LossSpikeError`` from the spike guard — rewinds
       to ``latest_verified_step`` and re-enters immediately: same
       process, no backoff, no restart budget burned, and the jitted
       step programs are reused from the compile cache (same shapes →
       no recompile);
    4. **full restart** (the PR 1 path): everything else — real
       crashes, hung collectives — costs a restart with jittered
       backoff, optional device healthcheck, and the attempt log.

    Every rung is logged to the attempt JSONL (``rollback`` /
    ``attempt_failed`` records carry a ``rung`` field) and forwarded to
    the caller's ``on_event`` — the telemetry stream renders the whole
    ladder on one ``report`` timeline.

    Each attempt drives ``run_with_checkpointing`` (segment size ``every``);
    a raised exception costs one restart, optionally re-probes the devices,
    and the next attempt resumes from the last published VERIFIED
    checkpoint — work completed before the failure is never recomputed,
    and the final params equal an uninterrupted run
    (tests/test_failure.py, tests/test_chaos.py). ``on_failure`` is
    called with ``(attempt, exception)`` before each restart — exactly
    ``max_restarts`` times when every attempt fails.

    Hardening (round 6):

    - **jittered exponential backoff** between restarts:
      ``backoff_base_s * 2^attempt`` capped at ``backoff_max_s``, scaled
      by ``uniform(1-j, 1+j)`` from a ``backoff_seed``-seeded RNG —
      deterministic in tests, thundering-herd-safe in fleets;
    - **structured per-attempt JSON logging** to ``log_path`` (default
      ``{ckpt_dir}/supervise.jsonl``): one line per attempt with the
      exception head, elapsed time, backoff chosen, restarts left, and
      watchdog state — plus every recovery event the checkpoint layer
      reports (non-finite skips, fallbacks);
    - **non-finite guard** (``nonfinite="skip"``, the default): a
      poisoned step (NaN/Inf gradients) is never checkpointed — the
      segment is skipped and logged instead of crashing the run or,
      worse, persisting the poison (``nonfinite="raise"`` turns it into
      a restart; ``None`` disables);
    - **hang detection evidence**: with ``watchdog_ms > 0`` a native
      ``Watchdog`` is armed around each attempt; its latch state is
      recorded in the attempt log (a hung collective shows up as
      ``watchdog_expired: true`` on the attempt that stalled);
    - **restart exhaustion carries the full per-attempt exception
      history** in the raised ``RuntimeError``, not just the last error —
      a flapping failure whose signature CHANGES across attempts (the
      round-5 outage) is diagnosable from the one exception message.

    ``chaos`` (a ``runtime.chaos.FaultPlan``) threads through to the
    checkpoint layer so any strategy can be run under fault load.
    """
    history: list[BaseException] = []
    rng = random.Random(backoff_seed)
    os.makedirs(ckpt_dir, exist_ok=True)
    if log_path is None:
        log_path = os.path.join(ckpt_dir, "supervise.jsonl")
    # a caller's own on_event (run_with_checkpointing's public hook) must
    # not collide with the supervisor's internal one — chain it instead
    caller_on_event = kwargs.pop("on_event", None)

    # one process owns the shared log file (the checkpoint layer's
    # primary-only filesystem-mutation discipline): P processes appending
    # to one supervise.jsonl over NFS would duplicate and tear records
    log_owner = jax.process_index() == 0

    def log(record: dict) -> None:
        if not log_owner:
            return
        record.setdefault("t", time.time())
        try:
            with open(log_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            pass  # logging must never take down the supervised run

    attempt = 0
    rollbacks = 0
    while attempt <= max_restarts:
        t0 = time.monotonic()
        dog = None
        hang_latched = False
        if watchdog_ms > 0:
            from . import native
            dog = native.Watchdog(watchdog_ms)

        def emit(record: dict, _dog=dog) -> None:
            # Checkpoint-layer progress (one event per published segment)
            # re-arms the hang detector — so the dog only stays latched
            # when a SEGMENT stalls past watchdog_ms, not when the whole
            # attempt merely outlives it. The latch state is captured
            # before the kick clears it: hang evidence survives recovery.
            nonlocal hang_latched
            if _dog is not None:
                if _dog.expired:
                    hang_latched = True
                    record = dict(record, watchdog_expired=True)
                _dog.kick()
            log(record)
            if caller_on_event is not None:
                try:
                    caller_on_event(record)
                except Exception:  # noqa: BLE001 — observability only
                    pass

        expired = None
        try:
            out = run_with_checkpointing(
                train_fn, params, seeds, *args, ckpt_dir=ckpt_dir,
                every=every, chaos=chaos, nonfinite=nonfinite,
                on_event=emit, **kwargs)
            if dog is not None:
                expired = bool(dog.expired) or hang_latched
            log({"event": "completed", "attempt": attempt,
                 "rollbacks": rollbacks,
                 "elapsed_s": round(time.monotonic() - t0, 3),
                 "watchdog_expired": expired})
            return out
        except Exception as e:  # noqa: BLE001 — supervisor catches all
            if dog is not None:
                expired = bool(dog.expired) or hang_latched
            if isinstance(e, RECOVERABLE) and rollbacks < max_rollbacks:
                # rung 3: in-process rollback — rewind to the last
                # verified checkpoint and re-enter NOW. No backoff (the
                # failure is a math anomaly, not contention), no restart
                # budget burned, no process death; the next entry's
                # restore lands on latest_verified_step and the jitted
                # step programs come straight from the compile cache.
                rollbacks += 1
                if isinstance(e, LossSpikeError) and e.baseline:
                    # the retry must keep the pre-spike reference scale:
                    # a persistent spike re-fires on the retrained
                    # segment instead of re-baselining on it
                    kwargs["spike_baseline"] = e.baseline
                if getattr(e, "guard_state", None) is not None:
                    # likewise the in-graph guard state: the dynamic
                    # loss scale and skip counters survive the rewind
                    # instead of snapping back to their initial values
                    kwargs["guard_state"] = e.guard_state
                emit({"event": "rollback", "rung": "rollback",
                      "rollback": rollbacks,
                      "max_rollbacks": max_rollbacks,
                      "attempt": attempt, "error": _head(e),
                      "resume_step": latest_verified_step(ckpt_dir),
                      "elapsed_s": round(time.monotonic() - t0, 3),
                      "watchdog_expired": expired})
                continue
            history.append(e)
            record = {"event": "attempt_failed", "rung": "restart",
                      "attempt": attempt,
                      "error": _head(e),
                      "elapsed_s": round(time.monotonic() - t0, 3),
                      "watchdog_expired": expired,
                      "restarts_left": max_restarts - attempt,
                      "backoff_s": None}
            if attempt == max_restarts:
                log(record)
                break  # exhausted: no restart follows, skip the probes
            backoff = backoff_delay(attempt, backoff_base_s,
                                    backoff_max_s, backoff_jitter, rng)
            record["backoff_s"] = round(backoff, 3)
            log(record)
            if on_failure is not None:
                on_failure(attempt, e)
            if healthcheck:
                device_healthcheck()
            if backoff > 0:
                time.sleep(backoff)
            attempt += 1
        finally:
            if dog is not None:
                dog.close()
    heads = "; ".join(f"attempt {i}: {_head(e)}"
                      for i, e in enumerate(history))
    raise RuntimeError(
        f"training failed after {max_restarts} restarts; "
        f"attempt history: [{heads}]") from history[-1]
