"""Failure detection + elastic recovery supervisor.

The reference has none of this: no try/except around workers, no timeout on
``join`` (``train_ffns.py:190-191``), no restart, no health checks
(SURVEY.md section 5). This module is the framework's answer, built from
the pieces the other subsystems provide:

- **detection**: the native ``Watchdog`` (hang detection,
  ``native/watchdog.cpp``), ``Rendezvous.barrier_timeout`` (dead/wedged
  peer detection at sync points), and ``device_healthcheck`` (a tiny
  compiled program proves each device still executes);
- **recovery**: ``supervise`` wraps ``checkpoint.run_with_checkpointing``
  — on failure it restarts the run, which resumes from the last published
  checkpoint and (by the checkpoint subsystem's exact-resume contract)
  lands on the same final params as an uninterrupted run.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import numpy as np

from ..checkpoint import run_with_checkpointing


class HealthCheckError(RuntimeError):
    """A device failed the liveness probe."""


def device_healthcheck(devices=None, timeout_s: float = 30.0) -> list:
    """Prove each device still compiles and executes: run ``x + 1`` on a
    tiny buffer per device and check the result. Returns the healthy
    devices; raises ``HealthCheckError`` naming the first failure.

    (A hung device surfaces as the jit call blocking — pair the probe with
    a ``Watchdog`` when that matters; XLA offers no portable async cancel.)
    """
    devices = list(devices if devices is not None else jax.devices())
    healthy = []
    for d in devices:
        t0 = time.monotonic()
        try:
            y = jax.device_put(np.ones((8,), np.float32), d) + 1.0
            ok = bool(np.all(np.asarray(y) == 2.0))
        except Exception as e:  # noqa: BLE001 — any backend error is a failure
            raise HealthCheckError(f"device {d} failed liveness probe: {e}")
        if not ok:
            raise HealthCheckError(f"device {d} returned wrong result")
        if time.monotonic() - t0 > timeout_s:
            raise HealthCheckError(f"device {d} probe exceeded {timeout_s}s")
        healthy.append(d)
    return healthy


def supervise(train_fn: Callable, params, seeds, *args,
              ckpt_dir: str, every: int, max_restarts: int = 3,
              on_failure: Callable[[int, BaseException], None] | None = None,
              healthcheck: bool = False, **kwargs):
    """Run a strategy launcher under failure supervision.

    Each attempt drives ``run_with_checkpointing`` (segment size ``every``);
    a raised exception costs one restart, optionally re-probes the devices,
    and the next attempt resumes from the last published checkpoint — work
    completed before the failure is never recomputed, and the final params
    equal an uninterrupted run (tests/test_failure.py). ``on_failure`` is
    called with ``(attempt, exception)`` before each restart.
    """
    last: BaseException | None = None
    for attempt in range(max_restarts + 1):
        try:
            return run_with_checkpointing(train_fn, params, seeds, *args,
                                          ckpt_dir=ckpt_dir, every=every,
                                          **kwargs)
        except Exception as e:  # noqa: BLE001 — supervisor catches all
            last = e
            if attempt == max_restarts:
                break  # exhausted: no restart follows, skip the probes
            if on_failure is not None:
                on_failure(attempt, e)
            if healthcheck:
                device_healthcheck()
    raise RuntimeError(
        f"training failed after {max_restarts} restarts") from last
