"""Trace-driven workload layer: replayable load traces for the fleet.

Every throughput/SLO claim before round 19 was measured on hand-rolled
prompt waves — fixed lengths, submitted all at once. DistServe's
goodput framing only means something relative to a STATED workload,
and Sarathi-Serve's stall-centric ITL behavior emerges specifically
under bursty arrivals and heavy-tail lengths that fixed waves never
exercise. This module is the missing measurement plane's input half: a
seeded, fully deterministic trace **generator** plus a versioned JSONL
trace **file format**, so "heavy traffic" claims are falsifiable —
the same ``(trace, seed)`` replayed twice yields byte-identical tokens
and identical admission order (``decode/workload_driver.py`` is the
replay half).

**The spec grammar** (``--trace_gen``; comma-separated ``key=value``,
the ``--chaos`` parse-rejection discipline — every malformed entry is
ONE ValueError naming the offense)::

    spec    := entry ("," entry)*
    entry   := "n=" INT                          total requests (required)
             | "arrival=" ARRIVAL                default poisson:8
             | "plen=" SAMPLER                   default fixed:6
             | "max_new=" SAMPLER | INT          default fixed:4
             | "tenants=" NAME ":" W (";" NAME ":" W)*   default none
             | "sessions=" K [":" GROW]          default none
             | "seed=" INT                       default 0
    ARRIVAL := "poisson:" RATE                   open-loop, rate req/s
             | "bursty:" RATE ":" ON_S ":" OFF_S on/off bursts
             | "ramp:" LO ":" HI                 rate ramps LO -> HI
    SAMPLER := "fixed:" N
             | "uniform:" LO ":" HI
             | "zipf:" ALPHA ":" LO ":" HI       heavy tail, clamped

- **Arrivals** are OPEN-LOOP (the DistServe stance): offsets are drawn
  up front from the seeded RNG, independent of service times, so an
  overloaded fleet sees the queue build instead of the workload
  politely backing off. ``bursty`` alternates ON windows at RATE with
  silent OFF windows; ``ramp`` interpolates the rate linearly across
  the trace (the diurnal shape compressed).
- **Heavy-tail lengths**: ``zipf:a:lo:hi`` draws ``lo - 1 + Zipf(a)``
  clamped to ``[lo, hi]`` — most prompts short, a heavy tail of long
  ones, bounds explicit so a trace can never exceed an engine's
  capacity by accident.
- **Sessions** (``sessions=K[:GROW]``): requests are dealt round-robin
  to K sessions; a session's turn ``t`` prompt is the first
  ``base + t * GROW`` tokens of ONE fixed per-session token stream, so
  each turn's prompt literally REGROWS the previous turn's as a prefix
  — the chat-shaped workload the radix prefix cache exists for
  (``decode/prefix.py``). GROW defaults to 4.
- **Tenants** (``tenants=a:3;b:1``): each request is tagged with a
  tenant drawn from the weighted mix (seeded). The tag travels the
  whole serving plane (schema v13: pinned on request/span records,
  folded per-tenant by ``report``) — the noisy-tenant drill is this
  knob plus two traces.

**The trace file** (``TRACE_VERSION`` 1): line 1 is the header
``{"trace_version", "id", "seed", "spec", "n"}`` — ``id`` is a stable
hash of ``(spec, seed)``, the identity ``workload`` telemetry records
pin — then one JSON object per request::

    {"t_offset_s", "uid_hint", "tenant", "session", "prompt_len",
     "max_new", "turn"}

``prompt_tokens`` (an explicit id list) may replace ``prompt_len`` for
hand-written traces; generated traces store lengths and the driver
materializes token ids deterministically from ``(seed, session)`` —
same stream per session, which is what makes turn prompts shared
prefixes. ``read_trace`` REJECTS damage with one-line ``TraceError``s
(missing/ bad header, version skew, missing keys, non-monotonic
offsets, torn tail): a trace is a determinism proof's input, so a torn
file is rc 2, never a best-effort parse (the opposite stance from the
telemetry stream's skip-and-report).

Deliberately jax-free (stdlib + numpy): generating a trace must not
pay a backend import, and the report/fleetstat tooling can read trace
identities without one.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

TRACE_VERSION = 1

# header + per-line required keys (the file-format contract
# tests/test_workload.py pins; prompt_tokens may replace prompt_len)
TRACE_HEADER_KEYS = ("trace_version", "id", "seed", "spec", "n")
TRACE_ENTRY_KEYS = ("t_offset_s", "uid_hint", "tenant", "session",
                    "max_new", "turn")

ARRIVAL_KINDS = ("poisson", "bursty", "ramp")
SAMPLER_KINDS = ("fixed", "uniform", "zipf")


class TraceError(ValueError):
    """A trace file failed validation (one-line named reason)."""


# the per-tenant JSON bucket for the single-tenant (None) case — ONE
# definition shared by the replay driver's cumulative book and the
# report fold, so the two sides can never drift on the key and break
# the reconciliation
DEFAULT_TENANT = "default"


def tenant_key(tenant) -> str:
    return DEFAULT_TENANT if tenant is None else str(tenant)


def _positive(name: str, val: float, *, integer: bool = False):
    if integer and val != int(val):
        raise ValueError(f"bad --trace_gen {name} {val!r}: must be an "
                         "integer")
    if val <= 0:
        raise ValueError(f"bad --trace_gen {name} {val!r}: must be > 0")
    return int(val) if integer else float(val)


def _parse_sampler(name: str, text: str) -> tuple:
    kind, _, rest = text.partition(":")
    if kind not in SAMPLER_KINDS:
        raise ValueError(f"bad --trace_gen {name} kind {kind!r}: known "
                         f"samplers {SAMPLER_KINDS}")
    parts = rest.split(":") if rest else []
    try:
        args = [float(x) for x in parts]
    except ValueError:
        raise ValueError(f"bad --trace_gen {name} args {rest!r}: "
                         "sampler args are numbers") from None
    if kind == "fixed":
        if len(args) != 1:
            raise ValueError(f"bad --trace_gen {name}: fixed takes "
                             "exactly one arg (fixed:N)")
        return ("fixed", _positive(name, args[0], integer=True))
    if kind == "uniform":
        if len(args) != 2:
            raise ValueError(f"bad --trace_gen {name}: uniform takes "
                             "LO:HI")
        lo = _positive(name, args[0], integer=True)
        hi = _positive(name, args[1], integer=True)
        if hi < lo:
            raise ValueError(f"bad --trace_gen {name}: uniform hi "
                             f"{hi} < lo {lo}")
        return ("uniform", lo, hi)
    if len(args) != 3:
        raise ValueError(f"bad --trace_gen {name}: zipf takes "
                         "ALPHA:LO:HI")
    alpha = args[0]
    if alpha <= 1.0:
        raise ValueError(f"bad --trace_gen {name}: zipf alpha "
                         f"{alpha!r} must be > 1")
    lo = _positive(name, args[1], integer=True)
    hi = _positive(name, args[2], integer=True)
    if hi < lo:
        raise ValueError(f"bad --trace_gen {name}: zipf hi {hi} < lo "
                         f"{lo}")
    return ("zipf", alpha, lo, hi)


def parse_trace_spec(spec: str) -> dict:
    """Parse + validate one ``--trace_gen`` spec (see the module
    docstring grammar). Returns the normalized spec dict the generator
    consumes; every malformed entry raises ONE ``ValueError`` naming
    it — the ``--chaos`` parse-rejection discipline."""
    out = {"n": None, "arrival": ("poisson", 8.0),
           "plen": ("fixed", 6), "max_new": ("fixed", 4),
           "tenants": None, "sessions": None, "seed": 0,
           "spec": spec}
    seen = set()
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        if "=" not in entry:
            raise ValueError(
                f"bad --trace_gen entry {entry!r}: expected key=value "
                "with key in n/arrival/plen/max_new/tenants/sessions/"
                "seed")
        key, _, val = entry.partition("=")
        if key in seen:
            raise ValueError(f"bad --trace_gen spec: duplicate key "
                             f"{key!r}")
        seen.add(key)
        if key == "n":
            try:
                out["n"] = int(val)
            except ValueError:
                raise ValueError(f"bad --trace_gen n {val!r}: must be "
                                 "an integer") from None
            if out["n"] < 1:
                raise ValueError(f"bad --trace_gen n {out['n']}: must "
                                 "be >= 1")
        elif key == "arrival":
            kind, _, rest = val.partition(":")
            if kind not in ARRIVAL_KINDS:
                raise ValueError(f"bad --trace_gen arrival kind "
                                 f"{kind!r}: known kinds "
                                 f"{ARRIVAL_KINDS}")
            try:
                args = [float(x) for x in rest.split(":")] if rest \
                    else []
            except ValueError:
                raise ValueError(f"bad --trace_gen arrival args "
                                 f"{rest!r}: numbers required") \
                    from None
            want = {"poisson": 1, "bursty": 3, "ramp": 2}[kind]
            if len(args) != want:
                raise ValueError(
                    f"bad --trace_gen arrival: {kind} takes {want} "
                    "arg(s) (poisson:RATE / bursty:RATE:ON_S:OFF_S / "
                    "ramp:LO:HI)")
            for a in args:
                _positive("arrival", a)
            out["arrival"] = (kind, *args)
        elif key in ("plen", "max_new"):
            if key == "max_new" and ":" not in val:
                # bare INT shorthand: max_new=4 == max_new=fixed:4
                try:
                    out["max_new"] = ("fixed",
                                      _positive("max_new", int(val),
                                                integer=True))
                    continue
                except ValueError:
                    raise ValueError(f"bad --trace_gen max_new "
                                     f"{val!r}") from None
            out[key] = _parse_sampler(key, val)
        elif key == "tenants":
            mix = []
            for part in (p.strip() for p in val.split(";")
                         if p.strip()):
                name, sep, w = part.partition(":")
                if not name or not sep:
                    raise ValueError(
                        f"bad --trace_gen tenants entry {part!r}: "
                        "expected NAME:WEIGHT (e.g. tenants=a:3;b:1)")
                try:
                    weight = float(w)
                except ValueError:
                    raise ValueError(f"bad --trace_gen tenants weight "
                                     f"{w!r}: must be a number") \
                        from None
                if weight <= 0:
                    raise ValueError(f"bad --trace_gen tenants weight "
                                     f"{weight}: must be > 0")
                mix.append((name, weight))
            if not mix:
                raise ValueError("bad --trace_gen tenants: empty mix")
            if len({n for n, _ in mix}) != len(mix):
                raise ValueError("bad --trace_gen tenants: duplicate "
                                 "tenant name")
            out["tenants"] = mix
        elif key == "sessions":
            parts = val.split(":")
            try:
                nums = [int(x) for x in parts]
            except ValueError:
                raise ValueError(f"bad --trace_gen sessions {val!r}: "
                                 "want K or K:GROW (integers)") \
                    from None
            if len(nums) not in (1, 2) or nums[0] < 1:
                raise ValueError(f"bad --trace_gen sessions {val!r}: "
                                 "want K[:GROW] with K >= 1")
            grow = nums[1] if len(nums) == 2 else 4
            if grow < 1:
                raise ValueError(f"bad --trace_gen sessions grow "
                                 f"{grow}: must be >= 1")
            out["sessions"] = (nums[0], grow)
        elif key == "seed":
            try:
                out["seed"] = int(val)
            except ValueError:
                raise ValueError(f"bad --trace_gen seed {val!r}: must "
                                 "be an integer") from None
        else:
            raise ValueError(
                f"bad --trace_gen key {key!r}: known keys "
                "n/arrival/plen/max_new/tenants/sessions/seed")
    if out["n"] is None:
        raise ValueError("bad --trace_gen spec: n=INT is required "
                         "(total requests)")
    return out


def trace_id_of(spec: str, seed: int) -> str:
    """The trace's stable identity: a hash of ``(spec, seed)`` — the
    same generator inputs always name the same trace, with no
    wall-clock or process entropy (replay IS the determinism proof, so
    the id must replay too)."""
    h = hashlib.sha256(f"{spec}\x00{seed}".encode()).hexdigest()
    return f"tr{h[:12]}"


def _arrivals(arrival: tuple, n: int, rng) -> list[float]:
    """Open-loop arrival offsets (seconds, non-decreasing, first at
    0.0 so replay always has work on round 0)."""
    kind = arrival[0]
    if kind == "poisson":
        rate = arrival[1]
        gaps = rng.exponential(1.0 / rate, size=n)
    elif kind == "bursty":
        rate, on_s, off_s = arrival[1], arrival[2], arrival[3]
        gaps = []
        t_in_window = 0.0
        for g in rng.exponential(1.0 / rate, size=n):
            gap = float(g)
            t_in_window += gap
            while t_in_window > on_s:
                # the ON window closed mid-gap: push the arrival past
                # the OFF window (the silent half of the duty cycle)
                t_in_window -= on_s
                gap += off_s
            gaps.append(gap)
        gaps = np.asarray(gaps)
    else:   # ramp
        lo, hi = arrival[1], arrival[2]
        # rate interpolates lo -> hi across the trace: draw each gap at
        # the CURRENT position's rate (the diurnal shape compressed)
        fracs = np.arange(n) / max(n - 1, 1)
        rates = lo + (hi - lo) * fracs
        gaps = rng.exponential(1.0, size=n) / rates
    offs = np.cumsum(gaps)
    offs -= offs[0]                 # first arrival at t 0
    return [round(float(t), 6) for t in offs]


def _sample(sampler: tuple, rng) -> int:
    kind = sampler[0]
    if kind == "fixed":
        return sampler[1]
    if kind == "uniform":
        lo, hi = sampler[1], sampler[2]
        return int(rng.integers(lo, hi + 1))
    alpha, lo, hi = sampler[1], sampler[2], sampler[3]
    return int(min(hi, lo - 1 + rng.zipf(alpha)))


def generate_trace(spec: str | dict) -> tuple[dict, list[dict]]:
    """Generate one trace from a spec (string or pre-parsed dict):
    returns ``(header, entries)``. Fully deterministic in
    ``(spec, seed)`` — no wall clock, no process entropy."""
    cfg = parse_trace_spec(spec) if isinstance(spec, str) else spec
    n = cfg["n"]
    rng = np.random.default_rng(cfg["seed"])
    offsets = _arrivals(cfg["arrival"], n, rng)
    tenants = cfg["tenants"]
    if tenants is not None:
        names = [t for t, _ in tenants]
        weights = np.asarray([w for _, w in tenants], np.float64)
        weights /= weights.sum()
        picks = rng.choice(len(names), size=n, p=weights)
    sessions = cfg["sessions"]
    turn_of: dict[str, int] = {}
    base_plen: dict[str, int] = {}
    entries = []
    for i in range(n):
        session = None
        turn = 0
        if sessions is not None:
            k, grow = sessions
            session = f"s{i % k}"
            turn = turn_of.get(session, 0)
            turn_of[session] = turn + 1
            if session not in base_plen:
                base_plen[session] = _sample(cfg["plen"], rng)
            plen = base_plen[session] + turn * grow
        else:
            plen = _sample(cfg["plen"], rng)
        entries.append({
            "t_offset_s": offsets[i],
            "uid_hint": i,
            "tenant": (names[int(picks[i])] if tenants is not None
                       else None),
            "session": session,
            "prompt_len": plen,
            "max_new": _sample(cfg["max_new"], rng),
            "turn": turn,
        })
    header = {"trace_version": TRACE_VERSION,
              "id": trace_id_of(cfg["spec"], cfg["seed"]),
              "seed": cfg["seed"], "spec": cfg["spec"], "n": n}
    return header, entries


def write_trace(path: str, header: dict, entries: list[dict]) -> str:
    """Persist one trace: header line + one JSON object per request,
    through the wire layer's atomic publish (a half-written trace
    must never replay as a shorter workload)."""
    lines = [json.dumps(header)]
    lines.extend(json.dumps(e) for e in entries)
    from .wire import publish_bytes
    publish_bytes(path, ("\n".join(lines) + "\n").encode("utf-8"))
    return path


def materialize_prompt(header: dict, entry: dict, vocab: int) -> list:
    """The entry's token ids, deterministically. An explicit
    ``prompt_tokens`` list wins (hand-written traces); otherwise the
    ids are the first ``prompt_len`` tokens of ONE fixed stream keyed
    by ``(trace seed, session or uid_hint)`` — the same session's
    turns therefore share a literally identical growing prefix (the
    prefix-cache workload), while distinct sessions/uids diverge."""
    if entry.get("prompt_tokens") is not None:
        toks = [int(t) for t in entry["prompt_tokens"]]
        if any(not 0 <= t < vocab for t in toks):
            raise TraceError(
                f"trace entry uid_hint {entry.get('uid_hint')}: "
                f"prompt_tokens out of vocab range [0, {vocab})")
        return toks
    key = entry.get("session") or f"u{entry['uid_hint']}"
    digest = hashlib.sha256(key.encode()).digest()
    stream_seed = [int(header["seed"]) & 0x7FFFFFFF,
                   int.from_bytes(digest[:4], "big")]
    rng = np.random.default_rng(stream_seed)
    plen = int(entry["prompt_len"])
    return rng.integers(0, vocab, size=plen).tolist()


def read_trace(path: str) -> tuple[dict, list[dict]]:
    """Parse + validate one trace file: ``(header, entries)``. Every
    rejection is a one-line ``TraceError`` naming the damage — a
    trace is a determinism proof's input, so a torn tail or missing
    key is fatal (rc 2 at the CLI), never skipped."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        raise TraceError(f"trace {path}: {e}") from None
    lines = [ln for ln in lines if ln.strip()]
    if not lines:
        raise TraceError(f"trace {path}: empty file (no header line)")
    try:
        header = json.loads(lines[0])
    except ValueError:
        raise TraceError(f"trace {path}: line 1 is not a JSON header "
                         "(torn or not a trace file)") from None
    if not isinstance(header, dict):
        raise TraceError(f"trace {path}: header is not a JSON object")
    if header.get("trace_version") != TRACE_VERSION:
        raise TraceError(
            f"trace {path}: trace_version "
            f"{header.get('trace_version')!r} != {TRACE_VERSION}")
    missing = [k for k in TRACE_HEADER_KEYS if k not in header]
    if missing:
        raise TraceError(f"trace {path}: header missing key(s) "
                         f"{missing}")
    entries = []
    prev_t = -1.0
    for i, line in enumerate(lines[1:], 2):
        try:
            e = json.loads(line)
        except ValueError:
            raise TraceError(f"trace {path}: line {i} unparseable "
                             "(torn write?)") from None
        if not isinstance(e, dict):
            raise TraceError(f"trace {path}: line {i} is not a JSON "
                             "object")
        missing = [k for k in TRACE_ENTRY_KEYS if k not in e]
        if missing:
            raise TraceError(f"trace {path}: line {i} missing key(s) "
                             f"{missing}")
        if "prompt_len" not in e and "prompt_tokens" not in e:
            raise TraceError(f"trace {path}: line {i} needs "
                             "prompt_len or prompt_tokens")
        if e.get("prompt_tokens") is None and int(e["prompt_len"]) < 1:
            raise TraceError(f"trace {path}: line {i} prompt_len "
                             f"{e['prompt_len']} must be >= 1")
        if int(e["max_new"]) < 1:
            raise TraceError(f"trace {path}: line {i} max_new "
                             f"{e['max_new']} must be >= 1")
        t = float(e["t_offset_s"])
        if t < prev_t:
            raise TraceError(
                f"trace {path}: line {i} t_offset_s {t} < previous "
                f"{prev_t} (offsets must be non-decreasing — replay "
                "submits in file order)")
        prev_t = t
        entries.append(e)
    if len(entries) != int(header["n"]):
        raise TraceError(
            f"trace {path}: header says n={header['n']} but file "
            f"holds {len(entries)} entr(ies) (torn tail?)")
    return header, entries


def _main(argv=None) -> int:
    """``python -m ...runtime.workload SPEC OUT.jsonl`` — generate a
    trace file standalone (the CLI's ``--trace_gen --trace_out`` pair
    without booting an engine)."""
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 2:
        print("usage: runtime.workload SPEC OUT.jsonl", file=sys.stderr)
        return 2
    try:
        header, entries = generate_trace(argv[0])
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    write_trace(argv[1], header, entries)
    print(json.dumps({"trace": argv[1], **header}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main())
