"""Request span tracing: where one serving request's latency goes.

PR 5's ``request`` records say WHAT happened to a request (admitted /
quarantined / completed); nothing says where its wall-clock went —
queue time vs prefill vs decode vs preemption churn. This module is the
missing phase accounting: a ``SpanTracer`` tracks one OPEN span per
uid and emits a schema-v5 ``span`` record every time the request
changes phase, through the same ``TelemetryWriter`` every other record
kind rides.

The span vocabulary (``telemetry.SPAN_NAMES``):

- ``queued`` — submit (or snapshot re-queue) -> admission,
- ``prefill`` — one span PER PREFILL CHUNK (each starts where the
  previous chunk's span ended, so a long prompt's chunk spans tile the
  whole prefill phase, engine steps spent on other slots included),
- ``replay`` — the teacher-forcing window after a re-admission
  (recorded tokens re-fed to rebuild the KV write history),
- ``decode`` — live token generation, one span per contiguous segment
  (a preemption or quarantine ends the segment); segment-ending
  records carry a ``tokens`` extra — under speculative decoding
  (round 12) a segment's step count and its token count diverge, and
  the span is where the per-segment yield lives,
- ``quarantine`` — quarantine -> re-admission (zero-length when the
  retry budget is exhausted and the request fails terminally),
- ``preempt_gap`` — pool-pressure eviction -> re-admission.

**The telescoping-clock contract.** Every transition closes the open
span and opens its successor at the SAME timestamp; the first span
opens at the request's ``t_submit`` and the last closes at the
completion timestamp the ``latency_s`` request record uses. Span
durations therefore sum — exactly, up to rounding — to the request's
recorded latency, which is what lets ``report``'s waterfall view
RECONCILE the phase breakdown against the latency percentiles instead
of presenting two unrelated numbers (the observability analogue of the
repo's differential-testing stance).

**Crash behavior.** Open spans are process state and die with it;
emitted spans are already on disk. An in-process supervisor restart
replays steps whose spans were already emitted — the replayed records
are byte-identical in ``(uid, span, start_step, step)`` and ``report``
dedups them exactly like replayed ``request`` records. A crash-resume
opens a fresh ``queued`` span at resume time, so the crash gap itself
is visibly unaccounted (the waterfall flags the request unreconciled
rather than inventing a phase for dead time).
"""

from __future__ import annotations

import time
from typing import Callable


class SpanTracer:
    """Per-uid lifecycle span tracking (one open span per uid).

    ``metrics_fn`` returns the live ``TelemetryWriter`` (or None) at
    emit time — the engine re-binds its writer mid-life
    (``DecodeEngine.run(metrics=...)``), so the tracer must not capture
    it at construction. All methods are host-side and O(1); with no
    writer attached the tracer still tracks phases (close/transition
    stay cheap no-ops on the emit half).
    """

    def __init__(self, metrics_fn: Callable):
        self._metrics_fn = metrics_fn
        self._open: dict[int, dict] = {}   # uid -> open-span state

    def open(self, uid: int, span: str, step: int,
             t: float | None = None) -> None:
        """Start ``uid``'s FIRST span (``queued``) at ``t`` (defaults
        to now; pass the request's ``t_submit`` so queue time counts
        from submission, not from bookkeeping)."""
        self._open[int(uid)] = {"span": span, "start_step": int(step),
                                "start_t": time.time() if t is None
                                else float(t)}

    def transition(self, uid: int, span: str, step: int,
                   t: float | None = None, **extra) -> None:
        """Close ``uid``'s open span at ``t`` (emitting its record,
        ``extra`` attached) and open ``span`` at the same instant —
        the telescoping handoff that makes span sums reconcile."""
        uid = int(uid)
        now = time.time() if t is None else float(t)
        cur = self._open.get(uid)
        if cur is not None:
            self._emit(uid, cur, int(step), now, extra)
        self._open[uid] = {"span": span, "start_step": int(step),
                           "start_t": now}

    def close(self, uid: int, step: int, t: float | None = None,
              **extra) -> None:
        """Close ``uid``'s open span with no successor (completion,
        terminal failure, deadline expiry)."""
        uid = int(uid)
        cur = self._open.pop(uid, None)
        if cur is None:
            return
        now = time.time() if t is None else float(t)
        self._emit(uid, cur, int(step), now, extra)

    def _emit(self, uid: int, cur: dict, end_step: int, end_t: float,
              extra: dict) -> None:
        metrics = self._metrics_fn()
        if metrics is None:
            return
        metrics.span({
            "uid": uid,
            "span": cur["span"],
            "start_step": cur["start_step"],
            "step": end_step,
            "start_t": cur["start_t"],
            "t": end_t,
            "duration_s": round(end_t - cur["start_t"], 6),
            **extra,
        })
