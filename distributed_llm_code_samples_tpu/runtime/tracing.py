"""Request span tracing: where one serving request's latency goes.

PR 5's ``request`` records say WHAT happened to a request (admitted /
quarantined / completed); nothing says where its wall-clock went —
queue time vs prefill vs decode vs preemption churn. This module is the
missing phase accounting: a ``SpanTracer`` tracks one OPEN span per
uid and emits a schema-v5 ``span`` record every time the request
changes phase, through the same ``TelemetryWriter`` every other record
kind rides.

The span vocabulary (``telemetry.SPAN_NAMES``):

- ``queued`` — submit (or snapshot re-queue) -> admission,
- ``prefill`` — one span PER PREFILL CHUNK (each starts where the
  previous chunk's span ended, so a long prompt's chunk spans tile the
  whole prefill phase, engine steps spent on other slots included),
- ``replay`` — the teacher-forcing window after a re-admission
  (recorded tokens re-fed to rebuild the KV write history),
- ``decode`` — live token generation, one span per contiguous segment
  (a preemption or quarantine ends the segment); segment-ending
  records carry a ``tokens`` extra — under speculative decoding
  (round 12) a segment's step count and its token count diverge, and
  the span is where the per-segment yield lives,
- ``quarantine`` — quarantine -> re-admission (zero-length when the
  retry budget is exhausted and the request fails terminally),
- ``preempt_gap`` — pool-pressure eviction -> re-admission.

**The telescoping-clock contract.** Every transition closes the open
span and opens its successor at the SAME timestamp; the first span
opens at the request's ``t_submit`` and the last closes at the
completion timestamp the ``latency_s`` request record uses. Span
durations therefore sum — exactly, up to rounding — to the request's
recorded latency, which is what lets ``report``'s waterfall view
RECONCILE the phase breakdown against the latency percentiles instead
of presenting two unrelated numbers (the observability analogue of the
repo's differential-testing stance).

**First-token marks (round 15).** The tracer also keeps one
first-token timestamp per uid (``mark_first_token``), set by the
engine at the instant the prefill-completing chunk emits its pick —
the same timestamp that closes the prefill span and opens the first
decode span. Completed ``request`` records carry it as ``ttft_s``
(schema v9), and because the mark sits exactly on a span boundary,
``ttft_s == sum(pre-first-token spans)`` and ``ttft_s +
sum(post-first-token spans) == latency_s`` hold by the same
telescoping argument as the full reconciliation. The mark travels
with the sequence (snapshot v5, handoff v2); when the first token
predates a crash-resume with no persisted mark, ``ttft_s`` is null —
unreconstructable, never invented.

**Crash behavior.** Open spans are process state and die with it;
emitted spans are already on disk. An in-process supervisor restart
replays steps whose spans were already emitted — the replayed records
are byte-identical in ``(uid, span, start_step, step)`` and ``report``
dedups them exactly like replayed ``request`` records. A crash-resume
opens a fresh ``queued`` span at resume time, so the crash gap itself
is visibly unaccounted (the waterfall flags the request unreconciled
rather than inventing a phase for dead time).
"""

from __future__ import annotations

import time
from typing import Callable


class SpanTracer:
    """Per-uid lifecycle span tracking (one open span per uid).

    ``metrics_fn`` returns the live ``TelemetryWriter`` (or None) at
    emit time — the engine re-binds its writer mid-life
    (``DecodeEngine.run(metrics=...)``), so the tracer must not capture
    it at construction. ``trace_fn(uid)`` returns the uid's causal
    ``trace_id`` (schema v12: every span record pins it — the stitch
    key of the cross-process trace waterfall; None with no trace
    plumbed, e.g. standalone tracer tests). ``tenant_fn(uid)`` returns
    the uid's tenant tag (schema v13: every span record pins it — the
    per-tenant ITL slice reads decode-segment spans by tenant; None
    single-tenant). All methods are host-side and O(1); with no writer
    attached the tracer still tracks phases (close/transition stay
    cheap no-ops on the emit half).
    """

    def __init__(self, metrics_fn: Callable,
                 trace_fn: Callable | None = None,
                 tenant_fn: Callable | None = None):
        self._metrics_fn = metrics_fn
        self._trace_fn = trace_fn
        self._tenant_fn = tenant_fn
        self._open: dict[int, dict] = {}   # uid -> open-span state
        # uid -> wall clock of the FIRST live token (round 15, the
        # TTFT decomposition): marked once at the prefill-completing
        # chunk's emission instant — the SAME timestamp that closes the
        # prefill span and opens the first decode span, so
        # ``ttft = t_first - t_submit`` equals the pre-first-token span
        # sum EXACTLY and ``ttft + post-first-token spans == latency``
        # telescopes by construction. Keyed by uid (not admission), so
        # preemption/retry churn keeps the original first-token time.
        self._first: dict[int, float] = {}

    def open(self, uid: int, span: str, step: int,
             t: float | None = None) -> None:
        """Start ``uid``'s FIRST span (``queued``) at ``t`` (defaults
        to now; pass the request's ``t_submit`` so queue time counts
        from submission, not from bookkeeping)."""
        self._open[int(uid)] = {"span": span, "start_step": int(step),
                                "start_t": time.time() if t is None
                                else float(t)}

    def transition(self, uid: int, span: str, step: int,
                   t: float | None = None, **extra) -> None:
        """Close ``uid``'s open span at ``t`` (emitting its record,
        ``extra`` attached) and open ``span`` at the same instant —
        the telescoping handoff that makes span sums reconcile."""
        uid = int(uid)
        now = time.time() if t is None else float(t)
        cur = self._open.get(uid)
        if cur is not None:
            self._emit(uid, cur, int(step), now, extra)
        self._open[uid] = {"span": span, "start_step": int(step),
                           "start_t": now}

    def mark_first_token(self, uid: int, t: float) -> None:
        """Record ``uid``'s first-token timestamp (idempotent: the
        first mark wins, so a replay re-reaching the prefill boundary
        — or a restore re-installing a persisted mark — never moves
        it)."""
        self._first.setdefault(int(uid), float(t))

    def first_token_t(self, uid: int) -> float | None:
        """The marked first-token wall clock, or None when the first
        token predates this tracer's life (crash-resume without a
        persisted mark — the decomposition is then honestly
        unreconstructable)."""
        return self._first.get(int(uid))

    def pop_first_token(self, uid: int) -> float | None:
        """``first_token_t`` + forget — the terminal-transition form
        (completion / terminal failure / handoff export)."""
        return self._first.pop(int(uid), None)

    def close(self, uid: int, step: int, t: float | None = None,
              **extra) -> None:
        """Close ``uid``'s open span with no successor (completion,
        terminal failure, deadline expiry)."""
        uid = int(uid)
        cur = self._open.pop(uid, None)
        if cur is None:
            return
        now = time.time() if t is None else float(t)
        self._emit(uid, cur, int(step), now, extra)

    def _emit(self, uid: int, cur: dict, end_step: int, end_t: float,
              extra: dict) -> None:
        metrics = self._metrics_fn()
        if metrics is None:
            return
        metrics.span({
            "uid": uid,
            "trace_id": (self._trace_fn(uid) if self._trace_fn
                         is not None else None),
            "tenant": (self._tenant_fn(uid) if self._tenant_fn
                       is not None else None),
            "span": cur["span"],
            "start_step": cur["start_step"],
            "step": end_step,
            "start_t": cur["start_t"],
            "t": end_t,
            "duration_s": round(end_t - cur["start_t"], 6),
            **extra,
        })
