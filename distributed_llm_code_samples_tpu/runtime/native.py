"""ctypes bindings + build driver for the native C++ runtime components.

Covers the SURVEY.md section 2.7 native-surface ledger: host ring
collectives (``collectives.cpp``), prefetching seeded data loader
(``dataloader.cpp``), TCP rendezvous/barrier (``rendezvous.cpp``), and the
XLA-FFI custom-call kernels (``ffi_ops.cpp``). Libraries are built on
demand with the in-tree Makefile (g++ is assumed; there is no wheel step).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Sequence

import numpy as np

from .. import DLOSS_DX_COEF

_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(_DIR), "native")
_LIB = None
_FFI_LIB = None
_FFI_REGISTERED = False


def _make(target: str, env_extra: dict | None = None) -> None:
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run(["make", "-C", _NATIVE_DIR, target],
                          capture_output=True, text=True, env=env)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed (make {target}):\n{proc.stdout}\n"
            f"{proc.stderr}")


def lib() -> ctypes.CDLL:
    """The host-runtime library, built on first use."""
    global _LIB
    if _LIB is None:
        # always invoke make: its prerequisite rules rebuild only when the
        # sources are newer than the .so (stale-binary trap otherwise)
        path = os.path.join(_NATIVE_DIR, "libdlcs_native.so")
        _make("all")
        _LIB = ctypes.CDLL(path)
        _LIB.dlcs_loader_create.restype = ctypes.c_void_p
        _LIB.dlcs_loader_create.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                            ctypes.c_int, ctypes.c_float]
        _LIB.dlcs_loader_submit.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        _LIB.dlcs_loader_next.restype = ctypes.c_int64
        _LIB.dlcs_loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_void_p]
        _LIB.dlcs_loader_destroy.argtypes = [ctypes.c_void_p]
        _LIB.dlcs_rdzv_coordinator.restype = ctypes.c_void_p
        _LIB.dlcs_rdzv_coordinator.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                               ctypes.c_int]
        _LIB.dlcs_rdzv_join.restype = ctypes.c_void_p
        _LIB.dlcs_rdzv_join.argtypes = [ctypes.c_char_p, ctypes.c_int]
        for f in ("dlcs_rdzv_rank", "dlcs_rdzv_world", "dlcs_rdzv_barrier"):
            getattr(_LIB, f).restype = ctypes.c_int
            getattr(_LIB, f).argtypes = [ctypes.c_void_p]
        _LIB.dlcs_rdzv_barrier_timeout.restype = ctypes.c_int
        _LIB.dlcs_rdzv_barrier_timeout.argtypes = [ctypes.c_void_p,
                                                   ctypes.c_int]
        _LIB.dlcs_rdzv_destroy.argtypes = [ctypes.c_void_p]
        _LIB.dlcs_watchdog_create.restype = ctypes.c_void_p
        _LIB.dlcs_watchdog_create.argtypes = [ctypes.c_int]
        _LIB.dlcs_watchdog_kick.argtypes = [ctypes.c_void_p]
        _LIB.dlcs_watchdog_expired.restype = ctypes.c_int
        _LIB.dlcs_watchdog_expired.argtypes = [ctypes.c_void_p]
        _LIB.dlcs_watchdog_destroy.argtypes = [ctypes.c_void_p]
        _LIB.dlcs_ckpt_writer_create.restype = ctypes.c_void_p
        _LIB.dlcs_ckpt_writer_create.argtypes = [ctypes.c_int]
        _LIB.dlcs_ckpt_writer_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        for f in ("dlcs_ckpt_writer_pending", "dlcs_ckpt_writer_errors"):
            getattr(_LIB, f).restype = ctypes.c_int
            getattr(_LIB, f).argtypes = [ctypes.c_void_p]
        _LIB.dlcs_ckpt_writer_wait.argtypes = [ctypes.c_void_p]
        _LIB.dlcs_ckpt_writer_destroy.argtypes = [ctypes.c_void_p]
    return _LIB


def _float_ptr_array(arrays: Sequence[np.ndarray]):
    Ptrs = ctypes.POINTER(ctypes.c_float) * len(arrays)
    return Ptrs(*[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                  for a in arrays])


def _check_same_size(arrays: Sequence[np.ndarray]) -> None:
    sizes = {a.size for a in arrays}
    if len(sizes) != 1:
        raise ValueError(f"per-rank arrays must have equal sizes, got "
                         f"{[a.size for a in arrays]}")


# ---------------------------------------------------------------- collectives

def all_reduce_sum(arrays: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Ring all-reduce(SUM) across per-rank float32 arrays (native engine);
    returns the reduced copies, inputs untouched."""
    _check_same_size(arrays)
    bufs = [np.ascontiguousarray(a, dtype=np.float32).copy() for a in arrays]
    lib().dlcs_all_reduce_sum_f32(_float_ptr_array(bufs), len(bufs),
                                  ctypes.c_int64(bufs[0].size))
    return bufs


def all_gather(shards: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Ring all-gather: every rank receives the rank-order concatenation."""
    _check_same_size(shards)
    shards = [np.ascontiguousarray(s, dtype=np.float32) for s in shards]
    n, cnt = len(shards), shards[0].size
    outs = [np.empty(n * cnt, dtype=np.float32) for _ in range(n)]
    lib().dlcs_all_gather_f32(_float_ptr_array(shards),
                              _float_ptr_array(outs), n,
                              ctypes.c_int64(cnt))
    return outs


def reduce_scatter_sum(full_arrays: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Ring reduce-scatter(SUM): rank r receives the sum of everyone's
    r-th shard (arrays must have size divisible by n_ranks)."""
    _check_same_size(full_arrays)
    ins = [np.ascontiguousarray(a, dtype=np.float32).ravel()
           for a in full_arrays]
    n = len(ins)
    if ins[0].size % n:
        raise ValueError(f"array size {ins[0].size} not divisible by {n}")
    shard = ins[0].size // n
    outs = [np.empty(shard, dtype=np.float32) for _ in range(n)]
    lib().dlcs_reduce_scatter_sum_f32(_float_ptr_array(ins),
                                      _float_ptr_array(outs), n,
                                      ctypes.c_int64(shard))
    return outs


def ring_permute(arrays: Sequence[np.ndarray], shift: int = 1) -> list[np.ndarray]:
    """ppermute on a ring: out[(r+shift) % n] = in[r]."""
    _check_same_size(arrays)
    ins = [np.ascontiguousarray(a, dtype=np.float32) for a in arrays]
    outs = [np.empty_like(a) for a in ins]
    lib().dlcs_ring_permute_f32(_float_ptr_array(ins), _float_ptr_array(outs),
                                len(ins), ctypes.c_int64(ins[0].size),
                                ctypes.c_int(shift))
    return outs


# ---------------------------------------------------------------- data loader

class NativeLoader:
    """Prefetching native data loader (see ``dataloader.cpp``).

    Usage::

        with NativeLoader(batch, d) as loader:
            loader.submit_all(seeds)
            for _ in seeds:
                seed, x, dloss_dx = loader.next()
    """

    def __init__(self, batch: int, d: int, n_threads: int = 2,
                 dloss_coef: float = DLOSS_DX_COEF):
        self.batch, self.d = batch, d
        self._h = lib().dlcs_loader_create(batch, d, n_threads,
                                           ctypes.c_float(dloss_coef))

    def submit(self, seed: int) -> None:
        lib().dlcs_loader_submit(self._h, int(seed))

    def submit_all(self, seeds) -> None:
        for s in np.asarray(seeds).tolist():
            self.submit(s)

    def next(self):
        x = np.empty((self.batch, self.d), dtype=np.float32)
        dl = np.empty((self.batch, self.d), dtype=np.float32)
        seed = lib().dlcs_loader_next(
            self._h, x.ctypes.data_as(ctypes.c_void_p),
            dl.ctypes.data_as(ctypes.c_void_p))
        if seed < 0:
            raise RuntimeError("loader.next() called more times than "
                               "batches were submitted")
        return seed, x, dl

    def close(self) -> None:
        if self._h:
            lib().dlcs_loader_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ----------------------------------------------------------------- rendezvous

class Rendezvous:
    """TCP rendezvous + barrier (MASTER_ADDR/PORT analogue, rendezvous.cpp)."""

    def __init__(self, addr: str, port: int, world_size: int | None = None,
                 coordinator: bool = False):
        if coordinator:
            if world_size is None:
                raise ValueError("coordinator needs world_size")
            self._h = lib().dlcs_rdzv_coordinator(addr.encode(), port,
                                                  world_size)
        else:
            self._h = lib().dlcs_rdzv_join(addr.encode(), port)
        if not self._h:
            raise RuntimeError("rendezvous failed")

    @property
    def rank(self) -> int:
        return lib().dlcs_rdzv_rank(self._h)

    @property
    def world_size(self) -> int:
        return lib().dlcs_rdzv_world(self._h)

    def barrier(self) -> None:
        if lib().dlcs_rdzv_barrier(self._h) != 0:
            raise RuntimeError("barrier failed")

    def barrier_timeout(self, timeout_ms: int) -> None:
        """Barrier that detects dead/wedged peers instead of hanging
        (the reference's join() has no timeout, ``train_ffns.py:190-191``).
        Raises ``PeerFailure`` with the failure kind. After a failure the
        group is desynchronized (in-flight tokens may remain buffered):
        ``close()`` it and re-rendezvous — detection hands off to
        recovery, it does not resume the same barrier."""
        rc = lib().dlcs_rdzv_barrier_timeout(self._h, timeout_ms)
        if rc == 1:
            raise PeerFailure("peer connection lost (process died)")
        if rc == 2:
            raise PeerFailure(f"peer missed barrier within {timeout_ms}ms "
                              "(wedged)")

    def close(self) -> None:
        if self._h:
            lib().dlcs_rdzv_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PeerFailure(RuntimeError):
    """A rendezvous peer died or missed a sync deadline."""


class Watchdog:
    """Native hang detector: a monitor thread (immune to a GIL held by hung
    Python) latches ``expired`` if ``kick()`` isn't called within
    ``timeout_ms``. Check the latch *before* kicking — ``kick()`` clears
    it. Usage::

        with Watchdog(5_000) as dog:
            for step in schedule:
                train_step(...)
                if dog.expired:   # this step overran the deadline
                    recover()
                dog.kick()        # re-arm for the next step
    """

    def __init__(self, timeout_ms: int):
        self._h = lib().dlcs_watchdog_create(timeout_ms)
        if not self._h:
            raise RuntimeError("watchdog thread creation failed")

    def kick(self) -> None:
        lib().dlcs_watchdog_kick(self._h)

    @property
    def expired(self) -> bool:
        return bool(lib().dlcs_watchdog_expired(self._h))

    def close(self) -> None:
        if self._h:
            lib().dlcs_watchdog_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -------------------------------------------------------- XLA FFI custom ops

def register_ffi_targets() -> None:
    """Build + register the native XLA custom calls on the CPU platform."""
    global _FFI_LIB, _FFI_REGISTERED
    if _FFI_REGISTERED:
        return
    import jax
    import jax.ffi

    path = os.path.join(_NATIVE_DIR, "libdlcs_ffi.so")
    _make("ffi", {"JAXLIB_INCLUDE": jax.ffi.include_dir()})
    _FFI_LIB = ctypes.CDLL(path)
    jax.ffi.register_ffi_target(
        "dlcs_fused_sgd", jax.ffi.pycapsule(_FFI_LIB.DlcsFusedSgd),
        platform="cpu")
    jax.ffi.register_ffi_target(
        "dlcs_relu_bwd", jax.ffi.pycapsule(_FFI_LIB.DlcsReluBwd),
        platform="cpu")
    _FFI_REGISTERED = True


def fused_sgd(p, g, lr: float):
    """``p - lr * g`` as a native XLA custom call (CPU platform)."""
    import jax
    import jax.ffi
    import jax.numpy as jnp

    register_ffi_targets()
    call = jax.ffi.ffi_call("dlcs_fused_sgd",
                            jax.ShapeDtypeStruct(p.shape, p.dtype))
    return call(p, g, jnp.asarray(lr, dtype=jnp.float32))


def native_relu_bwd(dy, x):
    """``where(x <= 0, 0, dy)`` as a native XLA custom call (CPU platform)."""
    import jax
    import jax.ffi

    register_ffi_targets()
    call = jax.ffi.ffi_call("dlcs_relu_bwd",
                            jax.ShapeDtypeStruct(dy.shape, dy.dtype))
    return call(dy, x)


class AsyncCheckpointWriter:
    """Background checkpoint writes through the native worker pool
    (``native/ckpt_writer.cpp``): ``submit`` copies the buffers and
    returns immediately — training on the next segment overlaps the disk
    write, and the staged directory is atomically renamed to ``final_dir``
    when complete (the checkpoint subsystem's publish protocol, done
    natively)."""

    def __init__(self, n_threads: int = 2):
        self._h = lib().dlcs_ckpt_writer_create(n_threads)

    def submit(self, tmp_dir: str, final_dir: str, names, arrays) -> None:
        """Queue one checkpoint: write each ``arrays[i]`` (C-contiguous
        numpy) to ``<tmp_dir>/<names[i]>.raw``, then rename to
        ``final_dir``. Buffers are copied before returning."""
        arrays = [np.ascontiguousarray(a) for a in arrays]
        n = len(arrays)
        c_names = (ctypes.c_char_p * n)(
            *[name.encode() for name in names])
        c_ptrs = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays])
        c_sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
        lib().dlcs_ckpt_writer_submit(
            self._h, tmp_dir.encode(), final_dir.encode(),
            c_names, c_ptrs, c_sizes, n)

    def pending(self) -> int:
        return lib().dlcs_ckpt_writer_pending(self._h)

    def wait(self) -> None:
        """Block until every submitted checkpoint is published."""
        lib().dlcs_ckpt_writer_wait(self._h)

    def errors(self) -> int:
        """Failed jobs so far (their tmp dirs are left for inspection)."""
        return lib().dlcs_ckpt_writer_errors(self._h)

    def close(self) -> None:
        if self._h is not None:
            lib().dlcs_ckpt_writer_destroy(self._h)  # drains first
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
