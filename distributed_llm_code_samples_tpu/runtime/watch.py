"""Fleet watchtower: streaming health detectors on the round clock
(DESIGN.md section 27).

The fleet records everything — spans, TTFT/ITL, router decisions,
per-tenant workload curves, autoscale histories — but until this
module nothing WATCHED those signals while a run was live: SLO
violations were discovered by ``report --slo`` after the fact. The
watchtower closes that gap with streaming detectors folded
incrementally over the same deterministic observations every routing
decision already reads, each emitting schema-v15 ``alert`` records
with a fired→resolved lifecycle:

- **burn_rate** — multi-window SLO error-budget burn. A completion
  VIOLATES when it took more than ``deadline`` fleet rounds from
  admission to finish (the round-denominated form of the ``--slo``
  TTFT+ITL attainment fold: under virtual pacing, rounds ARE the
  latency clock). Burn rate over a window = violated fraction /
  ``budget``; the alert fires when BOTH the fast and the slow window
  burn at >= ``burn`` (the classic multi-window page: the fast window
  catches the spike, the slow window keeps one bad round from paging)
  and resolves when the fast window recovers.
- **queue_growth** — total waiting depth has held at >= ``queue`` for
  a full fast window (sustained backlog, not one bursty round).
- **imbalance** — the fleet record's load-imbalance scalar has held
  at >= ``imbalance`` for a full fast window.
- **collapse** — live work but ZERO token progress for ``collapse``
  consecutive rounds (the throughput-collapse page a dead/hung
  engine causes before migration catches up).
- **incident_rate** — wire rejections + dead-engine declarations +
  failed (quarantined/expired) requests in the slow window reached
  ``incidents``.
- **latency_drift** — the windowed TTFT/ITL p95 exceeds ``drift`` x
  a DECLARED wall-clock baseline. This is the one wall-clock
  detector, so it only runs when the operator declares a baseline
  (``baseline=TTFT:ITL``) — and it therefore folds request records
  (the offline ``fold_records`` path), never the live round loop,
  which observes no wall-clock latencies.

**Determinism.** Every live detector folds only the round clock and
integer counters — queue depths, completion counts, incident counts,
token deltas — never the wall clock, exactly like the autoscale
controller's decisions (DESIGN.md section 26). Windows are
ROUND-denominated, so under virtual-clock trace replay the alert
history (fired/resolved rounds, window bounds, every pinned
justifying number) is byte-identical across replays AND across the
in-process/process transports — pinned by test and asserted in-bench
via ``scripts/stream_diff.py``.

The live half (``Watchtower``) runs like the autoscaler: constructed
against a ``FleetRouter``, ticked between rounds by the workload
driver, reading the router's own light digests (zero extra
round-trips beyond the per-round results/failed sweeps), mirroring
its active-alert block onto the router for the live status doc
(``fleet_status.json`` → ``fleetstat``/``report --follow``) and
emitting ``alert`` records through the router's writer. The offline
half (``fold_records``) replays the same detector core over any
recorded stream — the percentile-drift path, and a debugging lens
over historical runs.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class WatchPolicy:
    """The watchtower's detector thresholds. A threshold of 0 (or a
    null baseline) DISABLES its detector — a policy must enable at
    least one (``parse_watch_spec`` enforces it for the CLI)."""

    deadline: int = 0           # rounds admission->completion (burn)
    budget: float = 0.25        # allowed violation fraction
    burn: float = 1.0           # burn-rate threshold (both windows)
    fast: int = 8               # fast window, rounds
    slow: int = 32              # slow window, rounds
    queue: int = 0              # sustained waiting-depth threshold
    imbalance: float = 0.0      # sustained load-imbalance threshold
    collapse: int = 0           # zero-progress rounds threshold
    incidents: int = 0          # slow-window incident count threshold
    drift: float = 0.0          # p95 multiple over baseline
    baseline_ttft: float | None = None      # declared p95 TTFT, s
    baseline_itl: float | None = None       # declared p95 ITL, s

    def __post_init__(self):
        for name in ("deadline", "fast", "slow", "queue", "collapse",
                     "incidents"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ValueError(f"bad WatchPolicy {name} {v!r}: must "
                                 "be an integer >= 0")
        if self.fast < 1:
            raise ValueError(f"bad WatchPolicy fast {self.fast}: must "
                             "be >= 1 (a zero-round window observes "
                             "nothing)")
        if self.slow <= self.fast:
            raise ValueError(f"bad WatchPolicy slow {self.slow}: must "
                             f"be > fast {self.fast} (the slow window "
                             "is what keeps one bad round from paging)")
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(f"bad WatchPolicy budget {self.budget}: "
                             "must be in (0, 1]")
        if self.burn <= 0:
            raise ValueError(f"bad WatchPolicy burn {self.burn}: must "
                             "be > 0")
        if not 0.0 <= self.imbalance < 1.0:
            raise ValueError(f"bad WatchPolicy imbalance "
                             f"{self.imbalance}: must be in [0, 1)")
        if self.drift < 0:
            raise ValueError(f"bad WatchPolicy drift {self.drift}: "
                             "must be >= 0")
        if self.drift > 0 and (self.baseline_ttft is None
                               and self.baseline_itl is None):
            raise ValueError("bad WatchPolicy: drift > 0 needs a "
                             "declared baseline (baseline=TTFT:ITL)")
        for name in ("baseline_ttft", "baseline_itl"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"bad WatchPolicy {name} {v}: must "
                                 "be > 0 seconds")

    def enabled(self) -> tuple[str, ...]:
        """The detectors this policy actually runs."""
        out = []
        if self.deadline > 0:
            out.append("burn_rate")
        if self.queue > 0:
            out.append("queue_growth")
        if self.imbalance > 0:
            out.append("imbalance")
        if self.collapse > 0:
            out.append("collapse")
        if self.incidents > 0:
            out.append("incident_rate")
        if self.drift > 0:
            out.append("latency_drift")
        return tuple(out)

    def as_dict(self) -> dict:
        return {"deadline": self.deadline, "budget": self.budget,
                "burn": self.burn, "fast": self.fast,
                "slow": self.slow, "queue": self.queue,
                "imbalance": self.imbalance, "collapse": self.collapse,
                "incidents": self.incidents, "drift": self.drift,
                "baseline_ttft": self.baseline_ttft,
                "baseline_itl": self.baseline_itl}


def _watch_num(key: str, val: str, cast):
    try:
        return cast(val)
    except ValueError:
        kind = "an integer" if cast is int else "a number"
        raise ValueError(f"bad --watch {key} {val!r}: must be "
                         f"{kind}") from None


_WATCH_KEYS = ("deadline", "budget", "burn", "fast", "slow", "queue",
               "imbalance", "collapse", "incidents", "drift",
               "baseline")


def parse_watch_spec(spec: str) -> WatchPolicy:
    """Parse + validate one ``--watch`` spec (module-docstring
    grammar: ``deadline=24,budget=0.25,fast=8,slow=32,queue=12,...``).
    Every malformed entry is ONE ValueError naming the offense; the
    cross-field constraints (fast < slow, budget in (0,1], drift
    needs a baseline) are enforced by ``WatchPolicy`` itself."""
    out: dict = {}
    seen = set()
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        if "=" not in entry:
            raise ValueError(f"bad --watch entry {entry!r}: expected "
                             f"key=value with key in "
                             f"{'/'.join(_WATCH_KEYS)}")
        key, _, val = entry.partition("=")
        if key in seen:
            raise ValueError(f"bad --watch spec: duplicate key "
                             f"{key!r}")
        seen.add(key)
        if key in ("deadline", "fast", "slow", "queue", "collapse",
                   "incidents"):
            out[key] = _watch_num(key, val, int)
        elif key in ("budget", "burn", "imbalance", "drift"):
            out[key] = _watch_num(key, val, float)
        elif key == "baseline":
            ttft, sep, itl = val.partition(":")
            if not sep:
                raise ValueError(f"bad --watch baseline {val!r}: "
                                 "expected TTFT_S:ITL_S (declared p95 "
                                 "baselines in seconds)")
            out["baseline_ttft"] = _watch_num("baseline", ttft, float)
            out["baseline_itl"] = _watch_num("baseline", itl, float)
            out.setdefault("drift", 2.0)
        else:
            raise ValueError(f"bad --watch key {key!r}: known keys "
                             f"{'/'.join(_WATCH_KEYS)}")
    policy = WatchPolicy(**out)
    if not policy.enabled():
        raise ValueError("bad --watch spec: no detector enabled — set "
                         "at least one of deadline= (burn rate), "
                         "queue=, imbalance=, collapse=, incidents=, "
                         "baseline= (drift)")
    return policy


# detector -> page class: "page" burns goodput NOW, "warn" trends
# toward it (runtime/telemetry.py ALERT_SEVERITIES)
_SEVERITY = {"burn_rate": "page", "queue_growth": "warn",
             "imbalance": "warn", "collapse": "page",
             "incident_rate": "page", "latency_drift": "warn"}


class _Fold:
    """The detector core both halves share: consumes one per-round
    observation at a time, keeps the bounded window state, and returns
    the alert transitions the round caused (record dicts ready for
    ``TelemetryWriter.alert``, minus the envelope)."""

    def __init__(self, policy: WatchPolicy):
        self.policy = policy
        # completion ring: (round, violated) within the slow window
        self._completions: list[tuple[int, bool]] = []
        # incident ring: (round, count) within the slow window
        self._incidents: list[tuple[int, int]] = []
        # drift sample ring: (round, ttft_s, itl_s|None)
        self._samples: list[tuple[int, float, float | None]] = []
        self._queue_streak = 0
        self._imb_streak = 0
        self._stall_streak = 0
        # detector -> the pins it fired with (active alerts)
        self.active: dict[str, dict] = {}
        self.history: list[tuple[int, str, str]] = []

    # -- per-round inputs (fed BEFORE round_end for that round) --------

    def note_completion(self, round_: int, violated: bool) -> None:
        self._completions.append((round_, bool(violated)))

    def note_sample(self, round_: int, ttft_s, itl_s) -> None:
        if ttft_s is not None:
            self._samples.append((round_, float(ttft_s),
                                  None if itl_s is None
                                  else float(itl_s)))

    def note_incidents(self, round_: int, count: int) -> None:
        if count > 0:
            self._incidents.append((round_, int(count)))

    # -- the round boundary --------------------------------------------

    def round_end(self, round_: int, *, waiting: int, active: int,
                  imbalance: float,
                  tokens_delta: int | None) -> list[dict]:
        """Evaluate every enabled detector against the windows ending
        at ``round_``; returns the fired/resolved transition records
        (empty most rounds — the lifecycle emits once per edge, never
        per round)."""
        p = self.policy
        # prune the rings to the slow window (the widest any detector
        # reads)
        lo = round_ - p.slow
        self._completions = [c for c in self._completions if c[0] > lo]
        self._incidents = [c for c in self._incidents if c[0] > lo]
        self._samples = [s for s in self._samples if s[0] > lo]
        out: list[dict] = []

        if p.deadline > 0:
            fast = [v for r, v in self._completions
                    if r > round_ - p.fast]
            slow = [v for r, v in self._completions]
            burn_fast = (sum(fast) / len(fast) / p.budget
                         if fast else 0.0)
            burn_slow = (sum(slow) / len(slow) / p.budget
                         if slow else 0.0)
            firing = bool(fast) and burn_fast >= p.burn \
                and burn_slow >= p.burn
            # resolve on fast-window recovery only — the slow window
            # keeps the page from flapping while the backlog drains
            if "burn_rate" in self.active:
                firing = burn_fast >= p.burn
            self._edge(out, round_, "burn_rate", firing, p.slow, {
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "violations": int(sum(fast)),
                "completions": len(fast)})

        if p.queue > 0:
            self._queue_streak = (self._queue_streak + 1
                                  if waiting >= p.queue else 0)
            self._edge(out, round_, "queue_growth",
                       self._queue_streak >= p.fast, p.fast,
                       {"waiting": int(waiting),
                        "threshold": p.queue})

        if p.imbalance > 0:
            self._imb_streak = (self._imb_streak + 1
                                if imbalance >= p.imbalance else 0)
            self._edge(out, round_, "imbalance",
                       self._imb_streak >= p.fast, p.fast,
                       {"imbalance": round(imbalance, 4),
                        "threshold": p.imbalance})

        if p.collapse > 0 and tokens_delta is not None:
            live = waiting + active
            self._stall_streak = (self._stall_streak + 1
                                  if live > 0 and tokens_delta <= 0
                                  else 0)
            self._edge(out, round_, "collapse",
                       self._stall_streak >= p.collapse,
                       max(self._stall_streak, 1),
                       {"stalled_rounds": self._stall_streak,
                        "live": int(live)})

        if p.incidents > 0:
            count = sum(n for _, n in self._incidents)
            self._edge(out, round_, "incident_rate",
                       count >= p.incidents, p.slow,
                       {"incidents": int(count),
                        "threshold": p.incidents})

        if p.drift > 0:
            for metric, baseline, vals in (
                    ("ttft", p.baseline_ttft,
                     [t for _, t, _ in self._samples]),
                    ("itl", p.baseline_itl,
                     [i for _, _, i in self._samples
                      if i is not None])):
                if baseline is None:
                    continue
                p95 = _p95(vals)
                det = f"latency_drift_{metric}"
                firing = p95 is not None and p95 > p.drift * baseline
                self._edge(out, round_, det, firing, p.slow, {
                    "p95_s": (None if p95 is None
                              else round(p95, 4)),
                    "baseline_s": baseline, "metric": metric},
                    detector_kind="latency_drift")
        return out

    def _edge(self, out: list, round_: int, name: str, firing: bool,
              window: int, pins: dict,
              detector_kind: str | None = None) -> None:
        """One fired/resolved edge per threshold crossing. ``name``
        keys the active table (distinct per drift metric);
        ``detector_kind`` is the recorded detector vocabulary entry."""
        kind = detector_kind or name
        if firing and name not in self.active:
            rec = {"step": round_, "event": "fired", "detector": kind,
                   "severity": _SEVERITY[kind],
                   "window": [max(0, round_ - window), round_], **pins}
            self.active[name] = rec
            self.history.append((round_, "fired", kind))
            out.append(rec)
        elif not firing and name in self.active:
            fired = self.active.pop(name)
            self.history.append((round_, "resolved", kind))
            out.append({"step": round_, "event": "resolved",
                        "detector": kind, "severity": _SEVERITY[kind],
                        "window": [max(0, round_ - window), round_],
                        "fired_step": fired["step"], **pins})

    def active_block(self) -> list[dict]:
        """The live-surface view of what is firing RIGHT NOW (the
        status doc / fleetstat alert block): one entry per active
        alert, its fired round and the justifying pins it fired
        with."""
        return [{"detector": rec["detector"],
                 "severity": rec["severity"],
                 "since_round": rec["step"],
                 **{k: v for k, v in rec.items()
                    if k not in ("step", "event", "detector",
                                 "severity", "window")}}
                for _, rec in sorted(self.active.items())]


def _p95(vals: list[float]) -> float | None:
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(0.95 * (len(s) - 1) + 0.5))]


class Watchtower:
    """Live detectors over one ``FleetRouter``'s round clock.

    Constructed like the autoscaler: ``tick()`` runs between fleet
    rounds (the workload driver calls it after each round step),
    reading only the router's own deterministic state — light
    digests, the results/failed sweeps, the decision counters — and
    emitting ``alert`` records through ``metrics``. Active alerts are
    mirrored onto ``router.watch_state`` after every tick for the
    live status doc."""

    def __init__(self, router, policy: WatchPolicy, *, metrics=None):
        self.router = router
        self.policy = policy
        self.metrics = metrics
        self.fold = _Fold(policy)
        self.fired = 0
        self.resolved = 0
        self._seen_uids: set[int] = set()
        self._failed_last = 0
        self._incidents_last = 0
        self._tokens_by_engine: dict[str, int] = {}
        self._mirror()

    @property
    def history(self) -> list[tuple[int, str, str]]:
        return self.fold.history

    def tick(self) -> list[dict]:
        """One watchtower evaluation on the router's round clock;
        returns the alert transitions this round emitted (empty most
        rounds)."""
        r = self.router
        round_ = r.rounds
        p = self.policy
        alive = r.alive_handles()
        digests = {h.id: h.digest(light=True) for h in alive}
        waiting = sum(d["waiting"] for d in digests.values())
        active = sum(d["active"] for d in digests.values())
        loads = [d["active"] + d["waiting"]
                 for eid, d in digests.items()
                 if r.by_id[eid].role == "decode"]
        imb = 0.0
        if len(loads) > 1 and max(loads) > 0:
            imb = round((max(loads) - min(loads)) / max(loads), 4)
        # per-engine token deltas (summed over alive members only — a
        # killed engine's counter vanishing must not read as negative
        # progress)
        delta = 0
        for eid, d in digests.items():
            cur = int(d.get("tokens_generated") or 0)
            delta += max(0, cur - self._tokens_by_engine.get(eid, 0))
            self._tokens_by_engine[eid] = cur
        if p.deadline > 0:
            # the completion sweep: every uid finishing this round is
            # judged against the round-denominated deadline
            for uid in r.results().keys() - self._seen_uids:
                self._seen_uids.add(uid)
                adm = r.requests.get(int(uid), {}).get("round")
                if adm is None:
                    continue
                self.fold.note_completion(
                    round_, (round_ - int(adm)) > p.deadline)
        if p.incidents > 0:
            failed = len(r.failed())
            cum = r.wire_rejects + r.kills + failed
            self.fold.note_incidents(round_,
                                     cum - self._incidents_last)
            self._incidents_last = cum
        transitions = self.fold.round_end(
            round_, waiting=waiting, active=active, imbalance=imb,
            tokens_delta=delta)
        for rec in transitions:
            if rec["event"] == "fired":
                self.fired += 1
            else:
                self.resolved += 1
            if self.metrics is not None:
                self.metrics.alert(dict(rec))
        if transitions or r.watch_state is None:
            self._mirror()
        return transitions

    def _mirror(self) -> None:
        """Mirror the live alert block onto the router for the status
        doc (``fleet_status.json``'s ``alerts`` block)."""
        self.router.watch_state = {
            "active": self.fold.active_block(),
            "fired": self.fired,
            "resolved": self.resolved,
        }


def fold_records(records: list[dict], policy: WatchPolicy) -> list[dict]:
    """Offline replay of the detector core over a RECORDED stream (any
    merge of per-engine + router streams, in record order): returns
    the alert transition records the watchtower would have emitted.

    The round clock is reconstructed from the stream itself — each
    ``fleet`` record closes one round (single-engine streams, which
    have no fleet records, close a round per ``decode`` cadence record
    on the engine's own step clock). Completions and incidents seen
    between round boundaries fold into the round that closes after
    them; the latency_drift detector reads each completion's wall
    ``ttft_s``/observed ITL against the policy's declared baseline —
    this offline path is the ONLY place drift runs (the live round
    loop observes no wall-clock latencies)."""
    fold = _Fold(policy)
    out: list[dict] = []
    admitted: dict[int, int] = {}
    pending: list[bool] = []        # deadline verdicts awaiting a round
    incidents = 0
    round_ = 0

    def close_round(rnd: int, waiting: int, active: int,
                    imb: float, tokens_delta) -> None:
        nonlocal incidents
        for viol in pending:
            fold.note_completion(rnd, viol)
        pending.clear()
        fold.note_incidents(rnd, incidents)
        incidents = 0
        out.extend(fold.round_end(rnd, waiting=waiting, active=active,
                                  imbalance=imb,
                                  tokens_delta=tokens_delta))

    for rec in records:
        kind = rec.get("kind")
        if kind == "fleet":
            round_ = int(rec["step"])
            engines = rec.get("engines") or {}
            waiting = sum(int(e.get("waiting") or 0)
                          for e in engines.values() if e.get("alive"))
            act = sum(int(e.get("active") or 0)
                      for e in engines.values() if e.get("alive"))
            close_round(round_, waiting, act,
                        float(rec.get("load_imbalance") or 0.0), None)
        elif kind == "decode":
            # single-engine streams: the cadence record is the round
            # boundary (fleet streams carry their own fleet records —
            # worker decode records fold as samples only, their step
            # clock is not the router's)
            round_ = max(round_, int(rec["step"]))
        elif kind == "router":
            ev = rec.get("event")
            if ev == "routed":
                admitted[int(rec["uid"])] = int(rec["step"])
            elif ev == "wire_rejected":
                incidents += 1
        elif kind == "event":
            if rec.get("event") == "engine_killed":
                incidents += 1
        elif kind == "request":
            ev = rec.get("event")
            if ev in ("quarantined", "expired"):
                incidents += 1
            elif ev == "completed":
                uid = int(rec["uid"])
                adm = admitted.get(uid)
                if adm is not None and policy.deadline > 0:
                    pending.append((round_ - adm) > policy.deadline)
                ttft = rec.get("ttft_s")
                lat = rec.get("latency_s")
                n_new = rec.get("n_new")
                itl = None
                if (ttft is not None and lat is not None
                        and n_new and n_new > 1):
                    itl = (lat - ttft) / (n_new - 1)
                fold.note_sample(round_, ttft, itl)
    # close the trailing partial round so a stream that ends between
    # boundaries still folds its tail completions
    if pending or incidents:
        close_round(round_ + 1, 0, 0, 0.0, None)
    return out
