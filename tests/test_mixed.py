"""bf16 mixed precision through the distributed strategies (VERDICT r3 #3).

The policy (``ops.ffn.ffn_fwd_mixed``/``ffn_bwd_mixed``): bf16 matmul
inputs on the MXU, f32 params/grads/accumulation, recompute-style
backward. Because grads come out f32 and the reductions are unchanged,
the distributed differentials keep their power in mixed mode:

- DDP(mixed) == FSDP(mixed) — the reference's --method 0 assert
  (``train_ffns.py:386-391``) holds under the bf16 policy too;
- TP(mixed) == single(mixed) to reduction-order tolerance (the bf16
  products are identical value-for-value; only the f32 partial-sum
  order differs between one full contraction and per-shard psum);
- FSDP's shard gathers ride the wire in bf16 — HALF the collective
  bytes — asserted structurally in the lowered HLO.
"""

import re

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_llm_code_samples_tpu.data import make_seed_schedule
from distributed_llm_code_samples_tpu.models import init_ffn_stack
from distributed_llm_code_samples_tpu.ops.ffn import (ffn_block_mixed,
                                                      ffn_bwd_mixed,
                                                      ffn_fwd_mixed)
from distributed_llm_code_samples_tpu.parallel import (
    make_mesh, train_single, train_ddp, train_ddp_zero1, train_fsdp,
    train_tp, train_tp_sp, train_hybrid, DATA_AXIS, MODEL_AXIS)
from distributed_llm_code_samples_tpu.parallel import fsdp
from distributed_llm_code_samples_tpu.utils.hlo import lowered_text

D, L, B, S = 64, 3, 32, 8
LR_TEST = 0.1


@pytest.fixture(scope="module")
def setup():
    params = init_ffn_stack(jax.random.PRNGKey(42), D, L)
    seeds = make_seed_schedule(S, random_seed=7)
    return params, seeds


def _close(a, b, rtol, atol):
    np.testing.assert_allclose(np.asarray(a.w1), np.asarray(b.w1),
                               rtol=rtol, atol=atol)
    np.testing.assert_allclose(np.asarray(a.w2), np.asarray(b.w2),
                               rtol=rtol, atol=atol)


def test_pair_form_matches_custom_vjp_block():
    """ffn_fwd_mixed/ffn_bwd_mixed (the hook-surface dialect) produce
    bit-identical outputs and grads to ffn_block_mixed (the custom_vjp
    form the single-device trainer uses) — one math, two dialects."""
    k = jax.random.PRNGKey(0)
    w1 = jax.random.normal(jax.random.fold_in(k, 0), (4 * D, D)) * 0.02
    w2 = jax.random.normal(jax.random.fold_in(k, 1), (D, 4 * D)) * 0.02
    x = jax.random.normal(jax.random.fold_in(k, 2), (B, D))
    dy = jax.random.normal(jax.random.fold_in(k, 3), (B, D))

    y_pair = ffn_fwd_mixed(w1, w2, x)
    dx_pair, (dw1_pair, dw2_pair) = ffn_bwd_mixed(dy, w1, w2, x)

    y_blk, vjp = jax.vjp(ffn_block_mixed, w1, w2, x)
    dw1_blk, dw2_blk, dx_blk = vjp(dy)

    np.testing.assert_array_equal(np.asarray(y_pair), np.asarray(y_blk))
    np.testing.assert_array_equal(np.asarray(dx_pair), np.asarray(dx_blk))
    np.testing.assert_array_equal(np.asarray(dw1_pair), np.asarray(dw1_blk))
    np.testing.assert_array_equal(np.asarray(dw2_pair), np.asarray(dw2_blk))


def test_mixed_remat_block_matches_saved_block():
    """ffn_block_mixed_remat (bf16-stashed block input, pre-activation
    recomputed) is the SAME math as ffn_block_mixed (saved bf16
    post-ReLU) — outputs and all three grads bit-identical, since the
    recompute reproduces the exact bf16 activation the saved rule
    stashed."""
    from distributed_llm_code_samples_tpu.ops.ffn import (
        ffn_block_mixed_remat)
    k = jax.random.PRNGKey(5)
    w1 = jax.random.normal(jax.random.fold_in(k, 0), (4 * D, D)) * 0.02
    w2 = jax.random.normal(jax.random.fold_in(k, 1), (D, 4 * D)) * 0.02
    x = jax.random.normal(jax.random.fold_in(k, 2), (B, D))
    dy = jax.random.normal(jax.random.fold_in(k, 3), (B, D))

    y_s, vjp_s = jax.vjp(ffn_block_mixed, w1, w2, x)
    y_r, vjp_r = jax.vjp(ffn_block_mixed_remat, w1, w2, x)
    np.testing.assert_array_equal(np.asarray(y_s), np.asarray(y_r))
    for a, b in zip(vjp_s(dy), vjp_r(dy)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_single_mixed_remat_matches_saved(setup):
    """train_single(mixed=True) composes with the residual policy flag:
    remat (the new default, matching f32) == remat=False (saved) on
    final params."""
    params, seeds = setup
    out_r = train_single(params, seeds, B, D, lr=LR_TEST, mixed=True)
    out_s = train_single(params, seeds, B, D, lr=LR_TEST, mixed=True,
                         remat=False)
    _close(out_r, out_s, rtol=1e-6, atol=1e-7)


def test_mixed_close_to_f32_but_distinct(setup):
    """Sanity bracket: the bf16 policy tracks the f32 oracle (same math,
    lower precision) but actually runs in bf16 — the results must differ
    beyond f32 tolerance, or `mixed` silently fell back to f32."""
    params, seeds = setup
    f32 = train_single(params, seeds, B, D, lr=LR_TEST)
    mx = train_single(params, seeds, B, D, lr=LR_TEST, mixed=True)
    _close(f32, mx, rtol=0.1, atol=1e-3)
    assert not np.allclose(np.asarray(f32.w1), np.asarray(mx.w1),
                           rtol=1e-6, atol=1e-8)


def test_ddp_mixed_matches_fsdp_mixed(setup, mesh4):
    """The reference's core differential under the bf16 policy: per-rank
    grads are identical f32 values, DDP all_reduces them where FSDP
    reduce_scatters — same sums, same updates."""
    params, seeds = setup
    p_ddp = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST, mixed=True)
    p_fsdp = train_fsdp(params, seeds, B, D, mesh4, lr=LR_TEST, mixed=True)
    _close(p_ddp, p_fsdp, rtol=1e-5, atol=1e-7)


def test_tp_mixed_matches_single_mixed(setup, mesh_model4):
    """TP(mixed) == single(mixed) to reduction-order tolerance: every
    bf16 product is value-identical (w1 is column-parallel, so each
    shard's h slice is the full-d contraction; the bf16 casts commute
    with slicing); only the f32 accumulation order of the row-parallel
    w2 contraction differs (per-shard sums + psum vs one dot)."""
    params, seeds = setup
    single = train_single(params, seeds, B, D, lr=LR_TEST, mixed=True)
    p_tp = train_tp(params, seeds, B, D, mesh_model4, lr=LR_TEST,
                    mixed=True)
    _close(single, p_tp, rtol=1e-4, atol=1e-6)


def test_tp_sp_mixed_matches_single_mixed(setup, mesh_model4):
    """Sequence-parallel TP under the bf16 policy: the gather/scatter
    decomposition changes comms and memory shape, never the math."""
    params, seeds = setup
    single = train_single(params, seeds, B, D, lr=LR_TEST, mixed=True)
    sp = train_tp_sp(params, seeds, B, D, mesh_model4, lr=LR_TEST,
                     mixed=True)
    _close(single, sp, rtol=1e-4, atol=1e-6)


def test_hybrid_mixed_matches_ddp_mixed(setup, mesh4x2):
    """hybrid(4x2, mixed) == DDP(4, mixed): TP is an exact decomposition
    modulo f32 reduction order, so only the data axis affects the math."""
    params, seeds = setup
    mesh_ddp = make_mesh({DATA_AXIS: 4})
    p_ddp = train_ddp(params, seeds, B, D, mesh_ddp, lr=LR_TEST,
                      mixed=True)
    p_hy = train_hybrid(params, seeds, B, D, mesh4x2, lr=LR_TEST,
                        mixed=True)
    _close(p_ddp, p_hy, rtol=1e-4, atol=1e-6)


def test_zero1_mixed_matches_ddp_mixed(setup, mesh4):
    """ZeRO-1's state sharding is orthogonal to the precision policy."""
    from distributed_llm_code_samples_tpu.optim import momentum
    _, seeds = setup
    # ZeRO-1 partitions whole layers: L must divide the rank count
    params = init_ffn_stack(jax.random.PRNGKey(43), D, 4)
    p_ddp = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST,
                      optimizer=momentum(), mixed=True)
    p_z1 = train_ddp_zero1(params, seeds, B, D, mesh4, lr=LR_TEST,
                           optimizer=momentum(), mixed=True)
    _close(p_ddp, p_z1, rtol=1e-5, atol=1e-7)


def test_ddp_mixed_accum_matches_unchunked(setup, mesh4):
    """Gradient accumulation under the bf16 policy: per-row bf16 math is
    chunk-invariant (rows are independent), so only the f32 token-sum
    order differs."""
    params, seeds = setup
    one = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST, mixed=True)
    two = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST, mixed=True,
                    accum=2)
    _close(one, two, rtol=1e-5, atol=1e-7)


def test_fsdp_mixed_gathers_in_bf16(mesh4):
    """The comm win, asserted structurally: every all_gather in the mixed
    FSDP step moves bf16 — half the bytes of the f32 path's gathers."""
    params = init_ffn_stack(jax.random.PRNGKey(0), D, L)
    sp = fsdp.shard_params(params, mesh4)
    f = jax.shard_map(fsdp.make_step(B, D, 0.1, mixed=True), mesh=mesh4,
                      in_specs=(fsdp.PARAM_SPECS, P()),
                      out_specs=fsdp.PARAM_SPECS)
    text = lowered_text(f, sp, jax.numpy.int32(3))
    gather_lines = [ln for ln in text.splitlines()
                    if re.search(r"all_gather", ln)]
    assert gather_lines, "no all_gather in the mixed FSDP step?"
    for ln in gather_lines:
        assert "bf16" in ln, f"f32 gather survived in mixed mode: {ln}"
    # and the grad reduce_scatters stay f32 (master-grad exactness) —
    # the op's result type sits on a continuation line, so check the
    # whole text: a bf16 reduce_scatter anywhere would mean the grads
    # were demoted
    assert "reduce_scatter" in text
    assert not re.search(r"reduce_scatter.{0,400}?bf16", text, re.S)


def test_cli_mixed_flag_verifies(tmp_path):
    """--method 0 --mixed --strict on the fake 8-device mesh: all four
    core strategies run the bf16 policy and still cross-verify."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "distributed_llm_code_samples_tpu.cli",
         "-m", "0", "-s", "8", "-bs", "4", "-n", "8", "-l", "2", "-d",
         "32", "--mixed", "--strict", "--fake_devices", "8"],
        capture_output=True, text=True, timeout=600, cwd=repo)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SoftAssertionError" not in r.stdout


def test_lm_mixed_close_to_f32_but_distinct():
    """The LM family's bf16 policy (bf16 trunk + residual stream, f32
    head/master/update — models.lm.lm_loss(mixed=True)): tracks the f32
    oracle at bf16 tolerance, differs beyond f32 tolerance (i.e. the
    trunk really ran in bf16), and the params stay f32."""
    from distributed_llm_code_samples_tpu.models import init_lm
    from distributed_llm_code_samples_tpu.parallel import train_lm_single
    params = init_lm(jax.random.PRNGKey(0), 128, 32, 2, 16, n_heads=4)
    seeds = make_seed_schedule(4, random_seed=9)
    kw = dict(lr=0.1, seq_len=16, n_heads=4)
    f32 = train_lm_single(params, seeds, 2 * 16, 32, **kw)
    mx = train_lm_single(params, seeds, 2 * 16, 32, mixed=True, **kw)
    assert mx.wte.dtype == np.float32
    for a, b in zip(jax.tree_util.tree_leaves(mx),
                    jax.tree_util.tree_leaves(f32)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0.1, atol=2e-3)
    assert not np.allclose(np.asarray(f32.blocks.w1),
                           np.asarray(mx.blocks.w1),
                           rtol=1e-6, atol=1e-8)


def test_lm_mixed_composes_with_fused_head():
    """mixed=True + head_impl='fused': the bf16 trunk hands an f32 ``h``
    to the Pallas head, which must agree with the mixed oracle head."""
    from distributed_llm_code_samples_tpu.models import init_lm
    from distributed_llm_code_samples_tpu.parallel import train_lm_single
    params = init_lm(jax.random.PRNGKey(1), 128, 32, 2, 16, n_heads=4)
    seeds = make_seed_schedule(3, random_seed=11)
    kw = dict(lr=0.1, seq_len=16, n_heads=4, mixed=True)
    oracle = train_lm_single(params, seeds, 2 * 16, 32, **kw)
    fused = train_lm_single(params, seeds, 2 * 16, 32,
                            head_impl="fused", **kw)
    for a, b in zip(jax.tree_util.tree_leaves(fused),
                    jax.tree_util.tree_leaves(oracle)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_transformer_mixed_close_to_f32_but_distinct():
    """The transformer family's bf16 policy (bf16 blocks, f32 master):
    tracks the f32 oracle at bf16 tolerance, differs beyond f32
    tolerance, keeps f32 params."""
    from distributed_llm_code_samples_tpu.models import init_transformer
    from distributed_llm_code_samples_tpu.parallel import (
        train_transformer_single)
    params = init_transformer(jax.random.PRNGKey(2), 32, 2)
    seeds = make_seed_schedule(4, random_seed=13)
    kw = dict(lr=0.1, seq_len=8, n_heads=4)
    f32 = train_transformer_single(params, seeds, 2 * 8, 32, **kw)
    mx = train_transformer_single(params, seeds, 2 * 8, 32, mixed=True,
                                  **kw)
    assert mx.w1.dtype == np.float32
    # absolute bracket: 4 SGD steps at lr=0.1 move params O(1e-1);
    # the bf16 run tracks within ~1e-2 (relative checks degenerate on
    # the near-zero entries where bf16 rounding dominates)
    for a, b in zip(jax.tree_util.tree_leaves(mx),
                    jax.tree_util.tree_leaves(f32)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0.1, atol=1e-2)
    assert not np.allclose(np.asarray(f32.w1), np.asarray(mx.w1),
                           rtol=1e-6, atol=1e-8)


def test_lm_ddp_fsdp_mixed_match_single_mixed(mesh4):
    """The reference's cross-strategy differential under the LM bf16
    policy: DDP(mixed) and FSDP(mixed) both reproduce the single-device
    mixed run (same strided schedule emulated by seed design: n=4
    shards each step a disjoint seed — here we check DDP == FSDP, the
    train_ffns.py:386-391 pair, which share the schedule exactly)."""
    from distributed_llm_code_samples_tpu.models import init_lm
    from distributed_llm_code_samples_tpu.parallel import (
        train_lm_ddp, train_lm_fsdp)
    params = init_lm(jax.random.PRNGKey(3), 128, 32, 2, 16, n_heads=4)
    seeds = make_seed_schedule(8, random_seed=17)
    kw = dict(lr=0.1, seq_len=16, n_heads=4, mixed=True)
    ddp = train_lm_ddp(params, seeds, 4 * 16, 32, mesh4, **kw)
    fsdp = train_lm_fsdp(params, seeds, 4 * 16, 32, mesh4, **kw)
    assert ddp.wte.dtype == np.float32
    # bracket, not bit-equality: the two strategies' f32 grad sums
    # differ by reduction order, and a ~1e-7 param drift can cross a
    # bf16 rounding boundary on the next step's trunk cast (1 ulp ~
    # 0.8% relative), compounding over the scan — unlike the f32 and
    # FFN-mixed differentials, bit-tight equality is not available here
    for a, b in zip(jax.tree_util.tree_leaves(ddp),
                    jax.tree_util.tree_leaves(fsdp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-4)
    # and the policy really engaged: differs from the f32 DDP run
    f32 = train_lm_ddp(params, seeds, 4 * 16, 32, mesh4, lr=0.1,
                       seq_len=16, n_heads=4)
    assert not np.allclose(np.asarray(f32.blocks.w1),
                           np.asarray(ddp.blocks.w1),
                           rtol=1e-6, atol=1e-8)
