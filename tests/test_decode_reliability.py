"""Serving reliability (ISSUE 5): decode chaos grammar, in-graph logits
quarantine, per-request retry, pool-pressure preemption, snapshot-resume
and the request-record telemetry contract.

The acceptance bar: a run injecting ``nan_logits@k:uid`` plus a crash at
step ``m`` quarantines exactly one request, resumes the rest from the
host-side engine snapshot, and every surviving sequence's tokens are
BIT-IDENTICAL to an uninterrupted run that never admitted the poisoned
request — proven for f32, bf16, AND int8 KV (the replay mechanism
re-runs the exact KV write history, so the int8 quantization history
matches too), plus Megatron TP. The real-SIGKILL flavor runs once
through the generate CLI (subprocess, f32); the dtype matrix runs the
same scenario in-process against the same snapshot machinery.

Model shapes deliberately match tests/test_decode_engine.py (same
params seed, same BASE config) so the compiled programs land in the
same XLA cache entries.
"""

import json
import os
import signal
import subprocess
import sys

import jax
import numpy as np
import pytest

from conftest import load_scaled_timeout

from distributed_llm_code_samples_tpu.decode import (
    AdmissionError, DecodeEngine, EngineConfig, ServePolicy,
    corrupt_block, gather_layer, init_pool, load_snapshot,
    restore_engine_state, scrub_blocks, supervise_decode, write_snapshot)
from distributed_llm_code_samples_tpu.models import init_lm
from distributed_llm_code_samples_tpu.runtime.chaos import (
    DECODE_KINDS, FaultPlan, validate_decode_plan)

V, D, L, H = 64, 32, 2, 4
BASE = dict(block_size=8, n_blocks=33, max_slots=3, max_blocks_per_seq=6,
            prefill_chunk=8)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KV_DTYPES = ("f32", "bf16", "int8")


@pytest.fixture(scope="module")
def lm_params():
    return init_lm(jax.random.PRNGKey(0), V, D, L, max_seq_len=64)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(1)
    return [rng.integers(0, V, size=n).tolist() for n in (5, 9, 13)]


def _drain(params, cfg, prompts_uids, max_new=8, mesh=None, policy=None):
    """A fresh engine draining ``[(uid, prompt), ...]`` — the oracle
    helper (uids chosen by the caller: the determinism contract keys on
    uid, never on which other requests were admitted)."""
    eng = DecodeEngine(params, H, cfg, mesh=mesh, policy=policy)
    for uid, p in prompts_uids:
        eng.submit(p, max_new, uid=uid)
    return eng.run()


# ------------------------------------------------------------ chaos grammar


def test_decode_chaos_grammar_parse():
    plan = FaultPlan.parse(
        "nan_logits@3:1,hang_step@5:0.5,corrupt_block@4:2,kill@7")
    assert [(f.kind, f.step, f.arg) for f in plan.faults] == [
        ("nan_logits", 3, 1.0), ("hang_step", 5, 0.5),
        ("corrupt_block", 4, 2.0), ("kill", 7, None)]
    validate_decode_plan(plan)          # decode-legal spec passes
    assert set(DECODE_KINDS) == {"nan_logits", "hang_step",
                                 "corrupt_block", "corrupt_spill",
                                 "kill"}


@pytest.mark.parametrize("spec,msg", [
    ("nan_grad@3", "training fault"),
    ("loss_spike@2:10", "training fault"),
    ("corrupt_block@3", "requires :BLOCK"),
    ("corrupt_block@3:1.5", "non-negative integer"),
    ("corrupt_spill@3", "requires :ID"),
    ("corrupt_spill@3:1.5", "non-negative integer"),
    ("nan_logits@3:-2", "non-negative integer"),
    ("hang_step@2:-1", "non-negative sleep"),
    ("kill@4:2", "takes no :ARG"),
])
def test_decode_chaos_grammar_rejects(spec, msg):
    with pytest.raises(ValueError, match=msg):
        validate_decode_plan(FaultPlan.parse(spec))


def test_decode_due_and_mark_fired():
    plan = FaultPlan.parse("nan_logits@3:1,kill@5")
    assert [f.kind for f in plan.decode_due(3)] == ["nan_logits"]
    assert plan.decode_due(4) == []
    plan.mark_decode_fired_through(5)   # a resume past both faults
    assert plan.decode_due(3) == [] and plan.decode_due(5) == []
    # alignment goes BOTH ways: an in-process restart may restore a
    # snapshot OLDER than a fault it already injected once — the fault
    # must fire again on the replayed step (skipping it would diverge
    # from the pre-crash history)
    plan.mark_decode_fired_through(2)
    assert [f.kind for f in plan.decode_due(3)] == ["nan_logits"]
    assert [f.kind for f in plan.decode_due(5)] == ["kill"]


# ---------------------------------------------- quarantine (the guardrail)


@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_acceptance_quarantine_plus_crash_resume(tmp_path, lm_params,
                                                 prompts, kv_dtype):
    """THE acceptance scenario, per KV dtype: ``nan_logits@4:1`` plus a
    crash after step 6 (process death simulated by abandoning the
    engine — the subprocess SIGKILL flavor is
    ``test_kill_resume_via_generate_cli``). Exactly uid 1 is
    quarantined/FAILED; the crash resumes from the host-side snapshot;
    every surviving sequence is token-identical to an uninterrupted run
    that NEVER admitted the poisoned request."""
    cfg = EngineConfig(**BASE, kv_dtype=kv_dtype)
    oracle = _drain(lm_params, cfg,
                    [(0, prompts[0]), (2, prompts[2])])
    # chaos run, phase 1: poison at step 4, "die" after step 6
    eng = DecodeEngine(lm_params, H, cfg)
    for i, p in enumerate(prompts):
        eng.submit(p, 8, uid=i)
    snap_dir = str(tmp_path / "snap")
    for step in range(1, 7):
        if step == 4:
            eng.arm_poison(1)
        assert eng.step()
        write_snapshot(eng, snap_dir)
    assert set(eng.failed) == {1}
    assert eng.failed[1]["reason"] == "nonfinite_logits"
    # phase 2: a fresh process restores the snapshot and drains
    eng2 = DecodeEngine(lm_params, H, cfg)
    restore_engine_state(eng2, load_snapshot(snap_dir))
    assert eng2.step_base == 6
    done = eng2.run()
    assert set(eng2.failed) == {1}           # failure survives the crash
    assert done[0] == oracle[0] and done[2] == oracle[2]
    assert sorted(done) == [0, 2]


def test_quarantine_retry_recovers_clean_tokens(tmp_path, lm_params,
                                                prompts):
    """With retry budget, the quarantined request is replay-resumed and
    its FINAL tokens equal the never-poisoned run's (the fault fires
    once; the poisoned step's garbage pick was never appended)."""
    cfg = EngineConfig(**BASE)
    clean = _drain(lm_params, cfg, list(enumerate(prompts)))
    plan = FaultPlan.parse("nan_logits@4:1")
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg,
                             policy=ServePolicy(max_retries=1)),
        [(p, 8) for p in prompts], snapshot_dir=str(tmp_path / "s"),
        chaos=plan)
    assert eng.failed == {}
    assert {u: t for u, t in eng.finished.items()} == clean
    assert eng.quarantined == 1 and eng.retried == 1
    events = [(e["event"], e["uid"]) for e in eng.request_events]
    assert ("quarantined", 1) in events and ("retried", 1) in events
    assert [f.kind for f in plan.faults if f.fired] == ["nan_logits"]


def test_quarantine_tp_matches_single_device(tmp_path, lm_params,
                                             prompts, mesh_model4):
    """The guardrail under Megatron TP: the flag is computed on the
    gathered (replicated) logits, so every shard quarantines the same
    uid at the same step, and survivors match the single-device
    engine bit-for-bit."""
    cfg = EngineConfig(**BASE)
    oracle = _drain(lm_params, cfg, [(0, prompts[0]), (2, prompts[2])])
    plan = FaultPlan.parse("nan_logits@4:1")
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg, mesh=mesh_model4),
        [(p, 8) for p in prompts], snapshot_dir=str(tmp_path / "s"),
        chaos=plan)
    assert set(eng.failed) == {1}
    assert eng.finished[0] == oracle[0]
    assert eng.finished[2] == oracle[2]


def test_corrupt_block_quarantines_owner_then_retry_recovers(
        tmp_path, lm_params, prompts):
    """corrupt_block@4:1 poisons uid 0's first block (FCFS admission
    hands block 1 to the first request): uid 0 is quarantined, its
    blocks are scrubbed, and the retry — now on a factory-fresh pool
    region — completes with the clean run's exact tokens; survivors
    never notice."""
    cfg = EngineConfig(**BASE)
    clean = _drain(lm_params, cfg, list(enumerate(prompts)))
    plan = FaultPlan.parse("corrupt_block@4:1")
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg,
                             policy=ServePolicy(max_retries=1)),
        [(p, 8) for p in prompts], snapshot_dir=str(tmp_path / "s"),
        chaos=plan)
    assert eng.failed == {}
    assert {u: t for u, t in eng.finished.items()} == clean
    assert eng.quarantined == 1
    q = [e for e in eng.request_events if e["event"] == "quarantined"]
    assert q and q[0]["uid"] == 0


def test_corrupt_scratch_block_recovers_via_retry(tmp_path, lm_params,
                                                  prompts):
    """corrupt_block@4:0 poisons the SHARED scratch block every table
    pads with — all active sequences quarantine in one wave. Because
    quarantine scrubs the scratch block along with the owned blocks,
    the retries run on a clean pool and every request completes with
    the uninterrupted run's tokens (the regression was a permanent
    all-requests failure: scratch was never in any seq.blocks, so no
    scrub ever reached it)."""
    cfg = EngineConfig(**BASE)
    clean = _drain(lm_params, cfg, list(enumerate(prompts)))
    plan = FaultPlan.parse("corrupt_block@4:0")
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg,
                             policy=ServePolicy(max_retries=1)),
        [(p, 8) for p in prompts], snapshot_dir=str(tmp_path / "s"),
        chaos=plan)
    assert eng.failed == {}, eng.failed
    assert {u: t for u, t in eng.finished.items()} == clean
    assert eng.quarantined >= 1


def test_resume_never_reissues_finished_uids(tmp_path, lm_params,
                                             prompts):
    """Auto-assigned uids after a snapshot resume must clear the
    FINISHED/FAILED uids too, not just the live ones — a collision
    would sample in lockstep with the finished twin and overwrite its
    entry."""
    cfg = EngineConfig(**BASE)
    eng = DecodeEngine(lm_params, H, cfg)
    # the FINISHED uid (5) is the largest — the live uids alone would
    # leave _next_uid at 2, re-issuing 5 later in the resumed process
    eng.submit(prompts[0], 3, uid=5)    # short + first: finishes first
    eng.submit(prompts[1], 8, uid=0)
    eng.submit(prompts[2], 8, uid=1)
    while not eng.finished:
        eng.step()
    assert 5 in eng.finished
    sd = str(tmp_path / "snap")
    write_snapshot(eng, sd)
    eng2 = DecodeEngine(lm_params, H, cfg)
    restore_engine_state(eng2, load_snapshot(sd))
    new_uid = eng2.submit(prompts[0], 2)        # auto uid
    assert new_uid == 6                 # past the finished uid, not 2
    done = eng2.run()
    assert sorted(done) == [0, 1, 5, new_uid]


def test_expiry_only_final_step_still_snapshots(tmp_path, lm_params,
                                                prompts):
    """A run whose LAST step only expires requests must still persist
    the drained snapshot — a stale one would resume the dead uids and
    double-count their request records."""
    cfg = EngineConfig(**{**BASE, "max_slots": 1})
    sd = str(tmp_path / "snap")
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg,
                             policy=ServePolicy(deadline_steps=4)),
        [(p, 16) for p in prompts], snapshot_dir=sd)
    assert eng.failed and all(i["reason"] == "deadline"
                              for i in eng.failed.values())
    snap = load_snapshot(sd)
    assert snap["requests"] == []       # nothing listed as live
    assert {int(u) for u in snap["failed"]} == set(eng.failed)


def test_evicted_corrupted_block_scrubbed_before_reuse(lm_params,
                                                       prompts):
    """A corrupted block whose owner is EVICTED before its next
    dispatch (preemption here; deadline expiry is the same path) must
    be scrubbed on release — otherwise the NaN lands on whichever
    innocent request reserves the block next and, with max_retries=0,
    fails it terminally."""
    clean = _drain(lm_params, EngineConfig(**BASE),
                   list(enumerate(prompts)))
    cfg = EngineConfig(block_size=8, n_blocks=7, max_slots=3,
                       max_blocks_per_seq=3, prefill_chunk=8)
    eng = DecodeEngine(lm_params, H, cfg,
                       policy=ServePolicy(preempt_after_steps=1))
    eng.submit(prompts[0], 8, uid=0)     # blocks 1,2
    eng.submit(prompts[1], 8, uid=1)     # blocks 3,4 (the youngest)
    eng.step()
    eng.corrupt_block(3)                 # uid 1's block, between steps
    # uid 2 (3 blocks > 2 free) starves the head: step 2 preempts uid 1
    # BEFORE any dispatch could flag its poisoned block
    eng.submit(prompts[2], 8, uid=2)
    done = eng.run()
    assert eng.preempted >= 1
    assert eng.failed == {}, eng.failed  # nobody inherited the NaN
    assert {u: t for u, t in done.items()} == clean
    assert eng._corrupted == set()


def test_generate_sheds_to_none_not_exception(lm_params, prompts):
    """generate() under queue_limit: the shed prompt yields None in its
    position; the accepted ones still drain (the regression raised
    AdmissionError out of generate with the queue still loaded)."""
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                       policy=ServePolicy(queue_limit=2))
    outs = eng.generate(prompts, 4)
    assert outs[2] is None and eng.rejected == 1
    assert outs[0] is not None and outs[1] is not None
    ref = _drain(lm_params, EngineConfig(**BASE),
                 [(0, prompts[0]), (1, prompts[1])], max_new=4)
    assert outs[0] == ref[0] and outs[1] == ref[1]


@pytest.mark.parametrize("kv_dtype", ["f32", "int8"])
def test_corrupt_and_scrub_pool_units(kv_dtype):
    pool = init_pool(1, 4, 2, 4, 8, kv_dtype)
    pool = corrupt_block(pool, 2)
    table = jax.numpy.asarray([2, 0], jax.numpy.int32)
    k, _ = gather_layer(pool, 0, table)
    assert not np.isfinite(np.asarray(k)[:, :4]).all()
    pool = scrub_blocks(pool, [2])
    k, v = gather_layer(pool, 0, table)
    assert (np.asarray(k) == 0).all() and (np.asarray(v) == 0).all()
    if kv_dtype == "int8":
        assert (np.asarray(pool.k_scale) == 0).all()
    with pytest.raises(ValueError, match="outside pool"):
        corrupt_block(pool, 4)


# ------------------------------------------------- preemption / resume


@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_preempt_resume_token_identical(lm_params, prompts, kv_dtype):
    """Pool-pressure preemption: a pool holding ~2 sequences serves 3 —
    the youngest is evicted back to WAITING and later replay-resumed.
    Evicted-then-resumed AND survivor sequences are token-identical to
    the unconstrained engine at every KV dtype (replay re-runs the
    exact write history — the int8 quantization story included)."""
    clean = _drain(lm_params, EngineConfig(**BASE, kv_dtype=kv_dtype),
                   list(enumerate(prompts)))
    cfg_small = EngineConfig(block_size=8, n_blocks=7, max_slots=3,
                             max_blocks_per_seq=3, prefill_chunk=8,
                             kv_dtype=kv_dtype)
    eng = DecodeEngine(lm_params, H, cfg_small,
                       policy=ServePolicy(preempt_after_steps=1))
    for i, p in enumerate(prompts):
        eng.submit(p, 8, uid=i)
    done = eng.run()
    assert eng.preempted >= 1
    assert {u: t for u, t in done.items()} == clean
    events = [e["event"] for e in eng.request_events]
    assert "preempted" in events


def test_preempt_resume_sampled_token_identical(lm_params, prompts):
    """The sampled flavor — Gumbel draws keyed on (seed, uid, position)
    survive eviction + replay bit-for-bit (any numeric drift in the
    replayed cache would flip some argmax of z + g)."""
    kw = dict(temperature=0.9, top_k=12, top_p=0.9, seed=7)
    clean = _drain(lm_params, EngineConfig(**BASE, **kw),
                   list(enumerate(prompts)))
    cfg_small = EngineConfig(block_size=8, n_blocks=7, max_slots=3,
                             max_blocks_per_seq=3, prefill_chunk=8, **kw)
    eng = DecodeEngine(lm_params, H, cfg_small,
                       policy=ServePolicy(preempt_after_steps=1))
    for i, p in enumerate(prompts):
        eng.submit(p, 8, uid=i)
    done = eng.run()
    assert eng.preempted >= 1
    assert {u: t for u, t in done.items()} == clean


def test_preempt_resume_zero_new_compiles_after_first_cycle(lm_params,
                                                            prompts):
    """Recompile guard: preemption and replay-resume ride the SAME
    bucket programs — after the first preempt/resume cycle the compile
    count stops growing, however much more preempted traffic flows."""
    cfg_small = EngineConfig(block_size=8, n_blocks=7, max_slots=3,
                             max_blocks_per_seq=3, prefill_chunk=8)
    eng = DecodeEngine(lm_params, H, cfg_small,
                       policy=ServePolicy(preempt_after_steps=1))
    for i, p in enumerate(prompts):
        eng.submit(p, 8, uid=i)
    eng.run()
    assert eng.preempted >= 1           # the first preempt/resume cycle
    warm = eng.compile_count
    dispatches = eng.dispatch_count
    # same LENGTH schedule as wave one (content is irrelevant to the
    # scheduler), so any new compile could only come from the second
    # preempt/resume cycle itself
    rng = np.random.default_rng(9)
    more = [rng.integers(0, V, size=n).tolist() for n in (5, 9, 13)]
    for j, p in enumerate(more):
        eng.submit(p, 8, uid=100 + j)
    eng.run()
    assert eng.preempted >= 2           # pressure persisted
    assert eng.compile_count == warm    # zero new compiles
    assert eng.dispatch_count > dispatches


def test_head_streak_resets_when_head_changes(lm_params, prompts):
    """The preemption hysteresis belongs to ONE head-of-line request:
    when the starved head disappears (expired/shed), its successor must
    earn its own preempt_after_steps — inheriting the old streak would
    evict a victim after a single starved step."""
    cfg = EngineConfig(block_size=8, n_blocks=7, max_slots=3,
                       max_blocks_per_seq=3, prefill_chunk=8)
    eng = DecodeEngine(lm_params, H, cfg,
                       policy=ServePolicy(preempt_after_steps=3))
    eng.submit(prompts[0], 8, uid=0)     # 2 blocks
    eng.submit(prompts[1], 8, uid=1)     # 2 blocks -> 2 free
    eng.submit(prompts[2], 8, uid=2)     # needs 3: starved head
    eng.step()
    eng.step()
    assert eng._head_blocked == 2 and eng._head_blocked_uid == 2
    eng.waiting.popleft()                # the starved head vanishes
    eng.submit(prompts[2], 8, uid=3)     # a NEW starved head
    eng.step()
    assert eng._head_blocked == 1 and eng._head_blocked_uid == 3
    assert eng.preempted == 0            # successor earned nothing yet


def test_preemption_never_evicts_last_resident(lm_params, prompts):
    """The termination guard: with one running sequence, the head of
    line WAITS instead of evicting it (a lone resident's replay-only
    window is the one livelock shape) — the run still completes."""
    cfg = EngineConfig(block_size=8, n_blocks=4, max_slots=2,
                       max_blocks_per_seq=3, prefill_chunk=8)
    eng = DecodeEngine(lm_params, H, cfg,
                       policy=ServePolicy(preempt_after_steps=1))
    eng.submit(prompts[1], 8, uid=0)     # needs 2 of the 3 usable blocks
    eng.submit(prompts[1], 8, uid=1)     # must WAIT, never evict uid 0
    done = eng.run()
    assert eng.preempted == 0
    assert sorted(done) == [0, 1]


# ------------------------------------------------- snapshot / resume


@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_snapshot_resume_matches_uninterrupted(tmp_path, lm_params,
                                               prompts, kv_dtype):
    """Crash-resume mid-flight at every KV dtype: snapshot after 5
    steps, restore into a FRESH engine (new pool, new programs), drain —
    finished tokens equal the uninterrupted run's exactly."""
    cfg = EngineConfig(**BASE, kv_dtype=kv_dtype)
    oracle = _drain(lm_params, cfg, list(enumerate(prompts)))
    eng = DecodeEngine(lm_params, H, cfg)
    for i, p in enumerate(prompts):
        eng.submit(p, 8, uid=i)
    for _ in range(5):
        assert eng.step()
    sd = str(tmp_path / "snap")
    write_snapshot(eng, sd)
    snap = load_snapshot(sd)
    assert snap["step"] == 5 and snap["version"] == 9
    # v2: the KV-pool churn counters persist so schema-v5 decode
    # records stay monotonic across crash-resume
    assert snap["counters"]["block_allocs"] >= 1
    assert "block_scrubs" in snap["counters"]
    # v3: the speculation pair persists the same way (zero here — the
    # engine under test doesn't speculate; monotonicity is what's
    # pinned, tests/test_spec_decode.py covers the live values)
    assert snap["counters"]["drafted_tokens"] == 0
    assert snap["counters"]["accepted_tokens"] == 0
    # v4: the shared-prefix counters persist the same way, and the
    # radix share graph ships as ``prefix_tree`` — these prompts share
    # no prefix, so the tree holds the 9- and 13-token prompts' single
    # full blocks, each locked by its own prefiller (the shared-refs
    # pins are tests/test_prefix_cache.py's snapshot test)
    assert snap["counters"]["cow_copies"] == 0
    assert snap["counters"]["prefill_dispatches"] == 5
    assert [n["refs"] for n in snap["prefix_tree"]] == [1, 1]
    running = [r for r in snap["requests"] if r["state"] == "RUNNING"]
    assert running and all("block_table" in r and "position" in r
                           for r in running)
    if kv_dtype == "int8":
        assert snap["int8_scales"]["shape"] == [L, BASE["n_blocks"], H]
    eng2 = DecodeEngine(lm_params, H, cfg)
    restore_engine_state(eng2, snap)
    assert {u: t for u, t in eng2.run().items()} == oracle
    assert eng2.global_step > 5


def test_snapshot_restore_rejects_config_mismatch(tmp_path, lm_params,
                                                  prompts):
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE))
    eng.submit(prompts[0], 8, uid=0)
    eng.step()
    sd = str(tmp_path / "snap")
    write_snapshot(eng, sd)
    other = DecodeEngine(lm_params, H,
                         EngineConfig(**{**BASE, "kv_dtype": "bf16"}))
    with pytest.raises(ValueError, match="snapshot config"):
        restore_engine_state(other, load_snapshot(sd))
    withpol = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                           policy=ServePolicy(max_retries=2))
    with pytest.raises(ValueError, match="snapshot policy"):
        restore_engine_state(withpol, load_snapshot(sd))
    # a different MODEL (same shapes, different init seed) must be
    # rejected too: resume replays recorded tokens through the current
    # weights, so the token-identical contract needs the same params
    other_params = init_lm(jax.random.PRNGKey(42), V, D, L,
                           max_seq_len=64)
    other_model = DecodeEngine(other_params, H, EngineConfig(**BASE))
    with pytest.raises(ValueError, match="snapshot model"):
        restore_engine_state(other_model, load_snapshot(sd))


@pytest.mark.serial
def test_kill_resume_via_generate_cli(tmp_path):
    """The real-SIGKILL acceptance flavor: ``nan_logits@3:1,kill@6``
    through the generate CLI. Run 1 quarantines uid 1 and dies by
    SIGKILL right after the step-6 snapshot; run 2 (same command)
    resumes, completes rc 0, reports uid 1 FAILED, and the survivors'
    tokens equal an uninterrupted no-chaos run that never admitted the
    poisoned prompt. The metrics stream spans both processes and stays
    schema-valid."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    base_args = [sys.executable, "-m",
                 "distributed_llm_code_samples_tpu.cli", "generate",
                 "--max_new", "6", "-d", "32", "-l", "2", "--heads", "4",
                 "--vocab", "64", "--max_seq_len", "64", "--block_size",
                 "8", "--prefill_chunk", "4", "--log_every", "2"]
    # oracle: the two SURVIVING prompts only, with the uids they carry
    # in the chaos run (0 and 2 — the sampling keys fold the uid)
    rng = np.random.default_rng(0)
    lens = (3, 7, 5)
    prompts3 = [rng.integers(0, 64, size=n).tolist() for n in lens]
    oracle_args = base_args + [
        "--prompts", ",".join(map(str, prompts3[0])) + ";"
        + ",".join(map(str, prompts3[2]))]
    r0 = subprocess.run(oracle_args, capture_output=True, text=True,
                        env=env, cwd=REPO,
                        timeout=load_scaled_timeout(300))
    assert r0.returncode == 0, r0.stdout + r0.stderr
    oracle = {s["uid"]: s["tokens"]
              for s in json.loads(r0.stdout)["sequences"]}
    # the chaos run: 3 prompts via --prompt_lens (seed 0 => prompts3)
    args = base_args + [
        "--prompt_lens", ",".join(map(str, lens)),
        "--snapshot_dir", str(tmp_path / "snap"),
        "--metrics_dir", str(tmp_path / "metrics"),
        "--chaos", "nan_logits@3:1,kill@6"]
    r1 = subprocess.run(args, capture_output=True, text=True, env=env,
                        cwd=REPO, timeout=load_scaled_timeout(300))
    assert r1.returncode == -signal.SIGKILL, r1.stdout + r1.stderr
    assert os.path.exists(tmp_path / "snap" / "engine_snapshot.json")
    r2 = subprocess.run(args, capture_output=True, text=True, env=env,
                        cwd=REPO, timeout=load_scaled_timeout(300))
    assert r2.returncode == 0, r2.stdout + r2.stderr
    payload = json.loads(r2.stdout)
    assert payload["resumed_from_step"] == 6
    assert list(payload["failed"]) == ["1"]
    assert payload["failed"]["1"]["reason"] == "nonfinite_logits"
    got = {s["uid"]: s["tokens"] for s in payload["sequences"]}
    # oracle ran uids 0,1 for the two prompts; map survivor uids
    assert got[0] == oracle[0] and got[2] == oracle[1]
    # prompt_len survives the resume (engine-side record, not a
    # flag-derived guess)
    plens = {s["uid"]: s["prompt_len"] for s in payload["sequences"]}
    assert plens == {0: 3, 2: 5}
    from distributed_llm_code_samples_tpu.runtime.telemetry import (
        METRICS_FILENAME, read_metrics, validate_record)
    records, problems = read_metrics(
        str(tmp_path / "metrics" / METRICS_FILENAME))
    assert problems == []
    reqs = [r for r in records if r["kind"] == "request"]
    assert reqs and all(validate_record(r)[0] for r in reqs)
    assert {(r["event"], r["uid"]) for r in reqs} >= {
        ("quarantined", 1), ("completed", 0), ("completed", 2)}


# ------------------------------------------------- admission control


def test_duplicate_inflight_and_failed_uid_rejected(lm_params, prompts):
    """Satellite regression: a second submit with an in-flight uid (in
    a SLOT, not just waiting) is rejected — a silent collision would
    sample both sequences in lockstep (the key folds the uid) and
    overwrite the finished entry. A FAILED uid stays reserved too."""
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE))
    # negative uids collide with the poison operand sentinels (-1/-2):
    # uid -1 would match the idle poison comparison and NaN every step
    with pytest.raises(ValueError, match="uid must be >= 0"):
        eng.submit(prompts[0], 8, uid=-1)
    with pytest.raises(ValueError, match="uid must be >= 0"):
        eng.resume_request(-2, prompts[0], 8)
    eng.submit(prompts[0], 8, uid=5)
    eng.step()                                 # uid 5 now holds a slot
    assert eng.active == 1
    with pytest.raises(ValueError, match="already in use"):
        eng.submit(prompts[1], 8, uid=5)
    eng.arm_poison(5)
    eng.step()                                 # quarantined -> FAILED
    assert 5 in eng.failed
    with pytest.raises(ValueError, match="already in use"):
        eng.submit(prompts[1], 8, uid=5)


def test_queue_limit_rejects_with_event(lm_params, prompts):
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                       policy=ServePolicy(queue_limit=2))
    eng.submit(prompts[0], 4)
    eng.submit(prompts[1], 4)
    with pytest.raises(AdmissionError, match="queue full"):
        eng.submit(prompts[2], 4)
    assert eng.rejected == 1
    rej = [e for e in eng.request_events if e["event"] == "rejected"]
    assert rej and rej[0]["reason"] == "queue_full"
    # an auto-uid shed carries uid -1 in its record: the number was
    # never consumed and WILL be reused by a later accepted request —
    # recording it would alias two requests in the per-uid audit trail
    assert rej[0]["uid"] == -1
    assert sorted(eng.run()) == [0, 1]


def test_deadline_expires_overdue_requests(lm_params, prompts):
    """TTL: with one slot and a 4-step deadline, the queued request
    (and the too-slow running one) fail with reason 'deadline' instead
    of waiting forever — graceful degradation, reported per uid."""
    eng = DecodeEngine(lm_params, H,
                       EngineConfig(**{**BASE, "max_slots": 1}),
                       policy=ServePolicy(deadline_steps=4))
    u0 = eng.submit(prompts[0], 16)
    u1 = eng.submit(prompts[1], 16)
    done = eng.run()
    assert done == {}
    assert eng.failed[u0]["reason"] == "deadline"
    assert eng.failed[u1]["reason"] == "deadline"
    assert eng.expired == 2
    exp = [e for e in eng.request_events if e["event"] == "expired"]
    assert {e["uid"] for e in exp} == {u0, u1}


def test_deadline_not_extended_by_preemption(lm_params, prompts):
    """TTL measures from the ORIGINAL submission: preemption re-queues
    must not reset the clock, or churn would keep a request alive (and
    holding resources) unboundedly past its deadline."""
    cfg_small = EngineConfig(block_size=8, n_blocks=7, max_slots=3,
                            max_blocks_per_seq=3, prefill_chunk=8)
    eng = DecodeEngine(lm_params, H, cfg_small,
                       policy=ServePolicy(preempt_after_steps=1,
                                          deadline_steps=6))
    for i, p in enumerate(prompts):
        eng.submit(p, 8, uid=i)
    eng.run()
    # under this pool pressure at least one request both got preempted
    # and then ran out of TTL — the reset-on-requeue bug made this
    # combination immortal instead
    assert eng.preempted >= 1
    assert eng.expired >= 1
    assert all(info["reason"] == "deadline"
               for info in eng.failed.values())
    # generate()'s contract for failed requests: None, not KeyError
    eng2 = DecodeEngine(lm_params, H, cfg_small,
                        policy=ServePolicy(preempt_after_steps=1,
                                           deadline_steps=6))
    outs = eng2.generate(prompts, 8)
    assert len(outs) == 3 and any(o is None for o in outs)


def test_policy_validation():
    with pytest.raises(ValueError, match="queue_limit"):
        ServePolicy(queue_limit=-1)
    with pytest.raises(ValueError, match="max_retries"):
        ServePolicy(max_retries=-2)


# ------------------------------------------------- telemetry contract


def test_request_records_schema_valid(tmp_path, lm_params, prompts):
    from distributed_llm_code_samples_tpu.runtime.telemetry import (
        METRICS_FILENAME, REQUEST_REQUIRED, SCHEMA_VERSION,
        TelemetryWriter, read_metrics, validate_record)
    mdir = str(tmp_path / "metrics")
    with TelemetryWriter(mdir, meta={"subcommand": "generate"}) as w:
        eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                           policy=ServePolicy(max_retries=1), metrics=w)
        for i, p in enumerate(prompts):
            eng.submit(p, 6, uid=i)
        for _ in range(4):              # uid 1 finishes prefill at 4
            eng.step()
        eng.arm_poison(1)               # poisons run()'s first step
        eng.run(log_every=2)
    records, problems = read_metrics(os.path.join(mdir,
                                                  METRICS_FILENAME))
    assert problems == []
    reqs = [r for r in records if r["kind"] == "request"]
    assert reqs
    for r in reqs:
        assert r["schema"] == SCHEMA_VERSION
        for key in REQUEST_REQUIRED:
            assert key in r
    events = {(r["event"], r["uid"]) for r in reqs}
    assert {("admitted", 0), ("quarantined", 1), ("retried", 1),
            ("completed", 0)} <= events
    done = [r for r in reqs if r["event"] == "completed"]
    assert all(r.get("latency_s") is not None for r in done)
    # the contract rejects a request record missing a required key
    bad = {k: v for k, v in reqs[0].items() if k != "reason"}
    ok, reason = validate_record(bad)
    assert not ok and "reason" in reason


def test_report_renders_serving_reliability(tmp_path, lm_params,
                                            prompts, capsys):
    """report folds request records into the reliability summary +
    latency percentiles + the one merged timeline."""
    from distributed_llm_code_samples_tpu.report import report_main
    from distributed_llm_code_samples_tpu.runtime.telemetry import (
        TelemetryWriter)
    mdir = str(tmp_path / "metrics")
    with TelemetryWriter(mdir, meta={"subcommand": "generate"}) as w:
        eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                           metrics=w)
        for i, p in enumerate(prompts):
            eng.submit(p, 6, uid=i)
        for _ in range(7):              # uid 2 finishes prefill at 7
            eng.step()
        eng.arm_poison(2)               # poisons run()'s first step
        eng.run(log_every=2)
    assert report_main([mdir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    rel = doc["serving_reliability"]
    assert rel["admitted"] == 3 and rel["quarantined"] == 1
    assert rel["completed"] == 2 and rel["failed_uids"] == [2]
    assert "latency_p50_s" in rel
    assert any(r["source"] == "request" and "QUARANTINED" in r["what"]
               for r in doc["timeline"])
    assert report_main([mdir]) == 0
    text = capsys.readouterr().out
    assert "serving reliability:" in text and "FAILED uids: [2]" in text


# ------------------------------------------------- CLI flag guards


def test_generate_cli_rejects_bad_reliability_flags(capsys):
    import distributed_llm_code_samples_tpu.cli as cli
    base = ["generate", "--prompt_lens", "3", "--max_new", "2"]
    # --chaos without --snapshot_dir
    assert cli.main(base + ["--chaos", "kill@3"]) == 2
    assert "--snapshot_dir" in capsys.readouterr().err
    # unparseable / training-kind / missing-arg specs
    assert cli.main(base + ["--snapshot_dir", "/tmp/x",
                            "--chaos", "bogus@1"]) == 2
    assert cli.main(base + ["--snapshot_dir", "/tmp/x",
                            "--chaos", "nan_grad@2"]) == 2
    assert "training fault" in capsys.readouterr().err
    assert cli.main(base + ["--snapshot_dir", "/tmp/x",
                            "--chaos", "corrupt_block@2"]) == 2
    assert "requires :BLOCK" in capsys.readouterr().err
    # bad policy values reject cleanly (rc 2, no traceback)
    assert cli.main(base + ["--max_retries", "-1"]) == 2
    assert cli.main(base + ["--queue_limit", "-3"]) == 2
    assert cli.main(base + ["--deadline_steps", "-2"]) == 2
    # watchdog outside the supervisor
    assert cli.main(base + ["--watchdog_ms", "100"]) == 2
    # snapshot cadence must be >= 1
    assert cli.main(base + ["--snapshot_dir", "/tmp/x",
                            "--snapshot_every", "0"]) == 2
    # supervisor-only flags reject consistently without --snapshot_dir
    assert cli.main(base + ["--snapshot_every", "4"]) == 2
    assert cli.main(base + ["--max_restarts", "0"]) == 2
    # a corrupt_block id outside the configured pool rejects at parse
    # time instead of burning the restart ladder at fire time
    assert cli.main(base + ["--snapshot_dir", "/tmp/x",
                            "--chaos", "corrupt_block@2:999"]) == 2
    assert "outside the pool" in capsys.readouterr().err
    capsys.readouterr()


def test_train_cli_rejects_decode_chaos_kinds(tmp_path, capsys):
    """The mirror guard: a decode fault in a TRAINING --chaos spec
    would silently never fire — rejected rc 2 instead."""
    import distributed_llm_code_samples_tpu.cli as cli
    rc = cli.main(["-m", "1", "-s", "4", "-bs", "2", "-n", "4", "-d",
                   "8", "-l", "1", "--checkpoint_dir",
                   str(tmp_path / "ck"), "--checkpoint_every", "2",
                   "--chaos", "nan_logits@2:1"])
    assert rc == 2
    assert "decode" in capsys.readouterr().err


def test_generate_cli_queue_limit_sheds(tmp_path, capsys):
    """--queue_limit 2 with 3 prompts: one request shed (rejected, not
    an error), run exits 0, payload reports the shed count."""
    import distributed_llm_code_samples_tpu.cli as cli
    rc = cli.main(["generate", "--prompt_lens", "3,4,5", "--max_new",
                   "3", "-d", "32", "-l", "2", "--heads", "4",
                   "--vocab", "64", "--max_seq_len", "64",
                   "--block_size", "8", "--prefill_chunk", "4",
                   "--queue_limit", "2"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["rejected"] == 1 and payload["shed"] == 1
    assert len(payload["sequences"]) == 2


# ------------------------------------------------- watchdog evidence


def test_hang_step_latches_watchdog_evidence(tmp_path, lm_params,
                                             prompts):
    """hang_step@3:0.6 stalls one engine step past a 200ms watchdog:
    the run completes (a hang is evidence, not fatal, at this layer)
    and both the hung_step record and the completed record carry the
    latch."""
    plan = FaultPlan.parse("hang_step@3:0.6")
    sd = str(tmp_path / "snap")
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, EngineConfig(**BASE)),
        [(p, 6) for p in prompts], snapshot_dir=sd, chaos=plan,
        watchdog_ms=200)
    assert sorted(eng.finished) == [0, 1, 2]
    with open(os.path.join(sd, "serve_supervise.jsonl")) as f:
        log = [json.loads(ln) for ln in f if ln.strip()]
    hung = [r for r in log if r.get("event") == "hung_step"]
    assert hung and all(r["watchdog_expired"] for r in hung)
    completed = [r for r in log if r.get("event") == "completed"]
    assert completed and completed[0]["watchdog_expired"] is True
    assert [f.kind for f in plan.faults if f.fired] == ["hang_step"]
