"""Cross-strategy differential tests — the reference's core test asset
(``--method 0`` allclose, ``train_ffns.py:386-391``) made hard-failing and
extended: the reference only compared DDP vs FSDP; here TP is also pinned
to the single-device oracle (its data is replicated, so they must agree),
and the hybrid mesh is pinned to its two degeneracies."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_code_samples_tpu.data import make_seed_schedule
from distributed_llm_code_samples_tpu.models import init_ffn_stack
from distributed_llm_code_samples_tpu.parallel import (
    make_mesh, train_single, train_ddp, train_fsdp, train_tp, train_hybrid,
    train_pp, DATA_AXIS, MODEL_AXIS, PIPE_AXIS)

D, L, B, S = 64, 3, 32, 8
LR_TEST = 0.1  # the reference's testing LR (train_ffns.py:29)
RTOL, ATOL = 1e-5, 1e-7


@pytest.fixture(scope="module")
def setup():
    params = init_ffn_stack(jax.random.PRNGKey(42), D, L)
    seeds = make_seed_schedule(S, random_seed=7)
    return params, seeds


def _assert_params_close(a, b, rtol=RTOL, atol=ATOL):
    np.testing.assert_allclose(np.asarray(a.w1), np.asarray(b.w1),
                               rtol=rtol, atol=atol)
    np.testing.assert_allclose(np.asarray(a.w2), np.asarray(b.w2),
                               rtol=rtol, atol=atol)


def test_training_changes_params(setup):
    params, seeds = setup
    out = train_single(params, seeds, B, D, lr=LR_TEST)
    assert not np.allclose(np.asarray(out.w1), np.asarray(params.w1))
    assert out.w1.shape == params.w1.shape


def test_single_does_not_consume_caller_params(setup):
    params, seeds = setup
    train_single(params, seeds, B, D, lr=LR_TEST)
    # donation must consume a clone, not the caller's arrays (--method 0
    # feeds the same params to every strategy, train_ffns.py:376-379)
    _ = np.asarray(params.w1)


def test_tp_matches_single_device(setup, mesh_model4):
    # TP replicates the data (train_ffns.py:324) => must equal the 1-device
    # run exactly (modulo reduction order).
    params, seeds = setup
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    p_tp = train_tp(params, seeds, B, D, mesh_model4, lr=LR_TEST)
    _assert_params_close(p_single, p_tp)


def test_ddp_matches_fsdp(setup, mesh4):
    # the reference's --method 0 soft assert (train_ffns.py:386-391),
    # hard-failing here.
    params, seeds = setup
    p_ddp = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST)
    p_fsdp = train_fsdp(params, seeds, B, D, mesh4, lr=LR_TEST)
    _assert_params_close(p_ddp, p_fsdp)


def test_ddp_differs_from_single():
    # SUM-reduction with unscaled LR: multi-rank results intentionally
    # differ from 1-device (SURVEY.md 2.1) — assert the difference is real
    # so the equivalence tests above can't pass vacuously.
    params = init_ffn_stack(jax.random.PRNGKey(1), D, L)
    seeds = make_seed_schedule(S, random_seed=3)
    mesh = make_mesh({DATA_AXIS: 4})
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    p_ddp = train_ddp(params, seeds, B, D, mesh, lr=LR_TEST)
    assert not np.allclose(np.asarray(p_single.w1), np.asarray(p_ddp.w1),
                           rtol=RTOL, atol=ATOL)


def test_hybrid_degenerates_to_ddp(setup):
    params, seeds = setup
    mesh_ddp = make_mesh({DATA_AXIS: 4})
    mesh_hyb = make_mesh({DATA_AXIS: 4, MODEL_AXIS: 1})
    _assert_params_close(train_ddp(params, seeds, B, D, mesh_ddp, lr=LR_TEST),
                         train_hybrid(params, seeds, B, D, mesh_hyb, lr=LR_TEST))


def test_hybrid_degenerates_to_tp(setup):
    params, seeds = setup
    mesh_tp = make_mesh({MODEL_AXIS: 4})
    mesh_hyb = make_mesh({DATA_AXIS: 1, MODEL_AXIS: 4})
    _assert_params_close(train_tp(params, seeds, B, D, mesh_tp, lr=LR_TEST),
                         train_hybrid(params, seeds, B, D, mesh_hyb, lr=LR_TEST))


def test_hybrid_2d_matches_ddp(setup, mesh4x2):
    # TP is an exact decomposition, so hybrid(4x2) == DDP(4) — the BASELINE
    # config-4 topology validated against a 1-axis oracle.
    params, seeds = setup
    mesh_ddp = make_mesh({DATA_AXIS: 4})
    _assert_params_close(train_ddp(params, seeds, B, D, mesh_ddp, lr=LR_TEST),
                         train_hybrid(params, seeds, B, D, mesh4x2, lr=LR_TEST))


def test_pp_matches_single_device(setup):
    # PP replicates the data and microbatch grads sum to the full-batch
    # grad, so the staged run must equal the 1-device oracle. Needs a
    # layer count divisible by the stage count.
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 4)
    _, seeds = setup
    mesh = make_mesh({PIPE_AXIS: 4})
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    p_pp = train_pp(params, seeds, B, D, mesh, lr=LR_TEST)
    _assert_params_close(p_single, p_pp)


def test_pp_more_microbatches_than_stages(setup):
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 4)
    _, seeds = setup
    mesh = make_mesh({PIPE_AXIS: 4})
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    p_pp = train_pp(params, seeds, B, D, mesh, lr=LR_TEST, n_microbatches=8)
    _assert_params_close(p_single, p_pp)


def test_pp_two_stages_multi_layer(setup):
    # 2 stages x 2 layers/stage: the local stack loop inside a stage
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 4)
    _, seeds = setup
    mesh = make_mesh({PIPE_AXIS: 2})
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    p_pp = train_pp(params, seeds, B, D, mesh, lr=LR_TEST)
    _assert_params_close(p_single, p_pp)


def test_pp_rejects_indivisible_layers(setup):
    params, seeds = setup  # L=3 not divisible by 4 stages
    mesh = make_mesh({PIPE_AXIS: 4})
    with pytest.raises(ValueError):
        train_pp(params, seeds, B, D, mesh, lr=LR_TEST)


def test_pp_uses_collective_permute(setup):
    # the send/recv path must actually lower to collective_permute HLOs
    from distributed_llm_code_samples_tpu.parallel import pipeline
    from distributed_llm_code_samples_tpu.utils.hlo import count_collectives
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 4)
    mesh = make_mesh({PIPE_AXIS: 4})
    sharded = pipeline.shard_params(params, mesh)
    step = pipeline.make_step(B, D, 4, 4, lr=LR_TEST)
    from jax.sharding import PartitionSpec as P
    run = jax.shard_map(step, mesh=mesh,
                        in_specs=(pipeline.PARAM_SPECS, P()),
                        out_specs=pipeline.PARAM_SPECS)
    counts = count_collectives(run, sharded, jnp.int32(3))
    # one shift per tick per direction; each direction's final shift is
    # dead (nothing consumes it) and trace-time DCE'd
    assert counts["collective_permute"] >= 2 * (4 + 4 - 2)


@pytest.mark.parametrize("n_mb", [2, 4, 8])
def test_pp_1f1b_matches_single_device(setup, n_mb):
    # the 1F1B interleave covers all three regimes: M < S (deep warmup),
    # M == S, M > S (circular stash wraps)
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 4)
    _, seeds = setup
    mesh = make_mesh({PIPE_AXIS: 4})
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    p_pp = train_pp(params, seeds, B, D, mesh, lr=LR_TEST,
                    n_microbatches=n_mb, schedule="1f1b")
    _assert_params_close(p_single, p_pp)


def test_pp_1f1b_stash_depth_is_stage_bound(setup):
    """1F1B's point: in-flight activations are bounded by the stage depth
    S, not the microbatch count M. Structurally: the traced step holds a
    stash of depth min(S, M); no buffer of depth M (or M+S-1, the old
    per-tick stash) may exist for M > S. GPipe's stash, by contrast, is
    exactly M deep."""
    from distributed_llm_code_samples_tpu.parallel import pipeline
    from distributed_llm_code_samples_tpu.models.ffn_stack import (
        FFNStackParams)
    from jax.sharding import PartitionSpec as P
    S_, M_ = 4, 16
    n_local, mb = 1, B // M_  # 4 layers over 4 stages

    def stash_str(depth):  # the stash's printed aval, e.g. f32[4,1,2,64]
        return f"f32[{depth},{n_local},{mb},{D}]"

    def trace(schedule):
        step = pipeline.make_step(B, D, S_, M_, lr=LR_TEST,
                                  schedule=schedule)
        mesh = make_mesh({PIPE_AXIS: S_})
        run = jax.shard_map(step, mesh=mesh,
                            in_specs=(pipeline.PARAM_SPECS, P()),
                            out_specs=pipeline.PARAM_SPECS)
        full = FFNStackParams(
            w1=jax.ShapeDtypeStruct((S_, 4 * D, D), jnp.float32),
            w2=jax.ShapeDtypeStruct((S_, D, 4 * D), jnp.float32))
        return str(jax.make_jaxpr(run)(
            full, jax.ShapeDtypeStruct((), jnp.int32)))

    jx = trace("1f1b")
    assert stash_str(S_) in jx, "1f1b stash of depth min(S,M) missing"
    assert stash_str(M_) not in jx, "1f1b allocated an M-deep buffer"
    assert stash_str(M_ + S_ - 1) not in jx, "per-tick stash came back"
    jg = trace("gpipe")
    assert stash_str(M_) in jg, "gpipe stash should be exactly M deep"
    assert stash_str(M_ + S_ - 1) not in jg, "per-tick stash came back"


def test_pp_rejects_unknown_schedule(setup):
    params, seeds = setup
    mesh = make_mesh({PIPE_AXIS: 4})
    with pytest.raises(ValueError, match="schedule"):
        train_pp(init_ffn_stack(jax.random.PRNGKey(0), D, 4), seeds, B, D,
                 mesh, lr=LR_TEST, schedule="wavefront42")


@pytest.mark.parametrize("n_mb", [2, 4, 8, 16])
def test_pp_interleaved_matches_single_device(setup, n_mb):
    """Interleaved virtual stages (v=2 non-contiguous chunks per device,
    device-major layer permutation restored on output) == single device,
    across M < S, M == S, M > S, and multi-group M."""
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 8)
    _, seeds = setup
    mesh = make_mesh({PIPE_AXIS: 4})
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    p_pp = train_pp(params, seeds, B, D, mesh, lr=LR_TEST,
                    n_microbatches=n_mb, schedule="interleaved",
                    interleave=2)
    _assert_params_close(p_single, p_pp)


def test_pp_interleaved_deep_chunks_and_compositions(setup):
    """v=4 chunks on 2 stages == single; data x pipe interleaved == DDP
    over the data axis alone; pipe x model interleaved == single (the
    Megatron shard inside each chunk compute)."""
    from distributed_llm_code_samples_tpu.parallel import train_ddp
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 8)
    _, seeds = setup
    p_single = train_single(params, seeds, B, D, lr=LR_TEST)
    got = train_pp(params, seeds, B, D, make_mesh({PIPE_AXIS: 2}),
                   lr=LR_TEST, n_microbatches=4, schedule="interleaved",
                   interleave=4)
    _assert_params_close(p_single, got)
    p_ddp = train_ddp(params, seeds, B, D, make_mesh({DATA_AXIS: 2}),
                      lr=LR_TEST)
    got = train_pp(params, seeds, B, D,
                   make_mesh({DATA_AXIS: 2, PIPE_AXIS: 2}), lr=LR_TEST,
                   n_microbatches=4, schedule="interleaved", interleave=2)
    _assert_params_close(p_ddp, got)
    got = train_pp(params, seeds, B, D,
                   make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 2}), lr=LR_TEST,
                   n_microbatches=4, schedule="interleaved", interleave=2)
    _assert_params_close(p_single, got)


def test_pp_interleaved_partial_groups(setup):
    """M not a multiple of S (the schedule packs microbatch groups of S;
    the last group is partial and its missing offsets idle): M < S
    non-divisor and M > S non-multiple both stay exact."""
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 8)
    _, seeds = setup
    tokens = 48
    single = train_single(params, seeds, tokens, D, lr=LR_TEST)
    mesh = make_mesh({PIPE_AXIS: 4})
    for m in (3, 6):
        got = train_pp(params, seeds, tokens, D, mesh, lr=LR_TEST,
                       n_microbatches=m, schedule="interleaved",
                       interleave=2)
        _assert_params_close(single, got)


def test_pp_interleaved_rejects_bad_chunking(setup):
    _, seeds = setup
    with pytest.raises(ValueError, match="virtual chunks"):
        train_pp(init_ffn_stack(jax.random.PRNGKey(0), D, 8), seeds, B, D,
                 make_mesh({PIPE_AXIS: 4}), lr=LR_TEST,
                 schedule="interleaved", interleave=3)
    with pytest.raises(ValueError, match="interleave"):
        train_pp(init_ffn_stack(jax.random.PRNGKey(0), D, 8), seeds, B, D,
                 make_mesh({PIPE_AXIS: 4}), lr=LR_TEST,
                 schedule="interleaved", interleave=0)


def test_pp_interleaved_bubble_structure():
    """The schedule's whole point, pinned structurally: with v chunks per
    device the slot stream is v*M + S - 1 ticks per phase of CHUNK-sized
    compute (1/v of a stage), so fill costs (S-1)/v stage-units vs
    GPipe's S-1 — bubble fraction (S-1)/(vM+S-1) vs (S-1)/(M+S-1).
    Evidence in the traced program: (a) per-direction ring shifts ==
    ticks (the last one DCE'd), (b) the stash is the [V, M, Lc, mb, D]
    chunk stash — per-slot compute really is chunk-sized."""
    from distributed_llm_code_samples_tpu.parallel import pipeline
    from distributed_llm_code_samples_tpu.models.ffn_stack import (
        FFNStackParams)
    from jax.sharding import PartitionSpec as P
    S_, M_, V_ = 4, 4, 2
    L_, mb = 8, B // M_
    lc = L_ // (S_ * V_)

    def trace(schedule, **kw):
        step = pipeline.make_step(B, D, S_, M_, lr=LR_TEST,
                                  schedule=schedule, **kw)
        mesh = make_mesh({PIPE_AXIS: S_})
        run = jax.shard_map(step, mesh=mesh,
                            in_specs=(pipeline.PARAM_SPECS, P()),
                            out_specs=pipeline.PARAM_SPECS)
        full = FFNStackParams(
            w1=jax.ShapeDtypeStruct((L_, 4 * D, D), jnp.float32),
            w2=jax.ShapeDtypeStruct((L_, D, 4 * D), jnp.float32))
        return str(jax.make_jaxpr(run)(
            full, jax.ShapeDtypeStruct((), jnp.int32)))

    ji = trace("interleaved", interleave=V_)
    ticks = V_ * M_ + S_ - 1
    # one ppermute per slot per direction (each phase's final shift is
    # dead; whether trace-time DCE drops it varies, hence the range)
    assert 2 * (ticks - 1) <= ji.count("ppermute") <= 2 * ticks
    assert f"f32[{V_},{M_},{lc},{mb},{D}]" in ji, "chunk stash missing"
    jg = trace("gpipe")
    g_ticks = M_ + S_ - 1
    assert 2 * (g_ticks - 1) <= jg.count("ppermute") <= 2 * g_ticks
    # the interleaved stream really is longer in SLOTS but each slot is
    # chunk-sized: fill = (S-1)/v stage-units vs gpipe's S-1
    assert ticks == V_ * M_ + S_ - 1 and g_ticks == M_ + S_ - 1


def test_scan_path_agrees(setup, mesh4):
    params, seeds = setup
    p_u = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST, unroll=True)
    p_s = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST, unroll=False)
    _assert_params_close(p_u, p_s)


def test_fsdp_output_stays_sharded(setup, mesh4):
    params, seeds = setup
    out = train_fsdp(params, seeds, B, D, mesh4, lr=LR_TEST)
    spec = out.w1.sharding.spec
    assert spec[1] == DATA_AXIS  # per-layer dim 0 sharded, like chunk_p


def test_fsdp_rejects_indivisible_shapes(mesh4):
    params = init_ffn_stack(jax.random.PRNGKey(0), 6, 1, ffn_dim=6)
    seeds = make_seed_schedule(4, random_seed=1)
    with pytest.raises(ValueError):
        train_fsdp(params, seeds, B, 6, mesh4)


def test_tp_rejects_indivisible_shapes(mesh_model4):
    params = init_ffn_stack(jax.random.PRNGKey(0), 6, 1, ffn_dim=6)
    seeds = make_seed_schedule(4, random_seed=1)
    with pytest.raises(ValueError):
        train_tp(params, seeds, B, 6, mesh_model4)


def test_seed_count_must_divide_ranks(setup, mesh4):
    params, _ = setup
    seeds = make_seed_schedule(6, random_seed=1)  # 6 % 4 != 0
    with pytest.raises(ValueError):
        train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST)


def test_ddp_on_8_devices(setup, mesh8):
    params, seeds = setup
    p_ddp8 = train_ddp(params, seeds, B, D, mesh8, lr=LR_TEST)
    p_fsdp8 = train_fsdp(params, seeds, B, D, mesh8, lr=LR_TEST)
    _assert_params_close(p_ddp8, p_fsdp8)


@pytest.mark.parametrize("accum", [2, 4])
def test_accumulation_matches_full_batch_single(setup, accum):
    """Gradient accumulation is exactly the full-batch step: grads are
    linear in the batch and the update is SUM-semantics throughout."""
    params, seeds = setup
    full = train_single(params, seeds, B, D, lr=LR_TEST)
    acc = train_single(params, seeds, B, D, lr=LR_TEST, accum=accum)
    _assert_params_close(full, acc)


def test_accumulation_matches_full_batch_ddp(setup, mesh4):
    params, seeds = setup
    full = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST)
    acc = train_ddp(params, seeds, B, D, mesh4, lr=LR_TEST, accum=4)
    _assert_params_close(full, acc)


def test_accumulation_rejects_indivisible(setup):
    params, seeds = setup
    with pytest.raises(ValueError, match="accumulation"):
        train_single(params, seeds, B, D, lr=LR_TEST, accum=5)


def test_tp_sp_matches_tp_and_single(setup, mesh_model4):
    """Megatron sequence-parallel TP: token-sharded activation stream with
    all_gather+reduce_scatter replacing each all_reduce — must equal both
    plain TP and the single-device oracle exactly."""
    from distributed_llm_code_samples_tpu.parallel import train_tp_sp
    params, seeds = setup
    single = train_single(params, seeds, B, D, lr=LR_TEST)
    tp_plain = train_tp(params, seeds, B, D, mesh_model4, lr=LR_TEST)
    tp_sp = train_tp_sp(params, seeds, B, D, mesh_model4, lr=LR_TEST)
    _assert_params_close(tp_sp, single)
    _assert_params_close(tp_sp, tp_plain)


def test_tp_sp_comms_and_sharded_activations(setup, mesh_model4):
    """The mechanism: no all_reduce remains (each became a ring-equal
    all_gather + reduce_scatter pair), and the saved residuals are the
    token SHARDS [L, T/n, d] — the 1/n activation-memory claim."""
    from distributed_llm_code_samples_tpu.parallel import tp
    from distributed_llm_code_samples_tpu.utils.hlo import count_collectives
    from jax.sharding import PartitionSpec as P
    params, _ = setup
    sp = tp.shard_params(params, mesh_model4)
    step = tp.make_sp_step(B, D, 4, LR_TEST)
    run = jax.shard_map(step, mesh=mesh_model4,
                        in_specs=(tp.PARAM_SPECS, P()),
                        out_specs=tp.PARAM_SPECS, check_vma=False)
    c = count_collectives(run, sp, jnp.int32(3))
    assert c["all_reduce"] == 0, dict(c)
    assert c["all_gather"] >= 2 * L, dict(c)   # fwd x + bwd dy per layer
    assert c["reduce_scatter"] >= L + 1, dict(c)
    jx = str(jax.make_jaxpr(run)(sp, jnp.int32(3)))
    assert f"f32[{L},{B // 4},{D}]" in jx, "sharded acts stash missing"
    assert f"f32[{L},{B},{D}]" not in jx, "acts stash is full-token"


def test_tp_sp_rejects_indivisible_tokens(setup, mesh_model4):
    from distributed_llm_code_samples_tpu.parallel import train_tp_sp
    params, seeds = setup
    with pytest.raises(ValueError, match="tokens"):
        train_tp_sp(params, seeds, B + 2, D, mesh_model4, lr=LR_TEST)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pp_3d_compositions(setup, schedule):
    """3-D parallelism: the pipe ring composed with a DDP data axis
    and/or a Megatron model axis inside each stage. dp x pp [x tp] ==
    DDP over the data axis alone; pp x tp == single — the TP and PP
    decompositions are exact, so only the data axis changes the math."""
    params = init_ffn_stack(jax.random.PRNGKey(42), D, 4)
    _, seeds = setup
    single = train_single(params, seeds, B, D, lr=LR_TEST)
    ddp2 = train_ddp(params, seeds, B, D, make_mesh({DATA_AXIS: 2}),
                     lr=LR_TEST)
    pp_tp = train_pp(params, seeds, B, D,
                     make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 2}), lr=LR_TEST,
                     schedule=schedule)
    _assert_params_close(pp_tp, single, rtol=1e-5, atol=1e-6)
    dp_pp = train_pp(params, seeds, B, D,
                     make_mesh({DATA_AXIS: 2, PIPE_AXIS: 2}), lr=LR_TEST,
                     schedule=schedule)
    _assert_params_close(dp_pp, ddp2, rtol=1e-5, atol=1e-6)
    dp_pp_tp = train_pp(
        params, seeds, B, D,
        make_mesh({DATA_AXIS: 2, PIPE_AXIS: 2, MODEL_AXIS: 2}),
        lr=LR_TEST, schedule=schedule)
    _assert_params_close(dp_pp_tp, ddp2, rtol=1e-5, atol=1e-6)


def test_pp_3d_rejects_indivisible_ffn(setup):
    _, seeds = setup
    odd = init_ffn_stack(jax.random.PRNGKey(0), D, 4, ffn_dim=98)
    with pytest.raises(ValueError, match="ffn_dim"):
        train_pp(odd, seeds, B, D,
                 make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 4}), lr=LR_TEST)
