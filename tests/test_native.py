"""Native C++ runtime tests — the test_nccl.py / test_mp_barrier_gpus.py /
test_torch_distributed.py analogues against OUR native engines:
numpy-oracle checks for the ring collectives, a three-way agreement check
(native ring == numpy == XLA collective), data-loader determinism/prefetch,
multi-process TCP rendezvous+barrier, and the XLA FFI custom calls under
jit."""

import multiprocessing as mp
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_llm_code_samples_tpu.runtime import native
from distributed_llm_code_samples_tpu.parallel import collectives as xla_coll
from distributed_llm_code_samples_tpu.parallel import DATA_AXIS

N = 4


@pytest.fixture(scope="module")
def arrays():
    rng = np.random.default_rng(0)
    return [rng.normal(size=(33,)).astype(np.float32) for _ in range(N)]


def test_ring_all_reduce_matches_numpy(arrays):
    red = native.all_reduce_sum(arrays)
    expected = np.sum(arrays, axis=0)
    for r in range(N):
        np.testing.assert_allclose(red[r], expected, rtol=1e-5)


def test_ring_all_reduce_does_not_mutate_inputs(arrays):
    before = [a.copy() for a in arrays]
    native.all_reduce_sum(arrays)
    for a, b in zip(arrays, before):
        np.testing.assert_array_equal(a, b)


def test_ring_all_gather_matches_numpy(arrays):
    outs = native.all_gather(arrays)
    expected = np.concatenate(arrays)
    for r in range(N):
        np.testing.assert_array_equal(outs[r], expected)


def test_ring_reduce_scatter_matches_numpy():
    rng = np.random.default_rng(1)
    full = [rng.normal(size=(20,)).astype(np.float32) for _ in range(N)]
    outs = native.reduce_scatter_sum(full)
    expected = np.sum(full, axis=0).reshape(N, 5)
    for r in range(N):
        np.testing.assert_allclose(outs[r], expected[r], rtol=1e-5)


def test_ring_reduce_scatter_rejects_indivisible():
    bad = [np.zeros(7, np.float32) for _ in range(N)]
    with pytest.raises(ValueError):
        native.reduce_scatter_sum(bad)


def test_ring_permute_shifts(arrays):
    outs = native.ring_permute(arrays, shift=1)
    for r in range(N):
        np.testing.assert_array_equal(outs[(r + 1) % N], arrays[r])


def test_native_ring_agrees_with_xla_collective(mesh4):
    """Three-way: native ring engine == numpy == XLA psum over the mesh —
    the native engine serves as an independent oracle for the device path."""
    rng = np.random.default_rng(2)
    per_rank = [rng.normal(size=(8,)).astype(np.float32) for _ in range(4)]

    ring = native.all_reduce_sum(per_rank)[0]

    stacked = jnp.asarray(np.stack(per_rank)).reshape(4 * 8)
    xla = jax.jit(jax.shard_map(
        lambda s: xla_coll.all_reduce(s, DATA_AXIS), mesh=mesh4,
        in_specs=(P(DATA_AXIS),), out_specs=P(DATA_AXIS)))(stacked)
    xla_first = np.asarray(xla).reshape(4, 8)[0]

    np.testing.assert_allclose(ring, np.sum(per_rank, axis=0), rtol=1e-5)
    np.testing.assert_allclose(ring, xla_first, rtol=1e-5)


# ---------------------------------------------------------------- data loader

def test_loader_deterministic_and_ordered():
    with native.NativeLoader(8, 16) as L:
        L.submit_all([5, 9, 5])
        s1, x1, d1 = L.next()
        s2, x2, d2 = L.next()
        s3, x3, d3 = L.next()
    assert (s1, s2, s3) == (5, 9, 5)  # submission order preserved
    np.testing.assert_array_equal(x1, x3)  # same seed -> same batch
    assert not np.array_equal(x1, x2)


def test_loader_moments_and_dloss_scale():
    with native.NativeLoader(64, 64) as L:
        L.submit(123)
        _, x, dl = L.next()
    assert abs(float(x.mean())) < 0.1
    assert abs(float(x.std()) - 1.0) < 0.1
    assert abs(float(dl.std()) - 0.1) < 0.02  # DLOSS_DX_COEF scaling


def test_loader_many_threads_keep_order():
    with native.NativeLoader(4, 8, n_threads=4) as L:
        seeds = list(range(100, 120))
        L.submit_all(seeds)
        got = [L.next()[0] for _ in seeds]
    assert got == seeds


# ----------------------------------------------------------------- rendezvous

def _rdzv_worker(role, q, port):
    from distributed_llm_code_samples_tpu.runtime import native as nat
    if role == 0:
        r = nat.Rendezvous("127.0.0.1", port, world_size=3, coordinator=True)
    else:
        r = nat.Rendezvous("127.0.0.1", port)
    r.barrier()
    q.put((r.rank, r.world_size))
    r.barrier()
    r.close()


@pytest.mark.slow
def test_rendezvous_multiprocess_barrier():
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = 29613
    procs = [ctx.Process(target=_rdzv_worker, args=(i, q, port))
             for i in range(3)]
    for p in procs:
        p.start()
    results = sorted(q.get(timeout=60) for _ in range(3))
    for p in procs:
        p.join(timeout=30)
    assert results == [(0, 3), (1, 3), (2, 3)]


# ------------------------------------------------------- XLA FFI custom calls

def test_ffi_fused_sgd_matches_jnp():
    rng = np.random.default_rng(3)
    p = jnp.asarray(rng.normal(size=(6, 5)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=(6, 5)).astype(np.float32))
    out = native.fused_sgd(p, g, 0.05)
    np.testing.assert_allclose(out, p - 0.05 * g, rtol=1e-6)


def test_ffi_fused_sgd_under_jit():
    rng = np.random.default_rng(4)
    p = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    out = jax.jit(lambda p, g: native.fused_sgd(p, g, 0.1))(p, g)
    np.testing.assert_allclose(out, p - 0.1 * g, rtol=1e-6)


def test_ffi_relu_bwd_matches_reference_semantics():
    # grad zero at x == 0, like t_relu_bkwd_ (train_ffns.py:50-52)
    dy = jnp.asarray(np.array([1.0, 2.0, 3.0], np.float32))
    x = jnp.asarray(np.array([-1.0, 0.0, 1.0], np.float32))
    out = native.native_relu_bwd(dy, x)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.array([0.0, 0.0, 3.0], np.float32))


def test_collective_wrappers_reject_mismatched_sizes():
    bad = [np.zeros(8, np.float32), np.zeros(4, np.float32)]
    for fn in (native.all_reduce_sum, native.all_gather,
               native.reduce_scatter_sum, native.ring_permute):
        with pytest.raises(ValueError):
            fn(bad)


def test_loader_overpop_fails_fast():
    with native.NativeLoader(2, 4) as L:
        L.submit(1)
        L.next()
        with pytest.raises(RuntimeError):
            L.next()
