"""Pallas flash-attention kernel tests (interpret mode on CPU).

Oracle: the plain hand-VJP attention op (``models.attention``) and jax
autograd over it — forward values, lse policy, and all three gradients,
causal and bidirectional, across tile-boundary shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_code_samples_tpu.models.attention import attention, mha
from distributed_llm_code_samples_tpu.ops.pallas_attention import (
    flash_attention, flash_attention_fwd, flash_mha)

T, DH = 64, 16


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (T, DH)) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_fwd_matches_oracle(qkv, causal):
    q, k, v = qkv
    y = flash_attention(q, k, v, causal, True)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(attention(q, k, v, causal)),
                               rtol=1e-5, atol=1e-5)


def test_flash_fwd_multiple_kv_tiles(qkv):
    """Force >1 kv tile so the online-softmax accumulation path runs."""
    q, k, v = qkv
    y, lse = flash_attention_fwd(q, k, v, causal=True, block_q=16,
                                 block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(attention(q, k, v, True)),
                               rtol=1e-5, atol=1e-5)
    # lse is the true log-sum-exp of the scaled, masked scores
    s = (q @ k.T) / jnp.sqrt(jnp.asarray(DH, jnp.float32))
    s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -jnp.inf)
    np.testing.assert_allclose(np.asarray(lse),
                               np.asarray(jax.nn.logsumexp(s, axis=-1)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grads_match_autograd(qkv, causal):
    q, k, v = qkv
    dy = 0.1 * jax.random.normal(jax.random.PRNGKey(7), (T, DH))
    _, vjp_f = jax.vjp(lambda q, k, v: flash_attention(q, k, v, causal,
                                                       True), q, k, v)
    _, vjp_r = jax.vjp(lambda q, k, v: attention(q, k, v, causal), q, k, v)
    for name, a, b in zip("qkv", vjp_f(dy), vjp_r(dy)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-5, err_msg=f"d{name}")


def test_flash_grads_across_tiles(qkv):
    """Gradients with small tiles — exercises the recompute-p path over
    many (i, j) blocks including fully-masked causal tiles."""
    q, k, v = qkv
    dy = 0.1 * jax.random.normal(jax.random.PRNGKey(8), (T, DH))

    def f(q, k, v):
        from distributed_llm_code_samples_tpu.ops.pallas_attention import (
            flash_attention_bwd, flash_attention_fwd)
        y, lse = flash_attention_fwd(q, k, v, causal=True, block_q=16,
                                     block_k=16, interpret=True)
        return flash_attention_bwd(dy, q, k, v, y, lse, causal=True,
                                   block_q=16, block_k=16, interpret=True)

    _, vjp_r = jax.vjp(lambda q, k, v: attention(q, k, v, True), q, k, v)
    for name, a, b in zip("qkv", f(q, k, v), vjp_r(dy)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-5, err_msg=f"d{name}")


def test_flash_mha_matches_mha():
    H = 4
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (H, T, DH)) for kk in ks)
    y = flash_mha(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(mha(q, k, v, True)),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_aot_v5e_at_bench_shapes():
    """De-risks the bench_attention chip run (VERDICT r3 weak #2): the
    flash forward AND backward kernels compile under REAL Mosaic/VMEM
    constraints at the largest shape the bench times (T=8192, dh=64) —
    AOT against a v5e topology, no interpret mode anywhere. A tiling or
    VMEM regression in the kernels fails here, chip or no chip.
    (Mosaic kernels aren't auto-partitionable, so the compile wraps in a
    replicated shard_map — the same program a 1-chip run executes.)"""
    import functools
    import numpy as onp
    from conftest import require_aot_topology
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental import topologies
    require_aot_topology()  # bounded probe: a hung discovery skips fast
    try:
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x4")
    except Exception as e:
        pytest.skip(f"no TPU AOT topology support: {e}")
    mesh = Mesh(onp.array(topo.devices).reshape(8), ("d",))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, False))

    grad = jax.grad(loss, argnums=(0, 1, 2))
    f = jax.jit(jax.shard_map(grad, mesh=mesh, in_specs=(P(), P(), P()),
                              out_specs=(P(), P(), P()),
                              check_vma=False))
    x = jax.ShapeDtypeStruct((8192, 64), jnp.float32)
    hlo = f.lower(x, x, x).compile().as_text()
    assert hlo.count("custom-call") >= 3  # fwd + bwd-dq + bwd-dkv kernels


def test_flash_gqa_matches_oracle():
    """Grouped-query shapes through flash_mha (repeat-KV fan-out) ==
    the hand-VJP gqa oracle, values and all three grads; indivisible
    head counts rejected."""
    from distributed_llm_code_samples_tpu.models.attention import gqa

    H, HKV, T, DH = 4, 2, 64, 64
    kq, kk, kv, kd = jax.random.split(jax.random.PRNGKey(11), 4)
    q = jax.random.normal(kq, (H, T, DH))
    k = jax.random.normal(kk, (HKV, T, DH))
    v = jax.random.normal(kv, (HKV, T, DH))
    dy = jax.random.normal(kd, (H, T, DH))

    y0, vjp0 = jax.vjp(lambda q, k, v: gqa(q, k, v, True), q, k, v)
    y1, vjp1 = jax.vjp(lambda q, k, v: flash_mha(q, k, v, True, True),
                       q, k, v)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-5)
    for a, b, name in zip(vjp0(dy), vjp1(dy), ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5, err_msg=name)

    bad_k = jax.random.normal(kk, (3, T, DH))
    with pytest.raises(ValueError, match="not divisible"):
        flash_mha(q, bad_k, bad_k, True, True)


def test_gqa_trainer_accepts_flash():
    """init_lm(n_kv_heads=...) + attn_impl='flash' trains and matches
    the oracle-attention run (the CLI guard that rejected this combo is
    gone)."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.models import init_lm
    from distributed_llm_code_samples_tpu.parallel import train_lm_single

    params = init_lm(jax.random.PRNGKey(0), 128, 64, 2, 32, n_heads=4,
                     n_kv_heads=2)
    seeds = make_seed_schedule(2, random_seed=3)
    o = train_lm_single(params, seeds, 2 * 32, 64, lr=0.1, seq_len=32,
                        n_heads=4)
    f = train_lm_single(params, seeds, 2 * 32, 64, lr=0.1, seq_len=32,
                        n_heads=4, attn_impl="flash")
    for a, b in zip(jax.tree_util.tree_leaves(o),
                    jax.tree_util.tree_leaves(f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)
