"""Randomized-shape property tests for the hand-VJP numerical core.

The deterministic op tests (``test_ops.py``, ``test_lm.py``,
``test_transformer.py``) pin each rule at one or two shapes; these sweep
seeded random shapes/values so a rule that is accidentally
shape-specialized (a hardcoded axis, a transposed reduction, a residual
saved at the wrong rank) cannot hide. Every check is the same oracle the
framework uses throughout: the hand-written ``custom_vjp`` against
``jax.grad`` of an independent plain-op forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_code_samples_tpu.models.attention import attention
from distributed_llm_code_samples_tpu.ops import (ffn_block, layernorm,
                                                  xent_loss)

RNG = np.random.default_rng(20260730)
CASES = 6


def _shapes(n, lo=1, hi=17):
    return [tuple(int(x) for x in RNG.integers(lo, hi, size=2))
            for _ in range(n)]


@pytest.mark.parametrize("rows,vocab", _shapes(CASES, lo=2, hi=33))
def test_xent_random_shapes(rows, vocab):
    key = jax.random.fold_in(jax.random.PRNGKey(0), rows * 1000 + vocab)
    logits = jax.random.normal(key, (rows, vocab)) * 3.0
    targets = jax.random.randint(jax.random.fold_in(key, 1), (rows,), 0,
                                 vocab)

    def plain(z):
        lse = jax.scipy.special.logsumexp(z, axis=-1)
        picked = jnp.take_along_axis(z, targets[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - picked)

    np.testing.assert_allclose(float(xent_loss(logits, targets)),
                               float(plain(logits)), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(jax.grad(xent_loss)(logits, targets)),
        np.asarray(jax.grad(plain)(logits)), rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("rows,d", _shapes(CASES, lo=2, hi=33))
def test_layernorm_random_shapes(rows, d):
    key = jax.random.fold_in(jax.random.PRNGKey(1), rows * 1000 + d)
    g = jax.random.normal(key, (d,))
    x = jax.random.normal(jax.random.fold_in(key, 1), (rows, d)) * 2.0
    dy = jax.random.normal(jax.random.fold_in(key, 2), (rows, d))

    def plain(g, x):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return g * (x - mu) / jnp.sqrt(var + 1e-5)

    _, vjp = jax.vjp(layernorm, g, x)
    _, vjp_ref = jax.vjp(plain, g, x)
    for got, want in zip(vjp(dy), vjp_ref(dy)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("tokens,d", _shapes(CASES, lo=2, hi=25))
def test_ffn_block_random_shapes(tokens, d):
    # ffn derives from the case params (not the module RNG at run time)
    # so a single case reproduces in isolation
    ffn = (tokens % 3 + 1) * d + d % 7 + 1
    key = jax.random.fold_in(jax.random.PRNGKey(2), tokens * 1000 + d)
    w1 = jax.random.normal(key, (ffn, d)) * 0.1
    w2 = jax.random.normal(jax.random.fold_in(key, 1), (d, ffn)) * 0.1
    x = jax.random.normal(jax.random.fold_in(key, 2), (tokens, d))
    dy = jax.random.normal(jax.random.fold_in(key, 3), (tokens, d))

    def plain(w1, w2, x):
        return jnp.maximum(x @ w1.T, 0.0) @ w2.T

    _, vjp = jax.vjp(ffn_block, w1, w2, x)
    _, vjp_ref = jax.vjp(plain, w1, w2, x)
    for got, want in zip(vjp(dy), vjp_ref(dy)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-6)


@pytest.mark.parametrize("t,dh", _shapes(CASES, lo=2, hi=17))
@pytest.mark.parametrize("causal", [False, True])
def test_attention_random_shapes(t, dh, causal):
    key = jax.random.fold_in(jax.random.PRNGKey(3),
                             t * 1000 + dh + int(causal))
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (t, dh))
               for i in range(3))
    dy = jax.random.normal(jax.random.fold_in(key, 4), (t, dh))

    def plain(q, k, v):
        s = q @ k.T / jnp.sqrt(jnp.asarray(dh, q.dtype))
        if causal:
            s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -1e30)
        return jax.nn.softmax(s, axis=-1) @ v

    y = attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(y), np.asarray(plain(q, k, v)),
                               rtol=2e-4, atol=1e-5)
    _, vjp = jax.vjp(lambda q, k, v: attention(q, k, v, causal), q, k, v)
    _, vjp_ref = jax.vjp(plain, q, k, v)
    for got, want in zip(vjp(dy), vjp_ref(dy)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("t,d", _shapes(CASES, lo=2, hi=17))
def test_mixed_pair_form_random_shapes(t, d):
    """The bf16 pair-form rules (the strategies' hook dialect) match the
    custom_vjp block bit-for-bit across random shapes — the shared-core
    guarantee holds off the happy path too."""
    from distributed_llm_code_samples_tpu.ops.ffn import (
        ffn_block_mixed, ffn_bwd_mixed, ffn_fwd_mixed)
    key = jax.random.fold_in(jax.random.PRNGKey(8), t * 100 + d)
    w1 = jax.random.normal(jax.random.fold_in(key, 0), (4 * d, d)) * 0.1
    w2 = jax.random.normal(jax.random.fold_in(key, 1), (d, 4 * d)) * 0.1
    x = jax.random.normal(jax.random.fold_in(key, 2), (t, d))
    dy = jax.random.normal(jax.random.fold_in(key, 3), (t, d))
    y_pair = ffn_fwd_mixed(w1, w2, x)
    dx, (dw1, dw2) = ffn_bwd_mixed(dy, w1, w2, x)
    y_blk, vjp = jax.vjp(ffn_block_mixed, w1, w2, x)
    dw1_b, dw2_b, dx_b = vjp(dy)
    for got, want in ((y_pair, y_blk), (dx, dx_b), (dw1, dw1_b),
                      (dw2, dw2_b)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
