"""The driver contract: bench.py must print ONE parseable JSON line with
the agreed fields, whatever the platform, and the auxiliary benches must
keep their numeric-value contract. Run at smoke shapes on CPU — a
regression here means the round ends with no BENCH_r{N}.json."""

import json
import os
import subprocess
import sys

import pytest

from conftest import load_scaled_timeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, env_extra, timeout=600):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"BENCH_PLATFORM": "cpu"}, **env_extra)
    r = subprocess.run([sys.executable, script], capture_output=True,
                       text=True, env=env, cwd=REPO,
                       timeout=load_scaled_timeout(timeout))
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, r.stdout + r.stderr
    return json.loads(lines[-1])


@pytest.mark.slow
def test_bench_emits_driver_contract():
    # D/TOKENS large enough that model_tflops (round(_, 4)) stays
    # nonzero, so the MFU identity below is actually exercised
    payload = _run("bench.py", {
        "BENCH_D": "128", "BENCH_LAYERS": "2", "BENCH_TOKENS": "512",
        "BENCH_STEPS": "4", "BENCH_REPS": "1", "BENCH_PALLAS": "0",
        "BENCH_FAM_D": "32", "BENCH_FAM_LAYERS": "1",
        "BENCH_FAM_HEADS": "2", "BENCH_FAM_SEQ": "8",
        "BENCH_FAM_BATCH": "2", "BENCH_FAM_VOCAB": "64"})
    for field in ("metric", "value", "unit", "vs_baseline", "mfu",
                  "policy", "model_tflops"):
        assert field in payload, field
    assert isinstance(payload["value"], float) and payload["value"] > 0
    # the honest-MFU contract: value * model_tflops / peak == mfu
    # (both sides round(_, 4) in the payload — compare with a tolerance
    # covering that rounding, relative so fast machines don't trip it)
    assert payload["model_tflops"] > 0, payload
    recomputed = (payload["value"] * payload["model_tflops"]
                  / payload["peak_bf16_tflops"])
    tol = 1e-4 + 0.05 * max(payload["mfu"], recomputed)
    assert abs(recomputed - payload["mfu"]) <= tol, (recomputed, payload)
    # and the headline is the winning policy's own numbers, not a mix
    win = max(payload["remat_steps_per_sec"],
              payload["saved_steps_per_sec"])
    assert payload["value"] == win
    assert payload["mfu"] == max(payload["remat_mfu"],
                                 payload["saved_mfu"])
    # extras present (smoke shapes): breakdown components + families
    assert isinstance(payload.get("gap_breakdown"), dict)
    fams = payload.get("families")
    assert isinstance(fams, dict) and "transformer" in fams and "lm" in fams
    # the measured policy grids must ship: transformer oracle-vs-flash,
    # LM 2x2 attn x head (winner + full grid recorded)
    assert fams["transformer"]["attn"].removesuffix("+mixed") in (
        "oracle", "flash")
    assert isinstance(fams["transformer"]["flash_steps_per_sec"], float)
    assert isinstance(fams["transformer"]["mixed_vs_f32"], float)
    assert set(fams["lm"]["by_policy"]) == {
        "oracle+oracle", "oracle+fused", "flash+oracle", "flash+fused"}
    assert (fams["lm"]["policy"] in fams["lm"]["by_policy"]
            or fams["lm"]["policy"].removesuffix("+mixed")
            in fams["lm"]["by_policy"])
    # r5 additions: the bf16-trunk policy measurement, the derived
    # blocks-vs-head time split, and the FLOP shares
    assert isinstance(fams["lm"]["mixed_vs_f32"], float)
    gb = fams["lm"]["gap_breakdown"]
    assert gb["blocks_s"] > 0 and gb["head_embed_s"] >= 0
    shares = fams["lm"]["flop_shares"]
    assert abs(sum(shares.values()) - 1.0) < 0.01, shares
    # bf16 residual-policy grid (remat vs saved, winner ships);
    # `, payload` keeps the recorded error string visible on failure
    assert payload.get("bf16_policy") in ("remat", "saved"), payload
    assert isinstance(payload.get("bf16_remat_steps_per_sec"), float), payload
    assert isinstance(payload.get("bf16_saved_steps_per_sec"), float), payload
    # bf16 mixed-precision field (VERDICT r3 #3): numeric, with its own
    # MFU on the same model-FLOPs numerator and bf16-peak denominator
    assert isinstance(payload.get("bf16_vs_f32"), float), payload
    assert isinstance(payload.get("bf16_steps_per_sec"), float)
    recomputed_bf16 = (payload["bf16_steps_per_sec"]
                       * payload["model_tflops"]
                       / payload["peak_bf16_tflops"])
    tol = 1e-4 + 0.05 * max(payload["bf16_mfu"], recomputed_bf16)
    assert abs(recomputed_bf16 - payload["bf16_mfu"]) <= tol


def test_bench_fallback_zero_headline_with_last_measured_nested():
    """Advisor r5 + VERDICT r5 #1: when this run cannot measure (here:
    the round-5 outage signature — JAX_PLATFORMS pinned to a bogus
    backend), the emitted line's headline ``value`` must be 0.0 — a
    stale number carried forward as the headline misreads as a fresh
    measurement — with the last committed measured artifact's payload
    nested under ``last_measured`` (plus provenance naming the source),
    AND it must embed the env-matrix probe's final round
    (``probe_matrix``), one record per attempted env shape with its
    exception head, so the outage is diagnosable from the JSON alone."""
    env = dict(os.environ)
    env.pop("BENCH_PLATFORM", None)
    env["JAX_PLATFORMS"] = "bogus_backend"
    env["BENCH_WAIT_BUDGET"] = "1"
    env["BENCH_MAX_ATTEMPTS"] = "1"  # skip the quick-retry backoff
    env["BENCH_PROBE_SHAPE_TIMEOUT"] = str(load_scaled_timeout(150))
    # hermetic: a live-or-hung TPU relay must not be probed for real —
    # the unset/tpu shapes would block for the full per-shape timeout
    # (jax silently ignores a NONEXISTENT TPU_LIBRARY_PATH, so this must
    # be an existing invalid library that dlopen rejects instantly)
    from test_backend_probe import _hermetic_tpu
    _hermetic_tpu(env)
    r = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                       text=True, env=env, cwd=REPO,
                       timeout=load_scaled_timeout(300))
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, r.stdout + r.stderr
    payload = json.loads(lines[-1])
    assert "error" in payload
    assert payload["value"] == 0.0, payload   # headline never stale
    if os.path.exists(os.path.join(REPO, "BENCH_r04_local.json")):
        assert "provenance" in payload, payload
        nested = payload["last_measured"]
        assert nested["value"] > 0, payload   # old numbers survive here
        assert nested["artifact"].startswith("BENCH_r"), payload
    # the probe-matrix contract: every shape attempted before the budget
    # ran out is recorded (bench requires a real TPU, so on this CPU box
    # all four shapes fail; the bogus-backend head is the r5 signature)
    matrix = payload["probe_matrix"]
    assert [rec["shape"] for rec in matrix] == [
        "as_is", "pythonpath_minus_repo", "jax_platforms_unset",
        "jax_platforms_tpu"]
    for rec in matrix:
        assert not rec["ok"]
        assert rec["error"], rec
    assert "bogus_backend" in matrix[0]["error"], matrix
    assert payload["probe_rounds"] >= 1


@pytest.mark.slow
@pytest.mark.serial
def test_bench_moe_verdict_contract():
    payload = _run("bench_moe.py", {
        "MOE_TOKENS": "128", "MOE_D": "32", "MOE_LAYERS": "1",
        "MOE_STEPS": "8", "MOE_REPS": "1", "MOE_SEQ": "16",
        "MOE_VOCAB": "64"})  # 8 steps: divisible by the fake mesh
    assert isinstance(payload["value"], float)
    assert isinstance(payload["dense_steps_per_sec"], float)
    assert isinstance(payload["scatter_steps_per_sec"], float)
    assert isinstance(payload["gather_steps_per_sec"], float)
    assert payload["dispatch"] in ("dense", "scatter", "gather")
    assert "verdict" in payload
    # the r5 dispatch verdict is a GRID: E x capacity_factor points,
    # each with all three formulations and a per-point best
    sweep = payload["sweep"]
    assert len(sweep) >= 2, sweep
    for point in sweep.values():
        for disp in ("dense", "scatter", "gather"):
            assert isinstance(point[disp], float), point
        assert point["best"] in ("dense", "scatter", "gather")
    # the MoE-LM family ships its measured head-policy grid
    assert isinstance(payload.get("moe_lm_steps_per_sec"), float), payload
    assert payload.get("moe_lm_head") in ("oracle", "fused"), payload
    assert set(payload["moe_lm_by_head"]) == {"oracle", "fused"}


@pytest.mark.slow
def test_bench_attention_contract():
    payload = _run("bench_attention.py",
                   {"ATTN_TS": "64", "ATTN_REPS": "1", "ATTN_HEADS": "2"})
    assert payload["metric"] == "attn_pallas_vs_xla"
    # numeric, not an error string: a broken flash path must not ship
    assert isinstance(payload["per_T"].get("64"), float), payload


def best_point(curve):
    return min(curve[1:], key=lambda p: p["holdout_loss"])


@pytest.mark.slow
def test_train_real_text_contract(tmp_path):
    """The real-text trainer must emit falling train AND held-out loss
    curves (the VERDICT r3 honest-eval split), a sampled continuation,
    and the artifact file — the round's end-to-end capability demo
    cannot rot silently."""
    art = str(tmp_path / "textlm.json")
    payload = _run("train_real_text.py", {
        "TEXTLM_STEPS": "20", "TEXTLM_SEGMENTS": "2", "TEXTLM_D": "32",
        "TEXTLM_LAYERS": "1", "TEXTLM_HEADS": "2", "TEXTLM_SEQ": "32",
        "TEXTLM_BATCH": "4", "TEXTLM_ARTIFACT": art}, timeout=900)
    assert payload["metric"] == "real_text_lm_best_holdout_loss"
    curve = payload["loss_curve"]
    assert curve[0]["step"] == 0 and curve[-1]["step"] == 20
    # the headline is the BEST held-out loss over the curve (kept by the
    # checkpoint subsystem); both curves must fall
    assert payload["value"] < payload["initial_holdout_loss"], curve
    assert payload["value"] == min(p["holdout_loss"] for p in curve[1:])
    assert payload["best_step"] == best_point(curve)["step"]
    assert curve[-1]["train_loss"] < curve[0]["train_loss"], curve
    # the gap field keeps the memorization question visible
    assert "generalization_gap" in payload
    assert "final_holdout_loss" in payload
    assert "warmup_cosine" in payload["schedule"]
    # the held-out tail is never sampled by training windows
    assert payload["train_bytes"] + payload["holdout_bytes"] \
        == payload["corpus_bytes"]
    assert isinstance(payload["sample"], str) and len(payload["sample"])
    assert os.path.exists(art)


@pytest.mark.slow
def test_bench_decode_contract():
    """All three decode paths produce numeric tokens/s at smoke shapes;
    the tp path pre-shards outside the timed loop (ADVICE r3); the r5
    payload anchors the value on a KV-bandwidth roofline (scaling sweep
    skipped here — it spawns 4 subprocesses; its plumbing is covered by
    the DECODE_TP_ONLY env path the sweep drives)."""
    payload = _run("bench_decode.py", {
        "BENCH_D": "64", "BENCH_LAYERS": "2", "BENCH_HEADS": "4",
        "BENCH_VOCAB": "256", "BENCH_BATCH": "2", "BENCH_PROMPT": "4",
        "BENCH_NEW": "8", "BENCH_REPS": "1", "BENCH_MOE_D": "32",
        "BENCH_MOE_LAYERS": "1", "DECODE_SCALING": "0"})
    assert payload["value"] > 0
    for key in ("lm_tokens_per_sec", "tp_tokens_per_sec",
                "moe_tokens_per_sec"):
        assert isinstance(payload[key], float), payload
    # roofline fields (VERDICT r4 #8): positive anchor + the fraction
    # recomputes from its parts
    assert payload["roofline_tokens_per_sec"] > 0
    assert payload["roofline_fraction"] == pytest.approx(
        payload["value"] / payload["roofline_tokens_per_sec"], rel=1e-2)
    assert payload["param_bytes"] > 0
    # degenerate 1-chip tp runs must be labeled as overhead measurement
    if payload.get("tp_mesh") == 1:
        assert "tp_note" in payload
    # r9 engine rows: the KV-dtype x batching grid, measured occupancy,
    # and the per-dtype roofline ceiling (decode/engine.py)
    for key in ("engine_fixed_tokens_per_sec", "engine_f32_tokens_per_sec",
                "engine_bf16_tokens_per_sec",
                "engine_int8_tokens_per_sec"):
        assert isinstance(payload[key], float) and payload[key] > 0, key
    assert 0.0 < payload["engine_occupancy"] <= 1.0
    rkv = payload["roofline_by_kv_dtype"]
    assert rkv["int8"] >= rkv["bf16"] >= rkv["f32"] > 0
    # r10 pressure row: serving stays live through a half-size pool
    # with preemption armed (decode/engine.py ServePolicy)
    assert isinstance(payload["engine_pressure_tokens_per_sec"], float)
    assert payload["engine_pressure_tokens_per_sec"] > 0
    assert isinstance(payload["engine_pressure_preemptions"], int)
    # storage bytes halve/quarter exactly
    assert payload["kv_bytes_per_token_bf16"] * 2 == \
        payload["kv_bytes_per_token_f32"]
    assert payload["kv_bytes_per_token_int8"] * 4 == \
        payload["kv_bytes_per_token_f32"]
    # r11 pool-telemetry row (schema-v5 decode internals): a clean
    # drain returns every allocated block
    pool = payload["engine_pool_telemetry"]
    assert pool["block_allocs"] == pool["block_frees"] > 0
    assert pool["free_blocks_low_water"] >= 0
    # r13 prefix-cache rows (byte-identity vs the unshared engine is
    # asserted INSIDE the bench): the shared-prompt wave hits the radix
    # cache, skips prefill work, and fits more sequences per pool
    assert payload["engine_prefix_cache_tokens_per_sec"] > 0
    assert payload["engine_prefix_cache_hit_rate"] > 0
    assert payload["engine_prefix_cache_tokens_saved"] > 0
    assert payload["engine_prefix_cache_prefill_dispatches"] < \
        payload["engine_prefix_cache_prefill_dispatches_unshared"]
    assert payload["engine_prefix_cache_cow_copies"] == 0
    assert payload["engine_prefix_cache_capacity_gain"] > 1.0
    # r14 fleet rows (decode/fleet.py; byte-identity across N and the
    # >= 1.8x N=2 scaling are asserted INSIDE the bench — an error
    # string here means a contract violation, not noise)
    rel = payload["fleet_scaling_rel"]
    assert rel["1"] == 1.0 and rel["2"] >= 1.8 and rel["3"] > rel["2"]
    agg = payload["fleet_tokens_per_round"]
    assert all(isinstance(agg[k], float) and agg[k] > 0
               for k in ("1", "2", "3"))
    inter = payload["fleet_prefill_interference"]
    assert inter["colocated_p90_ms"] > 0
    assert inter["disaggregated_p90_ms"] > 0
    assert isinstance(inter["ratio"], float)
    assert isinstance(payload["fleet_handoffs"], int)
    assert payload["fleet_handoffs"] > 0
    # cross-engine prefix affinity: sharers were routed BY prefix and
    # the fleet paid measurably fewer prefill dispatches than the
    # unshared fleet
    assert payload["fleet_prefix_hit_rate"] > 0
    assert payload["fleet_prefix_routed"] > 0
    assert payload["fleet_prefix_prefill_dispatches"] < \
        payload["fleet_prefix_prefill_dispatches_unshared"]
    # r15 handoff-transport rows (ROADMAP item 1's bench criterion):
    # blocks shipped per second, wire bytes at the storage dtype, and
    # the migration-stall p90 by the CPU wall-clock proxy — measured
    # around export_sequence/import_sequence on every live move
    assert payload["fleet_handoff_blocks_per_sec"] > 0
    assert payload["fleet_handoff_bytes"] > 0
    assert payload["fleet_handoff_stall_p90_ms"] > 0
    # r16 wire-transport rows (runtime/wire.py through the router:
    # serialize + fsync'd publish + CRC verify + implant per live
    # move; byte-identity vs the in-process lane asserted INSIDE the
    # bench, zero rejections required for the row to price anything)
    assert payload["fleet_handoff_wire_blocks_per_sec"] > 0
    assert payload["fleet_handoff_wire_bytes"] > 0
    assert payload["fleet_handoff_wire_stall_p90_ms"] > 0
    assert payload["fleet_handoff_wire_vs_inproc"] > 0
    # r18 fleet ops rows (the trace spine + live ops plane): the
    # tracing-on/off bound is ASSERTED inside the bench (>= 0.95 on
    # median round wall, identical compile counts — an error string
    # here means the overhead discipline broke, not noise), and the
    # process-transport RPC rows price the socket per op off the
    # worker-side handle durations piggybacked on every response
    assert payload["fleet_tracing_tokens_ratio"] >= 0.95
    assert payload["fleet_tracing_round_ms"]["off_median"] > 0
    assert payload["fleet_rpc_overhead_p50_ms"] > 0
    assert payload["fleet_rpc_overhead_p99_ms"] >= \
        payload["fleet_rpc_overhead_p50_ms"]
    assert payload["fleet_rpc_heartbeat_rtt_p50_ms"] > 0
    assert payload["fleet_rpc_heartbeat_rtt_p99_ms"] >= \
        payload["fleet_rpc_heartbeat_rtt_p50_ms"]
    per_eng = payload["fleet_rpc_per_engine"]
    assert set(per_eng) == {"e0", "e1"}
    for st in per_eng.values():
        assert st["ops"].get("step", {}).get("n", 0) >= 1
        assert "overhead_p50_ms" in st["ops"]["step"]
        assert st["heartbeats"] >= 1
    # r19 workload rows (runtime/workload.py + the replay driver):
    # goodput under a STATED, replayable trace — byte-identity across
    # two replays and across colocated/disaggregated lanes is asserted
    # INSIDE the bench, so an error string here is a broken contract
    wg = payload["workload_goodput"]
    assert wg["slo"] == "0.5:0.05"
    assert wg["trace_bursty"].startswith("tr")
    assert wg["trace_bursty"] != wg["trace_uniform"]
    for lane in ("bursty", "uniform"):
        att = wg[lane]["attainment"]
        assert isinstance(att, float) and 0.0 <= att <= 1.0, (lane, wg)
        assert wg[lane]["completed"] > 0
    wd = payload["workload_disagg"]
    assert wd["trace"] == wg["trace_bursty"]
    for lane in ("colocated", "disaggregated"):
        assert isinstance(wd[lane]["attainment"], float), (lane, wd)
    # the two lane dicts for the SAME trace through the SAME colocated
    # fleet are one measurement, reported once each
    assert wd["colocated"] == wg["bursty"]
    # r23 kv_spill rows (byte-identity vs the big-pool oracle, the
    # >= 2x capacity floor, and restore-beats-reprefill are asserted
    # INSIDE the bench — an error string here means a contract
    # violation): session churn spilled and restored, restores saved
    # re-prefill dispatches, and the sub-block row shared a half block
    assert payload["kv_spill_tokens_per_sec"] > 0
    assert payload["kv_spill_restores"] > 0
    assert payload["kv_spill_restore_tokens_saved"] > 0
    assert payload["kv_spill_spilled_blocks"] >= \
        payload["kv_spill_restores"]
    assert payload["kv_spill_capacity_gain"] >= 2.0
    assert payload["kv_spill_prefill_dispatches"] < \
        payload["kv_spill_prefill_dispatches_no_spill"]
    assert payload["kv_spill_restore_stall_s"] >= 0
    assert payload["kv_spill_partial_hits"] > 0
    assert payload["kv_spill_partial_tokens_saved"] > 0


def _run_trend(root):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "bench_trend.py"), root],
        capture_output=True, text=True, cwd=REPO,
        timeout=load_scaled_timeout(60))


def test_bench_trend_validates_committed_artifacts():
    """The repo's own BENCH_*/SCALING_* round artifacts keep their row
    contracts: scripts/bench_trend.py exits 0 and prints one trend row
    per artifact (the bench-trajectory story stays parseable)."""
    r = _run_trend(REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    n_bench = len([f for f in os.listdir(REPO)
                   if f.startswith("BENCH_") and f.endswith(".json")])
    assert f"{n_bench} BENCH" in r.stdout, r.stdout
    assert "steps/s" in r.stdout


def test_bench_trend_rejects_schema_drift(tmp_path):
    """rc 2 on drift: a payload missing its headline key, a
    non-numeric value, an unparseable file, a wrapper missing contract
    keys, or a scaling file without rows — each named on stderr. A
    recorded outage wrapper (parsed null) is honest data, not drift."""
    root = str(tmp_path)

    def write(name, doc):
        with open(os.path.join(root, name), "w") as f:
            if isinstance(doc, str):
                f.write(doc)
            else:
                json.dump(doc, f)

    # a valid wrapper + a valid bare payload + a recorded outage: rc 0
    write("BENCH_r01.json", {"n": 1, "cmd": "x", "rc": 0, "tail": "",
                             "parsed": {"metric": "m", "value": 1.5,
                                        "unit": "steps/s"}})
    write("BENCH_r02_local.json", {"metric": "m", "value": 2.0,
                                   "unit": "steps/s"})
    write("BENCH_r03.json", {"n": 1, "cmd": "x", "rc": 1, "tail": "",
                             "parsed": None})
    write("SCALING_r01.json", {"rows": [{"scenario": "s", "chips": 8}],
                               "summary": "aot", "ok": True})
    r = _run_trend(root)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "outage" in r.stdout

    # missing headline key -> rc 2 naming the file and the key
    write("BENCH_r04.json", {"n": 1, "cmd": "x", "rc": 0, "tail": "",
                             "parsed": {"metric": "m",
                                        "unit": "steps/s"}})
    r = _run_trend(root)
    assert r.returncode == 2
    assert "BENCH_r04.json" in r.stderr and "value" in r.stderr
    os.remove(os.path.join(root, "BENCH_r04.json"))

    # non-numeric headline value -> rc 2
    write("BENCH_r05.json", {"metric": "m", "value": "fast",
                             "unit": "steps/s"})
    r = _run_trend(root)
    assert r.returncode == 2 and "not a number" in r.stderr
    os.remove(os.path.join(root, "BENCH_r05.json"))

    # unparseable JSON -> rc 2
    write("BENCH_r06.json", "{torn")
    r = _run_trend(root)
    assert r.returncode == 2 and "unparseable" in r.stderr
    os.remove(os.path.join(root, "BENCH_r06.json"))

    # scaling row missing its contract keys -> rc 2
    write("SCALING_r02.json", {"rows": [{"chips": 8}],
                               "summary": "aot", "ok": True})
    r = _run_trend(root)
    assert r.returncode == 2 and "scenario" in r.stderr
    os.remove(os.path.join(root, "SCALING_r02.json"))

    # r19 DECODE workload rows: a lane without a numeric attainment
    # is drift; an "error:" string lane-set is a recorded outage
    write("DECODE_r02.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "workload_goodput": {"slo": "0.5:0.05",
                             "bursty": {"attainment": 0.5},
                             "uniform": {"attainment": "high"}}})
    r = _run_trend(root)
    assert r.returncode == 2
    assert "DECODE_r02.json" in r.stderr and "uniform" in r.stderr
    write("DECODE_r02.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "workload_goodput": "error: RuntimeError: lane died"})
    r = _run_trend(root)
    assert r.returncode == 0, r.stdout + r.stderr
    os.remove(os.path.join(root, "DECODE_r02.json"))

    # r21 DECODE watch rows: a non-numeric reaction is drift; the
    # replay-identity row surviving with any verdict but "identical"
    # is drift (the bench raises rather than emit it); an "error:"
    # string is a recorded outage
    write("DECODE_r03.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "watch_reaction": {"kill_round": 4, "fired_round": 11,
                           "reaction_rounds": "fast", "fired": 2,
                           "resolved": 2},
        "watch_replay_identity": {"alert_history": "identical",
                                  "alert_records": 4}})
    r = _run_trend(root)
    assert r.returncode == 2
    assert "DECODE_r03.json" in r.stderr \
        and "reaction_rounds" in r.stderr
    write("DECODE_r03.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "watch_reaction": {"kill_round": 4, "fired_round": 11,
                           "reaction_rounds": 7, "fired": 2,
                           "resolved": 2},
        "watch_replay_identity": {"alert_history": "token-divergence",
                                  "alert_records": 4}})
    r = _run_trend(root)
    assert r.returncode == 2 and "identical" in r.stderr
    write("DECODE_r03.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "watch_reaction": "error: RuntimeError: lane died",
        "watch_replay_identity": "error: RuntimeError: lane died"})
    r = _run_trend(root)
    assert r.returncode == 0, r.stdout + r.stderr
    os.remove(os.path.join(root, "DECODE_r03.json"))

    # r22 DECODE fleet_tcp rows: one bench function emits the set, so
    # a numeric overhead headline without its stall sibling is drift,
    # a non-numeric stall lane is drift, and a complete set passes;
    # an "error:" string is a recorded outage
    write("DECODE_r04x.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "fleet_tcp_rpc_overhead_p50_ms": 0.4,
        "fleet_tcp_rpc_overhead_p99_ms": 1.2,
        "fleet_tcp_rpc_vs_unix": {"unix_p50_ms": 0.3,
                                  "unix_p99_ms": 0.9,
                                  "tcp_over_unix_p50": 1.33}})
    r = _run_trend(root)
    assert r.returncode == 2
    assert "DECODE_r04x.json" in r.stderr \
        and "fleet_tcp_handoff_stall_p90_ms" in r.stderr
    write("DECODE_r04x.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "fleet_tcp_rpc_overhead_p50_ms": 0.4,
        "fleet_tcp_rpc_overhead_p99_ms": 1.2,
        "fleet_tcp_rpc_vs_unix": {"unix_p50_ms": 0.3,
                                  "unix_p99_ms": 0.9,
                                  "tcp_over_unix_p50": 1.33},
        "fleet_tcp_handoff_stall_p90_ms": {"sync": 12.5,
                                           "async": "fast"}})
    r = _run_trend(root)
    assert r.returncode == 2 and "async" in r.stderr
    write("DECODE_r04x.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "fleet_tcp_rpc_overhead_p50_ms": 0.4,
        "fleet_tcp_rpc_overhead_p99_ms": 1.2,
        "fleet_tcp_rpc_vs_unix": {"unix_p50_ms": 0.3,
                                  "unix_p99_ms": 0.9,
                                  "tcp_over_unix_p50": 1.33},
        "fleet_tcp_handoff_stall_p90_ms": {"sync": 12.5,
                                           "async": 1.8}})
    r = _run_trend(root)
    assert r.returncode == 0, r.stdout + r.stderr
    write("DECODE_r04x.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "fleet_tcp_rpc_overhead_p50_ms":
            "error: RuntimeError: lane died"})
    r = _run_trend(root)
    assert r.returncode == 0, r.stdout + r.stderr
    os.remove(os.path.join(root, "DECODE_r04x.json"))

    # r23 DECODE kv_spill rows: one bench function emits the set, so a
    # numeric headline without its siblings is drift, a capacity gain
    # below the 2x acceptance floor is drift (a quietly-regressed
    # artifact must not validate), zero restores is drift, a complete
    # set passes, and an "error:" string is a recorded outage
    kv_ok = {"kv_spill_vs_no_spill": 1.1,
             "kv_spill_capacity_gain": 3.5, "kv_spill_restores": 6,
             "kv_spill_restore_tokens_saved": 96,
             "kv_spill_restore_stall_s": 0.02,
             "kv_spill_spilled_blocks": 8,
             "kv_spill_prefill_dispatches": 10,
             "kv_spill_prefill_dispatches_no_spill": 24,
             "kv_spill_partial_hits": 3,
             "kv_spill_partial_tokens_saved": 18}
    write("DECODE_r05x.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "kv_spill_tokens_per_sec": 50.0,
        "kv_spill_vs_no_spill": 1.1})
    r = _run_trend(root)
    assert r.returncode == 2
    assert "DECODE_r05x.json" in r.stderr \
        and "kv_spill_capacity_gain" in r.stderr
    write("DECODE_r05x.json", dict(
        {"metric": "m", "value": 1.0, "unit": "tokens/s",
         "kv_spill_tokens_per_sec": 50.0}, **dict(
            kv_ok, kv_spill_capacity_gain=1.4)))
    r = _run_trend(root)
    assert r.returncode == 2 and "2x acceptance floor" in r.stderr
    write("DECODE_r05x.json", dict(
        {"metric": "m", "value": 1.0, "unit": "tokens/s",
         "kv_spill_tokens_per_sec": 50.0}, **dict(
            kv_ok, kv_spill_restores=0)))
    r = _run_trend(root)
    assert r.returncode == 2 and "kv_spill_restores" in r.stderr
    write("DECODE_r05x.json", dict(
        {"metric": "m", "value": 1.0, "unit": "tokens/s",
         "kv_spill_tokens_per_sec": 50.0}, **kv_ok))
    r = _run_trend(root)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "kv_spill_capacity_gain" in r.stdout
    write("DECODE_r05x.json", {
        "metric": "m", "value": 1.0, "unit": "tokens/s",
        "kv_spill_tokens_per_sec":
            "error: RuntimeError: lane died"})
    r = _run_trend(root)
    assert r.returncode == 0, r.stdout + r.stderr
    os.remove(os.path.join(root, "DECODE_r05x.json"))

    # a missing artifact directory is rc 2, not a silent pass
    r = _run_trend(os.path.join(root, "nope"))
    assert r.returncode == 2


@pytest.mark.slow
def test_bench_decode_tp_only_probe():
    """The DECODE_TP_ONLY mode the scaling sweep spawns: only the tp
    path runs, at the forced mesh size."""
    payload = _run("bench_decode.py", {
        "BENCH_D": "64", "BENCH_LAYERS": "2", "BENCH_HEADS": "4",
        "BENCH_VOCAB": "256", "BENCH_BATCH": "2", "BENCH_PROMPT": "4",
        "BENCH_NEW": "8", "BENCH_REPS": "1", "DECODE_TP_ONLY": "2",
        "DECODE_SCALING": "0",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert isinstance(payload["tp_tokens_per_sec"], float)
    assert payload["tp_mesh"] == 2
    assert "lm_tokens_per_sec" not in payload


@pytest.mark.slow
def test_bench_memdemo_aot_inprocess():
    """The memory-capability verdict (FSDP fits / DDP RESOURCE_EXHAUSTED
    on the v5e-8 AOT compiler) — run IN-PROCESS because libtpu's AOT
    lockfile is per-process (same reason the scaling CI test is
    in-process)."""
    import sys
    sys.path.insert(0, REPO)
    import bench_memdemo
    payload = {}
    try:
        bench_memdemo._aot_verdict(payload)
    except Exception as e:  # noqa: BLE001 — only missing AOT support skips
        pytest.skip(f"no TPU AOT support: {e}")
    assert payload["fsdp_fits"], payload
    assert payload["ddp_aot"] == "RESOURCE_EXHAUSTED", payload
    assert payload["ddp_used_gb"] > payload["ddp_budget_gb"], payload


@pytest.mark.slow
def test_bench_trace_contract(tmp_path):
    """The overlap-trace harness records comm AND compute spans with a
    positive measured overlap on the fake 8-device mesh."""
    payload = _run("bench_trace.py", {
        "TRACE_D": "64", "TRACE_LAYERS": "2", "TRACE_TOKENS": "128",
        "TRACE_STEPS": "4",
        "TRACE_ARTIFACT_DIR": str(tmp_path / "tr"),
        "TRACE_ARTIFACT": str(tmp_path / "tr" / "TRACE.json")})
    assert payload["comm_spans"] > 0 and payload["compute_spans"] > 0
    assert payload["value"] > 0  # measured overlap microseconds
    assert os.path.exists(str(tmp_path / "tr" / "TRACE.json"))
