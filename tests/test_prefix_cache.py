"""Shared-prefix KV reuse (ISSUE 9): the radix prefix cache
(``decode/prefix.py``), refcounted copy-on-write block tables, and
their engine composition (``decode/engine.py``, DESIGN.md section 19).

The acceptance spine:

- **Dispatch-count-provable reuse**: N staggered requests sharing a
  k-block prompt run ~1 prefill pass over the shared prefix, not N
  (``prefill_dispatches`` pins it), with zero new compiles in steady
  state — the radix tree is host-side data, never a compiled shape.
- **Bit-identity everywhere**: prefix-cached output == unshared engine
  == ``models.lm.generate`` token for token at f32/bf16/int8 — a hit
  block's bytes are a pure function of the token prefix (full blocks
  only; chunk boundaries inside a full block are position-determined,
  so even the int8 requant history matches), and the CoW barrier keeps
  every write out of shared blocks.
- **Capacity is the product**: sharers reserve k + N*tail physical
  blocks instead of N*(k + tail) — the "effective sequences"
  multiplier the admission test measures directly.
"""

import os

import jax
import numpy as np
import pytest

from distributed_llm_code_samples_tpu.decode import (DecodeEngine,
                                                     EngineConfig,
                                                     PrefixCache,
                                                     ServePolicy,
                                                     load_snapshot,
                                                     restore_engine_state,
                                                     supervise_decode,
                                                     write_snapshot)
from distributed_llm_code_samples_tpu.models import generate, init_lm
from distributed_llm_code_samples_tpu.runtime.chaos import FaultPlan

V, D, L, H = 64, 32, 2, 4
BASE = dict(block_size=8, n_blocks=33, max_slots=3, max_blocks_per_seq=6,
            prefill_chunk=8)


@pytest.fixture(scope="module")
def lm_params():
    return init_lm(jax.random.PRNGKey(0), V, D, L, max_seq_len=64)


@pytest.fixture(scope="module")
def shared_prompts():
    """Three prompts sharing a 19-token prefix (2 full 8-blocks + 3
    tail tokens) and diverging on the final token — the canonical
    system-prompt workload."""
    rng = np.random.default_rng(7)
    head = rng.integers(0, V, size=19).tolist()
    return [head + [t] for t in (1, 2, 3)]


def _staggered(params, cfg, prompts, max_new=6, steps_between=3,
               uid0=0, mesh=None, engine=None, log_every=0):
    """Submit each prompt ``steps_between`` engine steps after the
    previous one — enough for the earlier sharer's full prompt blocks
    to be prefilled and inserted, so later admissions exercise the
    radix walk (concurrent admissions exercise late dedup instead)."""
    eng = engine or DecodeEngine(params, H, cfg, mesh=mesh)
    for i, p in enumerate(prompts):
        eng.submit(p, max_new, uid=uid0 + i)
        for _ in range(steps_between):
            eng.step()
    return eng, eng.run(log_every=log_every)


# ---------------------------------------------------------------------------
# radix tree units (pure host code, no compiled programs)


def test_match_cap_leaves_one_token_to_prefill():
    pc = PrefixCache(8)
    # every full block EXCEPT the one holding the final token: the
    # first pick must always come from a prefill program
    assert [pc.match_cap(n) for n in (1, 8, 9, 16, 17, 24)] == \
        [0, 0, 1, 1, 2, 2]


def test_insert_match_refcounts_and_dedup():
    pc = PrefixCache(4)
    prompt = list(range(12))
    a = pc.insert(prompt, 0, block=5, step=1)
    b = pc.insert(prompt, 1, block=6, step=1)
    assert a.block == 5 and b.block == 6 and b.parent is a
    assert len(pc) == 2 and pc.evictable_blocks() == 2
    # the walk returns the longest cached full-block path, capped
    assert [n.block for n in pc.match(prompt)] == [5, 6]
    assert [n.block for n in pc.match(prompt[:9])] == [5, 6]
    assert [n.block for n in pc.match(prompt[:8])] == [5]
    assert pc.match(list(range(1, 13))) == []          # diverges at 0
    # locking: refs are monotone non-increasing root-to-leaf
    hits = pc.match(prompt)
    pc.lock(hits, step=2)
    assert (a.refs, b.refs) == (1, 1) and pc.evictable_blocks() == 0
    assert pc.shared_blocks() == 0
    pc.lock(pc.match(prompt), step=3)
    assert (a.refs, b.refs) == (2, 2) and pc.shared_blocks() == 2
    # inserting an already-cached path dedups onto the existing node
    assert pc.insert(prompt, 0, block=9, step=4) is a
    pc.release(b, 5)
    pc.release(b, 5)
    with pytest.raises(RuntimeError, match="unlocked"):
        pc.release(b, 5)
    # a partial block refuses insertion (its remaining rows would be
    # decode writes — content no longer a function of the prompt)
    with pytest.raises(ValueError, match="not full"):
        pc.insert(prompt[:10], 2, block=7, step=6)


def test_evict_lru_is_leaf_only_and_lru_ordered():
    pc = PrefixCache(2)
    p1 = [0, 1, 2, 3]                   # path A: blocks 5 -> 6
    p2 = [0, 1, 9, 9]                   # path B: blocks 5 -> 7
    a = pc.insert(p1, 0, 5, step=1)
    b = pc.insert(p1, 1, 6, step=2)
    c = pc.insert(p2, 1, 7, step=9)     # touched later than b
    assert a is c.parent
    # leaf-only: the shared root block 5 survives while children exist;
    # LRU: the older leaf (6) goes before the newer (7)
    assert pc.evict_lru(1, step=10) == [6]
    assert pc.evict_lru(10, step=11) == [7, 5]
    assert len(pc) == 0 and pc.match(p1) == []
    # a live node refuses detach (the monotone-refs safety rail)
    n = pc.insert(p1, 0, 5, step=12)
    pc.lock([n], step=12)
    assert pc.evict_lru(1, step=13) == []
    with pytest.raises(RuntimeError, match="live"):
        pc.detach_subtree(n)
    assert b.parent is None             # detached nodes are orphaned


def test_poisoned_nodes_excluded_from_match_and_insert():
    pc = PrefixCache(4)
    prompt = list(range(8))
    a = pc.insert(prompt, 0, 3, step=1)
    b = pc.insert(prompt, 1, 4, step=1)
    a.poisoned = True
    assert pc.match(prompt + [9]) == []     # no new sharer inherits it
    # an insert under a poisoned parent stays private (returns None),
    # as does a dedup onto a poisoned twin
    assert pc.insert(prompt, 1, 6, step=2) is None
    assert pc.insert(prompt, 0, 6, step=2) is None
    # detach at refs 0 reclaims the poisoned path and its descendants
    assert sorted(pc.detach_subtree(a)) == [3, 4]
    assert len(pc) == 0 and b.refs == 0


def test_snapshot_is_preorder_with_parent_links():
    pc = PrefixCache(2)
    pc.lock([pc.insert([0, 1, 2, 3], 0, 5, step=1)], step=1)
    pc.insert([0, 1, 2, 3], 1, 6, step=2)
    snap = pc.snapshot()
    assert [(n["block"], n["parent"], n["refs"]) for n in snap] == \
        [(5, None, 1), (6, 0, 0)]
    assert snap[0]["tokens"] == [0, 1] and snap[1]["tokens"] == [2, 3]
    assert all(n["poisoned"] is False for n in snap)


# ---------------------------------------------------------------------------
# the tentpole: dispatch-count-provable reuse, bit-identical output


def test_staggered_sharers_run_one_prefill_pass(lm_params,
                                                shared_prompts):
    """Acceptance: 3 staggered requests sharing a 2-block prompt run
    the shared prefix's prefill ONCE (5 dispatches total: 3 chunks for
    the first + one 4-token tail each, vs 9 unshared), stay
    byte-identical to the unshared engine AND the lockstep oracle, and
    compile nothing new once the buckets are warm."""
    off, out_off = _staggered(lm_params, EngineConfig(
        **BASE, prefix_cache=False), shared_prompts)
    on, out_on = _staggered(lm_params, EngineConfig(**BASE),
                            shared_prompts)
    assert out_on == out_off
    for i, p in enumerate(shared_prompts):
        ref = np.asarray(generate(lm_params, jax.numpy.asarray([p]), 6,
                                  H))[0].tolist()
        assert out_on[i] == ref
    assert off.prefill_dispatches == 9 and on.prefill_dispatches == 5
    assert on.prefix_hit_blocks == 4            # 2 blocks x 2 sharers
    assert on.prefill_tokens_saved == 32
    assert on.cow_copies == 0                   # the barrier invariant
    assert off.prefix_hit_blocks == 0 and off.prefix is None
    # steady state: a second wave of sharers hits the (now refs-0)
    # cached blocks with ZERO new compiles — the tree is data
    warm = on.compile_count
    _, out2 = _staggered(lm_params, None, shared_prompts, uid0=10,
                         engine=on)
    assert on.compile_count == warm
    assert on.prefill_dispatches == 5 + 3       # one tail chunk each
    assert on.prefix_hit_blocks == 4 + 6        # wave 2: ALL 3 hit
    assert all(out2[10 + i] == out_off[i] for i in range(3))


@pytest.mark.parametrize("kv_dtype", ["f32", "bf16", "int8"])
def test_prefix_identity_across_kv_dtypes(lm_params, shared_prompts,
                                          kv_dtype):
    """Sharing changes which physical block a table names, never a byte
    the gather returns: prefix-cached == unshared at every storage
    dtype (int8 is the hard case — the requant history of a hit block
    must equal the one the admitting sequence's own prefill would have
    written)."""
    cfg_on = EngineConfig(**BASE, kv_dtype=kv_dtype)
    cfg_off = EngineConfig(**BASE, kv_dtype=kv_dtype,
                           prefix_cache=False)
    on, out_on = _staggered(lm_params, cfg_on, shared_prompts)
    _, out_off = _staggered(lm_params, cfg_off, shared_prompts)
    assert out_on == out_off
    assert on.prefix_hit_blocks == 4 and on.cow_copies == 0


def test_prefix_identity_sampled(lm_params, shared_prompts):
    """Sampling keys fold (seed, uid, position) — never the slot or the
    physical block — so sharing cannot move a sampled pick either."""
    kw = dict(temperature=0.9, top_k=12, seed=3)
    _, out_on = _staggered(lm_params, EngineConfig(**BASE, **kw),
                           shared_prompts)
    _, out_off = _staggered(lm_params, EngineConfig(
        **BASE, prefix_cache=False, **kw), shared_prompts)
    assert out_on == out_off


def test_effective_capacity_gain(lm_params, shared_prompts):
    """The pool-capacity multiplier, measured: three 4-block sharers
    need 12 physical blocks unshared (a 9-block pool stalls the third)
    but 8 shared (2 shared + 3 x 2 private tails) — all three resident
    at once. This "effective sequences" gain is the admission currency
    of the multi-engine router (ROADMAP item 3)."""
    small = dict(BASE, n_blocks=10, max_blocks_per_seq=4)
    on = DecodeEngine(lm_params, H, EngineConfig(**small))
    off = DecodeEngine(lm_params, H, EngineConfig(**small,
                                                  prefix_cache=False))
    for eng in (on, off):
        for i, p in enumerate(shared_prompts):
            eng.submit(p, 8, uid=i)
            eng.step()
            eng.step()
    assert on.active == 3 and not on.waiting        # all resident
    assert off.active == 2 and len(off.waiting) == 1  # pool-blocked
    assert on.prefix.shared_blocks() == 2
    out_on, out_off = on.run(), off.run()
    assert out_on == out_off                        # identity anyway


def test_lru_reclaim_under_pool_pressure(lm_params, shared_prompts):
    """refs-0 cached blocks convert back to free-list blocks on demand
    (LRU), so retention never starves admission: a non-sharing request
    that needs the whole pool still admits after the cache is warm."""
    small = dict(BASE, n_blocks=8, max_blocks_per_seq=5)
    eng = DecodeEngine(lm_params, H, EngineConfig(**small))
    eng.submit(shared_prompts[0], 5, uid=0)         # 3 blocks, 2 cached
    eng.run()
    assert len(eng.prefix) == 2 and eng.prefix.evictable_blocks() == 2
    assert len(eng.free_blocks) == 5
    rng = np.random.default_rng(11)
    eng.submit(rng.integers(0, V, size=33).tolist(), 7, uid=1)  # 5 blks
    eng.step()
    # 5 > 5 free? no — exactly fits; force the reclaim with a second
    eng.submit(rng.integers(32, V, size=17).tolist(), 8, uid=2)  # 3 blks
    done = eng.run()
    assert sorted(done) == [0, 1, 2]
    assert len(eng.prefix.nodes()) == len(eng.prefix)  # tree coherent


# ---------------------------------------------------------------------------
# copy-on-write: the enforced invariant


def test_cow_privatizes_without_touching_the_sharer(lm_params,
                                                    shared_prompts):
    """Force the barrier by hand (no scheduler write ever aims at a
    shared block, so the trigger must be synthetic): privatizing a
    shared block copies its bytes bit-identically, remaps exactly one
    table, drops exactly one ref — and the other sharer's output is
    untouched, because its bytes are."""
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE))
    for i, p in enumerate(shared_prompts[:2]):
        eng.submit(p, 8, uid=i)
        for _ in range(3):
            eng.step()
    slot1 = next(i for i, s in enumerate(eng.slots)
                 if s is not None and s.uid == 1)
    seq1 = eng.slots[slot1]
    node = seq1.nodes[0]
    src = node.block
    before = np.asarray(eng.pool.k[:, src]).copy()
    assert node.refs == 2
    eng._cow_private(slot1, 0, 0)
    assert eng.cow_copies == 1 and seq1.nodes[0] is None
    dst = seq1.blocks[0]
    assert dst != src and eng.tables[slot1][0] == dst
    assert node.refs == 1                       # the sharer's ref only
    np.testing.assert_array_equal(np.asarray(eng.pool.k[:, dst]),
                                  before)       # bit-identical copy
    np.testing.assert_array_equal(np.asarray(eng.pool.k[:, src]),
                                  before)       # sharer untouched
    out = eng.run()
    _, clean = _staggered(lm_params, EngineConfig(**BASE),
                          shared_prompts[:2], max_new=8)
    assert out == clean                         # CoW is invisible


def test_cow_zero_across_mixed_traffic(lm_params, shared_prompts):
    """The write-barrier invariant under everything at once: sharing +
    speculation + int8 + a second wave never triggers a single CoW —
    every write lands at or past the prefill frontier by construction,
    and the counter pins it."""
    cfg = EngineConfig(**BASE, kv_dtype="int8", speculate=3)
    eng, out = _staggered(lm_params, cfg, shared_prompts)
    _, out2 = _staggered(lm_params, None, shared_prompts, uid0=10,
                         engine=eng)
    assert eng.cow_copies == 0 and eng.prefix_hit_blocks == 10
    _, out_off = _staggered(lm_params, EngineConfig(
        **BASE, kv_dtype="int8", speculate=3, prefix_cache=False),
        shared_prompts)
    assert out == out_off
    assert {u - 10: t for u, t in out2.items() if u >= 10} == out_off


def test_int8_scales_frozen_while_shared(lm_params, shared_prompts):
    """An int8 block's per-block scales freeze at share time: requant
    only ever touches write-window blocks, and no write window covers
    a fully-prefilled prompt block — so two sharers decoding to
    completion never move the shared blocks' scales (a requant under a
    sharer's foot would silently re-round the other's prefix)."""
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE,
                                                  kv_dtype="int8"))
    for i, p in enumerate(shared_prompts):
        eng.submit(p, 8, uid=i)
        for _ in range(3):
            eng.step()
    blocks = [n.block for n in eng.prefix.nodes()]
    assert len(blocks) == 2
    k_sc = np.asarray(eng.pool.k_scale[:, blocks]).copy()
    v_sc = np.asarray(eng.pool.v_scale[:, blocks]).copy()
    vals = np.asarray(eng.pool.k[:, blocks]).copy()
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng.pool.k_scale[:, blocks]), k_sc)
    np.testing.assert_array_equal(
        np.asarray(eng.pool.v_scale[:, blocks]), v_sc)
    np.testing.assert_array_equal(np.asarray(eng.pool.k[:, blocks]),
                                  vals)


# ---------------------------------------------------------------------------
# telemetry v7 + TP composition + CLI flag


def test_decode_record_v7_prefix_keys(lm_params, shared_prompts,
                                      tmp_path):
    from distributed_llm_code_samples_tpu.runtime.telemetry import (
        METRICS_FILENAME, TelemetryWriter, read_metrics,
        validate_record)
    mdir = str(tmp_path / "m")
    with TelemetryWriter(mdir) as w:
        eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                           metrics=w)
        _staggered(lm_params, None, shared_prompts, engine=eng,
                   max_new=12, log_every=1)
    records, problems = read_metrics(os.path.join(mdir,
                                                  METRICS_FILENAME))
    assert problems == []
    decs = [r for r in records if r["kind"] == "decode"]
    assert decs
    for r in decs:
        ok, reason = validate_record(r)
        assert ok, reason
    last = decs[-1]
    assert last["prefix_hit_blocks"] == 4
    assert last["prefill_tokens_saved"] == 32
    assert last["cow_copies"] == 0
    assert last["shared_blocks"] == 0           # drained: refs all 0
    # while the sharers overlapped, some record saw both shared blocks
    assert any(r["shared_blocks"] == 2 for r in decs)
    # the first sharer's 2-block walk misses (cold tree), the other
    # two hit: 4 / 6
    assert last["prefix_hit_rate"] == round(4 / 6, 4)


def test_tp_sharing_token_identical(lm_params, shared_prompts,
                                    mesh_model4):
    """--tp composes with sharing: the radix tree is one host-side
    structure over a head-sharded pool, so every shard's table names
    the same shared blocks and the picks stay identical to the
    single-device prefix-cached engine."""
    tp, out_tp = _staggered(lm_params, EngineConfig(**BASE),
                            shared_prompts, max_new=4, mesh=mesh_model4)
    sd, out_sd = _staggered(lm_params, EngineConfig(**BASE),
                            shared_prompts, max_new=4)
    assert out_tp == out_sd
    assert tp.prefix_hit_blocks == sd.prefix_hit_blocks == 4


# ---------------------------------------------------------------------------
# reliability composition: quarantine, chaos corruption, preemption,
# snapshot v4 kill -> resume


def test_shared_block_quarantine_survivor_bit_identical(tmp_path,
                                                        lm_params,
                                                        shared_prompts):
    """The scrub-vs-decref contract, end to end: poison the logits of a
    sharer mid-decode — its quarantine DECREFS the shared prefix blocks
    (the survivors' bytes) instead of scrubbing them, and every
    survivor sharing the poisoned uid's prefix finishes bit-identical
    to a run that never admitted it. The retry then heals on the still-
    cached prefix."""
    cfg = EngineConfig(**BASE)
    oracle = DecodeEngine(lm_params, H, cfg)
    oracle.submit(shared_prompts[0], 8, uid=0)
    oracle.submit(shared_prompts[2], 8, uid=2)
    clean = oracle.run()
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg),
        [(p, 8) for p in shared_prompts],
        snapshot_dir=str(tmp_path / "s"),
        chaos=FaultPlan.parse("nan_logits@6:1"))
    assert set(eng.failed) == {1}
    assert eng.finished[0] == clean[0]
    assert eng.finished[2] == clean[2]
    # the shared nodes survived the quarantine (refs 2 at fault time:
    # decref, not scrub-and-detach) and drained to cached refs-0
    assert len(eng.prefix) >= 2 and eng.prefix.evictable_blocks() >= 2
    # with retry budget the poisoned sharer replays onto the cached
    # prefix and lands the clean tokens
    all_clean = _staggered(lm_params, cfg, shared_prompts, max_new=8,
                           steps_between=0)[1]
    eng2 = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg,
                             policy=ServePolicy(max_retries=1)),
        [(p, 8) for p in shared_prompts],
        snapshot_dir=str(tmp_path / "s2"),
        chaos=FaultPlan.parse("nan_logits@6:1"))
    assert eng2.failed == {}
    assert dict(eng2.finished) == all_clean


def test_corrupt_shared_block_poisons_tree_then_heals(tmp_path,
                                                      lm_params,
                                                      shared_prompts):
    """Chaos-corrupting a block the radix tree holds: the node is
    poisoned immediately (no NEW sharer may match it), current sharers
    quarantine as their dispatches flag the NaN, the LAST release
    scrubs-and-detaches the path, and the retries — re-prefilling from
    scratch on a clean pool — recover the uninterrupted run's exact
    tokens. FCFS admission hands block 1 to the first request's first
    prompt block, which is exactly the first shared node."""
    cfg = EngineConfig(**BASE)
    clean = _staggered(lm_params, cfg, shared_prompts, max_new=8,
                       steps_between=0)[1]
    eng = supervise_decode(
        lambda: DecodeEngine(lm_params, H, cfg,
                             policy=ServePolicy(max_retries=1)),
        [(p, 8) for p in shared_prompts],
        snapshot_dir=str(tmp_path / "s"),
        chaos=FaultPlan.parse("corrupt_block@6:1"))
    assert eng.failed == {}
    assert dict(eng.finished) == clean
    assert eng.quarantined >= 1
    # the poisoned path was detached at last release: whatever the
    # retries re-cached, no cached node names a corrupted block
    assert not eng._corrupted
    assert all(not n.poisoned for n in eng.prefix.nodes())


def test_preemption_decrefs_shared_blocks(lm_params, shared_prompts):
    """Pool-pressure preemption of a sharer releases its refs (decref,
    never scrub) and the replay-resume re-walks the tree: tokens stay
    identical to the unshared engine and the share graph stays
    coherent through the churn."""
    small = dict(BASE, n_blocks=9, max_blocks_per_seq=4)
    policy = ServePolicy(preempt_after_steps=2)
    eng = DecodeEngine(lm_params, H, EngineConfig(**small),
                       policy=policy)
    for i, p in enumerate(shared_prompts):
        eng.submit(p, 8, uid=i)
        eng.step()
    out = eng.run()
    _, out_off = _staggered(
        lm_params, EngineConfig(**small, prefix_cache=False),
        shared_prompts, max_new=8, steps_between=0)
    del out_off  # pool too small to admit all three unshared —
    # the identity oracle is the roomy unshared engine instead
    _, roomy = _staggered(lm_params,
                          EngineConfig(**BASE, prefix_cache=False),
                          shared_prompts, max_new=8, steps_between=0)
    assert out == roomy
    assert eng.cow_copies == 0
    # drained: every node refs-0, tree still coherent
    assert all(n.refs == 0 for n in eng.prefix.nodes())


def test_snapshot_v4_kill_resume_rebuilds_share_graph(tmp_path,
                                                      lm_params,
                                                      shared_prompts):
    """Snapshot v4 persists the radix tree (the share-graph
    certificate) + the prefix counters; a crash-resume deliberately
    starts with an EMPTY tree (pool content died with the process) and
    REBUILDS sharing through replay: the first replayed sharer
    re-prefills and re-inserts, later ones hit — outputs bit-identical
    to the uninterrupted run, counters monotonic, and the rebuilt tree
    carries the same token paths as the certificate."""
    cfg = EngineConfig(**BASE)
    _, clean = _staggered(lm_params, cfg, shared_prompts,
                          max_new=8, steps_between=0)
    eng = DecodeEngine(lm_params, H, cfg)
    for i, p in enumerate(shared_prompts[:2]):
        eng.submit(p, 8, uid=i)
        for _ in range(3):
            eng.step()
    eng.submit(shared_prompts[2], 8, uid=2)
    eng.step()                      # uid 2 admits: refs climb to 3
    sd = str(tmp_path / "snap")
    write_snapshot(eng, sd)
    snap = load_snapshot(sd)
    assert snap["version"] == 9
    tree = snap["prefix_tree"]
    # the certificate: 2 shared nodes, every live sharer holding a ref
    assert [n["refs"] for n in tree] == [3, 3]
    assert tree[0]["parent"] is None and tree[1]["parent"] == 0
    assert (tree[0]["tokens"] + tree[1]["tokens"]
            == shared_prompts[0][:16])
    assert snap["counters"]["prefill_tokens_saved"] > 0
    pre_hits = eng.prefix_hit_blocks
    # "crash": a fresh process restores — tree starts EMPTY, replay
    # rebuilds it
    eng2 = DecodeEngine(lm_params, H, cfg)
    restore_engine_state(eng2, load_snapshot(sd))
    assert len(eng2.prefix) == 0
    assert eng2.prefix_hit_blocks == pre_hits        # counters restored
    done = eng2.run()
    assert done == clean
    # all three replayed sharers re-admitted CONCURRENTLY (3 free
    # slots, empty tree -> no admission hits): the share graph
    # rebuilds through late DEDUP instead — each re-prefilled block
    # remaps onto the first replayer's cached twin — and the hit
    # counter stays exactly monotonic
    assert eng2.prefix_hit_blocks == pre_hits
    rebuilt = eng2.prefix.snapshot()
    assert ([n["tokens"] for n in rebuilt]
            == [n["tokens"] for n in tree])
    assert all(n["refs"] == 0 for n in rebuilt)      # drained
    # the rebuilt cache is HOT: a post-resume sharer hits at admission
    eng2.submit(shared_prompts[0][:19] + [9], 4, uid=7)
    out7 = eng2.run()[7]
    assert eng2.prefix_hit_blocks == pre_hits + 2
    assert out7 == np.asarray(generate(
        lm_params, jax.numpy.asarray([shared_prompts[0][:19] + [9]]),
        4, H))[0].tolist()
    # resume rejects a sharing-policy mismatch like any config drift
    with pytest.raises(ValueError, match="config"):
        restore_engine_state(
            DecodeEngine(lm_params, H,
                         EngineConfig(**BASE, prefix_cache=False)),
            load_snapshot(sd))


def test_generate_cli_prefix_cache_flag(tmp_path, capsys):
    """CLI surface: default on with payload accounting; the --no-
    variant restores the private-blocks engine; parse discipline
    rejects garbage."""
    import json as _json

    import distributed_llm_code_samples_tpu.cli as cli
    args = ["generate", "--prompts", "3,1,4,1,5,9,2,6,5,3;"
            "3,1,4,1,5,9,2,6,5,3", "--max_new", "4", "-d", "32", "-l",
            "2", "--heads", "4", "--vocab", "64", "--max_seq_len",
            "64", "--block_size", "4", "--prefill_chunk", "4",
            "--max_slots", "1"]
    assert cli.main(args) == 0
    on = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert on["prefix_cache"] is True
    # max_slots 1 serializes the two identical prompts: the second hits
    assert on["prefix_hit_blocks"] == 2 and on["cow_copies"] == 0
    assert on["prefill_tokens_saved"] == 8
    assert cli.main(args + ["--no-prefix_cache"]) == 0
    off = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert off["prefix_cache"] is False and off["prefix_hit_blocks"] == 0
    assert [s["tokens"] for s in on["sequences"]] == \
        [s["tokens"] for s in off["sequences"]]
    assert on["prefill_dispatches"] < off["prefill_dispatches"]
    # the boolean flag takes no value: argparse rejects one (rc 2)
    with pytest.raises(SystemExit) as exc:
        cli.main(args + ["--prefix_cache=maybe"])
    assert exc.value.code == 2
    capsys.readouterr()
