"""Data-layer tests: deterministic seeds-as-dataset semantics
(reference ``train_ffns.py:144-151, :182, :350-360``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_code_samples_tpu import DLOSS_DX_COEF
from distributed_llm_code_samples_tpu.data import (
    batch_from_seed, mock_data, make_seed_schedule, shard_seeds_strided)


def test_batch_deterministic():
    x1, d1 = batch_from_seed(jnp.int32(123), 8, 16)
    x2, d2 = batch_from_seed(jnp.int32(123), 8, 16)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(d1, d2)


def test_batch_differs_across_seeds():
    x1, _ = batch_from_seed(jnp.int32(1), 8, 16)
    x2, _ = batch_from_seed(jnp.int32(2), 8, 16)
    assert not np.allclose(x1, x2)


def test_batch_shapes_and_dloss_scale():
    x, dl = batch_from_seed(jnp.int32(5), 32, 8)
    assert x.shape == (32, 8) and dl.shape == (32, 8)
    # dloss_dx = 0.1 * normal — std should be ~DLOSS_DX_COEF (train_ffns.py:30)
    assert abs(float(jnp.std(dl)) - DLOSS_DX_COEF) < 0.03 * DLOSS_DX_COEF * 10


def test_batch_works_inside_jit_and_scan():
    def run(seeds):
        def body(c, s):
            x, dl = batch_from_seed(s, 4, 8)
            return c + x.sum() + dl.sum(), None
        return jax.lax.scan(body, 0.0, seeds)[0]

    seeds = jnp.arange(5, dtype=jnp.int32)
    eager = sum(float(x.sum() + dl.sum())
                for x, dl in mock_data(seeds, 4, 8))
    np.testing.assert_allclose(float(jax.jit(run)(seeds)), eager, rtol=1e-5)


def test_seed_schedule_reproducible():
    s1 = make_seed_schedule(10, random_seed=42)
    s2 = make_seed_schedule(10, random_seed=42)
    np.testing.assert_array_equal(s1, s2)
    assert s1.shape == (10,)
    assert int(s1.min()) >= 0 and int(s1.max()) < 100_000


def test_strided_shard_layout():
    # rank r's step t must consume global seed[t*n + r] (train_ffns.py:182)
    seeds = jnp.arange(12, dtype=jnp.int32)
    cols = shard_seeds_strided(seeds, 4)
    assert cols.shape == (3, 4)
    for r in range(4):
        np.testing.assert_array_equal(np.asarray(cols[:, r]),
                                      np.arange(12)[r::4])


def test_strided_shard_divisibility_error():
    with pytest.raises(ValueError):
        shard_seeds_strided(jnp.arange(10), 4)
