"""Data-layer tests: deterministic seeds-as-dataset semantics
(reference ``train_ffns.py:144-151, :182, :350-360``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_code_samples_tpu import DLOSS_DX_COEF
from distributed_llm_code_samples_tpu.data import (
    batch_from_seed, mock_data, make_seed_schedule, shard_seeds_strided)


def test_batch_deterministic():
    x1, d1 = batch_from_seed(jnp.int32(123), 8, 16)
    x2, d2 = batch_from_seed(jnp.int32(123), 8, 16)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(d1, d2)


def test_batch_differs_across_seeds():
    x1, _ = batch_from_seed(jnp.int32(1), 8, 16)
    x2, _ = batch_from_seed(jnp.int32(2), 8, 16)
    assert not np.allclose(x1, x2)


def test_batch_shapes_and_dloss_scale():
    x, dl = batch_from_seed(jnp.int32(5), 32, 8)
    assert x.shape == (32, 8) and dl.shape == (32, 8)
    # dloss_dx = 0.1 * normal — std should be ~DLOSS_DX_COEF (train_ffns.py:30)
    assert abs(float(jnp.std(dl)) - DLOSS_DX_COEF) < 0.03 * DLOSS_DX_COEF * 10


def test_batch_works_inside_jit_and_scan():
    def run(seeds):
        def body(c, s):
            x, dl = batch_from_seed(s, 4, 8)
            return c + x.sum() + dl.sum(), None
        return jax.lax.scan(body, 0.0, seeds)[0]

    seeds = jnp.arange(5, dtype=jnp.int32)
    eager = sum(float(x.sum() + dl.sum())
                for x, dl in mock_data(seeds, 4, 8))
    np.testing.assert_allclose(float(jax.jit(run)(seeds)), eager, rtol=1e-5)


def test_seed_schedule_reproducible():
    s1 = make_seed_schedule(10, random_seed=42)
    s2 = make_seed_schedule(10, random_seed=42)
    np.testing.assert_array_equal(s1, s2)
    assert s1.shape == (10,)
    assert int(s1.min()) >= 0 and int(s1.max()) < 100_000


def test_strided_shard_layout():
    # rank r's step t must consume global seed[t*n + r] (train_ffns.py:182)
    seeds = jnp.arange(12, dtype=jnp.int32)
    cols = shard_seeds_strided(seeds, 4)
    assert cols.shape == (3, 4)
    for r in range(4):
        np.testing.assert_array_equal(np.asarray(cols[:, r]),
                                      np.arange(12)[r::4])


def test_strided_shard_divisibility_error():
    with pytest.raises(ValueError):
        shard_seeds_strided(jnp.arange(10), 4)


def test_text_corpus_loads_real_bytes():
    from distributed_llm_code_samples_tpu.data import load_text_corpus
    corpus = load_text_corpus()
    assert corpus.dtype == np.uint8
    assert corpus.shape[0] > 100_000  # "a few hundred KB" of real text
    text = corpus.tobytes().decode("utf-8")
    # real English prose, not noise
    for phrase in ("License", "copyright", "distribute"):
        assert phrase in text


def test_text_batch_windows_and_determinism():
    from distributed_llm_code_samples_tpu.data import (load_text_corpus,
                                                       text_batch_from_seed)
    corpus = load_text_corpus()
    tok, tgt = text_batch_from_seed(jnp.int32(5), 4, 32)
    assert tok.shape == (4, 32) and tgt.shape == (4, 32)
    # targets are the next byte (windows are contiguous corpus slices)
    np.testing.assert_array_equal(np.asarray(tok[:, 1:]),
                                  np.asarray(tgt[:, :-1]))
    # every window is a verbatim corpus slice
    blob = corpus.tobytes()
    for row in np.asarray(tok, dtype=np.uint8):
        assert row.tobytes() in blob
    # counter-RNG contract: same seed == same batch, different seed differs
    tok2, _ = text_batch_from_seed(jnp.int32(5), 4, 32)
    np.testing.assert_array_equal(np.asarray(tok), np.asarray(tok2))
    tok3, _ = text_batch_from_seed(jnp.int32(6), 4, 32)
    assert not np.array_equal(np.asarray(tok), np.asarray(tok3))


def test_text_batch_traces_in_scan():
    # the seed may be a traced scalar: real text keeps the
    # seeds-as-dataset design (works under lax.scan like the synthetic
    # sources)
    from distributed_llm_code_samples_tpu.data import text_batch_from_seed
    import jax

    def body(c, s):
        tok, tgt = text_batch_from_seed(s, 2, 16)
        return c + tok.sum() + tgt.sum(), None

    total, _ = jax.jit(
        lambda seeds: jax.lax.scan(body, jnp.int32(0), seeds))(
            jnp.arange(3, dtype=jnp.int32))
    assert int(total) > 0


def test_real_text_training_loss_falls():
    """End to end on real bytes: a tiny LM trained through the batch_fn
    hook must beat its initial eval loss decisively (the capability
    synthetic seeds can't prove)."""
    from distributed_llm_code_samples_tpu.data import text_batch_from_seed
    from distributed_llm_code_samples_tpu.models import init_lm
    from distributed_llm_code_samples_tpu.models.lm import lm_loss
    from distributed_llm_code_samples_tpu.optim import adamw
    from distributed_llm_code_samples_tpu.parallel import train_lm_single
    import jax
    B, T, D_, H_ = 8, 32, 32, 4
    params = init_lm(jax.random.PRNGKey(0), 256, D_, 2, max_seq_len=T)
    etok, etgt = text_batch_from_seed(jnp.int32(999_983), B, T)
    loss0 = float(lm_loss(params, etok, etgt, H_))
    params, _ = train_lm_single(
        params, jnp.arange(30, dtype=jnp.int32), B * T, D_, lr=3e-3,
        seq_len=T, n_heads=H_, optimizer=adamw(weight_decay=0.01),
        return_state=True,
        batch_fn=lambda s: text_batch_from_seed(s, B, T))
    loss1 = float(lm_loss(params, etok, etgt, H_))
    assert loss1 < loss0 - 0.5, (loss0, loss1)
