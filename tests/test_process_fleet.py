"""The process-boundary fleet (decode/worker.py + decode/fleet.py,
DESIGN.md section 22): engine workers in REAL OS processes behind the
socket protocol, KV handoffs as CRC-verified wire files, and the chaos
drills a single process cannot run — SIGKILL a worker mid-stream, hang
one silent, tear a handoff file in transit — each completing every
request token-identically against the in-process oracle.

Every test here spawns worker subprocesses (jax import + engine build
per worker), so the module is ``serial``-marked and deadlines are
load-scaled. Worker counts are kept minimal; the model/config shapes
are the shared test fixtures (V=64, D=32, L=2, H=4, BASE blocks) so
every compiled program hits the persistent XLA cache.
"""

import os
import time

import jax
import numpy as np
import pytest

from conftest import load_scaled_timeout
from distributed_llm_code_samples_tpu.decode import (DecodeEngine,
                                                     EngineConfig,
                                                     FleetRouter)
from distributed_llm_code_samples_tpu.decode.worker import (
    spawn_fleet_handles)
from distributed_llm_code_samples_tpu.models import init_lm
from distributed_llm_code_samples_tpu.runtime.chaos import (
    FaultPlan, validate_fleet_plan)
from distributed_llm_code_samples_tpu.runtime.telemetry import (
    METRICS_FILENAME, TelemetryWriter, read_metrics, validate_record)

pytestmark = pytest.mark.serial

V, D, L, H = 64, 32, 2, 4
BASE = dict(block_size=8, n_blocks=33, max_slots=3, max_blocks_per_seq=6,
            prefill_chunk=8)
MODEL = dict(vocab=V, model_size=D, layers=L, heads=H, kv_heads=None,
             max_seq_len=64, random_seed=0)
MAX_NEW = 8


@pytest.fixture(scope="module")
def lm_params():
    return init_lm(jax.random.PRNGKey(0), V, D, L, max_seq_len=64)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(1)
    return [rng.integers(0, V, size=n).tolist() for n in (5, 9, 13)]


def _oracle(lm_params, prompts, **cfg_extra):
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE, **cfg_extra))
    for p in prompts:
        eng.submit(p, MAX_NEW)
    return eng.run()


def _spawn(n, prefill, base_dir, metrics_root=None, **cfg_extra):
    deadline = load_scaled_timeout(120.0)
    return spawn_fleet_handles(
        n, prefill, str(base_dir), model=MODEL,
        config={**BASE, **cfg_extra}, policy={},
        metrics_root=metrics_root,
        call_deadline_s=deadline, connect_deadline_s=deadline)


def test_process_fleet_matches_oracle_with_records(lm_params, prompts,
                                                   tmp_path):
    """Two worker processes behind the router: byte-identical to the
    single-engine oracle, schema-v10 router + fleet records from the
    router's own writer, every worker reaped on close."""
    want = _oracle(lm_params, prompts)
    rm = TelemetryWriter(str(tmp_path / "router"),
                         meta={"engine_id": "router"})
    handles = _spawn(2, 0, tmp_path / "spool")
    fl = FleetRouter(None, 2, handles=handles, metrics=rm)
    try:
        for p in prompts:
            fl.submit(p, MAX_NEW)
        out = fl.run()
    finally:
        fl.close()
        rm.close()
    assert out == want and not fl.failed()
    for h in handles:
        assert h.proc.poll() is not None        # reaped, no orphans
    records, problems = read_metrics(
        os.path.join(str(tmp_path / "router"), METRICS_FILENAME))
    assert not problems, problems
    routers = [r for r in records if r["kind"] == "router"]
    fleets = [r for r in records if r["kind"] == "fleet"]
    assert routers and fleets
    for r in routers + fleets:
        ok, reason = validate_record(r)
        assert ok, reason
    assert {r["event"] for r in routers} == {"routed"}


@pytest.mark.parametrize("kv_dtype", ["f32", "int8"])
def test_process_kill_one_of_three_drill(lm_params, prompts, tmp_path,
                                         kv_dtype):
    """THE acceptance drill across a real process boundary: SIGKILL one
    of three worker processes mid-stream (kill_worker@4:1 — a real
    dead host, pid-verified) and every request completes
    token-identically vs the unkilled oracle, at f32 and int8 KV. The
    replay-migration records carry transport mode "replay" with
    blocks/bytes honestly 0."""
    want = _oracle(lm_params, prompts, kv_dtype=kv_dtype)
    plan = FaultPlan.parse("kill_worker@4:1")
    validate_fleet_plan(plan)
    rm = TelemetryWriter(str(tmp_path / "router"),
                         meta={"engine_id": "router"})
    handles = _spawn(3, 0, tmp_path / "spool", kv_dtype=kv_dtype)
    fl = FleetRouter(None, 3, handles=handles, metrics=rm,
                     fleet_chaos=plan)
    try:
        pid = fl.by_id["e1"].proc.pid
        for p in prompts:
            fl.submit(p, MAX_NEW)
        out = fl.run()
    finally:
        fl.close()
        rm.close()
    assert out == want and not fl.failed()
    assert fl.kills == 1 and not fl.by_id["e1"].alive
    time.sleep(0.1)
    with pytest.raises(ProcessLookupError):
        os.kill(pid, 0)                 # the process is REALLY dead
    records, _ = read_metrics(
        os.path.join(str(tmp_path / "router"), METRICS_FILENAME))
    migs = [r for r in records if r["kind"] == "router"
            and r["event"] == "migrated"]
    assert migs and all(r["source"] == "e1" for r in migs)
    for r in migs:
        ok, reason = validate_record(r)
        assert ok, reason
        assert r["transport"]["mode"] == "replay"
        assert r["blocks"] == 0 and r["bytes"] == 0


def test_process_rolling_deploy_pinned_identity(lm_params, prompts,
                                                tmp_path):
    """The round-17 deploy drill across REAL worker processes: publish
    a checkpoint mid-serve, roll the 3-worker fleet engine by engine
    (each worker restores the step from the shared ledger dir itself —
    weights never ride the socket; a ``load_weights`` worker op), and
    every request matches its PINNED-version oracle: in-flight on the
    boot weights, post-deploy admissions on the deployed ones. Zero
    shed, schema-v11 deploy records on the router stream."""
    from distributed_llm_code_samples_tpu.checkpoint import \
        save_checkpoint
    new_params = init_lm(jax.random.PRNGKey(7), V, D, L,
                         max_seq_len=64)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, new_params, 5)
    rm = TelemetryWriter(str(tmp_path / "router"),
                         meta={"engine_id": "router"})
    handles = _spawn(3, 0, tmp_path / "spool")
    fl = FleetRouter(None, 3, handles=handles, metrics=rm)
    try:
        old_uids = [fl.submit(p, MAX_NEW) for p in prompts[:2]]
        for _ in range(4):
            fl.step()
        res = fl.rolling_deploy(ck)
        assert res["status"] == "completed" and res["to_version"] == 5
        new_uid = fl.submit(prompts[2], MAX_NEW)
        out = fl.run()
        st = fl.fleet_stats()
    finally:
        fl.close()
        rm.close()
    assert st["sheds"] == 0 and not fl.failed()
    assert st["deploys"] == 1
    assert all(v["serving_version"] == 5
               for v in st["engines"].values())
    for i, u in enumerate(old_uids):
        eng = DecodeEngine(lm_params, H, EngineConfig(**BASE))
        eng.submit(prompts[i], MAX_NEW, uid=u)
        assert out[u] == eng.run()[u], f"old-pin uid {u}"
    eng = DecodeEngine(new_params, H, EngineConfig(**BASE))
    eng.submit(prompts[2], MAX_NEW, uid=new_uid)
    assert out[new_uid] == eng.run()[new_uid]
    records, problems = read_metrics(
        os.path.join(str(tmp_path / "router"), METRICS_FILENAME))
    assert not problems, problems
    deps = [r for r in records if r["kind"] == "deploy"]
    assert [d["event"] for d in deps] == (
        ["started"] + ["engine_swapped"] * 3 + ["completed"])
    for d in deps:
        ok, reason = validate_record(d)
        assert ok, reason


def test_process_hang_worker_declared_dead(lm_params, prompts,
                                           tmp_path):
    """A silently hung worker (hang_worker@4:12 — alive but
    unresponsive): the liveness ladder (per-call deadline ->
    bounded-backoff retries -> declare dead -> SIGKILL) converts it
    into a dead host, and its requests complete token-identically on
    the survivor. Deadlines are tightened only AFTER the program set
    is warm — a compile inside a deadline would read as a hang."""
    want = _oracle(lm_params, prompts)
    plan = FaultPlan.parse("hang_worker@4:12")
    validate_fleet_plan(plan)
    handles = _spawn(2, 0, tmp_path / "spool")
    for h in handles:
        h.warm(deadline_s=load_scaled_timeout(300.0))
        h.call_deadline_s = load_scaled_timeout(3.0)
    fl = FleetRouter(None, 2, handles=handles, fleet_chaos=plan)
    try:
        for p in prompts:
            fl.submit(p, MAX_NEW)
        out = fl.run()
    finally:
        fl.close()
    assert out == want and not fl.failed()
    assert fl.kills == 1 and not fl.by_id["e0"].alive
    assert fl.by_id["e0"].proc.poll() is not None   # zombie fenced


def test_process_corrupt_wire_rejected_and_replayed(lm_params, prompts,
                                                    tmp_path):
    """A real half-shipped handoff: the disaggregated prefill tier
    exports over wire files, corrupt_wire@2 bit-flips the next one in
    transit, the CRC layer rejects it with a named reason
    (wire_rejected record), the request replays on the decode tier,
    and all tokens still match the oracle. Undamaged handoffs cross
    with transport mode "wire" and a measured crc_verify_s."""
    want = _oracle(lm_params, prompts)
    plan = FaultPlan.parse("corrupt_wire@2")
    validate_fleet_plan(plan)
    rm = TelemetryWriter(str(tmp_path / "router"),
                         meta={"engine_id": "router"})
    handles = _spawn(3, 1, tmp_path / "spool")
    fl = FleetRouter(None, 3, prefill_engines=1, handles=handles,
                     metrics=rm, fleet_chaos=plan)
    try:
        for p in prompts:
            fl.submit(p, MAX_NEW)
        out = fl.run()
    finally:
        fl.close()
        rm.close()
    assert out == want and not fl.failed()
    assert fl.wire_rejects == 1
    records, problems = read_metrics(
        os.path.join(str(tmp_path / "router"), METRICS_FILENAME))
    assert not problems, problems
    routers = [r for r in records if r["kind"] == "router"]
    [rej] = [r for r in routers if r["event"] == "wire_rejected"]
    assert ("CRC" in rej["reason"] or "unreadable" in rej["reason"]
            or "corrupted" in rej["reason"])
    replays = [r for r in routers if r["event"] == "migrated"
               and r["reason"] == "wire_rejected"]
    assert len(replays) == 1 and replays[0]["uid"] == rej["uid"]
    hand = [r for r in routers if r["event"] == "handoff"]
    assert hand, "no clean handoff crossed the wire"
    for r in hand:
        assert r["transport"]["mode"] == "wire"
        assert r["transport"]["crc_verify_s"] >= 0
        assert r["bytes"] > 0 and r["blocks"] > 0