"""LM family: hand-VJP cross-entropy, trainers, vocab-parallel TP, decode.

The reference mocks its loss (``train_ffns.py:12, :150``); the LM family
replaces the mock with the real objective, so the tests extend the
framework's two core patterns to it: every hand-written VJP checked against
``jax.grad`` on plain-op forwards, and every parallel trainer pinned to a
single-device oracle on identical seed schedules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_llm_code_samples_tpu.data import lm_batch_from_seed
from distributed_llm_code_samples_tpu.models import (
    generate, init_lm, lm_logits, lm_loss, sample)
from distributed_llm_code_samples_tpu.ops.xent import xent_loss
from distributed_llm_code_samples_tpu.parallel import (
    MODEL_AXIS, train_lm_ddp, train_lm_fsdp,
    train_lm_single, train_lm_tp, vp_embed, vp_xent)

V, D, L, HEADS, SEQ, TMAX = 32, 16, 2, 4, 8, 16


def small_lm(seed=0):
    return init_lm(jax.random.PRNGKey(seed), V, D, L, TMAX)


def tolerances():
    return dict(rtol=2e-4, atol=2e-5)


# --- ops.xent ---------------------------------------------------------------


def test_xent_matches_autograd():
    """Hand-written (softmax - onehot)/N VJP == jax.grad of a plain-op
    logsumexp cross-entropy."""
    key = jax.random.PRNGKey(1)
    logits = jax.random.normal(key, (24, V))
    targets = jax.random.randint(jax.random.PRNGKey(2), (24,), 0, V)

    def plain(z):
        lse = jax.scipy.special.logsumexp(z, axis=-1)
        picked = jnp.take_along_axis(z, targets[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - picked)

    np.testing.assert_allclose(xent_loss(logits, targets), plain(logits),
                               rtol=1e-6)
    np.testing.assert_allclose(jax.grad(xent_loss)(logits, targets),
                               jax.grad(plain)(logits), rtol=1e-5,
                               atol=1e-7)


def test_xent_stable_at_large_logits():
    """The logsumexp shift keeps huge logits finite, fwd and bwd."""
    logits = jnp.array([[1e4, -1e4, 0.0], [2e4, 2e4, 2e4]])
    targets = jnp.array([0, 2])
    loss, grad = jax.value_and_grad(xent_loss)(logits, targets)
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(grad)).all()


# --- LM model + trainers ----------------------------------------------------


def test_lm_loss_grad_matches_autograd_model():
    """The composed hand-VJP stack (blocks + LN + xent) == jax.grad of an
    all-plain-ops replica of the same math."""
    params = small_lm()
    tokens, targets = lm_batch_from_seed(jnp.int32(7), 2, SEQ, V)

    def plain_loss(p):
        t = tokens.shape[1]
        x = p.wte[tokens] + p.wpe[:t]
        for l in range(L):
            blk = p.blocks

            def ln(g, h):
                mu = h.mean(-1, keepdims=True)
                var = ((h - mu) ** 2).mean(-1, keepdims=True)
                return g * (h - mu) / jnp.sqrt(var + 1e-5)

            a = ln(blk.ln1[l], x)
            b, s, d = a.shape
            dh = d // HEADS
            q, k, v = (
                (a @ w[l].T).reshape(b, s, HEADS, dh).transpose(0, 2, 1, 3)
                for w in (blk.wq, blk.wk, blk.wv))
            scores = q @ k.transpose(0, 1, 3, 2) / jnp.sqrt(
                jnp.asarray(dh, a.dtype))
            mask = jnp.tril(jnp.ones((s, s), bool))
            scores = jnp.where(mask, scores, -1e30)
            y = jax.nn.softmax(scores, -1) @ v
            y = y.transpose(0, 2, 1, 3).reshape(b, s, d)
            x = x + y @ blk.wo[l].T
            h = ln(blk.ln2[l], x)
            x = x + jnp.maximum(h @ blk.w1[l].T, 0) @ blk.w2[l].T
        x = (lambda g, h: g * (h - h.mean(-1, keepdims=True)) /
             jnp.sqrt(((h - h.mean(-1, keepdims=True)) ** 2
                       ).mean(-1, keepdims=True) + 1e-5))(p.ln_f, x)
        z = (x @ p.wte.T).reshape(-1, V)
        lse = jax.scipy.special.logsumexp(z, axis=-1)
        picked = jnp.take_along_axis(
            z, targets.reshape(-1)[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - picked)

    ours = jax.grad(lambda p: lm_loss(p, tokens, targets, HEADS))(params)
    ref = jax.grad(plain_loss)(params)
    for got, want in zip(jax.tree_util.tree_leaves(ours),
                         jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-6)


def test_lm_ddp_matches_fsdp(mesh8):
    """The framework's core differential (``train_ffns.py:386-391``) on the
    LM surface: DDP == FSDP on the same strided seed schedule."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    params = small_lm()
    seeds = make_seed_schedule(8, random_seed=5)
    kw = dict(seq_len=SEQ, n_heads=HEADS)
    ddp = train_lm_ddp(params, seeds, 2 * SEQ, D, mesh8, **kw)
    fsdp = train_lm_fsdp(params, seeds, 2 * SEQ, D, mesh8, **kw)
    for got, want in zip(jax.tree_util.tree_leaves(fsdp),
                         jax.tree_util.tree_leaves(ddp)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())


def test_lm_tp_matches_single(mesh_model4):
    """Megatron TP with vocab-parallel embedding/head/loss == the
    single-device oracle (data replicated, so the match is exact-up-to-
    reduction-order)."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    params = small_lm()
    seeds = make_seed_schedule(4, random_seed=9)
    kw = dict(seq_len=SEQ, n_heads=HEADS)
    single = train_lm_single(params, seeds, 2 * SEQ, D, **kw)
    tp = train_lm_tp(params, seeds, 2 * SEQ, D, mesh_model4, **kw)
    for got, want in zip(jax.tree_util.tree_leaves(tp),
                         jax.tree_util.tree_leaves(single)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())


def test_lm_training_reduces_loss():
    """End to end on the real objective: SGD steps on one repeated batch
    drive its next-token cross-entropy down (the mock token stream is
    random, so memorization — not generalization — is the learnable
    signal)."""
    params = small_lm()
    tokens, targets = lm_batch_from_seed(jnp.int32(123), 4, SEQ, V)
    before = float(lm_loss(params, tokens, targets, HEADS))
    seeds = jnp.full((32,), 123, jnp.int32)  # the same batch every step
    trained = train_lm_single(params, seeds, 4 * SEQ, D, lr=0.5,
                              seq_len=SEQ, n_heads=HEADS)
    after = float(lm_loss(trained, tokens, targets, HEADS))
    assert after < before - 0.1


def test_lm_hybrid_matches_ddp(mesh4x2):
    """Hybrid(data=4 x model=2) == DDP(4): vocab-parallel TP is an exact
    decomposition, so only the data axis affects the math."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        make_mesh, DATA_AXIS, train_lm_hybrid)
    params = small_lm(seed=2)
    seeds = make_seed_schedule(8, random_seed=13)
    kw = dict(seq_len=SEQ, n_heads=HEADS)
    hyb = train_lm_hybrid(params, seeds, 2 * SEQ, D, mesh4x2, **kw)
    ddp = train_lm_ddp(params, seeds, 2 * SEQ, D,
                       make_mesh({DATA_AXIS: 4}), **kw)
    for got, want in zip(jax.tree_util.tree_leaves(hyb),
                         jax.tree_util.tree_leaves(ddp)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())


def test_lm_seq_composes_with_data_parallel():
    """2-D data x seq: each data replica trains its strided seed column
    with its sequence ring-sharded — must equal DDP over the data axis
    alone (the seq decomposition is exact)."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        make_mesh, DATA_AXIS, SEQ_AXIS, train_lm_seq)
    params = small_lm(seed=8)
    seeds = make_seed_schedule(4, random_seed=19)
    kw = dict(seq_len=SEQ, n_heads=HEADS)
    mesh2d = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
    seq2d = train_lm_seq(params, seeds, 2 * SEQ, D, mesh2d, **kw)
    ddp = train_lm_ddp(params, seeds, 2 * SEQ, D,
                       make_mesh({DATA_AXIS: 2}), **kw)
    for got, want in zip(jax.tree_util.tree_leaves(seq2d),
                         jax.tree_util.tree_leaves(ddp)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())


def test_lm_seq_matches_single():
    """Long-context LM over the seq axis (ring attention + 1/n-scaled
    local losses) == the single-device oracle on the same seeds, for both
    seq impls."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        make_mesh, SEQ_AXIS, train_lm_seq)
    params = small_lm(seed=3)
    seeds = make_seed_schedule(3, random_seed=17)
    kw = dict(seq_len=SEQ, n_heads=HEADS)
    single = train_lm_single(params, seeds, 2 * SEQ, D, **kw)
    mesh = make_mesh({SEQ_AXIS: 4})
    for impl in ("ring", "ulysses"):
        seq = train_lm_seq(params, seeds, 2 * SEQ, D, mesh,
                           seq_impl=impl, **kw)
        for got, want in zip(jax.tree_util.tree_leaves(seq),
                             jax.tree_util.tree_leaves(single)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       err_msg=impl, **tolerances())


def test_lm_seq_flash_matches_single():
    """The fused long-context path (VERDICT r3 #8): train_lm_seq with
    attn_impl="flash" — Pallas flash kernels as the per-hop ring block
    compute / Ulysses local op — still equals the single-device oracle
    on the real objective."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        make_mesh, SEQ_AXIS, train_lm_seq)
    params = small_lm(seed=3)
    seeds = make_seed_schedule(2, random_seed=17)
    # lr=0.1, NOT the 1e-5 default: the flash path runs check_vma=False
    # on CPU, where a silent grad under-reduction once hid below the
    # default-lr update size (~1e-7 < atol) — an observable lr keeps
    # this differential's power against exactly that failure mode
    kw = dict(seq_len=SEQ, n_heads=HEADS, lr=0.1)
    single = train_lm_single(params, seeds, 2 * SEQ, D, **kw)
    mesh = make_mesh({SEQ_AXIS: 4})
    for impl in ("ring", "ulysses"):
        seq = train_lm_seq(params, seeds, 2 * SEQ, D, mesh,
                           seq_impl=impl, attn_impl="flash", **kw)
        for got, want in zip(jax.tree_util.tree_leaves(seq),
                             jax.tree_util.tree_leaves(single)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       err_msg=impl, **tolerances())


def test_lm_stateful_optimizer_threads_state(mesh4):
    """The full LLM loop on the real objective: clipped AdamW through the
    single and DDP LM trainers. A segmented run — optimizer state
    threaded across the boundary — equals an uninterrupted one: the
    exact-resume contract (``ddp.py``) on the LM family."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.optim import adamw, clipped
    params = small_lm(seed=9)
    opt = clipped(adamw(weight_decay=0.01), 1.0)
    seeds = make_seed_schedule(8, random_seed=23)
    kw = dict(seq_len=SEQ, n_heads=HEADS, lr=1e-2, optimizer=opt)
    whole = train_lm_ddp(params, seeds, 2 * SEQ, D, mesh4, **kw)
    # segmented: 4 steps, carry state, 4 more
    p1, s1 = train_lm_ddp(params, seeds[:4], 2 * SEQ, D, mesh4,
                          return_state=True, **kw)
    p2 = train_lm_ddp(p1, seeds[4:], 2 * SEQ, D, mesh4, opt_state=s1, **kw)
    for got, want in zip(jax.tree_util.tree_leaves(p2),
                         jax.tree_util.tree_leaves(whole)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-7)
    # and the single-device stateful path agrees with itself segmented
    w_single = train_lm_single(params, seeds, 2 * SEQ, D, **kw)
    q1, t1 = train_lm_single(params, seeds[:4], 2 * SEQ, D,
                             return_state=True, **kw)
    q2 = train_lm_single(q1, seeds[4:], 2 * SEQ, D, opt_state=t1, **kw)
    for got, want in zip(jax.tree_util.tree_leaves(q2),
                         jax.tree_util.tree_leaves(w_single)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-7)


def test_lm_fsdp_stateful_matches_ddp(mesh4):
    """Full ZeRO-3 on the LM: Adam state sharded with the param shards ==
    DDP with replicated state (the partition must not change the math),
    and a segmented run threads the sharded state exactly."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.optim import adam
    params = small_lm(seed=10)
    seeds = make_seed_schedule(8, random_seed=25)
    kw = dict(seq_len=SEQ, n_heads=HEADS, lr=1e-2, optimizer=adam())
    ddp = train_lm_ddp(params, seeds, 2 * SEQ, D, mesh4, **kw)
    fsdp = train_lm_fsdp(params, seeds, 2 * SEQ, D, mesh4, **kw)
    for got, want in zip(jax.tree_util.tree_leaves(fsdp),
                         jax.tree_util.tree_leaves(ddp)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())
    p1, s1 = train_lm_fsdp(params, seeds[:4], 2 * SEQ, D, mesh4,
                           return_state=True, **kw)
    p2 = train_lm_fsdp(p1, seeds[4:], 2 * SEQ, D, mesh4, opt_state=s1,
                       **kw)
    for got, want in zip(jax.tree_util.tree_leaves(p2),
                         jax.tree_util.tree_leaves(fsdp)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-7)


def test_lm_tp_stateful_matches_single(mesh_model4):
    """Megatron optimizer layout: Adam state sharded with the TP params;
    segmented TP run (state threaded) == uninterrupted single-device run
    with the same optimizer."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.optim import adam
    params = small_lm(seed=11)
    seeds = make_seed_schedule(4, random_seed=27)
    kw = dict(seq_len=SEQ, n_heads=HEADS, lr=1e-2)
    single = train_lm_single(params, seeds, 2 * SEQ, D, optimizer=adam(),
                             **kw)
    tp = train_lm_tp(params, seeds, 2 * SEQ, D, mesh_model4,
                     optimizer=adam(), **kw)
    for got, want in zip(jax.tree_util.tree_leaves(tp),
                         jax.tree_util.tree_leaves(single)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())
    p1, s1 = train_lm_tp(params, seeds[:2], 2 * SEQ, D, mesh_model4,
                         optimizer=adam(), return_state=True, **kw)
    p2 = train_lm_tp(p1, seeds[2:], 2 * SEQ, D, mesh_model4,
                     optimizer=adam(), opt_state=s1, **kw)
    for got, want in zip(jax.tree_util.tree_leaves(p2),
                         jax.tree_util.tree_leaves(tp)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-7)


# --- vocab-parallel pieces in isolation ------------------------------------


def test_vp_embed_matches_dense(mesh_model4):
    params = small_lm()
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, SEQ), 0, V)

    def run(wte, tokens):
        return vp_embed(wte, tokens, MODEL_AXIS)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh_model4, in_specs=(P(MODEL_AXIS, None), P()),
        out_specs=P()))(params.wte, tokens)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(params.wte[tokens]), rtol=1e-6)


def test_vp_xent_matches_dense_fwd_and_bwd(mesh_model4):
    logits = jax.random.normal(jax.random.PRNGKey(4), (16, V))
    targets = jax.random.randint(jax.random.PRNGKey(5), (16,), 0, V)

    def run(z_local, t):
        return vp_xent(z_local, t, MODEL_AXIS)

    loss = jax.jit(jax.shard_map(
        run, mesh=mesh_model4, in_specs=(P(None, MODEL_AXIS), P()),
        out_specs=P()))(logits, targets)
    np.testing.assert_allclose(float(loss),
                               float(xent_loss(logits, targets)), rtol=1e-6)

    def grad_run(z_local, t):
        return jax.grad(lambda z: vp_xent(z, t, MODEL_AXIS))(z_local)

    got = jax.jit(jax.shard_map(
        grad_run, mesh=mesh_model4, in_specs=(P(None, MODEL_AXIS), P()),
        out_specs=P(None, MODEL_AXIS)))(logits, targets)
    want = jax.grad(xent_loss)(logits, targets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-7)


# --- grouped-query attention ------------------------------------------------


def test_gqa_reduces_to_mha_when_counts_match():
    """gqa with H_kv == H is bit-identical to mha (same kernel, same
    order)."""
    from distributed_llm_code_samples_tpu.models.attention import gqa, mha
    key = jax.random.PRNGKey(21)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (4, 8, 8))
               for i in range(3))
    np.testing.assert_array_equal(np.asarray(gqa(q, k, v, True)),
                                  np.asarray(mha(q, k, v, True)))


def test_gqa_matches_repeated_kv_oracle():
    """GQA == plain MHA with each KV head explicitly repeated over its
    group — forward and gradients."""
    from distributed_llm_code_samples_tpu.models.attention import gqa, mha
    key = jax.random.PRNGKey(22)
    q = jax.random.normal(jax.random.fold_in(key, 0), (4, 8, 8))
    k = jax.random.normal(jax.random.fold_in(key, 1), (2, 8, 8))
    v = jax.random.normal(jax.random.fold_in(key, 2), (2, 8, 8))

    def repeated(q, k, v):
        kr = jnp.repeat(k, 2, axis=0)
        vr = jnp.repeat(v, 2, axis=0)
        return mha(q, kr, vr, True)

    np.testing.assert_allclose(np.asarray(gqa(q, k, v, True)),
                               np.asarray(repeated(q, k, v)), rtol=1e-6)
    g1 = jax.grad(lambda q, k, v: jnp.sum(gqa(q, k, v, True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(repeated(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def gqa_lm(seed=0):
    return init_lm(jax.random.PRNGKey(seed), V, D, L, TMAX,
                   n_heads=HEADS, n_kv_heads=2)


def test_gqa_lm_trains_and_matches_across_strategies(mesh8):
    """The GQA LM (kv heads = H/2, cache and wk/wv half-size) trains
    under DDP == FSDP and memorizes a repeated batch — the grouping
    changes shapes, not the differential contracts."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    params = gqa_lm(seed=21)
    assert params.blocks.wk.shape[1] == D // 2
    seeds = make_seed_schedule(8, random_seed=41)
    kw = dict(seq_len=SEQ, n_heads=HEADS)
    ddp = train_lm_ddp(params, seeds, 2 * SEQ, D, mesh8, **kw)
    fsdp = train_lm_fsdp(params, seeds, 2 * SEQ, D, mesh8, **kw)
    for got, want in zip(jax.tree_util.tree_leaves(fsdp),
                         jax.tree_util.tree_leaves(ddp)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())
    tokens, targets = lm_batch_from_seed(jnp.int32(99), 4, SEQ, V)
    before = float(lm_loss(params, tokens, targets, HEADS))
    trained = train_lm_single(params, jnp.full((32,), 99, jnp.int32),
                              4 * SEQ, D, lr=0.5, **kw)
    assert float(lm_loss(trained, tokens, targets, HEADS)) < before - 0.1


def test_gqa_tp_training_works_when_divisible(mesh_model4):
    """TP training of a GQA model works when kv heads divide the model
    axis (here kv=4 over 4 shards == MHA-per-shard grouping preserved);
    an indivisible kv count and the TP decode path reject clearly."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (make_mesh,
                                                           MODEL_AXIS,
                                                           tp_generate)
    params2 = gqa_lm(seed=25)     # kv=2: not divisible by 4
    seeds = make_seed_schedule(2, random_seed=43)
    with pytest.raises(ValueError, match="n_kv_heads=2"):
        train_lm_tp(params2, seeds, 2 * SEQ, D, mesh_model4,
                    seq_len=SEQ, n_heads=HEADS)
    with pytest.raises(ValueError, match="n_kv_heads=2"):
        tp_generate(params2, jnp.zeros((1, 2), jnp.int32), 2,
                    mesh_model4, n_heads=HEADS)
    # kv=2 over 2 shards: one kv head per shard, groups preserved
    mesh2 = make_mesh({MODEL_AXIS: 2})
    # GQA decode with the head-sharded cache sized by LOCAL kv heads
    # (1 per shard) == the single-device decode
    prompt = jnp.asarray([[3, 1, 4, 1], [2, 7, 1, 8]], jnp.int32)
    want = generate(params2, prompt, 3, HEADS)
    got = tp_generate(params2, prompt, 3, mesh2, n_heads=HEADS)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    single = train_lm_single(params2, seeds, 2 * SEQ, D, seq_len=SEQ,
                             n_heads=HEADS)
    tp = train_lm_tp(params2, seeds, 2 * SEQ, D, mesh2, seq_len=SEQ,
                     n_heads=HEADS)
    for got, want in zip(jax.tree_util.tree_leaves(tp),
                         jax.tree_util.tree_leaves(single)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **tolerances())


def test_gqa_decode_matches_full_forward_and_shrinks_cache():
    """GQA decode == teacher-forced argmax, with the KV cache half the
    MHA size."""
    from distributed_llm_code_samples_tpu.models import init_cache
    params = gqa_lm(seed=23)
    cache = init_cache(params, 2, HEADS)
    assert cache.k.shape[2] == 2  # kv heads, not query heads
    prompt = jax.random.randint(jax.random.PRNGKey(24), (2, 3), 0, V)
    got = generate(params, prompt, 5, HEADS)
    toks = np.asarray(prompt)
    for _ in range(5):
        logits = lm_logits(params, jnp.asarray(toks), HEADS)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), toks)


# --- rotary positions -------------------------------------------------------


def test_rope_scores_are_relative():
    """RoPE's defining property: shifting every absolute position by a
    constant leaves the attention output unchanged (scores depend only on
    position differences)."""
    from distributed_llm_code_samples_tpu.models.attention import mha, rope
    key = jax.random.PRNGKey(31)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (2, 8, 8))
               for i in range(3))
    pos = jnp.arange(8)
    base_out = mha(rope(q, pos), rope(k, pos), v, True)
    shifted = mha(rope(q, pos + 17), rope(k, pos + 17), v, True)
    np.testing.assert_allclose(np.asarray(base_out), np.asarray(shifted),
                               rtol=1e-5, atol=1e-6)


def test_rope_training_and_decode_agree():
    """An LM trained with attn_impl='rope' decodes (use_rope=True)
    exactly like its teacher-forced argmax — the cache stores rotated
    keys matching the training rotation. Also composes with GQA."""
    from distributed_llm_code_samples_tpu.models.attention import rope_mha
    params = init_lm(jax.random.PRNGKey(33), V, D, L, TMAX,
                     n_heads=HEADS, n_kv_heads=2)
    seeds = jnp.full((8,), 55, jnp.int32)
    trained = train_lm_single(params, seeds, 2 * SEQ, D, lr=0.3,
                              seq_len=SEQ, n_heads=HEADS,
                              attn_impl="rope")
    # training moved the params on the rope path
    assert not np.allclose(np.asarray(trained.blocks.wq),
                           np.asarray(params.blocks.wq))
    prompt = jax.random.randint(jax.random.PRNGKey(34), (2, 3), 0, V)
    got = generate(trained, prompt, 4, HEADS, use_rope=True)
    toks = np.asarray(prompt)
    for _ in range(4):
        logits = lm_logits(trained, jnp.asarray(toks), HEADS,
                           attn=rope_mha)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), toks)


def test_rope_tp_decode_matches_dense(mesh_model4):
    """tp_generate(use_rope=True) on a rope-trained full-MHA model ==
    the dense rope decode, token for token."""
    from distributed_llm_code_samples_tpu.parallel import tp_generate
    params = small_lm(seed=14)
    seeds = jnp.full((4,), 77, jnp.int32)
    trained = train_lm_single(params, seeds, 2 * SEQ, D, lr=0.3,
                              seq_len=SEQ, n_heads=HEADS,
                              attn_impl="rope")
    prompt = jax.random.randint(jax.random.PRNGKey(35), (2, 3), 0, V)
    want = generate(trained, prompt, 4, HEADS, use_rope=True)
    got = tp_generate(trained, prompt, 4, mesh_model4, n_heads=HEADS,
                      use_rope=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rope_changes_the_math():
    """rope vs learned-only positions give different trainings (the
    rotation actually applies)."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    params = small_lm(seed=13)
    seeds = make_seed_schedule(2, random_seed=45)
    kw = dict(seq_len=SEQ, n_heads=HEADS, lr=0.1)
    plain = train_lm_single(params, seeds, 2 * SEQ, D, **kw)
    roped = train_lm_single(params, seeds, 2 * SEQ, D,
                            attn_impl="rope", **kw)
    assert not np.allclose(np.asarray(plain.blocks.wq),
                           np.asarray(roped.blocks.wq))


# --- decode ----------------------------------------------------------------


def test_generate_matches_full_forward_argmax():
    """KV-cache greedy decode == re-running the full forward per position
    and taking the last row's argmax — pins the cache writes, position
    embeddings, and causal masking in one check."""
    params = small_lm(seed=4)
    prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 3), 0, V)
    n_new = 5
    got = generate(params, prompt, n_new, HEADS)
    np.testing.assert_array_equal(np.asarray(got[:, :3]),
                                  np.asarray(prompt))

    toks = np.asarray(prompt)
    for _ in range(n_new):
        logits = lm_logits(params, jnp.asarray(toks), HEADS)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), toks)


def test_sample_topk1_is_greedy():
    """top_k=1 truncates to the argmax token: sampling must reproduce the
    greedy path exactly, at any temperature."""
    params = small_lm(seed=5)
    prompt = jax.random.randint(jax.random.PRNGKey(10), (2, 3), 0, V)
    greedy = generate(params, prompt, 5, HEADS)
    sampled = sample(params, prompt, 5, HEADS, temperature=2.0, top_k=1,
                     seed=11)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))


def test_sample_deterministic_per_seed():
    """Counter-RNG sampling: same seed -> identical continuation; the
    temperature is high enough that distinct seeds disagree somewhere."""
    params = small_lm(seed=7)
    prompt = jax.random.randint(jax.random.PRNGKey(12), (4, 2), 0, V)
    a = sample(params, prompt, 8, HEADS, temperature=5.0, seed=1)
    b = sample(params, prompt, 8, HEADS, temperature=5.0, seed=1)
    c = sample(params, prompt, 8, HEADS, temperature=5.0, seed=2)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_sample_validates_arguments():
    params = small_lm()
    prompt = jnp.zeros((1, 2), jnp.int32)
    import pytest
    with pytest.raises(ValueError, match="temperature"):
        sample(params, prompt, 2, HEADS, temperature=0.0)
    with pytest.raises(ValueError, match="top_k"):
        sample(params, prompt, 2, HEADS, top_k=V + 1)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_lm_pp_matches_single(schedule):
    """The full LM pipelined (embed stage 0, blocks staged, head + real
    loss on the last stage) == the single-device LM trainer, both
    schedules, M<S and M>S."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        PIPE_AXIS, make_mesh, train_lm_pp)
    params = init_lm(jax.random.PRNGKey(15), V, D, 4, TMAX)
    seeds = make_seed_schedule(2, random_seed=33)
    b = 8  # M=8 > S=4 exercises the deep-microbatch regime (and 1F1B's
    # circular stash reuse); M=2 < S the bubble-heavy one
    single = train_lm_single(params, seeds, b * SEQ, D, lr=0.05,
                             seq_len=SEQ, n_heads=HEADS)
    mesh = make_mesh({PIPE_AXIS: 4})
    for m in (2, 8):
        got = train_lm_pp(params, seeds, b * SEQ, D, mesh, lr=0.05,
                          seq_len=SEQ, n_heads=HEADS, n_microbatches=m,
                          schedule=schedule)
        for a, w in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(single)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=2e-4, atol=1e-5,
                                       err_msg=f"M={m}")


def test_lm_pp_interleaved_matches_single():
    """The full LM under interleaved virtual stages: embedding before
    virtual stage 0 (chunk 0 of device 0), head + real loss after the
    LAST virtual stage (chunk V-1 of the last device) — the chunk-gated
    roles. == single-device LM, M == S and M > S, plus the data
    composition."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        DATA_AXIS, PIPE_AXIS, make_mesh, train_lm_pp)
    params = init_lm(jax.random.PRNGKey(19), V, D, 4, TMAX)
    seeds = make_seed_schedule(2, random_seed=39)
    b = 4
    single = train_lm_single(params, seeds, b * SEQ, D, lr=0.05,
                             seq_len=SEQ, n_heads=HEADS)
    mesh = make_mesh({PIPE_AXIS: 2})
    for m in (2, 4):
        got = train_lm_pp(params, seeds, b * SEQ, D, mesh, lr=0.05,
                          seq_len=SEQ, n_heads=HEADS, n_microbatches=m,
                          schedule="interleaved", interleave=2)
        for a, w in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(single)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=2e-4, atol=1e-5,
                                       err_msg=f"M={m}")
    seeds4 = make_seed_schedule(4, random_seed=40)
    ddp = train_lm_ddp(params, seeds4, b * SEQ, D,
                       make_mesh({DATA_AXIS: 2}), lr=0.05, seq_len=SEQ,
                       n_heads=HEADS)
    got = train_lm_pp(params, seeds4, b * SEQ, D,
                      make_mesh({DATA_AXIS: 2, PIPE_AXIS: 2}), lr=0.05,
                      seq_len=SEQ, n_heads=HEADS, n_microbatches=2,
                      schedule="interleaved", interleave=2)
    for a, w in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ddp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                   rtol=2e-4, atol=1e-5)


def test_lm_pp_attn_impl_matches_single():
    """attn_impl threads through the LM pipeline path (every other LM
    trainer already accepts it): PP with rope == single with rope — a
    rope-trained LM can be continued/reproduced under PP."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        PIPE_AXIS, make_mesh, train_lm_pp)
    params = init_lm(jax.random.PRNGKey(21), V, D, 2, TMAX)
    seeds = make_seed_schedule(2, random_seed=37)
    b = 4
    single = train_lm_single(params, seeds, b * SEQ, D, lr=0.05,
                             seq_len=SEQ, n_heads=HEADS,
                             attn_impl="rope")
    got = train_lm_pp(params, seeds, b * SEQ, D,
                      make_mesh({PIPE_AXIS: 2}), lr=0.05, seq_len=SEQ,
                      n_heads=HEADS, attn_impl="rope")
    for a, w in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(single)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                   rtol=2e-4, atol=1e-5)
    # and it really is rope: differs from the oracle-attention PP run
    plain = train_lm_pp(params, seeds, b * SEQ, D,
                        make_mesh({PIPE_AXIS: 2}), lr=0.05, seq_len=SEQ,
                        n_heads=HEADS)
    assert not np.allclose(np.asarray(got.blocks.wq),
                           np.asarray(plain.blocks.wq))


def test_lm_pp_composes_with_data(mesh4):
    """data x pipe on the LM == LM DDP over the data axis alone."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        DATA_AXIS, PIPE_AXIS, make_mesh, train_lm_pp)
    params = small_lm(seed=16)
    seeds = make_seed_schedule(4, random_seed=35)
    b = 4
    ddp = train_lm_ddp(params, seeds, b * SEQ, D,
                       make_mesh({DATA_AXIS: 2}), lr=0.05,
                       seq_len=SEQ, n_heads=HEADS)
    mesh2d = make_mesh({DATA_AXIS: 2, PIPE_AXIS: 2})
    got = train_lm_pp(params, seeds, b * SEQ, D, mesh2d, lr=0.05,
                      seq_len=SEQ, n_heads=HEADS)
    for a, w in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ddp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                   rtol=2e-4, atol=1e-5)


def test_tp_generate_matches_single_device(mesh_model4):
    """Megatron-sharded decode (head-sharded cache, vocab-parallel head,
    gathered argmax) == the single-device greedy decode, token for
    token."""
    from distributed_llm_code_samples_tpu.parallel import tp_generate
    params = small_lm(seed=12)
    prompt = jax.random.randint(jax.random.PRNGKey(14), (2, 3), 0, V)
    want = generate(params, prompt, 5, HEADS)
    got = tp_generate(params, prompt, 5, mesh_model4, n_heads=HEADS)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_generate_presharded_skips_copy_and_matches(mesh_model4):
    """tp_shard_params once + tp_generate = the same tokens as handing
    tp_generate unsharded params, and the presharded layout is detected
    (no per-call reshard copy — the ADVICE r3 bench_decode fix)."""
    from distributed_llm_code_samples_tpu.parallel import (tp_generate,
                                                           tp_shard_params)
    from distributed_llm_code_samples_tpu.parallel.lm import (
        _tp_sharded_already)
    params = small_lm(seed=12)
    prompt = jax.random.randint(jax.random.PRNGKey(14), (2, 3), 0, V)
    want = tp_generate(params, prompt, 5, mesh_model4, n_heads=HEADS)
    sharded = tp_shard_params(params, mesh_model4)
    assert _tp_sharded_already(sharded, mesh_model4)
    assert not _tp_sharded_already(params, mesh_model4)
    got = tp_generate(sharded, prompt, 5, mesh_model4, n_heads=HEADS)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_is_prompt_length_oblivious():
    """One compiled program serves any prompt split of the same total:
    feeding a longer prompt whose extra tokens are exactly the greedy
    continuation yields the same final sequence."""
    params = small_lm(seed=6)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (1, 2), 0, V)
    full = generate(params, prompt, 6, HEADS)
    again = generate(params, full[:, :5], 3, HEADS)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(again))


def test_tp_sample_gumbel_decode(mesh_model4):
    """Stochastic TP decode via Gumbel-max over the vocab-parallel head:
    deterministic per seed, varies across seeds, stays in-vocab, and on
    a near-deterministic model (one dominant logit direction) agrees
    with greedy — the distributional sanity check."""
    from distributed_llm_code_samples_tpu.parallel import (tp_generate,
                                                           tp_sample)
    params = small_lm(seed=31)
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    a = tp_sample(params, prompt, 4, mesh_model4, n_heads=HEADS,
                  temperature=1.0, seed=5)
    b = tp_sample(params, prompt, 4, mesh_model4, n_heads=HEADS,
                  temperature=1.0, seed=5)
    c = tp_sample(params, prompt, 4, mesh_model4, n_heads=HEADS,
                  temperature=1.0, seed=6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert a.shape == (2, 3 + 4)
    assert (np.asarray(a) >= 0).all() and (np.asarray(a) < V).all()
    # prompt preserved
    np.testing.assert_array_equal(np.asarray(a[:, :3]), np.asarray(prompt))
    # tiny temperature ~= greedy (the Gumbel perturbation vanishes)
    cold = tp_sample(params, prompt, 4, mesh_model4, n_heads=HEADS,
                     temperature=1e-5, seed=7)
    greedy = tp_generate(params, prompt, 4, mesh_model4, n_heads=HEADS)
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(greedy))
    with pytest.raises(ValueError, match="temperature"):
        tp_sample(params, prompt, 2, mesh_model4, n_heads=HEADS,
                  temperature=0.0)


def test_lm_seq_fused_head_matches_single():
    """train_lm_seq(head_impl='fused'): the fused Pallas head + xent on
    each shard's token block (1/n-scaled, psum-reduced) still equals the
    single-device oracle — composed with flash ring attention, the fully
    fused long-context step."""
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.parallel import (
        make_mesh, SEQ_AXIS, train_lm_seq)
    params = small_lm(seed=5)
    seeds = make_seed_schedule(2, random_seed=19)
    kw = dict(seq_len=SEQ, n_heads=HEADS, lr=0.1)
    single = train_lm_single(params, seeds, 2 * SEQ, D, **kw)
    mesh = make_mesh({SEQ_AXIS: 4})
    for attn in (None, "flash"):
        seq = train_lm_seq(params, seeds, 2 * SEQ, D, mesh,
                           seq_impl="ring", attn_impl=attn,
                           head_impl="fused", **kw)
        for got, want in zip(jax.tree_util.tree_leaves(seq),
                             jax.tree_util.tree_leaves(single)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       err_msg=str(attn), **tolerances())
