"""Serving observability (ISSUE 7): request span tracing, decode cost
attribution, KV-pool telemetry, the fault flight recorder, and the
multi-stream report merge.

The proofs ride the repo's differential stance: span durations must
RECONCILE with the independently-recorded request latencies (two
instruments, one truth), the static KV accounting must equal the
device arrays byte-for-byte, and the named-scope contract is asserted
against the REAL compiled serving programs captured through the PR 2
launcher hook — never a reconstruction.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_code_samples_tpu.decode import (DecodeEngine,
                                                     EngineConfig,
                                                     FLIGHT_FILENAME,
                                                     ServePolicy)
from distributed_llm_code_samples_tpu.decode.engine import (
    FLIGHT_RECORDER_STEPS, POISON_ALL)
from distributed_llm_code_samples_tpu.models import init_lm
from distributed_llm_code_samples_tpu.runtime.telemetry import (
    METRICS_FILENAME, TelemetryWriter, read_metrics, validate_record)

V, D, L, H = 64, 32, 2, 4
BASE = dict(block_size=8, n_blocks=33, max_slots=3, max_blocks_per_seq=6,
            prefill_chunk=8)


@pytest.fixture(scope="module")
def lm_params():
    return init_lm(jax.random.PRNGKey(0), V, D, L, max_seq_len=64)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(1)
    return [rng.integers(0, V, size=n).tolist() for n in (5, 9, 13)]


def _span_sums(records):
    sums: dict = {}
    for s in records:
        if s["kind"] == "span":
            sums[s["uid"]] = sums.get(s["uid"], 0.0) + s["duration_s"]
    return sums


def _latencies(records):
    return {r["uid"]: r["latency_s"] for r in records
            if r["kind"] == "request" and r["event"] == "completed"}


# ---------------------------------------------------------------------------
# span tracing: the telescoping reconciliation contract


def test_span_stream_reconciles_with_latency(lm_params, prompts,
                                             tmp_path):
    """Every completed request's span durations sum to its recorded
    latency_s (the tracer's telescoping-clock contract) — and the
    instrumentation adds ZERO compiled programs (scopes and spans are
    metadata + host work; the serving surface is unchanged)."""
    mdir = str(tmp_path / "m")
    with TelemetryWriter(mdir, meta={"engine_id": "e0"}) as w:
        eng = DecodeEngine(lm_params, H, EngineConfig(**BASE), metrics=w)
        eng.generate(prompts, 8, log_every=2)
        warm = eng.compile_count
        # second wave reuses seen buckets (lens 4 and 5 -> chunks 4/1)
        eng.generate([[1, 2, 3, 4], [1, 2, 3, 4, 5]], 4, log_every=2)
        assert eng.compile_count == warm    # tracing never compiles
    records, problems = read_metrics(os.path.join(mdir,
                                                  METRICS_FILENAME))
    assert problems == []
    spans = [r for r in records if r["kind"] == "span"]
    assert spans and all(validate_record(s)[0] for s in spans)
    lat = _latencies(records)
    sums = _span_sums(records)
    assert set(lat) <= set(sums)
    for uid, latency in lat.items():
        assert abs(sums[uid] - latency) <= 0.01, (uid, sums[uid],
                                                  latency)
    # phase structure: every uid queued first, decoded last
    by_uid: dict = {}
    for s in spans:
        by_uid.setdefault(s["uid"], []).append(s)
    for uid, ss in by_uid.items():
        ss.sort(key=lambda s: (s["start_t"], s["t"]))
        assert ss[0]["span"] == "queued"
        assert ss[-1]["span"] == "decode"
        assert any(s["span"] == "prefill" for s in ss)


def test_quarantine_retry_spans_and_flight_recorder(lm_params, prompts,
                                                    tmp_path):
    """A poisoned step produces the quarantine span arc (decode ->
    quarantine -> prefill -> replay -> decode), the retried request
    still reconciles, and the flight recorder dumps atomically with
    digests covering the steps UP TO the quarantine — non-finite
    evidence included."""
    mdir = str(tmp_path / "m")
    with TelemetryWriter(mdir, meta={"engine_id": "e0"}) as w:
        eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                           metrics=w,
                           policy=ServePolicy(max_retries=1))
        for i, p in enumerate(prompts[:2]):
            eng.submit(p, 5, uid=i)
        for _ in range(3):
            eng.step()
        eng.arm_poison(POISON_ALL)
        eng.run()
    assert sorted(eng.finished) == [0, 1] and not eng.failed
    records, problems = read_metrics(os.path.join(mdir,
                                                  METRICS_FILENAME))
    assert problems == []
    spans = [r for r in records if r["kind"] == "span"]
    names0 = [s["span"] for s in sorted(
        (s for s in spans if s["uid"] == 0),
        key=lambda s: (s["start_t"], s["t"]))]
    assert "quarantine" in names0 and "replay" in names0
    # the quarantine gap hands off to the re-admission's prefill
    qi = names0.index("quarantine")
    assert names0[qi + 1] == "prefill"
    lat = _latencies(records)
    sums = _span_sums(records)
    for uid, latency in lat.items():
        assert abs(sums[uid] - latency) <= 0.01
    # flight recorder: dumped at the quarantine, digests cover the
    # steps up to (and including) the fault step
    fr = json.load(open(os.path.join(mdir, FLIGHT_FILENAME)))
    assert fr["version"] == 1 and "quarantine" in fr["reason"]
    steps = [d["step"] for d in fr["digests"]]
    assert steps == sorted(steps) and steps[-1] == fr["step"]
    last = fr["digests"][-1]
    assert last["finite"] is not None and not all(last["finite"])
    assert any("quarantined" in e for e in last["events"])
    assert eng.flight.maxlen == FLIGHT_RECORDER_STEPS


def test_preempt_gap_and_deadline_spans(lm_params, tmp_path):
    """Pool-pressure preemption emits a preempt_gap span that hands
    off to the re-admission (the churn is visible as wall time, not
    lost); a deadline expiry closes the victim's open span with the
    reason."""
    mdir = str(tmp_path / "m")
    cfg = EngineConfig(block_size=8, n_blocks=5, max_slots=3,
                       max_blocks_per_seq=2, prefill_chunk=8)
    with TelemetryWriter(mdir, meta={"engine_id": "e0"}) as w:
        eng = DecodeEngine(lm_params, H, cfg, metrics=w,
                           policy=ServePolicy(preempt_after_steps=2))
        eng.submit([1] * 9, 8, uid=0)      # 2 blocks
        eng.submit([1] * 9, 8, uid=1)      # 2 blocks: pool now full
        eng.submit([1] * 9, 8, uid=2)      # starved -> preemption
        eng.run()
        assert eng.preempted >= 1
    records, _ = read_metrics(os.path.join(mdir, METRICS_FILENAME))
    spans = [r for r in records if r["kind"] == "span"]
    gaps = [s for s in spans if s["span"] == "preempt_gap"]
    assert gaps
    lat = _latencies(records)
    sums = _span_sums(records)
    for uid, latency in lat.items():
        assert abs(sums[uid] - latency) <= 0.01

    mdir2 = str(tmp_path / "m2")
    with TelemetryWriter(mdir2, meta={"engine_id": "e0"}) as w:
        eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                           metrics=w,
                           policy=ServePolicy(deadline_steps=3))
        eng.submit([1, 2, 3], 16, uid=0)
        eng.run()
        assert eng.failed[0]["reason"] == "deadline"
    records, _ = read_metrics(os.path.join(mdir2, METRICS_FILENAME))
    spans = [r for r in records if r["kind"] == "span"]
    assert spans and spans[-1]["reason"] == "deadline"


# ---------------------------------------------------------------------------
# decode cost attribution: named scopes on the REAL compiled programs
# + the StepReport static fold vs the roofline's KV accounting


def test_decode_scope_contract_real_programs(lm_params, prompts):
    """Every region in SCOPES['decode'] / SCOPES['prefill'] appears in
    the optimized HLO of the engine's REAL dispatched programs —
    captured through the PR 2 launcher hook, the same contract the
    training strategies pin."""
    import distributed_llm_code_samples_tpu.parallel.launcher as launcher
    from distributed_llm_code_samples_tpu.utils.trace_analysis import (
        SCOPES)
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE))
    launcher.CAPTURE_COMPILED = cap = []
    try:
        eng.generate(prompts[:2], 4)
    finally:
        launcher.CAPTURE_COMPILED = None
    assert cap, "engine dispatched no captured programs"
    text = "\n".join(cap)
    for key in ("decode", "prefill"):
        missing = [r for r in SCOPES[key] if r not in text]
        assert not missing, (f"{key}: compiled serving HLO lacks "
                             f"named-scope region(s) {missing}")


@pytest.mark.parametrize("kv_dtype", ["f32", "bf16", "int8"])
def test_decode_static_report_matches_roofline_bytes(lm_params,
                                                     kv_dtype):
    """The static attribution's hand cross-check: the pool arrays'
    device bytes equal kv_bytes_per_token * n_blocks * block_size —
    the DECODE roofline's per-dtype prediction — exactly, and the
    StepReport folds without error (single-device: no collectives in
    the lowered program)."""
    eng = DecodeEngine(lm_params, H,
                       EngineConfig(**BASE, kv_dtype=kv_dtype))
    rep = eng.decode_static_report()
    assert rep["kv_dtype"] == kv_dtype
    assert rep["kv_pool_bytes"] == rep["kv_pool_bytes_predicted"]
    assert rep["slot_bucket"] == BASE["max_slots"]
    assert rep["step_report"]["collectives"] == {}
    per_elt = {"f32": 4, "bf16": 2, "int8": 1}[kv_dtype]
    assert rep["kv_bytes_per_token"] == 2 * L * H * (D // H) * per_elt
    if kv_dtype == "int8":
        assert rep["kv_scale_bytes"] > 0
    else:
        assert rep["kv_scale_bytes"] == 0


def test_decode_static_report_tp_collectives(lm_params, mesh_model4):
    """Under the Megatron decode layout the static report counts the
    hand-rolled schedule: one attention-out + one FFN all_reduce per
    layer, plus the vocab-parallel head's logits all_gather."""
    eng = DecodeEngine(lm_params, H, EngineConfig(**BASE),
                       mesh=mesh_model4)
    rep = eng.decode_static_report()
    c = rep["step_report"]["collectives"]
    assert c.get("all_reduce", 0) >= 2 * L, c
    assert c.get("all_gather", 0) >= 1, c
    assert rep["kv_pool_bytes"] == rep["kv_pool_bytes_predicted"]


# ---------------------------------------------------------------------------
# the acceptance drill: two engines, one merged report, waterfalls +
# postmortem — end to end through the CLI


def test_observability_drill_end_to_end(tmp_path, capsys):
    """ISSUE 7 acceptance: `generate --chaos nan_logits@3` (engine A,
    quarantine + retry) plus a clean engine B, folded by `report A B`:
    (a) a reconciled per-request waterfall for every completed uid,
    (b) a flight-recorder dump covering the steps up to the quarantine
    rendered by --postmortem, (c) one merged two-engine timeline with
    per-engine latency percentiles."""
    import distributed_llm_code_samples_tpu.cli as cli
    from distributed_llm_code_samples_tpu.report import report_main

    a_dir = str(tmp_path / "A")
    b_dir = str(tmp_path / "B")
    shape = ["-d", "32", "-l", "2", "--heads", "4", "--vocab", "64",
             "--max_seq_len", "64", "--block_size", "8",
             "--prefill_chunk", "8", "--max_new", "5",
             "--log_every", "2"]
    rc = cli.main(["generate", "--prompt_lens", "5,9"] + shape
                  + ["--chaos", "nan_logits@3", "--max_retries", "1",
                     "--snapshot_dir", str(tmp_path / "snapA"),
                     "--metrics_dir", a_dir, "--engine_id", "A"])
    assert rc == 0
    rc = cli.main(["generate", "--prompt_lens", "4,6"] + shape
                  + ["--metrics_dir", b_dir, "--engine_id", "B"])
    assert rc == 0
    capsys.readouterr()

    # (a) + (c): the merged JSON doc
    assert report_main([a_dir, b_dir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc["engines"]) == {"A", "B"}
    for eng_id in ("A", "B"):
        rel = doc["engines"][eng_id]["serving_reliability"]
        assert rel["completed"] == 2
        assert "latency_p50_s" in rel and "latency_p99_s" in rel
        wf = doc["waterfalls"][eng_id]
        assert len(wf) == 2
        for uid, w in wf.items():
            assert w["reconciled"], (eng_id, uid, w)
            assert w["latency_s"] is not None
    a_rel = doc["engines"]["A"]["serving_reliability"]
    assert a_rel["quarantined"] == 2 and a_rel["retried"] == 2
    # one merged timeline, every entry engine-tagged, sorted by time
    engines_seen = {r["engine"] for r in doc["timeline"]}
    assert engines_seen == {"A", "B"}
    ts = [r["t"] for r in doc["timeline"]]
    assert ts == sorted(ts)

    # (b): the postmortem render (text mode)
    assert report_main([a_dir, b_dir, "--postmortem"]) == 0
    text = capsys.readouterr().out
    assert "per-request waterfalls [A]" in text
    assert "(reconciled)" in text
    assert "postmortem [A]" in text and "quarantine" in text
    assert "FINITE" in text              # the non-finite evidence row
    assert "postmortem [B]: no flight-recorder dump" in text
    # the quarantined-and-retried arc is on the merged timeline
    assert "QUARANTINED" in text and "RETRIED" in text


def test_report_single_stream_waterfall_render(lm_params, prompts,
                                               tmp_path, capsys):
    """Single-dir report keeps its PR 2-era layout and adds the
    waterfall section when span records exist."""
    from distributed_llm_code_samples_tpu.report import report_main
    mdir = str(tmp_path / "m")
    with TelemetryWriter(mdir, meta={"engine_id": "solo"}) as w:
        DecodeEngine(lm_params, H, EngineConfig(**BASE),
                     metrics=w).generate(prompts, 6, log_every=2)
    capsys.readouterr()
    assert report_main([mdir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    # single-stream: sections stay top-level (no engines envelope)
    assert "engines" not in doc and "serving" in doc
    assert doc["waterfalls"] and all(
        w["reconciled"] for w in doc["waterfalls"].values())
    assert report_main([mdir]) == 0
    text = capsys.readouterr().out
    assert "per-request waterfalls" in text and "queued" in text


def test_report_dedups_replayed_spans(tmp_path, capsys):
    """An in-process restart re-emits span records for replayed steps
    byte-identical in (uid, span, start_step, step) — the report keeps
    one copy, so waterfall sums don't double-count (the request-record
    dedup stance applied to spans)."""
    from distributed_llm_code_samples_tpu.report import report_main
    mdir = str(tmp_path / "m")
    span = {"uid": 0, "span": "decode", "start_step": 2, "step": 5,
            "start_t": 10.0, "t": 11.0, "duration_s": 1.0}
    queued = {"uid": 0, "span": "queued", "start_step": 0, "step": 2,
              "start_t": 9.0, "t": 10.0, "duration_s": 1.0}
    with TelemetryWriter(mdir) as w:
        w.span(queued)
        w.span(span)
        w.span(dict(span))          # the restart's replay
        w.request({"step": 5, "uid": 0, "event": "completed",
                   "reason": None, "latency_s": 2.0, "ttft_s": 1.0,
                   "t": 11.0})
    capsys.readouterr()
    assert report_main([mdir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    w0 = doc["waterfalls"]["0"]
    assert len(w0["spans"]) == 2
    assert w0["span_sum_s"] == pytest.approx(2.0)
    assert w0["reconciled"]
    # the v9 decomposition: ttft + the (deduped) post-first-token span
    # telescopes to the latency too
    assert w0["ttft_s"] == 1.0
    assert w0["ttft_plus_post_s"] == pytest.approx(2.0)
    assert w0["ttft_reconciled"]
