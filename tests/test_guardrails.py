"""In-graph guardrail tests (runtime/guardrails.py + the launcher wrap).

The bar (ISSUE r8): a poisoned step inside a compiled multi-step chunk
is skipped IN-GRAPH — params and optimizer state untouched, zero host
round-trips, zero restarts — on every strategy with the guard surface
(single, DDP, FSDP, LM TP), with per-chunk counters that flow to the
telemetry stream. Skip accounting is exact: the guarded run equals the
same guarded trainer over the schedule with the poisoned step removed,
bit for bit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_code_samples_tpu.data import (
    POISON_INF_BIT, POISON_NAN_BIT, batch_from_seed, make_seed_schedule)
from distributed_llm_code_samples_tpu.models import init_ffn_stack
from distributed_llm_code_samples_tpu.parallel import (
    DATA_AXIS, make_mesh, train_ddp, train_fsdp, train_single)
from distributed_llm_code_samples_tpu.runtime.guardrails import (
    GuardState, GuardrailConfig, advance, check_guard_args,
    clip_by_global_norm, finite_flag, init_state, summarize)

BS, D, L = 32, 16, 2


@pytest.fixture
def params():
    return init_ffn_stack(jax.random.PRNGKey(0), D, L)


def _poison(seeds, idx, bit=POISON_NAN_BIT):
    s = np.array(seeds)
    s[idx] |= bit
    return s


# ------------------------------------------------------------------ units

def test_finite_flag_over_mixed_trees():
    ok = finite_flag({"a": jnp.ones(3), "n": jnp.arange(3)})
    assert bool(ok)
    bad = finite_flag((jnp.ones(3), jnp.asarray([1.0, jnp.nan])))
    assert not bool(bad)
    # integer leaves never poison the flag (Adam counts, seeds)
    assert bool(finite_flag({"count": jnp.asarray(7, jnp.int32)}))


def test_poison_bits_produce_poisoned_dy_same_x():
    x0, dy0 = batch_from_seed(jnp.int32(123), 8, D)
    x1, dy1 = batch_from_seed(jnp.int32(123 | POISON_NAN_BIT), 8, D)
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(x1))
    assert np.all(np.isnan(np.asarray(dy1)))
    _, dy2 = batch_from_seed(jnp.int32(123 | POISON_INF_BIT), 8, D)
    assert np.all(np.isinf(np.asarray(dy2)))


def test_advance_scale_schedule():
    cfg = GuardrailConfig(loss_scale=1024.0, growth_interval=2,
                          scale_backoff=0.5, min_scale=4.0)
    g = init_state(cfg)
    ok = jnp.asarray(True)
    bad = jnp.asarray(False)
    g = advance(cfg, g, ok)          # good step 1
    assert summarize(g) == {"skipped": 0, "overflows": 0,
                            "loss_scale": 1024.0, "good_steps": 1}
    g = advance(cfg, g, ok)          # good step 2 -> grow, counter resets
    assert summarize(g)["loss_scale"] == 2048.0
    assert summarize(g)["good_steps"] == 0
    g = advance(cfg, g, bad)         # overflow -> halve, count both ways
    s = summarize(g)
    assert s == {"skipped": 1, "overflows": 1, "loss_scale": 1024.0,
                 "good_steps": 0}
    for _ in range(12):              # shrink floor: min_scale holds
        g = advance(cfg, g, bad)
    assert summarize(g)["loss_scale"] == 4.0


def test_check_guard_args_contract():
    with pytest.raises(ValueError, match="guard config"):
        check_guard_args(None, None, True)
    with pytest.raises(TypeError, match="GuardrailConfig"):
        check_guard_args({"clip_norm": 1.0}, None, False)
    check_guard_args(GuardrailConfig(), None, True)  # fine


# -------------------------------------------------- in-graph skip per strategy

def test_single_skip_is_exact_and_counted(params):
    """The headline contract: a NaN step inside one compiled scan is
    where-skipped — the final params are BIT-IDENTICAL to the same
    guarded program run without that step's seed."""
    cfg = GuardrailConfig()
    seeds = np.asarray(make_seed_schedule(8, 3))
    out, g = train_single(params, _poison(seeds, 2), BS, D, lr=0.1,
                          guard=cfg, return_guard=True)
    assert summarize(g)["skipped"] == 1
    oracle = train_single(params, np.delete(seeds, 2), BS, D, lr=0.1,
                          guard=cfg)
    np.testing.assert_array_equal(np.asarray(out.w1), np.asarray(oracle.w1))
    np.testing.assert_array_equal(np.asarray(out.w2), np.asarray(oracle.w2))


def test_single_clean_run_unaffected(params):
    """guard on + no fault == guard off, bit for bit (the where-select
    is value-transparent on finite steps)."""
    seeds = make_seed_schedule(6, 3)
    ref = train_single(params, seeds, BS, D, lr=0.1)
    out, g = train_single(params, seeds, BS, D, lr=0.1,
                          guard=GuardrailConfig(), return_guard=True)
    assert summarize(g)["skipped"] == 0
    np.testing.assert_array_equal(np.asarray(out.w1), np.asarray(ref.w1))


def test_ddp_skip_drops_whole_update(params):
    """One poisoned rank poisons the psum — the guarded DDP step skips
    the WHOLE update on every shard (the psum'd finite flag keeps the
    replicated params consistent), exactly equal to the run without
    that update's 8-seed group."""
    cfg = GuardrailConfig()
    mesh = make_mesh({DATA_AXIS: 8})
    seeds = np.asarray(make_seed_schedule(24, 3))
    out, g = train_ddp(params, _poison(seeds, 9), BS, D, mesh, lr=0.1,
                       guard=cfg, return_guard=True)
    assert summarize(g)["skipped"] == 1
    oracle = train_ddp(params, np.delete(seeds, slice(8, 16)), BS, D,
                       mesh, lr=0.1, guard=cfg)
    np.testing.assert_array_equal(np.asarray(out.w1), np.asarray(oracle.w1))
    np.testing.assert_array_equal(np.asarray(out.w2), np.asarray(oracle.w2))


def test_fsdp_skip_keeps_shards_consistent_with_optimizer(params):
    """FSDP's finite flag is psum-reduced from per-shard views; a skip
    must leave sharded params AND sharded Adam state untouched — the
    poisoned update never perturbs the moments."""
    from distributed_llm_code_samples_tpu.optim import adam
    cfg = GuardrailConfig()
    mesh = make_mesh({DATA_AXIS: 8})
    seeds = np.asarray(make_seed_schedule(16, 3))
    opt = adam()
    (out, state), g = train_fsdp(params, _poison(seeds, 3), BS, D, mesh,
                                 lr=0.1, optimizer=opt, return_state=True,
                                 guard=cfg, guard_state=None,
                                 return_guard=True)
    assert summarize(g)["skipped"] == 1
    (ref, ref_state) = train_fsdp(params, np.delete(seeds, slice(0, 8)),
                                  BS, D, mesh, lr=0.1, optimizer=opt,
                                  return_state=True, guard=cfg)
    np.testing.assert_array_equal(np.asarray(out.w1), np.asarray(ref.w1))
    np.testing.assert_array_equal(np.asarray(state.mu.w1),
                                  np.asarray(ref_state.mu.w1))
    # Adam's count must NOT have advanced on the skipped step
    assert int(state.count) == int(ref_state.count) == 1


def test_lm_tp_guard_surface():
    """The launcher-level wrap reaches the LM family too: train_lm_tp
    runs guarded (replicated data, model-axis mesh) and reports clean
    counters on a clean run, same params as unguarded."""
    from distributed_llm_code_samples_tpu.models import init_lm
    from distributed_llm_code_samples_tpu.parallel import (MODEL_AXIS,
                                                           train_lm_tp)
    lm = init_lm(jax.random.PRNGKey(1), 32, D, 1, max_seq_len=8,
                 n_heads=4)
    mesh = make_mesh({MODEL_AXIS: 2})
    seeds = make_seed_schedule(4, 3)
    ref = train_lm_tp(lm, seeds, 2 * 8, D, mesh, lr=0.01, seq_len=8,
                      n_heads=4)
    out, g = train_lm_tp(lm, seeds, 2 * 8, D, mesh, lr=0.01, seq_len=8,
                         n_heads=4, guard=GuardrailConfig(),
                         return_guard=True)
    assert summarize(g)["skipped"] == 0
    np.testing.assert_array_equal(np.asarray(out.wte), np.asarray(ref.wte))


# ------------------------------------------------- dynamic loss scaling

def test_ddp_mixed_dynamic_scale_grows(params):
    """Clean mixed run with growth_interval=1: every finite update
    doubles the scale (2 updates on the 8-way mesh from 16 seeds)."""
    mesh = make_mesh({DATA_AXIS: 8})
    seeds = make_seed_schedule(16, 3)
    cfg = GuardrailConfig(loss_scale=1024.0, growth_interval=1)
    out, g = train_ddp(params, seeds, BS, D, mesh, lr=0.1, mixed=True,
                       guard=cfg, return_guard=True)
    s = summarize(g)
    assert s["skipped"] == 0 and s["loss_scale"] == 4096.0
    # scaling is exact in value: scale * dy backward / scale == dy backward
    ref = train_ddp(params, seeds, BS, D, mesh, lr=0.1, mixed=True)
    np.testing.assert_allclose(np.asarray(out.w1), np.asarray(ref.w1),
                               rtol=2e-2, atol=1e-4)


def test_ddp_mixed_overflow_shrinks_and_skips(params):
    """The shrink half of the grow/shrink loop: two non-finite updates
    (deterministic inf injections in distinct scan steps) each skip the
    update AND halve the scale, and the surviving update sequence
    equals the clean run without those two 8-seed groups."""
    mesh = make_mesh({DATA_AXIS: 8})
    seeds = np.asarray(make_seed_schedule(24, 3))
    bad = _poison(_poison(seeds, 1, POISON_INF_BIT), 17, POISON_INF_BIT)
    cfg = GuardrailConfig(loss_scale=1024.0, scale_backoff=0.5)
    out, g = train_ddp(params, bad, BS, D, mesh, lr=0.1, mixed=True,
                       guard=cfg, return_guard=True)
    s = summarize(g)
    assert s["skipped"] == 2 and s["overflows"] == 2
    assert s["loss_scale"] == pytest.approx(256.0)
    oracle = train_ddp(params, seeds[8:16], BS, D, mesh, lr=0.1,
                       mixed=True, guard=cfg)
    np.testing.assert_allclose(np.asarray(out.w1), np.asarray(oracle.w1),
                               rtol=1e-6, atol=1e-8)


def test_scaling_requires_mixed(params):
    mesh = make_mesh({DATA_AXIS: 8})
    with pytest.raises(ValueError, match="mixed"):
        train_ddp(params, make_seed_schedule(8, 3), BS, D, mesh,
                  guard=GuardrailConfig(loss_scale=128.0))
    with pytest.raises(ValueError, match="mixed"):
        train_fsdp(params, make_seed_schedule(8, 3), BS, D, mesh,
                   guard=GuardrailConfig(loss_scale=128.0))


def test_scaling_rejected_without_a_scale_hook(params):
    """A scaling config on a strategy with no loss-scale hook would
    never scale anything while the GuardState schedule still moved —
    refuse it loudly everywhere the hook is missing."""
    from distributed_llm_code_samples_tpu.models import init_lm
    from distributed_llm_code_samples_tpu.parallel import (MODEL_AXIS,
                                                           train_lm_tp)
    cfg = GuardrailConfig(loss_scale=128.0)
    with pytest.raises(ValueError, match="loss-scale hook"):
        train_single(params, make_seed_schedule(4, 3), BS, D, lr=0.1,
                     guard=cfg)
    lm = init_lm(jax.random.PRNGKey(1), 32, D, 1, max_seq_len=8,
                 n_heads=4)
    with pytest.raises(ValueError, match="loss-scale hook"):
        train_lm_tp(lm, make_seed_schedule(4, 3), 2 * 8, D,
                    make_mesh({MODEL_AXIS: 2}), lr=0.01, seq_len=8,
                    n_heads=4, guard=cfg)


# ----------------------------------------------------------- clipping

def test_guard_clip_matches_optimizer_clip(params):
    """guardrails.clip_by_global_norm == optim.clipped on the same run:
    the stateless-SGD guard clip and the optimizer-wrap clip implement
    one formula."""
    from distributed_llm_code_samples_tpu.optim import clipped, sgd_optimizer
    mesh = make_mesh({DATA_AXIS: 8})
    seeds = make_seed_schedule(8, 3)
    via_opt = train_ddp(params, seeds, BS, D, mesh, lr=0.1,
                        optimizer=clipped(sgd_optimizer(), 0.05))
    via_guard = train_ddp(params, seeds, BS, D, mesh, lr=0.1,
                          guard=GuardrailConfig(clip_norm=0.05))
    np.testing.assert_allclose(np.asarray(via_opt.w1),
                               np.asarray(via_guard.w1),
                               rtol=1e-6, atol=1e-8)


def test_clip_by_global_norm_scales_to_bound():
    g = {"a": jnp.full((4,), 3.0), "b": jnp.full((4,), 4.0)}
    clipped_tree = clip_by_global_norm(g, 1.0)
    total = np.sqrt(sum(float(jnp.sum(jnp.square(v)))
                        for v in clipped_tree.values()))
    assert total == pytest.approx(1.0, rel=1e-5)


# -------------------------------------------- state threading across chunks

def test_guard_state_threads_across_chunked_calls(params):
    """The log_every contract: chunked trainer calls thread the guard
    state, so counters are cumulative and one poisoned chunk doesn't
    reset another's scale."""
    cfg = GuardrailConfig()
    seeds = np.asarray(make_seed_schedule(8, 3))
    bad = _poison(_poison(seeds, 1), 6)
    out1, g1 = train_single(params, bad[:4], BS, D, lr=0.1, guard=cfg,
                            return_guard=True)
    out2, g2 = train_single(out1, bad[4:], BS, D, lr=0.1, guard=cfg,
                            guard_state=g1, return_guard=True)
    assert summarize(g1)["skipped"] == 1
    assert summarize(g2)["skipped"] == 2
    whole, gw = train_single(params, bad, BS, D, lr=0.1, guard=cfg,
                             return_guard=True)
    assert summarize(gw)["skipped"] == 2
    np.testing.assert_array_equal(np.asarray(out2.w1), np.asarray(whole.w1))


def test_anomaly_delta_builds_per_chunk_records():
    """Both chunk drivers emit through anomaly_delta: deltas per chunk,
    totals alongside, None (no record) when nothing advanced."""
    from distributed_llm_code_samples_tpu.runtime.guardrails import (
        anomaly_delta)
    prev = {"skipped": 1, "overflows": 1, "loss_scale": 512.0,
            "good_steps": 0}
    cur = {"skipped": 3, "overflows": 1, "loss_scale": 512.0,
           "good_steps": 4}
    rec = anomaly_delta(prev, cur, 8, [5, 8])
    assert rec == {"step": 8, "steps": [5, 8], "skipped": 2,
                   "total_skipped": 3, "overflows": 0,
                   "total_overflows": 1, "loss_scale": 512.0}
    assert anomaly_delta(cur, cur, 12, [9, 12]) is None


def test_guard_state_is_a_small_scalar_tree():
    g = init_state(GuardrailConfig(loss_scale=2.0))
    assert isinstance(g, GuardState)
    assert all(np.asarray(leaf).ndim == 0
               for leaf in jax.tree_util.tree_leaves(g))
