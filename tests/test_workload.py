"""The trace-driven workload observatory (runtime/workload.py +
decode/workload_driver.py, DESIGN.md section 25): seeded trace
generation, the versioned trace file's rejection discipline, and the
replay contract — same (trace, seed) yields byte-identical tokens,
identical admission order, and identical schema-v13 ``workload``
records through the single engine AND the fleet, with chaos (a
mid-trace kill) composing on top token-identically and the migrated
requests' tenant attribution intact. Model/config shapes are the
shared test fixtures (V=64, D=32, L=2, H=4, BASE blocks) so compiled
programs hit the persistent XLA cache.
"""

import contextlib
import io
import json
import os

import jax
import numpy as np
import pytest

from distributed_llm_code_samples_tpu.checkpoint import save_checkpoint
from distributed_llm_code_samples_tpu.decode import (DecodeEngine,
                                                     EngineConfig,
                                                     FleetRouter,
                                                     ServePolicy)
from distributed_llm_code_samples_tpu.decode.workload_driver import (
    WorkloadDriver, replay_trace)
from distributed_llm_code_samples_tpu.models import init_lm
from distributed_llm_code_samples_tpu.runtime.telemetry import (
    METRICS_FILENAME, TelemetryWriter, read_metrics, validate_record)
from distributed_llm_code_samples_tpu.runtime.workload import (
    TRACE_VERSION, TraceError, generate_trace, materialize_prompt,
    parse_trace_spec, read_trace, trace_id_of, write_trace)

V, D, L, H = 64, 32, 2, 4
BASE = dict(block_size=8, n_blocks=33, max_slots=3, max_blocks_per_seq=6,
            prefill_chunk=8)

# the canonical 2-tenant bursty spec most tests replay (tiny but
# real: on/off bursts, heavy-tail lengths, a weighted tenant mix)
SPEC = ("n=10,arrival=bursty:40:0.2:0.3,plen=zipf:1.7:3:12,max_new=4,"
        "tenants=a:3;b:1,seed=5")


@pytest.fixture(scope="module")
def lm_params():
    return init_lm(jax.random.PRNGKey(0), V, D, L, max_seq_len=64)


def _cfg(**extra):
    return EngineConfig(**{**BASE, **extra})


def _strip_t(rec: dict) -> dict:
    """A workload record minus its wall-clock envelope — everything
    that must replay identically."""
    return {k: v for k, v in rec.items() if k not in ("t",)}


# ---------------------------------------------------------------------------
# the trace generator + file format (runtime/workload.py)


def test_trace_spec_rejections():
    """The --chaos parse-rejection discipline: every malformed spec is
    ONE ValueError naming the offense."""
    for bad, frag in [
        ("", "n=INT is required"),
        ("n=0", "must be >= 1"),
        ("n=banana", "integer"),
        ("n=2,arrival=weird:1", "arrival kind"),
        ("n=2,arrival=poisson", "poisson takes 1"),
        ("n=2,arrival=bursty:4:0.1", "bursty takes 3"),
        ("n=2,arrival=poisson:0", "must be > 0"),
        ("n=2,plen=zipf:0.5:1:4", "alpha"),
        ("n=2,plen=uniform:9:4", "hi 4 < lo 9"),
        ("n=2,plen=gauss:3", "known samplers"),
        ("n=2,tenants=a:0", "must be > 0"),
        ("n=2,tenants=a:1;a:2", "duplicate tenant"),
        ("n=2,tenants=", "empty mix"),
        ("n=2,sessions=0", "K >= 1"),
        ("n=2,sessions=2:0", "grow"),
        ("n=2,seed=x", "seed"),
        ("n=2,n=3", "duplicate key"),
        ("n=2,bogus=1", "known keys"),
        ("n=2,arrival", "key=value"),
    ]:
        with pytest.raises(ValueError) as e:
            parse_trace_spec(bad)
        assert frag in str(e.value), (bad, str(e.value))
        assert "\n" not in str(e.value)


def test_trace_generation_deterministic_and_file_round_trip(tmp_path):
    """Same (spec, seed) -> identical entries and the SAME stable
    trace id (no wall clock, no process entropy); the written file
    round-trips exactly."""
    h1, e1 = generate_trace(SPEC)
    h2, e2 = generate_trace(SPEC)
    assert (h1, e1) == (h2, e2)
    assert h1["id"] == trace_id_of(SPEC, 5)
    assert h1["trace_version"] == TRACE_VERSION and h1["n"] == 10
    # a different seed is a different identity
    assert generate_trace(SPEC.replace("seed=5", "seed=6"))[0]["id"] \
        != h1["id"]
    path = str(tmp_path / "t.jsonl")
    write_trace(path, h1, e1)
    h3, e3 = read_trace(path)
    assert (h3, e3) == (h1, e1)
    # offsets are non-decreasing, first at 0; tenants drawn from the mix
    offs = [x["t_offset_s"] for x in e1]
    assert offs[0] == 0.0 and offs == sorted(offs)
    assert {x["tenant"] for x in e1} <= {"a", "b"}
    assert all(3 <= x["prompt_len"] <= 12 for x in e1)


def test_trace_file_rejection_discipline(tmp_path):
    """A trace is a determinism proof's input: torn tails, version
    skew, missing keys, and non-monotonic offsets are one-line
    TraceErrors, never a best-effort parse."""
    header, entries = generate_trace("n=3,plen=fixed:4,max_new=2")
    path = str(tmp_path / "t.jsonl")
    write_trace(path, header, entries)

    def rewrite(mutate):
        h, es = json.loads(json.dumps(header)), \
            [dict(x) for x in entries]
        mutate(h, es)
        with open(path, "w") as f:
            f.write("\n".join([json.dumps(h)]
                              + [json.dumps(x) for x in es]) + "\n")

    with open(path, "a") as f:
        f.write('{"torn')
    with pytest.raises(TraceError, match="unparseable"):
        read_trace(path)
    rewrite(lambda h, es: h.update(trace_version=99))
    with pytest.raises(TraceError, match="trace_version"):
        read_trace(path)
    rewrite(lambda h, es: h.pop("id"))
    with pytest.raises(TraceError, match="header missing"):
        read_trace(path)
    rewrite(lambda h, es: es[1].pop("max_new"))
    with pytest.raises(TraceError, match="max_new"):
        read_trace(path)
    rewrite(lambda h, es: es[2].update(t_offset_s=-1.0))
    with pytest.raises(TraceError, match="non-decreasing"):
        read_trace(path)
    rewrite(lambda h, es: es.pop())
    with pytest.raises(TraceError, match="torn tail"):
        read_trace(path)
    with pytest.raises(TraceError, match="empty"):
        open(path, "w").close() or read_trace(path)
    with pytest.raises(TraceError):
        read_trace(str(tmp_path / "missing.jsonl"))


def test_arrival_processes_have_their_shapes():
    """bursty leaves OFF-window silences, ramp accelerates, zipf is
    bounded with a heavy tail — the shapes the fixed waves never had."""
    _, eb = generate_trace("n=40,arrival=bursty:50:0.1:0.5,"
                           "plen=fixed:4,max_new=2,seed=1")
    gaps = np.diff([x["t_offset_s"] for x in eb])
    assert (gaps >= 0.5).sum() >= 2, "no OFF-window silences"
    assert (gaps < 0.1).sum() >= 20, "no in-burst clustering"
    _, er = generate_trace("n=60,arrival=ramp:2:60,plen=fixed:4,"
                           "max_new=2,seed=1")
    rg = np.diff([x["t_offset_s"] for x in er])
    assert rg[:15].mean() > 3 * rg[-15:].mean(), "ramp not ramping"
    _, ez = generate_trace("n=200,plen=zipf:1.3:4:40,max_new=2,seed=2")
    lens = [x["prompt_len"] for x in ez]
    assert min(lens) >= 4 and max(lens) <= 40
    assert max(lens) >= 3 * int(np.median(lens)), "no heavy tail"


def test_session_prompts_regrow_shared_prefixes(lm_params):
    """A session's turn t+1 prompt literally startswith turn t's (one
    fixed per-session stream), and replaying the session trace through
    a prefix-cached engine HITS: the chat-shaped workload the radix
    cache exists for."""
    header, entries = generate_trace(
        "n=6,sessions=2:8,plen=fixed:8,max_new=2,seed=3")
    by_session = {}
    for e in entries:
        by_session.setdefault(e["session"], []).append(e)
    for ses, turns in by_session.items():
        assert [t["turn"] for t in turns] == list(range(len(turns)))
        toks = [materialize_prompt(header, t, V) for t in turns]
        for a, b in zip(toks, toks[1:]):
            assert b[:len(a)] == a and len(b) == len(a) + 8
    # distinct sessions diverge (different streams)
    t0 = materialize_prompt(header, by_session["s0"][0], V)
    t1 = materialize_prompt(header, by_session["s1"][0], V)
    assert t0 != t1
    eng = DecodeEngine(lm_params, H, _cfg(max_slots=1))
    replay_trace(eng, header, entries, vocab=V)
    assert eng.prefix_hit_blocks > 0
    assert len(eng.finished) == 6 and not eng.failed


# ---------------------------------------------------------------------------
# replay determinism (the tentpole contract)


def test_single_engine_replay_deterministic_and_host_side_only(
        lm_params, tmp_path):
    """Two replays of one (trace, seed): byte-identical tokens and
    identical admission order; and trace-driven admission is HOST-side
    only — zero new compiles vs the same prompts submitted by hand
    (the overhead criterion, asserted on compile_count)."""
    header, entries = generate_trace(SPEC)

    def run(mdir):
        m = TelemetryWriter(mdir)
        eng = DecodeEngine(lm_params, H, _cfg(), metrics=m)
        summary = replay_trace(eng, header, entries, vocab=V,
                               log_every=4, metrics=m)
        m.close()
        recs, problems = read_metrics(os.path.join(mdir,
                                                   METRICS_FILENAME))
        assert not problems, problems
        return eng, summary, recs

    e1, s1, r1 = run(str(tmp_path / "m1"))
    e2, s2, r2 = run(str(tmp_path / "m2"))
    assert e1.finished == e2.finished and not e1.failed
    assert s1 == s2
    admits1 = [(r["uid"], r["step"]) for r in r1
               if r["kind"] == "request" and r["event"] == "admitted"]
    admits2 = [(r["uid"], r["step"]) for r in r2
               if r["kind"] == "request" and r["event"] == "admitted"]
    assert admits1 == admits2 and admits1
    wl1 = [_strip_t(r) for r in r1 if r["kind"] == "workload"]
    wl2 = [_strip_t(r) for r in r2 if r["kind"] == "workload"]
    assert wl1 == wl2 and wl1
    for r in r1:
        if r["kind"] == "workload":
            ok, reason = validate_record(r)
            assert ok, reason
    # every record for an admitted uid carries its tenant
    by_uid_tenant = {e["uid_hint"]: e["tenant"] for e in entries}
    for r in r1:
        if r["kind"] == "request" and r["event"] == "completed":
            assert r["tenant"] in ("a", "b")
    # the overhead criterion: hand-submit the SAME materialized
    # prompts — same program set, zero compiles the trace path adds
    hand = DecodeEngine(lm_params, H, _cfg())
    for e in entries:
        hand.submit(materialize_prompt(header, e, V),
                    int(e["max_new"]))
    hand.run()
    assert e1.compile_count == hand.compile_count
    assert hand.finished != {}  # sanity: the hand run really ran
    del by_uid_tenant


def test_fleet_replay_deterministic_with_identical_workload_records(
        lm_params, tmp_path):
    """The acceptance determinism drill, in-process: the same
    (trace, seed) through a 3-engine fleet twice — byte-identical
    tokens, identical admission order (router records), identical
    schema-v13 workload records — and the fleet's tokens equal the
    single-engine replay's (the routing layer moves placement, never
    content)."""
    header, entries = generate_trace(SPEC)

    def run(tag):
        mdir = str(tmp_path / tag)
        writers = []

        def mk(eid):
            m = TelemetryWriter(os.path.join(mdir, eid))
            writers.append(m)
            return DecodeEngine(lm_params, H, _cfg(), metrics=m)

        rm = TelemetryWriter(os.path.join(mdir, "router"))
        writers.append(rm)
        fl = FleetRouter(mk, 3, metrics=rm)
        summary = replay_trace(fl, header, entries, vocab=V,
                               log_every=4, metrics=rm)
        outs = fl.results()
        for w in writers:
            w.close()
        recs, problems = read_metrics(
            os.path.join(mdir, "router", METRICS_FILENAME))
        assert not problems, problems
        return outs, summary, recs

    o1, s1, r1 = run("f1")
    o2, s2, r2 = run("f2")
    assert o1 == o2 and s1 == s2
    routed1 = [(r["uid"], r["target"], r["step"]) for r in r1
               if r["kind"] == "router" and r["event"] == "routed"]
    routed2 = [(r["uid"], r["target"], r["step"]) for r in r2
               if r["kind"] == "router" and r["event"] == "routed"]
    assert routed1 == routed2 and len(routed1) == len(entries)
    wl1 = [_strip_t(r) for r in r1 if r["kind"] == "workload"]
    wl2 = [_strip_t(r) for r in r2 if r["kind"] == "workload"]
    assert wl1 == wl2 and wl1
    # single-engine replay of the same trace: same tokens
    eng = DecodeEngine(lm_params, H, _cfg())
    replay_trace(eng, header, entries, vocab=V)
    assert eng.finished == o1


def test_kill_mid_trace_token_identity_and_tenant_attribution(
        lm_params, tmp_path):
    """Chaos composes ON TOP of replay: the same trace with e1 killed
    mid-trace completes byte-identically to the unkilled replay, and
    the migrated requests' completed records keep their tenant tags
    (the per-tenant numbers survive the migration)."""
    header, entries = generate_trace(SPEC)
    oracle = DecodeEngine(lm_params, H, _cfg())
    replay_trace(oracle, header, entries, vocab=V)

    mdir = str(tmp_path / "killed")
    writers = []

    def mk(eid):
        m = TelemetryWriter(os.path.join(mdir, eid))
        writers.append(m)
        return DecodeEngine(lm_params, H, _cfg(), metrics=m)

    rm = TelemetryWriter(os.path.join(mdir, "router"))
    writers.append(rm)
    fl = FleetRouter(mk, 3, metrics=rm)
    fl.schedule_kill("e1", 4)
    summary = replay_trace(fl, header, entries, vocab=V, log_every=4,
                           metrics=rm)
    outs = fl.results()
    for w in writers:
        w.close()
    assert outs == oracle.finished, \
        "killed replay diverged from the unkilled oracle"
    assert not fl.failed()
    rrecs, problems = read_metrics(
        os.path.join(mdir, "router", METRICS_FILENAME))
    assert not problems, problems
    migrated = {r["uid"] for r in rrecs if r["kind"] == "router"
                and r["event"] == "migrated"}
    assert migrated, "the kill migrated nothing — drill vacuous"
    # the driver's uid->tenant book is authoritative for the trace;
    # every migrated uid's completed record (on whichever engine) must
    # carry that tenant verbatim
    tenant_of = {}
    recs_all = []
    for eid in ("e0", "e1", "e2"):
        recs, _ = read_metrics(os.path.join(mdir, eid,
                                            METRICS_FILENAME))
        recs_all.extend(recs)
    for r in recs_all:
        if r["kind"] == "request" and r["event"] == "admitted" \
                and r["uid"] not in tenant_of:
            tenant_of[r["uid"]] = r["tenant"]
    for r in recs_all:
        if r["kind"] == "request" and r["event"] == "completed" \
                and r["uid"] in migrated:
            assert r["tenant"] == tenant_of[r["uid"]] \
                and r["tenant"] in ("a", "b"), r
    # workload totals still reconcile after the kill
    last_wl = [r for r in
               read_metrics(os.path.join(
                   mdir, "router", METRICS_FILENAME))[0]
               if r["kind"] == "workload"][-1]
    per_tenant = {e["uid_hint"]: e["tenant"] for e in entries}
    want = {}
    for t in per_tenant.values():
        want[t] = want.get(t, 0) + 1
    got = {t: c["completed"] for t, c in last_wl["tenants"].items()}
    assert got == want, (got, want)
    del summary


# ---------------------------------------------------------------------------
# the noisy-tenant drill + report surfaces


def test_noisy_tenant_starvation_visible_and_reconciled(lm_params,
                                                        tmp_path):
    """One tenant floods at t=0, one trickles in behind: FCFS lets the
    flood starve the trickle, and the report's per-tenant numbers must
    RENDER that (quiet's TTFT p50 well above noisy's) while the
    per-tenant counts reconcile with the fleet totals — the baseline a
    future QoS scheduler PR must move."""
    from distributed_llm_code_samples_tpu.report import report_main
    header = {"trace_version": 1, "id": "trnoisy", "seed": 0,
              "spec": "hand", "n": 10}
    entries = (
        [{"t_offset_s": 0.0, "uid_hint": i, "tenant": "noisy",
          "session": None, "prompt_len": 6, "max_new": 6, "turn": 0}
         for i in range(8)]
        + [{"t_offset_s": 0.1, "uid_hint": 8 + j, "tenant": "quiet",
            "session": None, "prompt_len": 6, "max_new": 6, "turn": 0}
           for j in range(2)])
    mdir = str(tmp_path / "m")
    m = TelemetryWriter(mdir)
    eng = DecodeEngine(lm_params, H, _cfg(max_slots=2))
    # warm the program set FIRST (same shapes as the trace), with no
    # writer attached: the starvation assertion below compares
    # wall-clock TTFTs, and a cold compile inside the flood's service
    # would swamp the queueing signal being measured
    rng = np.random.default_rng(9)
    for _ in range(2):
        eng.submit(rng.integers(0, V, size=6).tolist(), 6)
    eng.run()
    eng.metrics = m
    replay_trace(eng, header, entries, vocab=V, log_every=4, metrics=m)
    m.close()
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = report_main([mdir, "--slo", "100:0.000001", "--json"])
    assert rc == 0
    doc = json.loads(buf.getvalue())
    wl = doc["workload"]
    assert wl["reconciled"], wl
    assert wl["tenants"]["noisy"]["completed"] == 8
    assert wl["tenants"]["quiet"]["completed"] == 2
    assert sum(e["completed"] for e in wl["tenants"].values()) \
        == wl["completed_total"] == 10
    # the starvation: the quiet requests queue behind the whole flood
    # (FCFS admits them last), so their median TTFT sits above the
    # noisy tenant's — the number a future QoS scheduler must move
    assert wl["tenants"]["quiet"]["ttft_p50_s"] > \
        wl["tenants"]["noisy"]["ttft_p50_s"], wl["tenants"]
    # the per-tenant SLO slice counts reconcile too
    bt = doc["slo"]["by_tenant"]
    assert bt["noisy"]["completed"] == 8
    assert bt["quiet"]["completed"] == 2
    assert sum(b["completed"] for b in bt.values()) \
        == doc["slo"]["completed"]
    # the text render names both tenants
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = report_main([mdir, "--slo", "100:0.000001"])
    assert rc == 0
    text = buf.getvalue()
    assert "tenant noisy" in text and "tenant quiet" in text
    assert "offered vs admitted" in text


def test_wfq_flips_noisy_tenant_baseline(lm_params, tmp_path):
    """The QoS scheduler moves the recorded FCFS baseline on the SAME
    (trace, seed): under weighted-fair scheduling (quiet:3;noisy:1)
    the quiet tenant's median TTFT is no longer above the noisy
    flood's, per-tenant counts still reconcile with the fleet totals,
    and every token is byte-identical to the FCFS run — fairness
    reorders ADMISSION, never sampling identity."""
    from distributed_llm_code_samples_tpu.report import report_main
    from distributed_llm_code_samples_tpu.runtime.policy import (
        QosPolicy)
    header = {"trace_version": 1, "id": "trnoisy", "seed": 0,
              "spec": "hand", "n": 10}
    entries = (
        [{"t_offset_s": 0.0, "uid_hint": i, "tenant": "noisy",
          "session": None, "prompt_len": 6, "max_new": 6, "turn": 0}
         for i in range(8)]
        + [{"t_offset_s": 0.1, "uid_hint": 8 + j, "tenant": "quiet",
            "session": None, "prompt_len": 6, "max_new": 6, "turn": 0}
           for j in range(2)])

    def warmed(qos=None):
        eng = DecodeEngine(lm_params, H, _cfg(max_slots=2), qos=qos)
        # warm the program set FIRST (same shapes), no writer: the
        # flip assertion compares wall-clock TTFTs — a cold compile
        # inside the flood would swamp the queueing signal
        rng = np.random.default_rng(9)
        for _ in range(2):
            eng.submit(rng.integers(0, V, size=6).tolist(), 6)
        eng.run()
        return eng

    fcfs = warmed()
    replay_trace(fcfs, header, entries, vocab=V)
    mdir = str(tmp_path / "m")
    m = TelemetryWriter(mdir)
    wfq = warmed(qos=QosPolicy(discipline="wfq",
                               weights=(("quiet", 3), ("noisy", 1))))
    wfq.metrics = m
    replay_trace(wfq, header, entries, vocab=V, log_every=4, metrics=m)
    m.close()
    # token identity across disciplines: keys fold (seed, uid,
    # position), so the fair schedule changed WHEN, never WHAT
    assert wfq.finished == fcfs.finished
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = report_main([mdir, "--slo", "100:0.000001", "--json"])
    assert rc == 0
    doc = json.loads(buf.getvalue())
    wl = doc["workload"]
    assert wl["reconciled"], wl
    assert wl["tenants"]["noisy"]["completed"] == 8
    assert wl["tenants"]["quiet"]["completed"] == 2
    assert sum(e["completed"] for e in wl["tenants"].values()) \
        == wl["completed_total"] == 10
    # THE FLIP: the baseline drill pins quiet's p50 ABOVE noisy's
    # under FCFS; weighted-fair admission must bring it down to at
    # most the flood's own median
    assert wl["tenants"]["quiet"]["ttft_p50_s"] <= \
        wl["tenants"]["noisy"]["ttft_p50_s"], wl["tenants"]
    bt = doc["slo"]["by_tenant"]
    assert sum(b["completed"] for b in bt.values()) \
        == doc["slo"]["completed"]
    # the scheduler's decisions are on the record: at least one
    # schema-valid wfq_pick naming the tenant it favored
    recs, problems = read_metrics(os.path.join(mdir, METRICS_FILENAME))
    assert not problems
    picks = [r for r in recs if r["kind"] == "qos"
             and r["event"] == "wfq_pick"]
    assert picks, "wfq run emitted no wfq_pick qos record"
    for r in picks:
        ok, reason = validate_record(r)
        assert ok, reason
        assert r["tenant"] in ("noisy", "quiet")


def test_queue_limit_sheds_count_per_tenant(lm_params, tmp_path):
    """Sheds at the door land in the DRIVER's per-tenant book (the
    engine's rejected record is the anonymous uid -1): the workload
    record and the report fold carry them by tenant."""
    header = {"trace_version": 1, "id": "trshed", "seed": 0,
              "spec": "hand", "n": 6}
    entries = [{"t_offset_s": 0.0, "uid_hint": i,
                "tenant": ("flood" if i < 5 else "late"),
                "session": None, "prompt_len": 4, "max_new": 4,
                "turn": 0} for i in range(6)]
    m = TelemetryWriter(str(tmp_path / "m"))
    eng = DecodeEngine(lm_params, H, _cfg(max_slots=1),
                       policy=ServePolicy(queue_limit=2), metrics=m)
    summary = replay_trace(eng, header, entries, vocab=V, log_every=2,
                           metrics=m)
    m.close()
    # queue_limit 2: flood0/1 queue, flood2..4 shed at the door, and
    # the late submission behind them sheds too — per tenant, exactly
    assert summary["shed"] == 4
    assert summary["tenants"]["flood"]["shed"] == 3
    assert summary["tenants"]["late"]["shed"] == 1
    assert summary["tenants"]["flood"]["offered"] == 5
    recs, problems = read_metrics(
        os.path.join(str(tmp_path / "m"), METRICS_FILENAME))
    assert not problems
    last_wl = [r for r in recs if r["kind"] == "workload"][-1]
    assert last_wl["tenants"]["flood"]["shed"] == 3
    assert last_wl["tenants"]["late"]["shed"] == 1
    # offered == admitted + shed interval accounting
    offered = sum(r["offered"] for r in recs
                  if r["kind"] == "workload")
    admitted = sum(r["admitted"] for r in recs
                   if r["kind"] == "workload")
    assert offered - admitted == summary["shed"]


# ---------------------------------------------------------------------------
# driver validation + wall pacing


def test_driver_validation_and_wall_pace(lm_params):
    header, entries = generate_trace("n=3,plen=fixed:4,max_new=2,"
                                     "arrival=poisson:200")
    eng = DecodeEngine(lm_params, H, _cfg())
    with pytest.raises(ValueError, match="pace"):
        WorkloadDriver(eng, header, entries, vocab=V, pace="warp")
    with pytest.raises(ValueError, match="steps_per_s"):
        WorkloadDriver(eng, header, entries, vocab=V, steps_per_s=0)
    # wall pacing: token identity holds (sampling never reads the
    # clock) even though admission timing is real seconds
    replay_trace(eng, header, entries, vocab=V, pace="wall")
    virt = DecodeEngine(lm_params, H, _cfg())
    replay_trace(virt, header, entries, vocab=V)
    assert eng.finished == virt.finished


def test_deploy_watch_rolls_on_real_mid_serve_publish(lm_params,
                                                      tmp_path):
    """The deploy-on-publish watcher (ROADMAP item 3 follow-on): a
    REAL checkpoint publish lands mid-serve, the watcher's poll sees
    ``latest_verified`` advance, and the fleet rolls forward with zero
    shed — no operator, no scheduled round."""
    ck = str(tmp_path / "ck")
    new_params = init_lm(jax.random.PRNGKey(7), V, D, L, max_seq_len=64)
    fl = FleetRouter(lambda eid: DecodeEngine(lm_params, H, _cfg()), 2)
    fl.deploy_watch(ck, poll_every_s=1e-6)
    with pytest.raises(ValueError, match="> 0"):
        fl.deploy_watch(ck, poll_every_s=0)
    fl.deploy_watch(ck, poll_every_s=1e-6)
    rng = np.random.default_rng(2)
    for n in (5, 9, 6, 7):
        fl.submit(rng.integers(0, V, size=n).tolist(), 10)
    for _ in range(3):
        fl.step()
    assert fl.deploys == 0      # nothing published yet: no deploy
    save_checkpoint(ck, new_params, 5)      # the REAL mid-serve publish
    fl.run()
    assert fl.deploys == 1 and fl.deploy_rollbacks == 0
    assert fl.sheds == 0 and not fl.failed()
    assert {h.serving_version for h in fl.alive_handles()} == {5}
    # idempotent: the watcher must not re-deploy an already-serving step
    fl.submit(rng.integers(0, V, size=4).tolist(), 4)
    fl.run()
    assert fl.deploys == 1


# ---------------------------------------------------------------------------
# the process transport (the acceptance criterion's second half)


@pytest.mark.serial
def test_process_transport_replay_matches_inprocess_with_kill(
        lm_params, tmp_path):
    """The same (trace, seed) through 3 engine WORKER PROCESSES with
    kill_worker@4:1 (a REAL SIGKILL mid-trace): tokens byte-identical
    to the in-process killed fleet AND to the unkilled oracle,
    identical admission order, identical workload records, and the
    migrated requests keep their tenant on the completed records."""
    from conftest import load_scaled_timeout
    from distributed_llm_code_samples_tpu.decode.worker import (
        spawn_fleet_handles)
    from distributed_llm_code_samples_tpu.runtime.chaos import (
        FaultPlan, validate_fleet_plan)
    header, entries = generate_trace(SPEC)
    oracle = DecodeEngine(lm_params, H, _cfg())
    replay_trace(oracle, header, entries, vocab=V)

    def killed_lane(tag, handles=None, chaos=None):
        mdir = str(tmp_path / tag)
        writers = []
        rm = TelemetryWriter(os.path.join(mdir, "router"))
        writers.append(rm)
        if handles is None:
            def mk(eid):
                m = TelemetryWriter(os.path.join(mdir, eid))
                writers.append(m)
                return DecodeEngine(lm_params, H, _cfg(), metrics=m)
            fl = FleetRouter(mk, 3, metrics=rm, fleet_chaos=chaos)
        else:
            fl = FleetRouter(None, 3, handles=handles, metrics=rm,
                             fleet_chaos=chaos)
        try:
            summary = replay_trace(fl, header, entries, vocab=V,
                                   log_every=4, metrics=rm)
            outs = fl.results()
            failed = fl.failed()
        finally:
            fl.close()
            for w in writers:
                w.close()
        recs, problems = read_metrics(
            os.path.join(mdir, "router", METRICS_FILENAME))
        assert not problems, problems
        return outs, failed, summary, recs

    plan_in = FaultPlan.parse("kill_worker@4:1")
    # in-process kill_worker is honored via the scheduled-kill path
    inp = FleetRouter(
        lambda eid: DecodeEngine(lm_params, H, _cfg()), 3)
    inp.schedule_kill("e1", 4)
    sum_in = replay_trace(inp, header, entries, vocab=V)
    outs_in = inp.results()
    assert outs_in == oracle.finished

    plan = FaultPlan.parse("kill_worker@4:1")
    validate_fleet_plan(plan)
    deadline = load_scaled_timeout(120.0)
    handles = spawn_fleet_handles(
        3, 0, str(tmp_path / "spool"),
        model=dict(vocab=V, model_size=D, layers=L, heads=H,
                   kv_heads=None, max_seq_len=64, random_seed=0),
        config=dict(BASE), policy={},
        metrics_root=str(tmp_path / "proc"),
        call_deadline_s=deadline, connect_deadline_s=deadline)
    outs_p, failed_p, sum_p, recs_p = killed_lane("proc",
                                                  handles=handles,
                                                  chaos=plan)
    assert outs_p == oracle.finished and not failed_p
    assert sum_p["tenants"] == sum_in["tenants"]
    migrated = {r["uid"] for r in recs_p if r["kind"] == "router"
                and r["event"] == "migrated"}
    assert migrated, "the SIGKILL migrated nothing — drill vacuous"
    wl = [r for r in recs_p if r["kind"] == "workload"]
    assert wl and all(validate_record(r)[0] for r in wl)
    # admission order across the process boundary == in-process
    routed_p = [(r["uid"], r["target"], r["step"]) for r in recs_p
                if r["kind"] == "router" and r["event"] == "routed"]
    assert [u for u, _t, _s in routed_p] ==         sorted(u for u, _t, _s in routed_p)
    # the migrated uids' completed records (in the workers' own
    # streams) kept their tenant attribution across the real SIGKILL
    tenant_want = {}
    comp_tenant = {}
    for eid in ("e0", "e1", "e2"):
        recs, _ = read_metrics(os.path.join(
            str(tmp_path / "proc"), eid, METRICS_FILENAME))
        for r in recs:
            if r["kind"] != "request":
                continue
            if r["event"] == "admitted" and r["uid"] not in tenant_want:
                tenant_want[r["uid"]] = r["tenant"]
            if r["event"] == "completed":
                comp_tenant[r["uid"]] = r["tenant"]
    for uid in migrated:
        assert comp_tenant.get(uid) == tenant_want[uid]             and comp_tenant.get(uid) in ("a", "b"), uid
    del plan_in


# ---------------------------------------------------------------------------
# CLI surface (rc-2 rejection discipline; the end-to-end runs live in
# tier1.sh's workload smoke)


def test_generate_cli_trace_rejections(tmp_path):
    from distributed_llm_code_samples_tpu.decode.generate_cli import (
        generate_main)
    trace = str(tmp_path / "t.jsonl")
    write_trace(trace, *generate_trace("n=2,plen=fixed:4,max_new=2"))
    shape = ["-d", "32", "-l", "2", "--heads", "4", "--vocab", "64",
             "--max_seq_len", "64", "--block_size", "8",
             "--prefill_chunk", "4"]
    for bad in (
        ["--trace_gen", "n=0"],                      # bad spec
        ["--trace_gen", "n=2,arrival=x:1"],          # bad arrival
        ["--trace", str(tmp_path / "missing.jsonl")],  # no file
        ["--trace", trace, "--prompts", "1,2"],      # two sources
        ["--trace", trace, "--trace_gen", "n=2"],    # two sources
        ["--trace_out", trace, "--prompt_lens", "3"],  # out w/o gen
        ["--trace_pace", "wall", "--prompt_lens", "3"],  # pace w/o trace
        ["--trace", trace, "--trace_steps_per_s", "0"],  # bad rate
        ["--trace", trace, "--snapshot_dir", str(tmp_path / "s")],
        # the watcher tracks latest_verified; a pinned step needs
        # --deploy_round (silently dropping it would be the
        # ignored-flag failure the guard block rejects)
        ["--prompt_lens", "3", "--fleet", "2", "--deploy_dir",
         str(tmp_path / "ck"), "--deploy_watch", "1",
         "--deploy_step", "7"],
    ):
        err = io.StringIO()
        with contextlib.redirect_stderr(err), \
                contextlib.redirect_stdout(io.StringIO()):
            rc = generate_main(bad + shape)
        assert rc == 2, (bad, err.getvalue())
        assert "error:" in err.getvalue(), bad
    # a torn trace file rejects rc 2 with the one-line reason
    with open(trace, "a") as f:
        f.write('{"torn')
    err = io.StringIO()
    with contextlib.redirect_stderr(err), \
            contextlib.redirect_stdout(io.StringIO()):
        rc = generate_main(["--trace", trace] + shape)
    assert rc == 2 and "unparseable" in err.getvalue()
